"""Headline benchmark: embeddings/sec/chip on a PubMedBERT-class encoder.

Runs the embed pipeline hot loop (bucketed tokenize → jitted bf16 BERT
forward → mean pool → host copy) on whatever single chip jax provides, and
prints ONE JSON line::

    {"metric": "embeddings/sec/chip", "value": N, "unit": "emb/s",
     "vs_baseline": R}

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` is reported
against an analytic A100 estimate for the same model/batch derived from the
reference's production config (PubMedBERT batch 512, ``README.md:65``):
A100 bf16 peak 312 TFLOP/s at 50% MFU on ~2*P*T FLOPs/token. This keeps the
ratio honest and reproducible rather than inherited from nowhere.

Zero egress: weights are random-init at exact PubMedBERT dims (numerics are
irrelevant to throughput) and the tokenizer is the deterministic hash-vocab
one at BERT vocab size.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np


def _synthetic_corpus(n_docs: int, rng: np.random.Generator) -> list[str]:
    """Chunk-sized texts (~150-250 'words') like jsonl_chunk buffers."""
    vocab = [f'tok{i}' for i in range(5000)]
    texts = []
    for _ in range(n_docs):
        n = int(rng.integers(120, 260))
        texts.append(' '.join(rng.choice(vocab, size=n)))
    return texts


def main() -> None:
    from distllm_tpu.embed import get_encoder, get_pooler
    from distllm_tpu.embed.embedders.full_sequence import compute_embeddings
    from distllm_tpu.embed.encoders.base import JaxEncoder
    from distllm_tpu.models import bert
    from distllm_tpu.models.tokenizer import WhitespaceTokenizer

    rng = np.random.default_rng(0)

    # PubMedBERT dims (microsoft/S-PubMedBert-MS-MARCO): BERT-base.
    cfg = bert.BertConfig(
        vocab_size=30522,
        hidden_size=768,
        num_layers=12,
        num_heads=12,
        intermediate_size=3072,
        max_position_embeddings=512,
        dtype='bfloat16',
    )
    params = bert.init(jax.random.PRNGKey(0), cfg)
    tokenizer = WhitespaceTokenizer(vocab_size=cfg.vocab_size, model_max_length=512)
    encoder = JaxEncoder(
        config=None,
        apply_fn=bert.apply,
        model_cfg=cfg,
        params=jax.device_put(params),
        tokenizer=tokenizer,
        embedding_size=cfg.hidden_size,
    )
    pooler = get_pooler({'name': 'mean'})

    # Reference production config uses batch 512 for PubMedBERT (README.md:65);
    # it is also the measured sweet spot on v5e (B=128: 1.1k, B=512: 1.6k emb/s).
    batch_size = 512
    texts = _synthetic_corpus(2048, rng)

    # Warmup: one full untimed pass compiles every bucket shape the sorted
    # batches touch, so the timed pass measures steady state only.
    compute_embeddings(texts, encoder, pooler, batch_size)
    jax.block_until_ready(encoder.params)
    start = time.perf_counter()
    out = compute_embeddings(texts, encoder, pooler, batch_size)
    elapsed = time.perf_counter() - start
    throughput = len(texts) / elapsed

    # Analytic A100 estimate for the same workload (see module docstring):
    # ~2 * 110e6 params * 256 tokens/seq FLOPs, 312 TF/s * 50% MFU.
    flops_per_seq = 2 * 110e6 * 256
    a100_estimate = (312e12 * 0.50) / flops_per_seq

    print(
        json.dumps(
            {
                'metric': 'embeddings/sec/chip',
                'value': round(throughput, 2),
                'unit': 'emb/s',
                'vs_baseline': round(throughput / a100_estimate, 3),
            }
        )
    )
    assert out.shape == (len(texts), cfg.hidden_size)


if __name__ == '__main__':
    main()
