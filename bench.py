"""Headline benchmarks: embeddings/sec/chip + generation tokens/sec/chip.

Prints ONE JSON line of the driver-contract shape::

    {"metric": "embeddings/sec/chip", "value": N, "unit": "emb/s",
     "vs_baseline": R, ...extra fields...}

Extra fields carry the second BASELINE.md metric (generation tokens/sec/chip
on a Mistral-7B-dims decoder through the continuous-batching engine), MFU
telemetry for both stages, and an ``error`` field per stage when a stage
fails — the driver always gets a parseable line, never a bare traceback.

Structure: ``python bench.py`` is an orchestrator. It first probes the TPU
backend in a short-lived subprocess (retrying — round 1 died on a stale
"backend UNAVAILABLE" state), then runs each stage in its own subprocess
(``--stage embed`` / ``--stage gen``) so an OOM or backend wedge in one
stage cannot take down the other, and composes the single output line.

**Crash-proof contract (ISSUE 3 tentpole).** Rounds 3–5 all produced an
empty official record because this line was composed only after the LAST
stage. The orchestrator is now built around an incremental on-disk run
record and a global wall-clock deadline:

- every completed stage's JSON fragment is fsync'd to ``BENCH_partial.jsonl``
  (plus an atomically-rewritten ``BENCH_snapshot.json``) the moment the
  stage exits — a later crash can truncate coverage, never zero it;
- the deadline (``DISTLLM_BENCH_DEADLINE_S``, default 3300 s — safely under
  a 1 h driver timeout; the driver's ``timeout`` sends SIGTERM, rc 124)
  caps every per-stage budget and the backend-probe retry ladder, and a
  SIGALRM fires just before it expires;
- SIGTERM / SIGALRM / normal exit all emit the SAME driver-contract line,
  composed from whatever the run record holds — so an external kill still
  publishes every completed stage;
- stages run cheapest-first (embed → embed_q → gen → gen_prefix →
  gen_mixed → gen_spec → gen_kernel → gen_load → gen_tier → gen_chaos →
  gen_kvq → gen_q: embed warmups are minutes, ``gen_prefix``/
  ``gen_mixed``/``gen_spec``/``gen_load``/``gen_tier``/``gen_chaos`` and
  ``gen_kernel``'s XLA arm reuse ``gen``'s compile cache, ``gen_kvq``
  compiles its own block_size=32 bf16/int8 shapes, and int8 weight-quant
  ``gen_q``'s cold warmup — 22–45 min in round 4 — goes last);
- a failing or SIGTERM'd stage dumps a debug bundle (flight ring, metrics,
  traces — ``observability.dump_debug_bundle``) so a dead stage still
  explains itself, and gen stages run under a ``StallWatchdog``.

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` ratios are
against analytic A100 estimates derived from the reference's production
configs, stated inline where computed. Zero egress: weights are random-init
at exact model dims (numerics are irrelevant to throughput) and the
tokenizer is the deterministic hash-vocab one.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

# ----------------------------------------------------------------- stages


def _workload_fingerprint(payload) -> str:
    """Stable 12-hex digest of a stage's full workload (prompts + params).

    Recorded in the bench JSON so any two runs claiming the same metric can
    be checked for actually measuring the same thing (round 2 vs round 3
    reported 795 vs 605 tok/s on what turned out to be different prompt
    sets — this makes such drift visible instead of mysterious).
    """
    import hashlib

    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _cache_entries() -> int | None:
    """Number of entries in the persistent compilation cache (None if the
    cache dir doesn't exist). before/after deltas reveal whether warmup
    compiles HIT the AOT-preflight-seeded cache or re-lowered everything."""
    cache_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), '.jax_cache'
    )
    try:
        return len(os.listdir(cache_dir))
    except OSError:
        return None


def _cache_fields(prefix: str, cache_before: int | None) -> dict:
    """Compile-cache evidence for the stage: a warmed serve start must
    compile NOTHING (vLLM has no multi-minute unrolled-window compile to
    hide; our persistent cache is what matches that). ``warm_start`` is
    the claim checked across back-to-back bench runs: run 1 may populate,
    run 2 must show delta 0. DISTLLM_BENCH_REQUIRE_WARM=1 turns a cold
    start into a hard failure (CI on a preflight-seeded cache)."""
    cache_after = _cache_entries()
    delta = (
        cache_after - cache_before
        if cache_after is not None and cache_before is not None
        else None
    )
    if os.environ.get('DISTLLM_BENCH_REQUIRE_WARM'):
        if delta is None:
            raise RuntimeError(
                f'{prefix}stage: DISTLLM_BENCH_REQUIRE_WARM set but the '
                'compilation cache dir is missing — nothing can be warm '
                '(seed with scripts/aot_preflight.py first)'
            )
        if delta > 0:
            raise RuntimeError(
                f'{prefix}stage compiled {delta} new cache entries on a '
                'cache expected warm (seed with scripts/aot_preflight.py '
                'first)'
            )
    return {
        f'{prefix}cache_entries_before': cache_before,
        f'{prefix}cache_entries_after': cache_after,
        f'{prefix}warm_start': delta == 0 if delta is not None else None,
    }


def _stage_embed(quantization: str | None = None, prefix: str = '') -> dict:
    """Embed pipeline hot loop: bucketed tokenize -> jitted bf16 BERT
    forward -> mean pool -> host copy. PubMedBERT dims
    (microsoft/S-PubMedBert-MS-MARCO = BERT-base), reference production
    batch 512 (ref README.md:65). ``quantization='int8'`` measures the
    weight-only quantized encoder (the TPU stand-in for the reference's
    NF4 load path, embed/encoders/auto.py:46-56)."""
    import jax
    import numpy as np

    from distllm_tpu.embed import get_pooler
    from distllm_tpu.embed.embedders.full_sequence import compute_embeddings
    from distllm_tpu.embed.encoders.base import JaxEncoder
    from distllm_tpu.models import bert
    from distllm_tpu.models.tokenizer import WhitespaceTokenizer

    rng = np.random.default_rng(0)

    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        # Smoke-test dims for CPU CI; real runs use PubMedBERT dims.
        cfg = bert.BertConfig(
            vocab_size=2048, hidden_size=128, num_layers=2, num_heads=4,
            intermediate_size=256, max_position_embeddings=512,
            dtype='float32',
        )
    else:
        cfg = bert.BertConfig(
            vocab_size=30522,
            hidden_size=768,
            num_layers=12,
            num_heads=12,
            intermediate_size=3072,
            max_position_embeddings=512,
            dtype='bfloat16',
        )
    params = bert.init(jax.random.PRNGKey(0), cfg)
    tokenizer = WhitespaceTokenizer(vocab_size=cfg.vocab_size, model_max_length=512)
    encoder = JaxEncoder(
        config=None,
        apply_fn=bert.apply,
        model_cfg=cfg,
        params=jax.device_put(params),
        tokenizer=tokenizer,
        embedding_size=cfg.hidden_size,
        quantization=quantization,
    )
    pooler = get_pooler({'name': 'mean'})

    batch_size = 64 if small else 512
    # Chunk-sized texts (~150-250 'words') like jsonl_chunk buffers.
    vocab = [f'tok{i}' for i in range(5000)]
    texts = []
    for _ in range(128 if small else 2048):
        n = int(rng.integers(120, 260))
        texts.append(' '.join(rng.choice(vocab, size=n)))

    # Warmup compiles every bucket shape the sorted batches touch.
    cache_before = _cache_entries()
    warmup_start = time.perf_counter()
    compute_embeddings(texts, encoder, pooler, batch_size)
    jax.block_until_ready(encoder.params)
    warmup_secs = time.perf_counter() - warmup_start
    bucket_stats: dict = {}
    start = time.perf_counter()
    out = compute_embeddings(
        texts, encoder, pooler, batch_size, stats=bucket_stats
    )
    elapsed = time.perf_counter() - start
    assert out.shape == (len(texts), cfg.hidden_size)
    throughput = len(texts) / elapsed

    # Analytic A100 estimate: 2 * n_params * 256 tokens/seq FLOPs at
    # 312 TF/s bf16 peak * 50% MFU. n_params comes from the actual config
    # (110M at PubMedBERT dims) so the small smoke mode reports honest
    # ratios instead of constants sized for the full model.
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    tokens_per_seq = 256
    flops_per_seq = 2 * n_params * tokens_per_seq
    a100_estimate = (312e12 * 0.50) / flops_per_seq

    peak = _chip_peak_flops(jax.devices()[0])
    mfu = throughput * flops_per_seq / peak if peak else None
    out = {
        f'{prefix}metric': 'embeddings/sec/chip',
        f'{prefix}value': round(throughput, 2),
        f'{prefix}unit': 'emb/s',
        f'{prefix}vs_baseline': round(throughput / a100_estimate, 3),
        f'{prefix}mfu': round(mfu, 3) if mfu is not None else None,
        f'{prefix}device': str(jax.devices()[0].device_kind),
        f'{prefix}workload': _workload_fingerprint(
            {'texts': texts, 'batch_size': batch_size,
             'dims': cfg.model_dump() if hasattr(cfg, 'model_dump') else str(cfg)}
        ),
        f'{prefix}warmup_secs': round(warmup_secs, 1),
        **_cache_fields(prefix, cache_before),
        f'{prefix}padding_frac': round(
            1 - bucket_stats['tokens_real'] / bucket_stats['tokens_padded'], 3
        ),
        f'{prefix}bucket_batches': {
            str(k): v
            for k, v in sorted(bucket_stats['bucket_batches'].items())
        },
    }
    if quantization:
        out[f'{prefix}quantization'] = quantization
    return out


def _measure_load_ttft(engine, prompts, probe_prompt, sampling,
                       probe_sampling) -> float | None:
    """TTFT of a request injected while the engine is mid-stream at full
    decode batch (``gen_load_ttft_s``) — the interference number mixed
    batching exists to improve: standalone prefill dispatches serialize
    between decode windows (probe_gen, BENCH_NOTES_r05.md), so a request
    arriving under load pays its prefill AGAINST the running stream.

    Saturates the batch via ``step()``, waits until every slot is
    actively decoding, injects one probe request, and reads its
    first-token latency off the request-lifecycle timestamps.
    """
    from distllm_tpu.generate.engine.engine import RequestState

    for prompt in prompts:
        engine.add_request(prompt, sampling)
    probe_rid = None
    while engine.has_unfinished:
        engine.step()
        if probe_rid is not None:
            continue
        running = [
            r for r in engine._requests.values()
            if r.state is RequestState.RUNNING
        ]
        if len(running) >= min(
            len(prompts), engine.config.max_num_seqs
        ) and all(r.output_ids for r in running):
            probe_rid = engine.add_request(probe_prompt, probe_sampling)
    if probe_rid is None:
        return None
    probe = engine._finished.pop(probe_rid, None)
    if probe is None or not probe.t_first_token:
        return None
    return probe.t_first_token - probe.t_enqueue


def _run_gen(quantization: str | None, prefix: str) -> dict:
    """Generation through the continuous-batching engine at Mistral-7B dims
    (random weights on device; numerics irrelevant to throughput).

    Workload shape follows the reference's production serving pattern
    (mixed prompt lengths; ref examples/miscellaneous/
    multi_gpu_batch_config.yaml: max_num_seqs 128, client batch 16;
    sampling defaults ref vllm_backend.py:19-27). bf16 serving fits
    max_num_seqs=32 beside 13.5 GiB of weights on a 16 GiB v5e; int8
    weight-only quantization (the TPU answer to the reference's NF4 HF
    path, huggingface_backend.py:66-77) halves weight HBM and runs the
    reference's full max_num_seqs=128."""
    import jax
    import numpy as np

    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.models import mistral

    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        # Smoke-test dims for CPU CI; real runs use the 7B defaults.
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
        )
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults
    n_params = sum(
        int(np.prod(x.shape))
        for x in jax.tree.leaves(
            jax.eval_shape(
                lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg)
            )
        )
    )

    if quantization is None:
        # bf16: 13.5 GiB weights + 32 seqs x 22 blocks x 2 MiB = 1.4 GiB KV.
        max_num_seqs, num_blocks, n_prompts = 32, 712, 96
    else:
        # int8: ~7 GiB weights frees HBM for the reference's production
        # batch (max_num_seqs 128).
        max_num_seqs, num_blocks, n_prompts = 128, 2840, 320
    # A/B toggle for mixed prefill+decode windows (docs/serving.md):
    # DISTLLM_BENCH_MIXED=1 serves this stage with prefill chunks riding
    # decode windows; the dedicated gen_mixed stage runs the token-
    # identity A/B either way.
    mixed = os.environ.get('DISTLLM_BENCH_MIXED', '') not in ('', '0')
    engine_cfg = EngineConfig(
        block_size=16,
        num_blocks=num_blocks,
        max_num_seqs=max_num_seqs,
        max_model_len=512,
        decode_steps=16,
        pipeline_depth=2,
        quantization=quantization,
        # Serving fast path: top-64 sampling window instead of a 32k-vocab
        # sort per decode step (exact top-p within the window).
        sampling_top_window=64,
        enable_mixed_batching=mixed,
        max_window_prefill_tokens=256,
        # Only paged-route tails ride windows; chunking is what puts this
        # stage's fresh 32-192-token prompts on that route when the
        # toggle is on. Off keeps the classic batched dense prefill.
        prefill_chunk_tokens=64 if mixed else 0,
    )
    rng = np.random.default_rng(0)
    prompts = [
        list(rng.integers(1, model_cfg.vocab_size, size=int(n)))
        for n in rng.integers(32, 192, size=n_prompts)
    ]
    gen_tokens = 128
    sampling = SamplingParams(
        temperature=0.5, top_p=0.95, min_p=0.1, max_tokens=gen_tokens
    )

    # engine.warmup() compiles every (batch, bucket) prefill shape, the KV
    # scatter, the fused decode window, and the samplers outside the timed
    # region; the persistent compilation cache (enabled in main) makes
    # repeat runs start hot. jax.jit is lazy, so an unavailable Pallas
    # lowering only surfaces here — probe via warmup and fall back to XLA,
    # recording WHY the preferred backend was rejected.
    def make_params():
        if quantization is not None and jax.default_backend() != 'cpu':
            # Quantize on the HOST cpu device and ship only the codes:
            # letting the engine quantize device-resident bf16 streams
            # 14.5 GB D2H + 7.25 GB H2D through the serving tunnel
            # (~2 GB/s) — most of the gen_q stage's 22-45 min warmup,
            # which run 4 pushed past the stage timeout. The engine
            # passes pre-quantized QTensor leaves through untouched.
            import ml_dtypes

            from distllm_tpu.ops.quantization import quantize_pytree

            shapes = jax.eval_shape(
                lambda: mistral.init_on_device(
                    jax.random.PRNGKey(0), model_cfg
                )
            )
            host_rng = np.random.default_rng(0)
            np_dtype = {
                'bfloat16': ml_dtypes.bfloat16, 'float32': np.float32,
            }[model_cfg.dtype]

            def _host_leaf(leaf):
                return (
                    host_rng.standard_normal(leaf.shape, dtype=np.float32)
                    * 0.02
                ).astype(np_dtype)

            qtree = quantize_pytree(
                jax.tree.map(_host_leaf, shapes),
                mode=quantization,
                out_dtype=model_cfg.dtype,
            )
            return jax.device_put(qtree, jax.devices()[0])
        return mistral.init_on_device(jax.random.PRNGKey(0), model_cfg)

    cache_before = _cache_entries()
    warmup_start = time.perf_counter()
    engine, fallback_reason = _build_engine_with_fallback(
        model_cfg,
        engine_cfg,
        make_params,
        prompts[:2],
        SamplingParams(temperature=0.5, top_p=0.95, min_p=0.1, max_tokens=4),
    )
    warmup_secs = time.perf_counter() - warmup_start

    # Time-to-first-token on the WARMED engine: one prompt, one token —
    # prefill dispatch + first decode window + host sync. This is the
    # serving latency a vLLM user compares against; on a warm compile
    # cache it must be free of compile time (see warm_start below).
    ttft_start = time.perf_counter()
    engine.generate_ids(
        prompts[:1],
        SamplingParams(temperature=0.5, top_p=0.95, min_p=0.1, max_tokens=1),
    )
    ttft_s = time.perf_counter() - ttft_start

    # TTFT *under load*: inject a request while the engine is mid-stream
    # at full decode batch (gen_load_ttft_s, next to gen_ttft_s). This is
    # the interference number mixed batching must improve — the idle-
    # engine ttft_s above cannot see prefill/decode serialization.
    load_ttft_s = _measure_load_ttft(
        engine,
        prompts[: min(max_num_seqs, len(prompts))],
        prompts[-1],
        SamplingParams(
            temperature=0.5, top_p=0.95, min_p=0.1, max_tokens=32
        ),
        SamplingParams(
            temperature=0.5, top_p=0.95, min_p=0.1, max_tokens=2
        ),
    )

    # DISTLLM_BENCH_PROFILE=<dir> wraps the timed region in a profiler
    # trace (XPlane + TensorBoard format): on hardware this shows per-op
    # device time for the decode windows — the ground truth the AOT HLO
    # census (scripts/probe_decode_hlo.py) can only approximate. Routed
    # through the bounded capture helper (observability/profiling.py):
    # an unsupported-backend profiler error downgrades to a fragment
    # field instead of killing the stage, and a hung region cannot leave
    # the trace growing forever.
    profile_dir = os.environ.get('DISTLLM_BENCH_PROFILE')
    capture = None
    if profile_dir:
        from distllm_tpu.observability.profiling import get_profiler_capture

        capture = get_profiler_capture()
        if not capture.start(profile_dir, max_seconds=1800.0):
            capture = None
    try:
        start = time.perf_counter()
        outs = engine.generate_ids(prompts, sampling)
        elapsed = time.perf_counter() - start
    finally:
        # Flush even when generation dies mid-decode — a partial trace of
        # the failing run is exactly what the profile exists to capture.
        if capture is not None:
            capture.stop()
    n_tokens = sum(len(o) for o in outs)
    throughput = n_tokens / elapsed

    # Analytic A100 estimate for decode of this model: the roofline is
    # min(compute, HBM bandwidth). At these batches decode is
    # weight-bandwidth bound: tokens/s ~= batch * BW_eff / model_bytes with
    # A100-80GB 2.0e12 B/s at 60% efficiency and bf16 weights — i.e. the
    # reference's own vLLM serving dtype at the SAME concurrency. (Per
    # chip, an A100 has 2.4x the HBM bandwidth and 1.6x the bf16 FLOPs of
    # a v5e, so ratios compare silicon, not software.)
    flops_per_token = 2 * n_params
    model_bytes = 2 * n_params
    a100_bw_bound = max_num_seqs * (2.0e12 * 0.60) / model_bytes
    a100_compute_bound = (312e12 * 0.50) / flops_per_token
    a100_estimate = min(a100_bw_bound, a100_compute_bound)

    peak = _chip_peak_flops(jax.devices()[0])
    mfu = throughput * flops_per_token / peak if peak else None
    out = {
        f'{prefix}metric': 'gen tokens/sec/chip',
        f'{prefix}value': round(throughput, 2),
        f'{prefix}unit': 'tok/s',
        f'{prefix}vs_baseline': round(throughput / a100_estimate, 3),
        f'{prefix}mfu': round(mfu, 4) if mfu is not None else None,
        f'{prefix}n_tokens': n_tokens,
        f'{prefix}attn_backend': engine.config.attn_backend,
        f'{prefix}batch': max_num_seqs,
        f'{prefix}decode_steps': engine_cfg.decode_steps,
        f'{prefix}scheduler_impl': type(engine.sched).__name__,
        f'{prefix}workload': _workload_fingerprint(
            {'prompts': [list(map(int, p)) for p in prompts],
             'sampling': sampling.__dict__,
             'engine': {'block_size': engine_cfg.block_size,
                        'num_blocks': num_blocks,
                        'max_num_seqs': max_num_seqs,
                        'decode_steps': engine_cfg.decode_steps},
             'gen_tokens': gen_tokens}
        ),
        f'{prefix}warmup_secs': round(warmup_secs, 1),
        f'{prefix}ttft_s': round(ttft_s, 3),
        f'{prefix}load_ttft_s': (
            round(load_ttft_s, 3) if load_ttft_s is not None else None
        ),
        f'{prefix}mixed_batching': mixed,
        **_cache_fields(prefix, cache_before),
    }
    if quantization:
        out[f'{prefix}quantization'] = quantization
    if fallback_reason:
        out[f'{prefix}attn_fallback_reason'] = fallback_reason
    if profile_dir and capture is None:
        # The profiler was requested but could not start (unsupported
        # backend, busy slot): the stage ran unprofiled and says so.
        from distllm_tpu.observability.profiling import get_profiler_capture

        out[f'{prefix}profile_error'] = (
            get_profiler_capture().state().get('last_error')
        )
    for key, val in engine.telemetry.items():
        out[f'{prefix}{key}'] = val
    return out


def _build_engine_with_fallback(
    model_cfg, engine_cfg, make_params, smoke_prompts, smoke_params
):
    """Build the serving engine, probing attn backends in preference order
    (Pallas first on TPU). jax.jit is lazy, so an unavailable Pallas
    lowering only surfaces at warmup — each candidate is warmed and
    smoke-run before being accepted, and a failed candidate's KV cache is
    freed BEFORE the fallback is built (two live caches beside 7B weights
    would OOM HBM). Returns ``(engine, fallback_reason)``; raises when the
    last backend fails too. One home for this ladder so the gen stages
    cannot drift on the teardown ordering.
    """
    import jax

    from distllm_tpu.generate.engine.engine import LLMEngine

    class _Tok:
        eos_id = None

    backends = ['xla'] if jax.default_backend() == 'cpu' else ['pallas', 'xla']
    fallback_reason = None
    for backend in backends:
        engine_cfg.attn_backend = backend
        # Fresh params per candidate: the engine owns (and may delete)
        # them for destructive HBM optimizations (relayout, quant cleanup).
        params = make_params()
        candidate = LLMEngine(
            model_cfg, params, _Tok(), engine_cfg, own_params=True
        )
        try:
            candidate.warmup()
            candidate.generate_ids(smoke_prompts, smoke_params)
            return candidate, fallback_reason
        except Exception as exc:
            if backend != backends[-1]:
                fallback_reason = f'{backend}: {exc!r}'[:400]
            candidate.shutdown()
            del params
            if backend == backends[-1]:
                raise
    raise AssertionError('unreachable')


def _stage_gen_prefix() -> dict:
    """Prefix-caching serving stage (docs/prefix_caching.md): repeated
    shared-prefix prompts — the RAG-chat / MCQA shape where every request
    repeats a long system-prompt/stem and differs only in a short tail.

    Records ``gen_prefix_ttft_s`` (warm TTFT with the prefix cached — the
    number prefix caching exists to shrink), the cold TTFT baseline on the
    SAME engine, cache hit rate, and throughput over the full workload.
    """
    import jax
    import numpy as np

    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.models import mistral

    prefix = 'gen_prefix_'
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
        )
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults

    engine_cfg = EngineConfig(
        block_size=16,
        num_blocks=712,
        max_num_seqs=32,
        max_model_len=512,
        decode_steps=16,
        pipeline_depth=2,
        sampling_top_window=64,
        enable_prefix_cache=True,
        prefill_chunk_tokens=256,
    )
    cache_before = _cache_entries()
    warmup_start = time.perf_counter()
    engine, fallback_reason = _build_engine_with_fallback(
        model_cfg,
        engine_cfg,
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
        [[1, 2, 3]],
        SamplingParams(temperature=0.0, max_tokens=2),
    )
    warmup_secs = time.perf_counter() - warmup_start

    # Workload: one 320-token shared prefix (20 blocks), 32 requests with
    # distinct 16-token tails — the round-5 RAG serving shape.
    rng = np.random.default_rng(0)
    shared = list(rng.integers(1, model_cfg.vocab_size, size=320))
    prompts = [
        shared + list(rng.integers(1, model_cfg.vocab_size, size=16))
        for _ in range(32)
    ]
    one_token = SamplingParams(
        temperature=0.5, top_p=0.95, min_p=0.1, max_tokens=1
    )
    # Cold TTFT: nothing cached, full 336-token prefill.
    t0 = time.perf_counter()
    engine.generate_ids(prompts[:1], one_token)
    ttft_cold_s = time.perf_counter() - t0
    # Warm TTFT: the 320-token prefix is cached; prefill covers the tail.
    t0 = time.perf_counter()
    engine.generate_ids(prompts[1:2], one_token)
    ttft_warm_s = time.perf_counter() - t0

    sampling = SamplingParams(
        temperature=0.5, top_p=0.95, min_p=0.1, max_tokens=64
    )
    start = time.perf_counter()
    outs = engine.generate_ids(prompts[2:], sampling)
    elapsed = time.perf_counter() - start
    n_tokens = sum(len(o) for o in outs)
    out = {
        f'{prefix}metric': 'warm shared-prefix TTFT',
        f'{prefix}ttft_s': round(ttft_warm_s, 3),
        f'{prefix}ttft_cold_s': round(ttft_cold_s, 3),
        f'{prefix}ttft_speedup': round(ttft_cold_s / max(ttft_warm_s, 1e-9), 2),
        f'{prefix}throughput_tok_s': round(n_tokens / elapsed, 2),
        f'{prefix}n_tokens': n_tokens,
        f'{prefix}attn_backend': engine.config.attn_backend,
        f'{prefix}shared_prefix_tokens': len(shared),
        f'{prefix}warmup_secs': round(warmup_secs, 1),
        f'{prefix}workload': _workload_fingerprint(
            {'prompts': [list(map(int, p)) for p in prompts],
             'sampling': sampling.__dict__,
             'engine': {'block_size': engine_cfg.block_size,
                        'num_blocks': engine_cfg.num_blocks,
                        'max_num_seqs': engine_cfg.max_num_seqs,
                        'prefill_chunk_tokens':
                            engine_cfg.prefill_chunk_tokens}}
        ),
        **_cache_fields(prefix, cache_before),
    }
    if fallback_reason:
        out[f'{prefix}attn_fallback_reason'] = fallback_reason
    for key, val in engine.telemetry.items():
        out[f'{prefix}{key}'] = val
    return out


def _stage_gen_mixed() -> dict:
    """Mixed serving-window A/B (docs/serving.md): the SAME staggered
    serving workload with ``enable_mixed_batching`` off, then on.

    The contract this stage checks and records:

    - greedy output tokens are BIT-IDENTICAL between the arms;
    - the on arm folds prefill chunks into decode windows (``mixed``
      flight records present, standalone prefill dispatch count strictly
      lower than the off arm);
    - both arms record the mid-stream ``load_ttft`` interference number
      (the idle-engine TTFT cannot see prefill/decode serialization).

    The workload staggers finish budgets so slots free while neighbours
    still decode — mid-stream admission is what rides windows; a uniform
    batch that drains all slots at once never exercises the fold.
    """
    import jax
    import numpy as np

    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.models import mistral
    from distllm_tpu.observability.flight import get_flight_recorder

    prefix = 'gen_mixed_'
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
        )
        max_num_seqs, num_blocks = 4, 160
        n_prompts, prompt_lo, prompt_hi = 12, 8, 48
        budget, chunk, out_lo, out_hi = 16, 16, 4, 24
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults
        max_num_seqs, num_blocks = 32, 712
        n_prompts, prompt_lo, prompt_hi = 64, 32, 192
        budget, chunk, out_lo, out_hi = 256, 256, 16, 96

    rng = np.random.default_rng(0)
    # Every third prompt repeats a 2-block shared prefix (the RAG/MCQA
    # shape): its cached-prefix tail is a paged-route span that rides
    # windows; the long fresh prompts ride through chunked tails.
    shared = list(rng.integers(1, model_cfg.vocab_size, size=32))
    prompts = []
    for i, n in enumerate(rng.integers(prompt_lo, prompt_hi, size=n_prompts)):
        tail = list(rng.integers(1, model_cfg.vocab_size, size=int(n)))
        prompts.append(shared + tail if i % 3 == 0 else tail)
    budgets = [int(n) for n in rng.integers(out_lo, out_hi, size=n_prompts)]
    # The load-TTFT probe must be a NEVER-SEEN prompt: by probe time the
    # main A/B run has adopted every workload prompt's full blocks into
    # the per-engine prefix cache, and a cached probe would measure a
    # ~1-token COW admission instead of prefill-under-load interference.
    probe_prompt = list(
        rng.integers(1, model_cfg.vocab_size, size=prompt_hi)
    )

    def run_arm(mixed: bool) -> dict:
        engine_cfg = EngineConfig(
            block_size=16,
            num_blocks=num_blocks,
            max_num_seqs=max_num_seqs,
            max_model_len=512,
            decode_steps=16,
            pipeline_depth=2,
            sampling_top_window=64,
            enable_prefix_cache=True,
            prefill_chunk_tokens=chunk,
            enable_mixed_batching=mixed,
            max_window_prefill_tokens=budget,
        )
        engine, fallback_reason = _build_engine_with_fallback(
            model_cfg,
            engine_cfg,
            lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
            [[1, 2, 3]],
            SamplingParams(temperature=0.0, max_tokens=2),
        )
        flight_before = sum(
            1 for r in get_flight_recorder().snapshot()
            if r['kind'] == 'mixed'
        )
        rids = [
            engine.add_request(
                p, SamplingParams(temperature=0.0, max_tokens=n)
            )
            for p, n in zip(prompts, budgets)
        ]
        start = time.perf_counter()
        seen: dict = {rid: [] for rid in rids}
        while engine.has_unfinished:
            for rid, tok in engine.step():
                seen[rid].append(tok)
        elapsed = time.perf_counter() - start
        n_tokens = sum(len(v) for v in seen.values())
        load_ttft_s = _measure_load_ttft(
            engine,
            prompts[:max_num_seqs],
            probe_prompt,
            SamplingParams(temperature=0.0, max_tokens=32),
            SamplingParams(temperature=0.0, max_tokens=2),
        )
        arm = {
            'tokens': [seen[rid] for rid in rids],
            'throughput_tok_s': round(n_tokens / elapsed, 2),
            'prefill_dispatches': int(
                engine._stats.get('prefill_dispatches', 0)
            ),
            'mixed_windows': int(engine._stats.get('mixed_windows', 0)),
            'mixed_prefill_tokens': int(
                engine._stats.get('mixed_prefill_tokens', 0)
            ),
            'mixed_flight_records': sum(
                1 for r in get_flight_recorder().snapshot()
                if r['kind'] == 'mixed'
            ) - flight_before,
            'load_ttft_s': (
                round(load_ttft_s, 3) if load_ttft_s is not None else None
            ),
            'fallback_reason': fallback_reason,
        }
        engine.shutdown()
        return arm

    cache_before = _cache_entries()
    warmup_start = time.perf_counter()
    off = run_arm(False)
    on = run_arm(True)
    warmup_secs = time.perf_counter() - warmup_start
    identical = on['tokens'] == off['tokens']
    out = {
        f'{prefix}metric': 'mixed-window A/B',
        f'{prefix}tokens_identical': identical,
        f'{prefix}throughput_tok_s': on['throughput_tok_s'],
        f'{prefix}off_throughput_tok_s': off['throughput_tok_s'],
        f'{prefix}load_ttft_s': on['load_ttft_s'],
        f'{prefix}off_load_ttft_s': off['load_ttft_s'],
        f'{prefix}prefill_dispatches': on['prefill_dispatches'],
        f'{prefix}off_prefill_dispatches': off['prefill_dispatches'],
        f'{prefix}windows': on['mixed_windows'],
        f'{prefix}prefill_tokens_ridden': on['mixed_prefill_tokens'],
        f'{prefix}flight_records': on['mixed_flight_records'],
        f'{prefix}off_flight_records': off['mixed_flight_records'],
        f'{prefix}elapsed_both_arms_s': round(warmup_secs, 1),
        f'{prefix}workload': _workload_fingerprint(
            {'prompts': [list(map(int, p)) for p in prompts],
             'budgets': budgets,
             'engine': {'max_num_seqs': max_num_seqs,
                        'num_blocks': num_blocks,
                        'max_window_prefill_tokens': budget,
                        'prefill_chunk_tokens': chunk}}
        ),
        **_cache_fields(prefix, cache_before),
    }
    if not identical:
        out[f'{prefix}error'] = (
            'mixed on/off token mismatch — the A/B identity contract is '
            'broken'
        )
    if on['fallback_reason'] or off['fallback_reason']:
        out[f'{prefix}attn_fallback_reason'] = (
            on['fallback_reason'] or off['fallback_reason']
        )
    return out


def _stage_gen_spec() -> dict:
    """Prompt-lookup speculative decoding A/B (docs/speculative.md): the
    SAME staggered workload through the greedy arms — the classic
    decode scan (``draft_k=0``), verify windows with drafting disabled
    (``spec_draft_source='none'``), and full speculation — plus a
    sampled (temperature > 0) arm run twice for determinism evidence.

    The contract this stage checks and records:

    - drafting on vs off INSIDE the verify kernel is BIT-IDENTICAL
      (``tokens_identical`` — same compiled executable, so this holds in
      bf16; a mismatch means the acceptance rule or rollback is broken
      and the stage records an error);
    - agreement with the classic decode-scan arm is recorded as
      ``tokens_match_decode_path``: guaranteed only in fp32 — two
      compiled programs may round a near-tied bf16 logit differently
      (measured: a 3.9e-3 top-2 gap flipped on CPU smoke), the same
      reason vLLM does not promise bitwise spec parity — so it is
      evidence, not an assert;
    - ``gen_spec_accept_rate`` — accepted / drafted tokens, the
      speculative win in one number (every accepted token skipped its
      weight pass) — and tok/s for all arms, comparable to
      ``gen_tok_per_s``;
    - verify windows actually ran (``spec_windows`` > 0);
    - the SAMPLED arm (``gen_spec_sampled_*``): the same workload at
      temperature > 0 with explicit per-request seeds rides the verify
      kernel through device-side rejection sampling
      (docs/speculative.md "Sampled verification"). Run twice —
      ``sampled_deterministic`` is the (seed, schedule) determinism
      evidence, and ``sampled_accepted_tokens`` must be > 0 (the stage
      records an error otherwise). ``sampled_accept_rate`` gates
      higher-better in benchdiff.

    ``DISTLLM_BENCH_SPEC=0`` skips the stage (default on). The workload
    is deliberately repetitive — shared prefixes plus prompts that
    repeat an n-gram motif, the RAG-quote/MCQA-stem shape prompt lookup
    exploits.
    """
    import jax
    import numpy as np

    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.models import mistral

    prefix = 'gen_spec_'
    if os.environ.get('DISTLLM_BENCH_SPEC', '1') in ('', '0'):
        return {f'{prefix}skipped': 'DISTLLM_BENCH_SPEC=0'}
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
        )
        max_num_seqs, num_blocks = 4, 160
        n_prompts, prompt_lo, prompt_hi = 12, 8, 48
        out_lo, out_hi, draft_k = 4, 24, 4
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults
        max_num_seqs, num_blocks = 32, 712
        n_prompts, prompt_lo, prompt_hi = 64, 32, 192
        out_lo, out_hi, draft_k = 16, 96, 4

    rng = np.random.default_rng(0)
    shared = list(rng.integers(1, model_cfg.vocab_size, size=32))
    motif = list(rng.integers(1, model_cfg.vocab_size, size=8))
    prompts = []
    for i, n in enumerate(rng.integers(prompt_lo, prompt_hi, size=n_prompts)):
        tail = list(rng.integers(1, model_cfg.vocab_size, size=int(n)))
        if i % 2 == 0:
            # Tile the motif through the tail so the prompt itself holds
            # repeated n-grams — prompt-lookup's draft material.
            tail = (motif * (1 + len(tail) // len(motif)))[: len(tail)]
        prompts.append(shared + tail if i % 3 == 0 else tail)
    budgets = [int(n) for n in rng.integers(out_lo, out_hi, size=n_prompts)]

    def run_arm(
        k: int,
        source: str = 'prompt_lookup',
        temperature: float = 0.0,
        top_p: float = 1.0,
    ) -> dict:
        engine_cfg = EngineConfig(
            block_size=16,
            num_blocks=num_blocks,
            max_num_seqs=max_num_seqs,
            max_model_len=512,
            decode_steps=16,
            pipeline_depth=2,
            sampling_top_window=64,
            enable_prefix_cache=True,
            draft_k=k,
            spec_draft_source=source,
        )
        engine, fallback_reason = _build_engine_with_fallback(
            model_cfg,
            engine_cfg,
            lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
            [[1, 2, 3]],
            SamplingParams(temperature=0.0, max_tokens=2),
        )
        rids = [
            engine.add_request(
                p,
                SamplingParams(
                    temperature=temperature,
                    top_p=top_p,
                    max_tokens=n,
                    # Explicit per-request seed: the sampled arm's output
                    # must be a pure function of (seed, schedule) so two
                    # runs give determinism evidence, not a coin flip.
                    seed=(1000 + i) if temperature > 0 else None,
                ),
            )
            for i, (p, n) in enumerate(zip(prompts, budgets))
        ]
        start = time.perf_counter()
        seen: dict = {rid: [] for rid in rids}
        while engine.has_unfinished:
            for rid, tok in engine.step():
                seen[rid].append(tok)
        elapsed = time.perf_counter() - start
        n_tokens = sum(len(v) for v in seen.values())
        drafted = int(engine._stats.get('spec_draft_tokens', 0))
        accepted = int(engine._stats.get('spec_accepted_tokens', 0))
        arm = {
            'tokens': [seen[rid] for rid in rids],
            'throughput_tok_s': round(n_tokens / elapsed, 2),
            'spec_windows': int(engine._stats.get('spec_windows', 0)),
            'draft_tokens': drafted,
            'accepted_tokens': accepted,
            'accept_rate': round(accepted / drafted, 4) if drafted else None,
            'fallback_reason': fallback_reason,
        }
        engine.shutdown()
        return arm

    cache_before = _cache_entries()
    warmup_start = time.perf_counter()
    classic = run_arm(0)
    null = run_arm(draft_k, source='none')
    on = run_arm(draft_k)
    # Sampled arm (docs/speculative.md "Sampled verification"): the same
    # workload at temperature > 0 with explicit per-request seeds, run
    # TWICE for determinism evidence. Low temperature keeps the filtered
    # target sharp, so prompt-lookup drafts (point-mass q) are accepted
    # with high probability — the accepted > 0 contract is robust, not a
    # fluke of a flat random-weights distribution.
    sampled_temp, sampled_top_p = 0.15, 0.95
    sampled = run_arm(draft_k, temperature=sampled_temp, top_p=sampled_top_p)
    sampled_again = run_arm(
        draft_k, temperature=sampled_temp, top_p=sampled_top_p
    )
    warmup_secs = time.perf_counter() - warmup_start
    identical = on['tokens'] == null['tokens']
    matches_decode = on['tokens'] == classic['tokens']
    sampled_deterministic = sampled['tokens'] == sampled_again['tokens']
    out = {
        f'{prefix}metric': 'speculative-decoding A/B',
        f'{prefix}tokens_identical': identical,
        f'{prefix}tokens_match_decode_path': matches_decode,
        f'{prefix}tok_per_s': on['throughput_tok_s'],
        f'{prefix}off_tok_per_s': classic['throughput_tok_s'],
        f'{prefix}nodraft_tok_per_s': null['throughput_tok_s'],
        f'{prefix}accept_rate': on['accept_rate'],
        f'{prefix}windows': on['spec_windows'],
        f'{prefix}draft_tokens': on['draft_tokens'],
        f'{prefix}accepted_tokens': on['accepted_tokens'],
        f'{prefix}sampled_tok_per_s': sampled['throughput_tok_s'],
        f'{prefix}sampled_accept_rate': sampled['accept_rate'],
        f'{prefix}sampled_accepted_tokens': sampled['accepted_tokens'],
        f'{prefix}sampled_windows': sampled['spec_windows'],
        f'{prefix}sampled_deterministic': sampled_deterministic,
        f'{prefix}sampled_temperature': sampled_temp,
        f'{prefix}sampled_top_p': sampled_top_p,
        f'{prefix}draft_k': draft_k,
        f'{prefix}elapsed_all_arms_s': round(warmup_secs, 1),
        f'{prefix}workload': _workload_fingerprint(
            {'prompts': [list(map(int, p)) for p in prompts],
             'budgets': budgets,
             'engine': {'max_num_seqs': max_num_seqs,
                        'num_blocks': num_blocks,
                        'draft_k': draft_k}}
        ),
        **_cache_fields(prefix, cache_before),
    }
    if not identical:
        out[f'{prefix}error'] = (
            'speculation on/off token mismatch inside the verify kernel '
            '— the acceptance/rollback identity contract is broken'
        )
    elif on['spec_windows'] == 0:
        # Without verify windows the spec arms silently degenerate to the
        # classic path and every assertion above passes vacuously.
        out[f'{prefix}error'] = (
            'no speculative verify windows ran — draft_k routing is '
            'broken or the workload never decoded'
        )
    elif not sampled_deterministic:
        out[f'{prefix}error'] = (
            'sampled spec arm is nondeterministic across identical '
            '(seed, schedule) runs — the counter-based PRNG contract '
            '(docs/speculative.md "Sampled verification") is broken'
        )
    elif sampled['accepted_tokens'] == 0:
        out[f'{prefix}error'] = (
            'sampled spec arm accepted zero draft tokens — rejection '
            'sampling is discarding every draft, so temperature > 0 '
            'requests get no speculative win'
        )
    if not matches_decode:
        # Expected occasionally in bf16 (near-tie rounding across two
        # compiled programs, see the stage docstring); never in fp32.
        out[f'{prefix}decode_path_note'] = (
            'spec stream diverged from the classic decode-scan stream: '
            'bf16 near-tie across kernels (docs/speculative.md), not an '
            'acceptance bug — tokens_identical is the contract assert'
        )
    if on['fallback_reason'] or classic['fallback_reason']:
        out[f'{prefix}attn_fallback_reason'] = (
            on['fallback_reason'] or classic['fallback_reason']
        )
    return out


def _stage_gen_kernel() -> dict:
    """Attention-kernel A/B (docs/serving.md "Attention kernel backends"):
    the SAME staggered greedy serving workload with ``attn_backend``
    pinned to 'xla', then to the fused ragged Pallas kernel ('pallas' on
    TPU; 'interpret' — the same kernel on the Pallas interpreter — for
    the CPU smoke).

    The contract this stage checks and records:

    - tok/s per arm (``gen_kernel_xla_tok_s`` /
      ``gen_kernel_pallas_tok_s``) and their ratio
      (``gen_kernel_speedup``) — the headline the ROADMAP's r5
      1101 tok/s isolated-window rate is measured against;
    - MEASURED MFU / bandwidth utilization per arm (mean of the
      per-window ``mfu_measured``/``bw_util_measured`` flight fields —
      ``compiled.cost_analysis()`` truth, docs/observability.md) next to
      the analytic roofline pair, so a kernel win shows up as measured
      bytes down with tokens/s up and the benchdiff gate can hold the
      trajectory;
    - greedy token agreement across arms (``tokens_identical``):
      guaranteed in fp32, evidence-not-assert in bf16 (two compiled
      programs may round a near-tied logit differently — the same
      boundary gen_spec documents);
    - a failed Pallas arm records ``gen_kernel_pallas_unavailable``
      (deliberately NOT an ``_error`` key — the kept XLA numbers still
      count as a completed stage) — the stage never zeroes the record
      because the fast path regressed.

    ``DISTLLM_BENCH_KERNEL=0`` skips the stage (default on).
    """
    import jax
    import numpy as np

    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.models import mistral
    from distllm_tpu.observability.flight import get_flight_recorder

    prefix = 'gen_kernel_'
    if os.environ.get('DISTLLM_BENCH_KERNEL', '1') in ('', '0'):
        return {f'{prefix}skipped': 'DISTLLM_BENCH_KERNEL=0'}
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        # head_dim is pinned to 128 (not hidden//heads = 32): the Mosaic
        # kernel rejects head_dim % 128 != 0, so without it the fast arm
        # could never run under DISTLLM_BENCH_SMALL on a TPU — and the
        # CPU interpret arm then smokes the exact TPU-eligible geometry.
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, head_dim=128, intermediate_size=512,
            dtype='bfloat16',
        )
        max_num_seqs, num_blocks = 4, 160
        n_prompts, prompt_lo, prompt_hi = 10, 8, 48
        out_lo, out_hi = 4, 24
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults
        max_num_seqs, num_blocks = 32, 712
        n_prompts, prompt_lo, prompt_hi = 64, 32, 192
        out_lo, out_hi = 16, 96

    # The fast arm: the real Mosaic kernel on TPU, the same kernel under
    # the Pallas interpreter on the CPU smoke (numerics + plumbing, no
    # perf claim — interpret lowers to plain XLA ops).
    fast_backend = 'interpret' if jax.default_backend() == 'cpu' else 'pallas'

    rng = np.random.default_rng(0)
    shared = list(rng.integers(1, model_cfg.vocab_size, size=32))
    prompts = []
    for i, n in enumerate(rng.integers(prompt_lo, prompt_hi, size=n_prompts)):
        tail = list(rng.integers(1, model_cfg.vocab_size, size=int(n)))
        prompts.append(shared + tail if i % 3 == 0 else tail)
    budgets = [int(n) for n in rng.integers(out_lo, out_hi, size=n_prompts)]

    def run_arm(backend: str) -> dict:
        engine_cfg = EngineConfig(
            block_size=16,
            num_blocks=num_blocks,
            max_num_seqs=max_num_seqs,
            max_model_len=512,
            decode_steps=16,
            pipeline_depth=2,
            sampling_top_window=64,
            enable_prefix_cache=True,
            prefill_chunk_tokens=256,
            attn_backend=backend,
        )

        class _Tok:
            eos_id = None

        from distllm_tpu.generate.engine.engine import LLMEngine

        engine = LLMEngine(
            model_cfg,
            mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
            _Tok(), engine_cfg, own_params=True,
        )
        try:
            engine.warmup()
            flight_before = len(get_flight_recorder().snapshot())
            roofline_before = engine.roofline_snapshot()
            rids = [
                engine.add_request(
                    p, SamplingParams(temperature=0.0, max_tokens=n)
                )
                for p, n in zip(prompts, budgets)
            ]
            start = time.perf_counter()
            seen: dict = {rid: [] for rid in rids}
            while engine.has_unfinished:
                for rid, tok in engine.step():
                    seen[rid].append(tok)
            elapsed = time.perf_counter() - start
            n_tokens = sum(len(v) for v in seen.values())
            # Per-window measured truth (compiled.cost_analysis() over
            # wall time; decode/spec fixed-shape dispatches only — see
            # engine._record_step) and the analytic roofline summary for
            # the measured interval.
            records = get_flight_recorder().snapshot()[flight_before:]
            measured_mfu = [
                r['mfu_measured'] for r in records if 'mfu_measured' in r
            ]
            measured_bw = [
                r['bw_util_measured']
                for r in records
                if 'bw_util_measured' in r
            ]
            roofline = engine.roofline_summary(baseline=roofline_before)
            decode_roofline = roofline.get('decode', {})
            arm = {
                'tokens': [seen[rid] for rid in rids],
                'tok_s': round(n_tokens / elapsed, 2),
                'resolved_backend': engine.telemetry['attn_backend'],
                'mfu_measured': (
                    round(float(np.mean(measured_mfu)), 5)
                    if measured_mfu else None
                ),
                'bw_util_measured': (
                    round(float(np.mean(measured_bw)), 5)
                    if measured_bw else None
                ),
                'mfu': decode_roofline.get('mfu'),
                'bw_util': decode_roofline.get('bw_util'),
            }
            return arm
        finally:
            engine.shutdown()

    cache_before = _cache_entries()
    t0 = time.perf_counter()
    xla = run_arm('xla')
    try:
        fast = run_arm(fast_backend)
        fast_error = None
    except Exception as exc:
        fast, fast_error = None, f'{fast_backend}: {exc!r}'[:400]
    elapsed_both = time.perf_counter() - t0

    out = {
        f'{prefix}metric': 'attention-kernel A/B',
        f'{prefix}backend': fast_backend,
        f'{prefix}xla_resolved_backend': xla['resolved_backend'],
        f'{prefix}xla_tok_s': xla['tok_s'],
        f'{prefix}xla_mfu_measured': xla['mfu_measured'],
        f'{prefix}xla_bw_util_measured': xla['bw_util_measured'],
        f'{prefix}xla_mfu': xla['mfu'],
        f'{prefix}xla_bw_util': xla['bw_util'],
        f'{prefix}elapsed_both_arms_s': round(elapsed_both, 1),
        f'{prefix}workload': _workload_fingerprint(
            {'prompts': [list(map(int, p)) for p in prompts],
             'budgets': budgets,
             'engine': {'max_num_seqs': max_num_seqs,
                        'num_blocks': num_blocks,
                        'prefill_chunk_tokens': 256}}
        ),
        **_cache_fields(prefix, cache_before),
    }
    if fast is not None:
        out.update({
            f'{prefix}pallas_tok_s': fast['tok_s'],
            f'{prefix}pallas_mfu_measured': fast['mfu_measured'],
            f'{prefix}pallas_bw_util_measured': fast['bw_util_measured'],
            f'{prefix}pallas_mfu': fast['mfu'],
            f'{prefix}pallas_bw_util': fast['bw_util'],
            f'{prefix}speedup': round(
                fast['tok_s'] / max(xla['tok_s'], 1e-9), 3
            ),
            f'{prefix}tokens_identical': fast['tokens'] == xla['tokens'],
            f'{prefix}resolved_backend': fast['resolved_backend'],
        })
        if fast['tokens'] != xla['tokens']:
            # bf16 near-tie rounding across two compiled programs is the
            # expected cause (fp32 identity is the test-tier assert,
            # tests/test_ragged_attention.py); still worth surfacing.
            out[f'{prefix}identity_note'] = (
                'token streams differ across kernels: expected only from '
                'bf16 near-tie rounding (fp32 identity is asserted in the '
                'fast test tier); investigate if widespread'
            )
    else:
        # NOT an '_error'-suffixed key: per the stage contract the XLA
        # numbers above still count as a completed stage
        # (_completed_stages excludes any fragment carrying *_error /
        # *_skipped keys), and a broken fast arm must truncate the A/B —
        # never zero the round's kernel record.
        out[f'{prefix}pallas_unavailable'] = fast_error
    return out


def _stage_gen_load() -> dict:
    """Open-loop load-generation stage (docs/observability.md): a
    deterministic seeded Poisson arrival stream with a warm/cold prefix
    mix, driven through ``distllm_tpu.generate.loadgen`` against a
    prefix-cached engine with serving-path attribution ON.

    The contract this stage checks and records:

    - TTFT / TPOT / queue-wait p50/p95/p99 (``Histogram.quantile``
      estimates over the request-lifecycle histogram deltas), goodput
      under the configured TTFT SLO, and per-window throughput
      percentiles;
    - per-window-kind MFU and weight-stream bandwidth utilization from
      the engine's roofline accumulators (``roofline_summary``);
    - at least one warm-prefix cache hit (the warm sessions share
      block-aligned prefixes — zero hits means the mix is broken);
    - the SAME workload replayed with attribution flipped OFF emits
      BIT-IDENTICAL tokens (attribution is pure host-side bookkeeping;
      a mismatch is an error in the fragment).

    ``DISTLLM_BENCH_LOAD=0`` skips the stage (chip runs that want the
    deadline for the heavier stages).
    """
    import jax

    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.generate.loadgen import (
        LoadgenConfig,
        build_workload,
        run_loadgen,
    )
    from distllm_tpu.models import mistral

    prefix = 'gen_load_'
    if os.environ.get('DISTLLM_BENCH_LOAD', '1') in ('', '0'):
        return {f'{prefix}skipped': 'DISTLLM_BENCH_LOAD=0'}
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
        )
        # max_model_len 128 keeps the CPU-smoke compile ladder at four
        # prefill buckets — warmup dominates this stage's fast-tier cost.
        max_num_seqs, num_blocks, max_model_len, decode_steps = 4, 160, 128, 4
        load_cfg = LoadgenConfig(
            seed=0, num_requests=24, rate_rps=12.0, num_sessions=3,
            warm_fraction=0.6, prefix_tokens=32, prompt_tokens=(8, 40),
            output_tokens=(4, 16), vocab_size=model_cfg.vocab_size,
        )
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults
        max_num_seqs, num_blocks, max_model_len, decode_steps = (
            32, 712, 512, 16
        )
        load_cfg = LoadgenConfig(
            seed=0, num_requests=256, rate_rps=16.0, num_sessions=16,
            warm_fraction=0.6, prefix_tokens=64, prompt_tokens=(32, 192),
            output_tokens=(16, 96), vocab_size=model_cfg.vocab_size,
        )
    engine_cfg = EngineConfig(
        block_size=16,
        num_blocks=num_blocks,
        max_num_seqs=max_num_seqs,
        max_model_len=max_model_len,
        decode_steps=decode_steps,
        pipeline_depth=2,
        sampling_top_window=64,
        enable_prefix_cache=True,
        ttft_slo_s=2.0,
        attribution=True,
    )
    cache_before = _cache_entries()
    warmup_start = time.perf_counter()
    engine, fallback_reason = _build_engine_with_fallback(
        model_cfg,
        engine_cfg,
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
        [[1, 2, 3]],
        SamplingParams(temperature=0.0, max_tokens=2),
    )
    warmup_secs = time.perf_counter() - warmup_start

    workload = build_workload(load_cfg)
    on = run_loadgen(engine, workload)
    # Attribution OFF replay of the SAME workload on the SAME engine
    # (greedy → the prefix cache being warm now cannot change tokens —
    # the engine's cache-on/off identity guarantee): attribution must be
    # pure host-side bookkeeping.
    engine.attribution = False
    off = run_loadgen(engine, workload)
    engine.attribution = True
    identical = on.tokens_by_request == off.tokens_by_request

    out = {
        f'{prefix}metric': 'open-loop load generation',
        **on.to_fragment(prefix),
        f'{prefix}tokens_identical': identical,
        f'{prefix}attribution_off_tok_s': round(off.achieved_tok_s, 2),
        f'{prefix}slo_s': engine_cfg.ttft_slo_s,
        f'{prefix}attn_backend': engine.config.attn_backend,
        f'{prefix}warmup_secs': round(warmup_secs, 1),
        f'{prefix}device': str(jax.devices()[0].device_kind),
        f'{prefix}workload': _workload_fingerprint(
            {
                'arrivals': [
                    [a.at_s, list(a.prompt_ids), a.max_tokens, a.session]
                    for a in workload
                ],
                'engine': {'max_num_seqs': max_num_seqs,
                           'num_blocks': num_blocks,
                           'decode_steps': decode_steps},
            }
        ),
        **_cache_fields(prefix, cache_before),
    }
    if not identical:
        out[f'{prefix}error'] = (
            'attribution on/off token mismatch — attribution must be '
            'pure host-side bookkeeping'
        )
    elif on.warm_prefix_hit_tokens <= 0:
        out[f'{prefix}error'] = (
            'no warm-prefix cache hits — the warm/cold session mix is '
            'not exercising the prefix cache'
        )
    if fallback_reason:
        out[f'{prefix}attn_fallback_reason'] = fallback_reason
    return out


def _stage_gen_tier() -> dict:
    """Host-RAM KV tier stage (docs/prefix_caching.md "Tier hierarchy"):
    the loadgen's warm-session workload driven at a paged pool sized
    BELOW the warm working set, so HBM-tier eviction is constant and the
    warm prefixes only survive by spilling to the host tier.

    Two arms over the identical workload:

    - **tier on** (``host_kv_tier_bytes`` generous): evicted prefix
      blocks spill device→host and promote back on re-arrival — records
      warm-session TTFT, spill/promotion counts, and promotion overlap
      efficiency (1 - blocking wait / promotion span);
    - **tier off**: eviction drops KV, every warm repeat whose prefix
      was evicted pays full prefill — the cold TTFT baseline.

    The contract checked into the fragment: warm TTFT (tier on)
    measurably below the tier-off cold TTFT, ≥1 recorded spill and ≥1
    promotion, and tier on/off BIT-IDENTICAL tokens (greedy fp32 in the
    smoke tier — promotion round-trips KV byte-exactly).
    ``DISTLLM_BENCH_TIER=0`` skips the stage.
    """
    import jax

    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.generate.loadgen import (
        LoadgenConfig,
        build_workload,
        run_loadgen,
    )
    from distllm_tpu.models import mistral

    prefix = 'gen_tier_'
    if os.environ.get('DISTLLM_BENCH_TIER', '1') in ('', '0'):
        return {f'{prefix}skipped': 'DISTLLM_BENCH_TIER=0'}
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        # fp32 so the tier on/off identity check is bit-exact across the
        # two separately compiled arms (the acceptance contract); tiny
        # dims keep the two warmups in the fast tier.
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='float32',
        )
        # 47 usable blocks vs a warm working set of 6 sessions x 9
        # prefix blocks (54) + per-request tails + 3 running rows x ~11
        # blocks: session prefixes cannot all stay resident, so warm
        # re-arrivals must spill AND promote, by construction. The
        # 144-token prefix keeps the promotion-vs-reprefill margin
        # visible even at CPU-smoke model dims (a promoted block moves
        # ~linear bytes; re-prefilling it pays the padded 256-bucket
        # dense dispatch).
        max_num_seqs, num_blocks, max_model_len, decode_steps = 3, 48, 256, 4
        load_cfg = LoadgenConfig(
            seed=0, num_requests=32, rate_rps=12.0, num_sessions=6,
            warm_fraction=0.75, prefix_tokens=144, prompt_tokens=(8, 16),
            output_tokens=(4, 10), vocab_size=model_cfg.vocab_size,
            cache_blocks=num_blocks,
        )
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults
        # Pool ~1/2 the warm working set (16 sessions x 8 prefix blocks
        # + 32 rows x ~24 blocks): chip-scale tier churn.
        max_num_seqs, num_blocks, max_model_len, decode_steps = (
            32, 640, 512, 16
        )
        load_cfg = LoadgenConfig(
            seed=0, num_requests=192, rate_rps=16.0, num_sessions=16,
            warm_fraction=0.65, prefix_tokens=128, prompt_tokens=(32, 160),
            output_tokens=(16, 64), vocab_size=model_cfg.vocab_size,
            cache_blocks=num_blocks,
        )
    workload = build_workload(load_cfg)
    # Warm repeats: warm-session arrivals AFTER the session's first
    # request — the requests whose TTFT the tier exists to shrink.
    seen_sessions: set = set()
    warm_repeat_idx: list[int] = []
    for i, arrival in enumerate(workload):
        if arrival.session is None:
            continue
        if arrival.session in seen_sessions:
            warm_repeat_idx.append(i)
        seen_sessions.add(arrival.session)

    cache_before = _cache_entries()
    warmup_total = 0.0
    reports = {}
    tier: dict = {}
    fallback_reason = None
    for arm, tier_bytes in (('on', 256 << 20), ('off', 0)):
        engine_cfg = EngineConfig(
            block_size=16,
            num_blocks=load_cfg.cache_blocks or num_blocks,
            max_num_seqs=max_num_seqs,
            max_model_len=max_model_len,
            decode_steps=decode_steps,
            pipeline_depth=2,
            sampling_top_window=64,
            enable_prefix_cache=True,
            host_kv_tier_bytes=tier_bytes,
            attribution=True,
        )
        warmup_start = time.perf_counter()
        engine, reason = _build_engine_with_fallback(
            model_cfg,
            engine_cfg,
            lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
            [[1, 2, 3]],
            SamplingParams(temperature=0.0, max_tokens=2),
        )
        warmup_total += time.perf_counter() - warmup_start
        fallback_reason = fallback_reason or reason
        try:
            reports[arm] = run_loadgen(engine, workload)
            if arm == 'on':
                tier = engine.tier_summary()
        finally:
            # Each arm's weights + KV pool leave the chip before the next
            # arm builds — two resident 7B engines would OOM HBM.
            engine.shutdown()

    on, off = reports['on'], reports['off']
    identical = on.tokens_by_request == off.tokens_by_request

    def _mean_ttft(report) -> float | None:
        vals = [
            report.ttft_by_request[i]
            for i in warm_repeat_idx
            if i < len(report.ttft_by_request)
            and report.ttft_by_request[i] is not None
        ]
        return sum(vals) / len(vals) if vals else None

    warm_ttft = _mean_ttft(on)
    cold_ttft = _mean_ttft(off)
    prompt_tokens = sum(len(a.prompt_ids) for a in workload)
    out = {
        f'{prefix}metric': 'warm-TTFT at cache sizes >> HBM (KV tier)',
        f'{prefix}warm_ttft_s': round(warm_ttft, 6) if warm_ttft else None,
        f'{prefix}cold_ttft_s': round(cold_ttft, 6) if cold_ttft else None,
        f'{prefix}warm_ttft_speedup': (
            round(cold_ttft / warm_ttft, 3)
            if warm_ttft and cold_ttft else None
        ),
        f'{prefix}warm_repeats': len(warm_repeat_idx),
        f'{prefix}tok_s': round(on.achieved_tok_s, 2),
        f'{prefix}tier_off_tok_s': round(off.achieved_tok_s, 2),
        f'{prefix}spills': tier.get('spills'),
        f'{prefix}spilled_blocks': tier.get('spilled_blocks'),
        f'{prefix}promotions': tier.get('promotions'),
        f'{prefix}promoted_blocks': tier.get('promoted_blocks'),
        f'{prefix}promotion_overlap': tier.get('promotion_overlap'),
        f'{prefix}host_blocks': tier.get('host_blocks'),
        f'{prefix}host_bytes': tier.get('host_bytes'),
        f'{prefix}hit_rate': (
            round(on.warm_prefix_hit_tokens / prompt_tokens, 4)
            if prompt_tokens else None
        ),
        f'{prefix}tokens_identical': identical,
        f'{prefix}pool_blocks': load_cfg.cache_blocks or num_blocks,
        f'{prefix}warmup_secs': round(warmup_total, 1),
        f'{prefix}device': str(jax.devices()[0].device_kind),
        f'{prefix}workload': _workload_fingerprint(
            {
                'arrivals': [
                    [a.at_s, list(a.prompt_ids), a.max_tokens, a.session]
                    for a in workload
                ],
                'engine': {'max_num_seqs': max_num_seqs,
                           'num_blocks': num_blocks,
                           'decode_steps': decode_steps},
            }
        ),
        **_cache_fields(prefix, cache_before),
    }
    if not identical:
        out[f'{prefix}error'] = (
            'tier on/off token mismatch — spill→promote round-trips must '
            'be bit-exact against never-evicted KV'
        )
    elif not tier.get('spills') or not tier.get('promotions'):
        out[f'{prefix}error'] = (
            'no spill/promotion recorded — the pool is not below the '
            'warm working set, the tier never engaged'
        )
    elif warm_ttft is None or cold_ttft is None or warm_ttft >= cold_ttft:
        out[f'{prefix}error'] = (
            f'warm TTFT {warm_ttft} not below tier-off cold TTFT '
            f'{cold_ttft} — promotion is not beating re-prefill'
        )
    if fallback_reason:
        out[f'{prefix}attn_fallback_reason'] = fallback_reason
    return out


def _stage_gen_router() -> dict:
    """Multi-replica router stage (docs/routing.md): in-process chat_server
    replicas behind the prefix-affinity router, proven against a
    round-robin control plus a replica-kill failover arm and a direct
    peer-KV-handoff arm.

    Replicas always run at smoke-scale model dims — N engines share ONE
    process and ONE accelerator here, so the stage measures the routing
    and tier deltas (which are dimension-independent), never model FLOPs;
    the non-small tier only widens the workload.

    Four arms:

    - **round_robin** (control): every warm session's prefix lands on
      alternating replicas, so each replica re-prefills (and, with the
      pool below the union working set, churns) prefixes a peer already
      holds;
    - **prefix_affinity**: the router learns residency from the
      ``X-Distllm-Prefix-Digest`` response headers and pins each session
      to one replica — warm-repeat TTFT must beat the control
      (``router_warm_ttft_speedup > 1.0``);
    - **failover**: one of three replicas is killed mid-run with health
      probes effectively off — discovery happens on the proxy path, the
      caught request retries ONCE on a healthy peer (``retried >= 1``),
      goodput stays > 0, zero quarantines, and every survivor answer is
      token-identical to the control arm's answer for the same arrival
      (greedy fp32, same weights: content depends only on the prompt);
    - **peer handoff** (no HTTP): engine A spills a warm prefix to its
      host tier and serves it over the fabric
      (``peer_kv_serve_endpoint``); engine B, cold but configured with
      ``peer_kv_endpoints``, adopts A's blocks like a disk promotion
      (``>= 1`` peer fetch) and must emit tokens bit-identical to a
      peer-less control engine C.

    Per-replica flight rings from the affinity arm are dumped and merged
    into one Perfetto trace (``aggregate.write_combined_perfetto`` — the
    replica-id process naming this PR adds). ``DISTLLM_BENCH_ROUTER=0``
    skips the stage.
    """
    prefix = 'gen_router_'
    if os.environ.get('DISTLLM_BENCH_ROUTER', '1') in ('', '0'):
        return {f'{prefix}skipped': 'DISTLLM_BENCH_ROUTER=0'}

    import socket
    import threading
    import zlib

    import jax
    import requests
    from aiohttp import web

    from distllm_tpu.chat import ChatAppConfig
    from distllm_tpu.chat_server import build_app
    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.generate.loadgen import (
        LoadgenConfig,
        build_workload,
        run_http_loadgen,
    )
    from distllm_tpu.models import mistral
    from distllm_tpu.observability import instruments
    from distllm_tpu.observability.aggregate import write_combined_perfetto
    from distllm_tpu.observability.flight import FlightRecorder
    from distllm_tpu.observability.metrics import quantile_from_cumulative
    from distllm_tpu.router import RouterConfig, build_router_app

    # N replicas in one process: one metric-history sampler per app would
    # stack 5+ background threads for nothing this stage reads.
    os.environ['DISTLLM_HISTORY_S'] = '0'

    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    # fp32 everywhere: the failover and peer arms gate on token IDENTITY
    # across separately built engines.
    model_cfg = mistral.MistralConfig(
        vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
        num_kv_heads=4, intermediate_size=512, dtype='float32',
    )
    max_num_seqs, num_blocks, max_model_len, decode_steps = 3, 48, 256, 4
    # Pool arithmetic mirrors gen_tier: 6 sessions x 9 shared full prefix
    # blocks (the 'user:'-prefixed 144-id prefix) = 54 > 47 usable, so one
    # replica holding ALL sessions (round-robin) churns; an affinity
    # partition of ~3 sessions/replica (27 blocks) stays resident. The
    # arrival rate is low enough that responses (and therefore learned
    # digests) land before most warm repeats fire — affinity needs the
    # headers to have come back.
    load_cfg = LoadgenConfig(
        seed=0,
        num_requests=32 if small else 96,
        rate_rps=2.0 if small else 4.0,
        num_sessions=6, warm_fraction=0.75, prefix_tokens=144,
        prompt_tokens=(8, 16), output_tokens=(4, 10),
        vocab_size=model_cfg.vocab_size,
    )
    workload = build_workload(load_cfg)
    seen_sessions: set = set()
    warm_repeat_idx: list[int] = []
    for i, arrival in enumerate(workload):
        if arrival.session is None:
            continue
        if arrival.session in seen_sessions:
            warm_repeat_idx.append(i)
        seen_sessions.add(arrival.session)
    last_at = max(a.at_s for a in workload)

    class _EngineChatGenerator:
        """Replica backend: deterministic word-hash tokenizer over the
        rendered chat prompt + greedy engine decode. Exposes ``.engine``
        for the ``/loadinfo`` probe. Two replicas with the same weights
        answer any prompt identically — the failover identity gate."""

        def __init__(self, engine, vocab_size: int, max_tokens: int = 8):
            self.engine = engine
            self.vocab_size = vocab_size
            self.max_tokens = max_tokens

        def _ids(self, prompt: str) -> list[int]:
            ids = []
            for word in prompt.split():
                if word.isdigit():
                    ids.append(int(word) % (self.vocab_size - 2) + 1)
                else:
                    ids.append(
                        zlib.crc32(word.encode()) % (self.vocab_size - 1) + 1
                    )
            return ids

        def generate(self, prompts: list[str]) -> list[str]:
            outs = self.engine.generate_ids(
                [self._ids(p) for p in prompts],
                SamplingParams(temperature=0.0,
                               max_tokens=self.max_tokens),
            )
            return [' '.join(str(t) for t in out) for out in outs]

    from typing import ClassVar

    class _ReplicaChatConfig(ChatAppConfig):
        """ChatAppConfig whose generator is a pre-built in-process engine
        wrapper (keyed off-model: pydantic configs must stay YAML-shaped,
        a live engine is not a field)."""

        replica_key: int = 0
        _live_generators: ClassVar[dict] = {}

        def build_generator(self):
            return type(self)._live_generators[self.replica_key]

    def _serve_app(app) -> tuple[str, 'callable']:
        """Boot an aiohttp app on a free port in a daemon thread; returns
        ``(base_url, idempotent_stop)`` (tests/test_chat.py pattern)."""
        with socket.socket() as s:
            s.bind(('127.0.0.1', 0))
            port = s.getsockname()[1]
        holder: dict = {}

        def run():
            import asyncio

            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            holder['loop'] = loop
            # Short shutdown grace: the failover arm kills a replica
            # mid-run and needs the port gone NOW, not in 60 s.
            runner = web.AppRunner(app, shutdown_timeout=1.0)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, '127.0.0.1', port)
            loop.run_until_complete(site.start())
            holder['runner'] = runner
            loop.run_forever()

        thread = threading.Thread(target=run, daemon=True)
        thread.start()
        for _ in range(100):
            try:
                requests.get(f'http://127.0.0.1:{port}/health', timeout=1)
                break
            except Exception:
                time.sleep(0.05)

        done = {'stopped': False}

        def stop():
            if done['stopped']:
                return
            done['stopped'] = True
            loop = holder['loop']

            async def _shutdown():
                await holder['runner'].cleanup()
                loop.stop()

            loop.call_soon_threadsafe(
                lambda: loop.create_task(_shutdown())
            )
            thread.join(timeout=10)

        return f'http://127.0.0.1:{port}', stop

    replica_counter = {'next': 0}

    def _build_replica(engine_cfg: EngineConfig):
        """One replica: fresh engine (+ its own flight ring) behind its
        own chat_server app. Returns (engine, url, stop, reason)."""
        engine, reason = _build_engine_with_fallback(
            model_cfg,
            engine_cfg,
            lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
            [[1, 2, 3]],
            SamplingParams(temperature=0.0, max_tokens=2),
        )
        engine.flight = FlightRecorder()
        key = replica_counter['next']
        replica_counter['next'] += 1
        _ReplicaChatConfig._live_generators[key] = _EngineChatGenerator(
            engine, model_cfg.vocab_size
        )
        url, stop = _serve_app(
            build_app(_ReplicaChatConfig(replica_key=key))
        )
        return engine, url, stop, reason

    def _replica_engine_cfg() -> EngineConfig:
        return EngineConfig(
            block_size=16, num_blocks=num_blocks,
            max_num_seqs=max_num_seqs, max_model_len=max_model_len,
            decode_steps=decode_steps, pipeline_depth=2,
            sampling_top_window=64, enable_prefix_cache=True,
            attribution=True,
        )

    def _counter_total(counter) -> float:
        return sum(child.value for _, child in counter.children())

    bundle = _bundle_dir('gen_router')
    os.makedirs(bundle, exist_ok=True)
    cache_before = _cache_entries()
    warmup_total = 0.0
    fallback_reason = None
    arm_stats: dict[str, dict] = {}
    flight_paths: list[str] = []

    def _run_router_arm(
        arm: str, policy: str, n_replicas: int, kill_idx: int | None = None
    ) -> dict:
        nonlocal warmup_total, fallback_reason
        engines, stops, urls = [], [], []
        warmup_start = time.perf_counter()
        try:
            for _ in range(n_replicas):
                engine, url, stop, reason = _build_replica(
                    _replica_engine_cfg()
                )
                engines.append(engine)
                urls.append(url)
                stops.append(stop)
                fallback_reason = fallback_reason or reason
            warmup_total += time.perf_counter() - warmup_start
            router_cfg = RouterConfig(
                replicas=tuple(urls),
                policy=policy,
                loadinfo_ttl_s=0.05,
                # Failover: probes effectively off, so the kill is
                # DISCOVERED on the proxy path (the retry contract),
                # not masked by a lucky health tick.
                health_interval_s=30.0 if kill_idx is not None else 0.5,
                request_timeout_s=60.0,
            )
            router_url, router_stop = _serve_app(
                build_router_app(router_cfg)
            )
            stops.append(router_stop)
            decisions_before = {
                k: child.value
                for k, child in instruments.ROUTER_REQUESTS.children()
            }
            counters_before = {
                'retries': instruments.ROUTER_RETRIES.value,
                'quarantined': _counter_total(
                    instruments.RESILIENCE_QUARANTINED
                ),
            }
            tpot_before = instruments.REQUEST_TPOT.cumulative_counts()
            killer = None
            if kill_idx is not None:
                killer = threading.Timer(
                    max(0.5, 0.35 * last_at), stops[kill_idx]
                )
                killer.start()
            try:
                report = run_http_loadgen(
                    router_url, workload, slo_s=0.0, timeout_s=60.0
                )
            finally:
                if killer is not None:
                    killer.cancel()
            tpot_delta = [
                after - before
                for after, before in zip(
                    instruments.REQUEST_TPOT.cumulative_counts(),
                    tpot_before,
                )
            ]
            warm_ttfts = [
                report.ttft_by_request[i]
                for i in warm_repeat_idx
                if i < len(report.ttft_by_request)
                and report.ttft_by_request[i] is not None
                and report.statuses[i] == 200
            ]
            if arm == 'prefix_affinity':
                for r, engine in enumerate(engines):
                    path = os.path.join(bundle, f'replica-{r}')
                    os.makedirs(path, exist_ok=True)
                    path = os.path.join(path, 'flight.jsonl')
                    engine.flight.dump_jsonl(path)
                    flight_paths.append(path)
            return {
                'report': report,
                'warm_ttft': (
                    sum(warm_ttfts) / len(warm_ttfts)
                    if warm_ttfts else None
                ),
                'tpot': {
                    f'p{q}': round(
                        quantile_from_cumulative(
                            instruments.REQUEST_TPOT.buckets,
                            tpot_delta, q / 100.0,
                        ) or 0.0, 6,
                    )
                    for q in (50, 95, 99)
                } if tpot_delta and tpot_delta[-1] > 0 else {},
                'decisions': {
                    '/'.join(k): round(
                        child.value - decisions_before.get(k, 0.0)
                    )
                    for k, child in
                    instruments.ROUTER_REQUESTS.children()
                    if child.value > decisions_before.get(k, 0.0)
                },
                'retries_delta': (
                    instruments.ROUTER_RETRIES.value
                    - counters_before['retries']
                ),
                'quarantined_delta': (
                    _counter_total(instruments.RESILIENCE_QUARANTINED)
                    - counters_before['quarantined']
                ),
            }
        finally:
            for stop in stops:
                stop()
            for engine in engines:
                engine.shutdown()

    arm_stats['round_robin'] = _run_router_arm('round_robin',
                                               'round_robin', 2)
    arm_stats['prefix_affinity'] = _run_router_arm('prefix_affinity',
                                                   'prefix_affinity', 2)
    arm_stats['failover'] = _run_router_arm('failover', 'round_robin', 3,
                                            kill_idx=0)

    # ------------------------------------------------ peer handoff arm
    # Direct engines, no HTTP: A spills a warm prefix to its host tier
    # and serves it over the fabric; B adopts it as a peer promotion; C
    # is the cold control the tokens must match bit-for-bit.
    peer_prompt = [1 + (i * 7) % (model_cfg.vocab_size - 8)
                   for i in range(150)]
    junk_prompts = [
        [2 + (j * 997 + i * 13) % (model_cfg.vocab_size - 8)
         for i in range(150)]
        for j in range(6)
    ]
    peer_params = SamplingParams(temperature=0.0, max_tokens=8)
    peer_hits_before = instruments.PREFIX_TIER_HITS.labels(
        tier='peer'
    ).value

    def _peer_engine_cfg(**overrides) -> EngineConfig:
        cfg = _replica_engine_cfg().model_copy(
            update={'host_kv_tier_bytes': 64 << 20, **overrides}
        )
        return cfg

    warmup_start = time.perf_counter()
    engine_a, reason = _build_engine_with_fallback(
        model_cfg,
        _peer_engine_cfg(peer_kv_serve_endpoint='tcp://127.0.0.1:0'),
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
        [[1, 2, 3]],
        SamplingParams(temperature=0.0, max_tokens=2),
    )
    fallback_reason = fallback_reason or reason
    peer_summary: dict = {}
    try:
        engine_a.generate_ids([peer_prompt], peer_params)
        for junk in junk_prompts:
            engine_a.generate_ids([junk], peer_params)
        spills_a = engine_a.tier_summary().get('spills', 0)

        engine_b, reason = _build_engine_with_fallback(
            model_cfg,
            _peer_engine_cfg(
                peer_kv_endpoints=(engine_a.peer_kv_endpoint,)
            ),
            lambda: mistral.init_on_device(
                jax.random.PRNGKey(0), model_cfg
            ),
            [[1, 2, 3]],
            SamplingParams(temperature=0.0, max_tokens=2),
        )
        fallback_reason = fallback_reason or reason
        try:
            tokens_b = engine_b.generate_ids([peer_prompt], peer_params)
            peer_summary = {
                **engine_b.tier_summary(),
                'spills_a': spills_a,
                'served_blocks_a': engine_a.tier_summary().get(
                    'peer_served_blocks', 0
                ),
            }
        finally:
            engine_b.shutdown()
    finally:
        engine_a.shutdown()

    engine_c, reason = _build_engine_with_fallback(
        model_cfg,
        _peer_engine_cfg(),
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
        [[1, 2, 3]],
        SamplingParams(temperature=0.0, max_tokens=2),
    )
    fallback_reason = fallback_reason or reason
    try:
        tokens_c = engine_c.generate_ids([peer_prompt], peer_params)
    finally:
        engine_c.shutdown()
    warmup_total += time.perf_counter() - warmup_start
    peer_hits = (
        instruments.PREFIX_TIER_HITS.labels(tier='peer').value
        - peer_hits_before
    )
    peer_identical = tokens_b == tokens_c

    # -------------------------------------------------- merged Perfetto
    perfetto_path = os.path.join(bundle, 'combined_perfetto.json')
    perfetto_inputs = write_combined_perfetto(flight_paths, perfetto_path)

    rr, aff, failover = (
        arm_stats['round_robin'],
        arm_stats['prefix_affinity'],
        arm_stats['failover'],
    )
    speedup = (
        round(rr['warm_ttft'] / aff['warm_ttft'], 3)
        if rr['warm_ttft'] and aff['warm_ttft'] else None
    )
    # Survivor identity: every failover 200 must carry the SAME content
    # the control arm produced for that arrival — greedy fp32 engines
    # built from one PRNG key answer by prompt alone, so a kill must not
    # perturb a single survivor token.
    survivor_identical = all(
        content == rr['report'].contents[i]
        for i, content in enumerate(failover['report'].contents)
        if failover['report'].statuses[i] == 200
        and rr['report'].statuses[i] == 200
    )

    out = {
        f'{prefix}metric': (
            'warm-repeat TTFT, prefix-affinity routing vs round-robin '
            '(2 replicas)'
        ),
        f'{prefix}router_warm_ttft_speedup': speedup,
        f'{prefix}affinity_warm_ttft_s': (
            round(aff['warm_ttft'], 6) if aff['warm_ttft'] else None
        ),
        f'{prefix}rr_warm_ttft_s': (
            round(rr['warm_ttft'], 6) if rr['warm_ttft'] else None
        ),
        f'{prefix}warm_repeats': len(warm_repeat_idx),
        f'{prefix}failover_goodput': round(
            failover['report'].goodput_rps, 3
        ),
        f'{prefix}failover_retried': failover['report'].retried,
        f'{prefix}failover_router_retries': round(
            failover['retries_delta']
        ),
        f'{prefix}failover_errors': failover['report'].errors,
        f'{prefix}failover_quarantines': round(
            failover['quarantined_delta']
        ),
        f'{prefix}failover_survivor_tokens_identical': survivor_identical,
        f'{prefix}peer_hits': round(peer_hits),
        f'{prefix}peer_fetched_blocks': peer_summary.get(
            'peer_fetched_blocks'
        ),
        f'{prefix}peer_fetched_bytes': peer_summary.get(
            'peer_fetched_bytes'
        ),
        f'{prefix}peer_served_blocks': peer_summary.get(
            'served_blocks_a'
        ),
        f'{prefix}peer_spills': peer_summary.get('spills_a'),
        f'{prefix}peer_tokens_identical': peer_identical,
        f'{prefix}perfetto_inputs': perfetto_inputs,
        f'{prefix}perfetto_path': perfetto_path,
        f'{prefix}workload': _workload_fingerprint(
            {
                'arrivals': [
                    [a.at_s, list(a.prompt_ids), a.max_tokens, a.session]
                    for a in workload
                ],
                'engine': {'max_num_seqs': max_num_seqs,
                           'num_blocks': num_blocks,
                           'decode_steps': decode_steps},
            }
        ),
        f'{prefix}warmup_secs': round(warmup_total, 1),
        f'{prefix}device': str(jax.devices()[0].device_kind),
        **_cache_fields(prefix, cache_before),
    }
    for arm in ('round_robin', 'prefix_affinity', 'failover'):
        stats = arm_stats[arm]
        report = stats['report']
        tag = {'round_robin': 'rr', 'prefix_affinity': 'affinity',
               'failover': 'failover'}[arm]
        out[f'{prefix}{tag}_ok'] = report.ok
        out[f'{prefix}{tag}_goodput_rps'] = round(report.goodput_rps, 3)
        out[f'{prefix}{tag}_decisions'] = stats['decisions']
        for key, value in report.percentiles.items():
            out[f'{prefix}{tag}_{key}'] = (
                round(value, 6) if value is not None else None
            )
        for key, value in stats['tpot'].items():
            out[f'{prefix}{tag}_tpot_{key}'] = value

    if speedup is None or speedup <= 1.0:
        out[f'{prefix}error'] = (
            f'affinity warm TTFT speedup {speedup} not > 1.0 over '
            'round-robin — digest learning is not concentrating sessions'
        )
    elif failover['report'].retried < 1 or (
        failover['report'].goodput_rps <= 0
    ):
        out[f'{prefix}error'] = (
            f'failover arm retried={failover["report"].retried} '
            f'goodput={failover["report"].goodput_rps} — the kill was '
            'not absorbed by the retry-once contract'
        )
    elif failover['quarantined_delta'] or not survivor_identical:
        out[f'{prefix}error'] = (
            'failover perturbed the survivors '
            f'(quarantines={failover["quarantined_delta"]}, '
            f'identical={survivor_identical}) — a dead peer must cost '
            'its own in-flight requests at most'
        )
    elif peer_hits < 1 or not peer_summary.get('peer_fetched_blocks'):
        out[f'{prefix}error'] = (
            'no peer-tier hit recorded — the spilled prefix never '
            'crossed the fabric (check spills_a and the tier walk)'
        )
    elif not peer_identical:
        out[f'{prefix}error'] = (
            'peer-adopted tokens differ from the cold control — the '
            '.kvblock payload did not round-trip byte-exactly'
        )
    if fallback_reason:
        out[f'{prefix}attn_fallback_reason'] = fallback_reason
    return out


def _stage_gen_chaos() -> dict:
    """Chaos serving stage (docs/resilience.md): the open-loop Poisson
    loadgen driven through a DETERMINISTIC fault schedule, gating that the
    resilience layer actually survives what it claims to.

    Three arms on one engine:

    - **clean** (cold cache): the fault-free baseline token streams;
    - **chaos** (same workload, faults armed): dispatch raises, a window
      stall, and an injected scheduler exhaustion fire on a fixed call
      schedule while the loadgen keeps offering load — records
      goodput-under-fault, recovery count, retries, and quarantines;
    - **overload** (denser schedule, admission control ON with a tight
      SLO): shed rate + Retry-After behavior, informational by design
      (shed volume is offered-load policy, not quality).

    The contract checked into the fragment: every armed fault fired,
    ≥1 recovery, zero quarantines (the schedule is survivable by
    construction), nonzero goodput while faults were firing, and chaos
    tokens BIT-IDENTICAL to the clean arm (greedy fp32 in the smoke
    tier — recovery must replay, not approximate).
    ``DISTLLM_BENCH_CHAOS=0`` skips the stage.
    """
    import jax

    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.generate.loadgen import (
        LoadgenConfig,
        build_workload,
        run_loadgen,
    )
    from distllm_tpu.models import mistral
    from distllm_tpu.resilience import get_fault_injector

    prefix = 'gen_chaos_'
    if os.environ.get('DISTLLM_BENCH_CHAOS', '1') in ('', '0'):
        return {f'{prefix}skipped': 'DISTLLM_BENCH_CHAOS=0'}
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        # fp32 so the chaos/clean identity check is bit-exact (recovery
        # re-dispatches must replay the same stream); tiny dims keep the
        # single warmup in the fast tier.
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='float32',
        )
        max_num_seqs, num_blocks, max_model_len, decode_steps = 4, 160, 128, 4
        load_cfg = LoadgenConfig(
            seed=0, num_requests=24, rate_rps=16.0, num_sessions=3,
            warm_fraction=0.5, prefix_tokens=32, prompt_tokens=(8, 32),
            output_tokens=(4, 12), vocab_size=model_cfg.vocab_size,
        )
        overload_cfg = LoadgenConfig(
            seed=1, num_requests=32, rate_rps=200.0, num_sessions=3,
            warm_fraction=0.5, prefix_tokens=32, prompt_tokens=(8, 32),
            output_tokens=(4, 12), vocab_size=model_cfg.vocab_size,
        )
        slo_s, overload_slo_s, deadline_s = 2.0, 0.02, 60.0
        fault_schedule = (
            ('dispatch', dict(times=2, after=4)),
            ('slow_window', dict(times=2, delay_s=0.02, after=2)),
            ('sched_exhausted', dict(times=1, after=10)),
        )
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults
        max_num_seqs, num_blocks, max_model_len, decode_steps = (
            32, 712, 512, 16
        )
        load_cfg = LoadgenConfig(
            seed=0, num_requests=192, rate_rps=16.0, num_sessions=16,
            warm_fraction=0.6, prefix_tokens=64, prompt_tokens=(32, 192),
            output_tokens=(16, 96), vocab_size=model_cfg.vocab_size,
        )
        overload_cfg = LoadgenConfig(
            seed=1, num_requests=128, rate_rps=256.0, num_sessions=16,
            warm_fraction=0.6, prefix_tokens=64, prompt_tokens=(32, 192),
            output_tokens=(16, 64), vocab_size=model_cfg.vocab_size,
        )
        slo_s, overload_slo_s, deadline_s = 4.0, 0.25, 120.0
        fault_schedule = (
            ('dispatch', dict(times=3, after=16)),
            ('slow_window', dict(times=3, delay_s=0.2, after=8)),
            ('sched_exhausted', dict(times=2, after=32)),
        )
    engine_cfg = EngineConfig(
        block_size=16,
        num_blocks=num_blocks,
        max_num_seqs=max_num_seqs,
        max_model_len=max_model_len,
        decode_steps=decode_steps,
        pipeline_depth=2,
        sampling_top_window=64,
        enable_prefix_cache=True,
        ttft_slo_s=slo_s,
        request_deadline_s=deadline_s,
        max_dispatch_retries=3,
        retry_backoff_s=0.01,
        attribution=True,
    )
    cache_before = _cache_entries()
    warmup_start = time.perf_counter()
    engine, fallback_reason = _build_engine_with_fallback(
        model_cfg,
        engine_cfg,
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
        [[1, 2, 3]],
        SamplingParams(temperature=0.0, max_tokens=2),
    )
    warmup_secs = time.perf_counter() - warmup_start

    workload = build_workload(load_cfg)
    clean = run_loadgen(engine, workload)

    injector = get_fault_injector()
    faults_by_site: dict[str, int] = {}
    try:
        for site, kwargs in fault_schedule:
            injector.arm(site, **kwargs)
        chaos = run_loadgen(engine, workload)
        faults_by_site = {
            site: injector.fired(site) for site, _ in fault_schedule
        }
    finally:
        injector.disarm()

    # Overload arm: admission control on, SLO tightened to the point the
    # denser schedule must shed — the 429/Retry-After surface exercised
    # end-to-end, reported informationally (shed volume is policy).
    engine.config.ttft_slo_s = overload_slo_s
    engine.admission_control = True
    overload = run_loadgen(engine, build_workload(overload_cfg))
    engine.admission_control = False
    engine.config.ttft_slo_s = slo_s

    identical = chaos.tokens_by_request == clean.tokens_by_request
    faults_injected = sum(faults_by_site.values())
    out = {
        f'{prefix}metric': 'goodput + recovery under an injected fault '
                           'schedule',
        f'{prefix}tok_s': round(chaos.achieved_tok_s, 2),
        f'{prefix}clean_tok_s': round(clean.achieved_tok_s, 2),
        f'{prefix}goodput_tokens': chaos.goodput_tokens,
        f'{prefix}goodput_frac': chaos.goodput_frac,
        f'{prefix}recoveries': chaos.recoveries,
        f'{prefix}retries': chaos.window_retries,
        f'{prefix}quarantined': chaos.quarantined,
        f'{prefix}failed_requests': chaos.failed_requests,
        f'{prefix}faults_injected': faults_injected,
        **{
            f'{prefix}faults_{site}': count
            for site, count in faults_by_site.items()
        },
        f'{prefix}tokens_identical': identical,
        f'{prefix}shed_requests': overload.shed_requests,
        f'{prefix}shed_rate': overload.shed_rate,
        f'{prefix}overload_slo_met': overload.slo_met,
        f'{prefix}overload_slo_missed': overload.slo_missed,
        f'{prefix}slo_s': slo_s,
        f'{prefix}deadline_s': deadline_s,
        f'{prefix}warmup_secs': round(warmup_secs, 1),
        f'{prefix}device': str(jax.devices()[0].device_kind),
        f'{prefix}workload': _workload_fingerprint(
            {
                'arrivals': [
                    [a.at_s, list(a.prompt_ids), a.max_tokens, a.session]
                    for a in workload
                ],
                'faults': [
                    [site, sorted(kwargs.items())]
                    for site, kwargs in fault_schedule
                ],
                'engine': {'max_num_seqs': max_num_seqs,
                           'num_blocks': num_blocks,
                           'decode_steps': decode_steps},
            }
        ),
        **_cache_fields(prefix, cache_before),
    }
    if any(count == 0 for count in faults_by_site.values()):
        # Per-site, not total: 4 dispatch fires must not paper over a
        # sched_exhausted schedule that never engaged its hazard point.
        out[f'{prefix}error'] = (
            f'armed fault site(s) never fired: {faults_by_site} — the '
            'schedule did not engage every hazard point it targets'
        )
    elif not identical:
        out[f'{prefix}error'] = (
            'chaos/clean token mismatch — recovery must replay the '
            'fault-free stream bit-exactly (greedy fp32), not '
            'approximate it'
        )
    elif chaos.recoveries < 1:
        out[f'{prefix}error'] = (
            'faults fired but no recovery was recorded — the retry '
            'ladder never engaged'
        )
    elif chaos.quarantined or chaos.failed_requests:
        out[f'{prefix}error'] = (
            f'{chaos.quarantined} quarantined / {chaos.failed_requests} '
            'failed requests on a survivable fault schedule'
        )
    elif not chaos.goodput_tokens:
        out[f'{prefix}error'] = (
            'zero goodput under fault — the engine stopped serving '
            'while faults were firing'
        )
    if fallback_reason:
        out[f'{prefix}attn_fallback_reason'] = fallback_reason
    return out


def _stage_gen_history() -> dict:
    """Telemetry-history serving stage (docs/observability.md "Metric
    history & sampling"): the open-loop loadgen with the metric-history
    ring live, gating that the retention layer, the SLO burn-rate
    engine, and the runtime regression sentinel actually work against
    real traffic — not just unit fixtures.

    Five arms on one engine:

    - **clean** (sampler on): fault-free serving; its measured tok/s and
      TTFT/TPOT p95 distill into a baseline envelope through the SHARED
      ``build_envelope`` (the ``benchdiff.py --emit-baseline`` code
      path, so this stage and the offline gate can never disagree on
      what a record says);
    - **identity** (sampler OFF): the same workload with no sampler
      thread running — history is pure host-side observation, so tokens
      must be BIT-IDENTICAL to the clean arm (greedy fp32; asserted,
      not assumed);
    - **verify** (sentinel armed with the clean envelope): the same
      workload again — a sentinel judging a run statistically identical
      to its own baseline must stay QUIET (0 regressions);
    - **slow** (``slow_window`` fault armed): every decode window eats an
      injected sleep, throughput collapses — the sentinel must fire
      ≥ 1 regression, and a second pass must fire 0 (the episode latch);
    - **overload** (admission control + a hopeless TTFT SLO, denser
      schedule): misses flow into ``distllm_request_slo_total`` and the
      60 s burn-rate gauge must move off zero.

    Thread hygiene rides along: after the stage stops its sampler, no
    live thread may carry ``SAMPLER_THREAD_NAME``.
    ``DISTLLM_BENCH_HISTORY=0`` skips the stage.
    """
    import threading

    import jax

    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.generate.loadgen import (
        LoadgenConfig,
        build_workload,
        run_loadgen,
    )
    from distllm_tpu.models import mistral
    from distllm_tpu.observability.baseline import build_envelope
    from distllm_tpu.observability.history import (
        SAMPLER_THREAD_NAME,
        HistorySampler,
        get_metrics_history,
    )
    from distllm_tpu.observability.sentinel import RegressionSentinel
    from distllm_tpu.observability.slo import slo_status, update_burn_gauges
    from distllm_tpu.resilience import get_fault_injector

    prefix = 'gen_history_'
    if os.environ.get('DISTLLM_BENCH_HISTORY', '1') in ('', '0'):
        return {f'{prefix}skipped': 'DISTLLM_BENCH_HISTORY=0'}
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        # fp32 so the history-on/off identity check is bit-exact; tiny
        # dims keep the single warmup in the fast tier.
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='float32',
        )
        max_num_seqs, num_blocks, max_model_len, decode_steps = 4, 160, 128, 4
        load_cfg = LoadgenConfig(
            seed=0, num_requests=24, rate_rps=16.0, num_sessions=3,
            warm_fraction=0.5, prefix_tokens=32, prompt_tokens=(8, 32),
            output_tokens=(4, 12), vocab_size=model_cfg.vocab_size,
        )
        overload_cfg = LoadgenConfig(
            seed=1, num_requests=32, rate_rps=200.0, num_sessions=3,
            warm_fraction=0.5, prefix_tokens=32, prompt_tokens=(8, 32),
            output_tokens=(4, 12), vocab_size=model_cfg.vocab_size,
        )
        slo_s, overload_slo_s = 2.0, 0.02
        sample_interval_s, slow_delay_s = 0.25, 0.2
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults
        max_num_seqs, num_blocks, max_model_len, decode_steps = (
            32, 712, 512, 16
        )
        load_cfg = LoadgenConfig(
            seed=0, num_requests=192, rate_rps=16.0, num_sessions=16,
            warm_fraction=0.6, prefix_tokens=64, prompt_tokens=(32, 192),
            output_tokens=(16, 96), vocab_size=model_cfg.vocab_size,
        )
        overload_cfg = LoadgenConfig(
            seed=1, num_requests=128, rate_rps=256.0, num_sessions=16,
            warm_fraction=0.6, prefix_tokens=64, prompt_tokens=(32, 192),
            output_tokens=(16, 64), vocab_size=model_cfg.vocab_size,
        )
        slo_s, overload_slo_s = 4.0, 0.25
        sample_interval_s, slow_delay_s = 1.0, 0.5
    engine_cfg = EngineConfig(
        block_size=16,
        num_blocks=num_blocks,
        max_num_seqs=max_num_seqs,
        max_model_len=max_model_len,
        decode_steps=decode_steps,
        pipeline_depth=2,
        sampling_top_window=64,
        enable_prefix_cache=True,
        ttft_slo_s=slo_s,
        attribution=True,
    )
    cache_before = _cache_entries()
    warmup_start = time.perf_counter()
    engine, fallback_reason = _build_engine_with_fallback(
        model_cfg,
        engine_cfg,
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
        [[1, 2, 3]],
        SamplingParams(temperature=0.0, max_tokens=2),
    )
    warmup_secs = time.perf_counter() - warmup_start

    history = get_metrics_history()
    history.clear()  # this stage's windows, not a prior stage's tail
    sampler = HistorySampler(history, interval_s=sample_interval_s)
    workload = build_workload(load_cfg)

    # Clean arm (sampler on) → the live-measured baseline envelope.
    sampler.start()
    clean = run_loadgen(engine, workload)
    history.sample_once()  # fold the tail before the envelope reads
    envelope = build_envelope(
        {
            f'{prefix}tok_s': clean.achieved_tok_s,
            f'{prefix}ttft_p95': clean.percentiles.get('ttft_p95'),
            f'{prefix}tpot_p95': clean.percentiles.get('tpot_p95'),
        },
        source='gen_history clean arm',
    )

    # Identity arm: sampler stopped — history off must not change tokens.
    sampler.stop()
    identity = run_loadgen(engine, workload)
    identical = identity.tokens_by_request == clean.tokens_by_request
    sampler.start()

    # Verify arm: the sentinel armed with the clean arm's own envelope
    # must stay quiet on a statistically identical run. Thresholds are
    # loose (50%) because live windows include idle sampler ticks the
    # end-of-run aggregate never sees.
    verify = run_loadgen(engine, workload)
    history.sample_once()
    sentinel_quiet = RegressionSentinel(
        history, envelope=envelope, threshold=0.5,
        window_s=verify.elapsed_s + 2.0 * sample_interval_s,
    )
    clean_fired = sentinel_quiet.evaluate()

    # Slow arm: a per-window injected sleep collapses throughput; the
    # sentinel must notice, and its episode latch must fire only once.
    injector = get_fault_injector()
    try:
        injector.arm(
            'slow_window', times=10**6, delay_s=slow_delay_s, after=0
        )
        slow = run_loadgen(engine, workload)
    finally:
        injector.disarm()
    history.sample_once()
    sentinel_slow = RegressionSentinel(
        history, envelope=envelope, threshold=0.5,
        window_s=slow.elapsed_s + 2.0 * sample_interval_s,
    )
    slow_fired = sentinel_slow.evaluate()
    slow_refired = sentinel_slow.evaluate()  # latched: must be empty

    # Overload arm (a): a hopeless TTFT SLO with admission OFF — every
    # arrival is served and judged, so the misses flow into
    # ``distllm_request_slo_total`` and the 60 s burn gauge must move.
    engine.config.ttft_slo_s = overload_slo_s
    overload = run_loadgen(engine, build_workload(overload_cfg))
    history.sample_once()
    burns = update_burn_gauges(history)
    verdict = slo_status(history)['verdict']
    # Overload arm (b): the same schedule with admission control ON —
    # the shed path under the same pressure (informational, like
    # gen_chaos: shed volume is offered-load policy, not quality).
    engine.admission_control = True
    shed_run = run_loadgen(engine, build_workload(overload_cfg))
    engine.admission_control = False
    engine.config.ttft_slo_s = slo_s

    sampler.stop()
    leaked = any(
        t.name == SAMPLER_THREAD_NAME for t in threading.enumerate()
    )

    out = {
        f'{prefix}metric': 'live history + sentinel + burn rates under '
                           'real traffic',
        f'{prefix}tok_s': round(clean.achieved_tok_s, 2),
        f'{prefix}ttft_p95': clean.percentiles.get('ttft_p95'),
        f'{prefix}tpot_p95': clean.percentiles.get('tpot_p95'),
        f'{prefix}goodput_tokens': clean.goodput_tokens,
        f'{prefix}samples': history.samples,
        f'{prefix}envelope_metrics': len(envelope['metrics']),
        f'{prefix}tokens_identical': identical,
        f'{prefix}clean_regressions': len(clean_fired),
        f'{prefix}slow_regressions': len(slow_fired),
        f'{prefix}slow_relatch_regressions': len(slow_refired),
        f'{prefix}slow_tok_s': round(slow.achieved_tok_s, 2),
        f'{prefix}slow_fired_metrics': sorted(
            e['metric'] for e in slow_fired
        ),
        f'{prefix}burn_60s': round(burns['60s'], 3),
        f'{prefix}slo_verdict': verdict,
        f'{prefix}overload_slo_missed': overload.slo_missed,
        f'{prefix}shed_requests': shed_run.shed_requests,
        f'{prefix}sampler_leaked': leaked,
        f'{prefix}warmup_secs': round(warmup_secs, 1),
        f'{prefix}device': str(jax.devices()[0].device_kind),
        f'{prefix}workload': _workload_fingerprint(
            {
                'arrivals': [
                    [a.at_s, list(a.prompt_ids), a.max_tokens, a.session]
                    for a in workload
                ],
                'engine': {'max_num_seqs': max_num_seqs,
                           'num_blocks': num_blocks,
                           'decode_steps': decode_steps},
                'slow_delay_s': slow_delay_s,
            }
        ),
        **_cache_fields(prefix, cache_before),
    }
    if not envelope['metrics']:
        out[f'{prefix}error'] = (
            'clean arm produced an empty baseline envelope — the shared '
            'extraction found none of its own stage keys'
        )
    elif not identical:
        out[f'{prefix}error'] = (
            'history on/off token mismatch — sampling must be pure '
            'observation (greedy fp32), it may never perturb serving'
        )
    elif clean_fired:
        out[f'{prefix}error'] = (
            f'sentinel fired {len(clean_fired)} regression(s) on a run '
            'statistically identical to its own baseline: '
            f'{[e["metric"] for e in clean_fired]}'
        )
    elif not slow_fired:
        out[f'{prefix}error'] = (
            'slow_window fault collapsed throughput '
            f'({clean.achieved_tok_s:.1f} -> {slow.achieved_tok_s:.1f} '
            'tok/s) but the sentinel never fired'
        )
    elif slow_refired:
        out[f'{prefix}error'] = (
            'sentinel re-fired on a latched degradation episode — '
            'once-per-episode alarm discipline is broken'
        )
    elif not overload.slo_missed:
        out[f'{prefix}error'] = (
            'overload arm recorded zero SLO misses — the burn-rate '
            'check below would be vacuous'
        )
    elif burns['60s'] <= 0:
        out[f'{prefix}error'] = (
            f'{overload.slo_missed} SLO misses but the 60s burn-rate '
            'gauge never moved off zero'
        )
    elif leaked:
        out[f'{prefix}error'] = (
            'a sampler thread is still alive after stop() — the '
            'shutdown contract leaks threads'
        )
    if fallback_reason:
        out[f'{prefix}attn_fallback_reason'] = fallback_reason
    return out


def _stage_gen_kvq() -> dict:
    """Quantized-KV-cache A/B (docs/serving.md "Quantized KV cache"): the
    SAME staggered greedy workload (the gen_mixed shape — shared-prefix
    repeats, staggered finish budgets) through a bf16-KV arm and an
    int8-KV arm of ``EngineConfig.kv_cache_dtype``, same model weights,
    same pool geometry.

    The contract this stage checks and records:

    - tok/s per arm (``gen_kvq_bf16_tok_s`` / ``gen_kvq_int8_tok_s``)
      and their ratio (``gen_kvq_speedup``);
    - MEASURED bandwidth utilization per arm (mean of the per-window
      ``bw_util_measured`` flight fields — ``compiled.cost_analysis()``
      truth, docs/observability.md) plus each arm's measured
      per-decode-dispatch bytes (``*_decode_bytes_accessed``) and exact
      KV pool bytes (``*_kv_pool_bytes``): the int8 pool is ~half the
      bf16 pool and the measured dispatch bytes must drop by the KV
      share — roofline EVIDENCE, not a modelled claim;
    - admission capacity at fixed pool bytes
      (``gen_kvq_int8_capacity_blocks``): how many int8 blocks — data
      plus their per-block scales — the bf16 arm's HBM budget would
      hold, i.e. the extra sequences the same chip admits;
    - the ACCURACY arm: ``gen_kvq_greedy_match``, the fraction of int8
      greedy tokens matching the bf16 stream position-for-position over
      the paired requests. Divergence is RECORDED, never asserted away;
      scripts/benchdiff.py gates the fraction higher-better (the
      'greedy_match' token), so a lossier compression trips the
      trajectory gate exactly like a throughput fall.

    A failed int8 arm records ``gen_kvq_error`` — unlike gen_kernel's
    fast arm, the quantized pool is the stage's whole subject, so its
    absence IS a stage failure. ``DISTLLM_BENCH_KVQ=0`` skips (default
    on).
    """
    import jax
    import numpy as np

    from distllm_tpu.generate.engine.engine import EngineConfig, SamplingParams
    from distllm_tpu.models import mistral
    from distllm_tpu.observability.flight import get_flight_recorder

    prefix = 'gen_kvq_'
    if os.environ.get('DISTLLM_BENCH_KVQ', '1') in ('', '0'):
        return {f'{prefix}skipped': 'DISTLLM_BENCH_KVQ=0'}
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
        )
        max_num_seqs, num_blocks = 4, 80
        n_prompts, prompt_lo, prompt_hi = 10, 8, 48
        out_lo, out_hi = 4, 24
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults
        max_num_seqs, num_blocks = 32, 356
        n_prompts, prompt_lo, prompt_hi = 64, 32, 192
        out_lo, out_hi = 16, 96

    rng = np.random.default_rng(0)
    # The gen_mixed staggered shape: every third prompt repeats a shared
    # prefix (RAG/MCQA), finish budgets stagger so slots free mid-stream
    # and decode windows carry mixed work — the serving regime where KV
    # bandwidth, not weights, is the decode bottleneck.
    shared = list(rng.integers(1, model_cfg.vocab_size, size=32))
    prompts = []
    for i, n in enumerate(rng.integers(prompt_lo, prompt_hi, size=n_prompts)):
        tail = list(rng.integers(1, model_cfg.vocab_size, size=int(n)))
        prompts.append(shared + tail if i % 3 == 0 else tail)
    budgets = [int(n) for n in rng.integers(out_lo, out_hi, size=n_prompts)]

    def run_arm(kv_dtype: str) -> dict:
        # block_size=32 (not the gen-stage-usual 16): the int8 sublane
        # tile (ops.paged_attention.kv_sublane_tile) — BOTH arms use it
        # so the A/B compares KV dtype, never pool geometry, and the
        # int8 arm stays Pallas-eligible on TPU.
        engine_cfg = EngineConfig(
            block_size=32,
            num_blocks=num_blocks,
            max_num_seqs=max_num_seqs,
            max_model_len=512,
            decode_steps=16,
            pipeline_depth=2,
            sampling_top_window=64,
            enable_prefix_cache=True,
            prefill_chunk_tokens=256,
            kv_cache_dtype=kv_dtype,
        )
        engine, fallback_reason = _build_engine_with_fallback(
            model_cfg,
            engine_cfg,
            lambda: mistral.init_on_device(jax.random.PRNGKey(0), model_cfg),
            [[1, 2, 3]],
            SamplingParams(temperature=0.0, max_tokens=2),
        )
        try:
            flight_before = len(get_flight_recorder().snapshot())
            rids = [
                engine.add_request(
                    p, SamplingParams(temperature=0.0, max_tokens=n)
                )
                for p, n in zip(prompts, budgets)
            ]
            start = time.perf_counter()
            seen: dict = {rid: [] for rid in rids}
            while engine.has_unfinished:
                for rid, tok in engine.step():
                    seen[rid].append(tok)
            elapsed = time.perf_counter() - start
            n_tokens = sum(len(v) for v in seen.values())
            records = get_flight_recorder().snapshot()[flight_before:]
            measured_bw = [
                r['bw_util_measured']
                for r in records
                if 'bw_util_measured' in r
            ]
            decode_cost = engine.measured_costs().get('decode', {})
            return {
                'tokens': [seen[rid] for rid in rids],
                'tok_s': round(n_tokens / elapsed, 2),
                'resolved_backend': engine.telemetry['attn_backend'],
                'kv_cache_dtype': engine.telemetry['kv_cache_dtype'],
                'bw_util_measured': (
                    round(float(np.mean(measured_bw)), 5)
                    if measured_bw else None
                ),
                'decode_bytes_accessed': decode_cost.get('bytes_accessed'),
                'kv_pool_bytes': int(engine.kv.hbm_bytes),
                'fallback_reason': fallback_reason,
            }
        finally:
            engine.shutdown()

    cache_before = _cache_entries()
    t0 = time.perf_counter()
    bf16 = run_arm('bf16')
    try:
        q8 = run_arm('int8')
        q8_error = None
    except Exception as exc:
        q8, q8_error = None, f'int8 arm: {exc!r}'[:400]
    elapsed_both = time.perf_counter() - t0

    out = {
        f'{prefix}metric': 'bf16-KV vs int8-KV A/B',
        f'{prefix}bf16_tok_s': bf16['tok_s'],
        f'{prefix}bf16_bw_util_measured': bf16['bw_util_measured'],
        f'{prefix}bf16_decode_bytes_accessed': bf16['decode_bytes_accessed'],
        f'{prefix}bf16_kv_pool_bytes': bf16['kv_pool_bytes'],
        f'{prefix}bf16_resolved_backend': bf16['resolved_backend'],
        f'{prefix}elapsed_both_arms_s': round(elapsed_both, 1),
        f'{prefix}workload': _workload_fingerprint(
            {'prompts': [list(map(int, p)) for p in prompts],
             'budgets': budgets,
             'engine': {'max_num_seqs': max_num_seqs,
                        'num_blocks': num_blocks,
                        'block_size': 32,
                        'prefill_chunk_tokens': 256}}
        ),
        **_cache_fields(prefix, cache_before),
    }
    if q8 is not None:
        # The accuracy arm: position-for-position greedy agreement over
        # the paired streams. Divergent-length tails count as misses
        # (max, not min, in the denominator) — an early-stopping stream
        # is itself a divergence, not a shorter exam.
        matched = total = 0
        for a, b in zip(bf16['tokens'], q8['tokens']):
            total += max(len(a), len(b))
            matched += sum(1 for x, y in zip(a, b) if x == y)
        # Admission capacity at FIXED pool bytes: the block count the
        # bf16 arm's HBM budget funds when each block is int8 data plus
        # its fp32 per-(block, KV-head) scales.
        per_block_q8 = q8['kv_pool_bytes'] / num_blocks
        out.update({
            f'{prefix}int8_tok_s': q8['tok_s'],
            f'{prefix}int8_bw_util_measured': q8['bw_util_measured'],
            f'{prefix}int8_decode_bytes_accessed': (
                q8['decode_bytes_accessed']
            ),
            f'{prefix}int8_kv_pool_bytes': q8['kv_pool_bytes'],
            f'{prefix}int8_resolved_backend': q8['resolved_backend'],
            f'{prefix}int8_kv_cache_dtype': q8['kv_cache_dtype'],
            f'{prefix}kv_pool_bytes_ratio': round(
                q8['kv_pool_bytes'] / max(bf16['kv_pool_bytes'], 1), 4
            ),
            f'{prefix}bf16_capacity_blocks': num_blocks,
            f'{prefix}int8_capacity_blocks': int(
                bf16['kv_pool_bytes'] // per_block_q8
            ),
            f'{prefix}speedup': round(
                q8['tok_s'] / max(bf16['tok_s'], 1e-9), 3
            ),
            f'{prefix}greedy_match': round(matched / max(total, 1), 4),
        })
    else:
        out[f'{prefix}error'] = q8_error
    if bf16['fallback_reason'] or (q8 and q8['fallback_reason']):
        out[f'{prefix}attn_fallback_reason'] = (
            bf16['fallback_reason'] or q8['fallback_reason']
        )
    return out


def _stage_gen() -> dict:
    return _run_gen(None, 'gen_')


def _stage_gen_q() -> dict:
    return _run_gen('int8', 'gen_int8_')


def _stage_embed_q() -> dict:
    return _stage_embed('int8', 'embed_int8_')


def _chip_peak_flops(device) -> float | None:
    """Best-effort bf16 peak FLOP/s for MFU telemetry."""
    kind = getattr(device, 'device_kind', '') or ''
    table = {
        'TPU v4': 275e12,
        'TPU v5 lite': 197e12,
        'TPU v5e': 197e12,
        'TPU v5': 459e12,
        'TPU v5p': 459e12,
        'TPU v6 lite': 918e12,
        'TPU v6e': 918e12,
    }
    for name, peak in table.items():
        if kind.lower().startswith(name.lower()):
            return peak
    return None


# ------------------------------------------------------------ orchestrator

# Cheapest-first: embed warmups are minutes, gen_prefix reuses gen's
# compile cache (same bf16 7B dims), and int8 gen_q's cold warmup — the
# round-4 22-45 min outlier — runs last so a deadline truncates the most
# expensive coverage first, never the headline metrics.
STAGE_ORDER = (
    'embed', 'embed_q', 'gen', 'gen_prefix', 'gen_mixed', 'gen_spec',
    'gen_kernel', 'gen_load', 'gen_tier', 'gen_router', 'gen_chaos',
    'gen_history', 'gen_kvq', 'gen_q',
)
NOMINAL_BUDGET_S = {
    'embed': 1200.0,
    'embed_q': 1200.0,
    'gen': 2700.0,
    'gen_prefix': 2700.0,
    'gen_mixed': 2700.0,
    'gen_spec': 2700.0,
    'gen_kernel': 2700.0,
    'gen_load': 2700.0,
    'gen_tier': 2700.0,
    'gen_router': 2700.0,
    'gen_chaos': 2700.0,
    'gen_history': 2700.0,
    'gen_kvq': 2700.0,
    'gen_q': 2700.0,
}
GEN_STAGES = frozenset(
    {'gen', 'gen_q', 'gen_prefix', 'gen_mixed', 'gen_spec', 'gen_kernel',
     'gen_load', 'gen_tier', 'gen_router', 'gen_chaos', 'gen_history',
     'gen_kvq'}
)
# Under a 1 h driver timeout (rc 124 in r5 was `timeout` sending SIGTERM):
# stages stop with ~5 min to spare even if the guess is exact, and the
# SIGTERM handler is the backstop if the real budget is shorter.
DEFAULT_DEADLINE_S = 3300.0

_REPO_DIR = os.path.dirname(os.path.abspath(__file__))
# Orchestrator state shared with the signal handlers.
_CURRENT_CHILD: dict = {'proc': None}
_EMITTED = {'done': False}


def _record_paths() -> tuple[str, str]:
    base = os.environ.get('DISTLLM_BENCH_RECORD_DIR') or _REPO_DIR
    return (
        os.path.join(base, 'BENCH_partial.jsonl'),
        os.path.join(base, 'BENCH_snapshot.json'),
    )


def _bundle_dir(stage: str) -> str:
    base = os.environ.get('DISTLLM_BENCH_BUNDLE_DIR') or os.path.join(
        _REPO_DIR, 'bench_debug'
    )
    return os.path.join(base, f'{stage}_{os.getpid()}')


def _completed_stages(record) -> list[str]:
    """Stages whose recorded fragment carries metrics, not an error/skip."""
    done: list[str] = []
    for entry in record.entries():
        stage = entry.get('stage')
        fragment = entry.get('fragment') or {}
        if (
            stage in NOMINAL_BUDGET_S
            and stage not in done
            and not any(
                key.endswith(('_error', '_skipped')) for key in fragment
            )
        ):
            done.append(stage)
    return done


def _emit_final(record, base: dict, extra: dict) -> None:
    """Compose + print the single driver-contract line, exactly once.

    Called from normal exit AND from the SIGTERM/SIGALRM handlers. Must be
    async-signal-tolerant: it reads the on-disk record (no locks shared
    with the main thread) and writes stdout directly.
    """
    if _EMITTED['done']:
        return
    _EMITTED['done'] = True
    result = dict(base)
    result.update(record.compose())
    result.update(extra)
    result['stages_completed'] = _completed_stages(record)
    sys.stdout.write(json.dumps(result) + '\n')
    sys.stdout.flush()


def _probe_backend(deadline, record) -> str | None:
    """Confirm the TPU backend initializes, in a killable subprocess.

    Round 1's bench died with 'backend UNAVAILABLE' after a wedged earlier
    process; a hung init here is killed by the timeout and retried rather
    than hanging the bench itself. Round 3 saw a pool-side wedged claim
    hang clients for hours — a transient wedge is worth waiting out, BUT
    the ladder is now capped by a share of the global deadline (it could
    previously burn ~15 min before any stage ran), and every attempt's
    outcome lands in the run record. Returns None on success, else the
    error.
    """
    attempts = int(os.environ.get('DISTLLM_BENCH_PROBE_ATTEMPTS', '6'))
    per_attempt_s = float(os.environ.get('DISTLLM_BENCH_PROBE_TIMEOUT_S', '150'))
    # At most a quarter of what's left (and never more than 15 min): the
    # probe exists to protect the stages' time, not to consume it.
    budget_s = min(900.0, 0.25 * deadline.remaining())
    probe_start = time.monotonic()
    err = 'unknown'
    attempts_log: list[dict] = []
    # Mirror the stage subprocesses: re-apply JAX_PLATFORMS through the
    # config API so a CPU smoke run probes CPU, not the pinned TPU.
    probe_src = (
        'import os, jax\n'
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "jax.config.update('jax_platforms', p) if p else None\n"
        'print(jax.devices()[0].platform)\n'
    )
    for attempt in range(attempts):
        left = budget_s - (time.monotonic() - probe_start)
        if left <= 5.0:
            err = (
                f'probe budget exhausted after {attempt} attempts '
                f'({budget_s:.0f}s share of the deadline): {err}'
            )
            attempts_log.append({'attempt': attempt, 'outcome': 'budget_exhausted'})
            break
        attempt_start = time.monotonic()
        outcome: dict = {'attempt': attempt}
        try:
            proc = subprocess.run(
                [sys.executable, '-c', probe_src],
                capture_output=True, text=True,
                timeout=min(per_attempt_s, left),
            )
            if proc.returncode == 0:
                outcome.update(
                    outcome='ok',
                    elapsed_s=round(time.monotonic() - attempt_start, 1),
                    platform=proc.stdout.strip()[-40:],
                )
                attempts_log.append(outcome)
                record.record('probe', {'probe_attempts': attempts_log})
                return None
            err = (proc.stderr or '').strip()[-500:]
            outcome.update(outcome='error', error=err[-200:])
        except subprocess.TimeoutExpired:
            err = f'backend init timed out after {min(per_attempt_s, left):.0f}s'
            outcome.update(outcome='timeout', error=err)
        outcome['elapsed_s'] = round(time.monotonic() - attempt_start, 1)
        attempts_log.append(outcome)
        record.record('probe', {'probe_attempts': attempts_log})
        if attempt < attempts - 1:
            backoff = 20.0 * (attempt + 1)
            left = budget_s - (time.monotonic() - probe_start)
            time.sleep(max(0.0, min(backoff, left)))
    record.record('probe', {'probe_attempts': attempts_log})
    return err


def _run_stage(stage: str, timeout: float) -> tuple[dict, str]:
    """Run one stage in a subprocess; parse its single JSON stdout line.

    Returns ``(fragment, outcome)`` with outcome ok/error/timeout. On
    timeout the child gets SIGTERM first (its handler dumps a debug
    bundle — the corpse carries evidence), then SIGKILL after a grace
    period.
    """
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), '--stage', stage],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    _CURRENT_CHILD['proc'] = proc
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.terminate()  # SIGTERM: the stage dumps its bundle and exits
        try:
            out, err = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
        fragment = {f'{stage}_error': f'stage timed out after {timeout:.0f}s'}
        bundle = _stage_bundle_hint(err)
        if bundle:
            fragment[f'{stage}_bundle_dir'] = bundle
        return fragment, 'timeout'
    finally:
        _CURRENT_CHILD['proc'] = None
    if proc.returncode != 0:
        fragment = {f'{stage}_error': (err or out or '').strip()[-800:]}
        bundle = _stage_bundle_hint(err)
        if bundle:
            fragment[f'{stage}_bundle_dir'] = bundle
        return fragment, 'error'
    for line in reversed((out or '').strip().splitlines()):
        try:
            return json.loads(line), 'ok'
        except json.JSONDecodeError:
            continue
    return (
        {f'{stage}_error': f'no JSON in stage output: {(out or "")[-300:]}'},
        'error',
    )


def _stage_bundle_hint(stderr: str | None) -> str | None:
    """The stage prints ``[bench-bundle] <dir>`` to stderr when it dumps a
    debug bundle; surface that path in the run record."""
    for line in reversed((stderr or '').splitlines()):
        if line.startswith('[bench-bundle] '):
            return line[len('[bench-bundle] '):].strip()
    return None


def _run_stage_entry(stage: str) -> None:
    """``--stage`` subprocess body: run the stage fn, print its fragment.

    Failure paths dump a debug bundle (flight ring + metrics + traces) so
    a dead stage still explains itself: on exception, AND on the SIGTERM
    the orchestrator sends at budget expiry. Gen stages additionally run
    under a StallWatchdog (the engine's flight ring is the progress
    signal) that dumps a bundle if the chip wedges mid-stage.
    """
    from distllm_tpu.observability.flight import (
        StallWatchdog,
        dump_debug_bundle,
    )
    from distllm_tpu.observability.startup import record_backend_init

    # Smoke-test hook (tests/test_smoke_bench_contract.py): park this stage
    # before any heavy import so the orchestrator's kill paths can be
    # exercised in seconds.
    if os.environ.get('DISTLLM_BENCH_TEST_HANG_STAGE') == stage:
        while True:
            time.sleep(1)

    bundle_dir = _bundle_dir(stage)

    def _dump(reason: str) -> None:
        try:
            dump_debug_bundle(bundle_dir, reason=reason)
            print(f'[bench-bundle] {bundle_dir}', file=sys.stderr, flush=True)
        except Exception:
            pass

    # Attribute this stage subprocess's REAL backend init: by the time an
    # engine exists the PJRT client is already up (params load first), so
    # the engine-side record measures ~0 — here is where r03/r04's wedged
    # init actually happened. A dead backend raises AFTER the phase
    # records the error, so the bundle carries it.
    try:
        record_backend_init()
    except Exception as exc:
        _dump(f'{stage}: backend init failed: {exc!r}'[:300])
        raise

    def _on_sigterm(signum, frame):  # budget kill from the orchestrator
        _dump(f'{stage}: SIGTERM (stage budget expired)')
        os._exit(143)

    signal.signal(signal.SIGTERM, _on_sigterm)

    stage_fns = {
        'embed': _stage_embed,
        'embed_q': _stage_embed_q,
        'gen': _stage_gen,
        'gen_q': _stage_gen_q,
        'gen_prefix': _stage_gen_prefix,
        'gen_mixed': _stage_gen_mixed,
        'gen_spec': _stage_gen_spec,
        'gen_kernel': _stage_gen_kernel,
        'gen_load': _stage_gen_load,
        'gen_tier': _stage_gen_tier,
        'gen_router': _stage_gen_router,
        'gen_chaos': _stage_gen_chaos,
        'gen_history': _stage_gen_history,
        'gen_kvq': _stage_gen_kvq,
    }
    watchdog = None
    watchdog_s = float(os.environ.get('DISTLLM_BENCH_WATCHDOG_S', '300') or 0)
    if stage in GEN_STAGES and watchdog_s > 0:
        watchdog = StallWatchdog(
            watchdog_s, bundle_dir=bundle_dir, name=f'bench-{stage}'
        ).start()
    try:
        fragment = stage_fns[stage]()
    except BaseException as exc:
        _dump(f'{stage}: {exc!r}'[:300])
        raise
    finally:
        if watchdog is not None:
            watchdog.stop()
    print(json.dumps(fragment))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        '--stage',
        choices=[
            'embed', 'embed_q', 'gen', 'gen_q', 'gen_prefix', 'gen_mixed',
            'gen_spec', 'gen_kernel', 'gen_load', 'gen_tier', 'gen_router',
            'gen_chaos', 'gen_history', 'gen_kvq',
        ],
    )
    args = parser.parse_args()

    # The environment's sitecustomize pins jax_platforms='axon,cpu' at
    # interpreter start, which overrides the JAX_PLATFORMS env var; re-apply
    # the env var through the config API so `JAX_PLATFORMS=cpu python
    # bench.py --stage gen` really runs on CPU (smoke tests).
    if args.stage:
        import jax

        if os.environ.get('JAX_PLATFORMS'):
            jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])
        # XLA compiles amortize across runs (the 7B engine has ~25 serving
        # shapes); harmless if the backend doesn't support the cache.
        try:
            jax.config.update(
                'jax_compilation_cache_dir',
                os.path.join(_REPO_DIR, '.jax_cache'),
            )
        except Exception:
            pass
        _run_stage_entry(args.stage)
        return

    from distllm_tpu.observability.flight import Deadline, RunRecord

    base: dict = {
        'metric': 'embeddings/sec/chip',
        'value': 0.0,
        'unit': 'emb/s',
        'vs_baseline': 0.0,
    }
    # Setup itself can fail (unwritable record dir, non-numeric deadline
    # env, full disk) — before the signal handlers and the emit-protected
    # try/finally exist. Even then the driver must get a parseable line.
    try:
        deadline = Deadline(
            float(
                os.environ.get('DISTLLM_BENCH_DEADLINE_S')
                or DEFAULT_DEADLINE_S
            ),
            reserve_s=20.0,
        )
        partial_path, snapshot_path = _record_paths()
        # Each orchestrator run is a fresh record: a stale partial file
        # from a previous run must not leak its stages into this run's
        # contract line.
        for stale in (partial_path, snapshot_path):
            try:
                os.unlink(stale)
            except OSError:
                pass
        record = RunRecord(partial_path, snapshot_path)
    except BaseException as exc:
        base['error'] = f'bench orchestrator setup failed: {exc!r}'[:500]
        sys.stdout.write(json.dumps(base) + '\n')
        sys.stdout.flush()
        raise

    def _on_signal(signum, frame):
        # Runs in the main thread, possibly mid-communicate(): touch no
        # locks the main thread could hold — read the on-disk record,
        # emit, hard-exit. Exit 0: the line on stdout IS the result.
        reason = (
            'deadline_expired' if signum == signal.SIGALRM else 'sigterm'
        )
        child = _CURRENT_CHILD.get('proc')
        if child is not None:
            try:
                child.terminate()
            except Exception:
                pass
        _emit_final(
            record,
            base,
            {
                'interrupted': reason,
                'deadline_s': deadline.total_s,
                'elapsed_s': round(deadline.elapsed(), 1),
            },
        )
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGALRM, _on_signal)
    # The alarm is the deadline made unconditional: even a wedged
    # communicate() or a hung probe gets interrupted in time to emit.
    signal.alarm(max(1, int(deadline.total_s)))

    # EVERY exit path emits: signals are handled above, and the finally
    # below covers exceptions (a typo'd stage name, a full disk, a broken
    # env override) — an orchestrator bug must not re-open the zeroed-
    # record failure this file exists to close. _emit_final is idempotent.
    try:
        record.record(
            'run',
            {
                'bench_deadline_s': deadline.total_s,
                'bench_started_wall_s': round(time.time(), 1),
            },
        )
        probe_err = _probe_backend(deadline, record)
        if probe_err is not None:
            record.record(
                'probe_failed',
                {'error': f'TPU backend unavailable: {probe_err}'},
            )
            return

        stages_env = os.environ.get('DISTLLM_BENCH_STAGES')
        stages = (
            [s.strip() for s in stages_env.split(',') if s.strip()]
            if stages_env
            else list(STAGE_ORDER)
        )
        # Budget override for smoke tests: a single float applies to every
        # stage, a JSON object ({"gen": 5}) per stage.
        override = os.environ.get('DISTLLM_BENCH_STAGE_TIMEOUT_S', '').strip()
        overrides: dict = (
            json.loads(override) if override.startswith('{')
            else dict.fromkeys(NOMINAL_BUDGET_S, float(override)) if override
            else {}
        )
        floor_s = float(os.environ.get('DISTLLM_BENCH_STAGE_FLOOR_S', '60'))
        outcomes: dict = {}
        for stage in stages:
            nominal = float(overrides.get(stage, NOMINAL_BUDGET_S[stage]))
            budget = deadline.budget(nominal, floor_s=min(floor_s, nominal))
            if budget <= 0:
                outcomes[stage] = 'skipped'
                record.record(
                    stage,
                    {
                        f'{stage}_skipped': (
                            f'deadline: {deadline.remaining():.0f}s left of '
                            f'{deadline.total_s:.0f}s'
                        ),
                        'bench_stage_outcomes': dict(outcomes),
                    },
                )
                continue
            fragment, outcome = _run_stage(stage, budget)
            outcomes[stage] = outcome
            fragment['bench_stage_outcomes'] = dict(outcomes)
            record.record(stage, fragment)
    except BaseException as exc:
        try:
            record.record(
                'orchestrator_error',
                {'orchestrator_error': repr(exc)[:300]},
            )
        except Exception:
            pass
        raise
    finally:
        _emit_final(record, base, {})


if __name__ == '__main__':
    main()
