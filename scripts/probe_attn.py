"""Isolate attention's share of the embed forward on the real chip.

Times the bf16 BERT-base forward at the bench's hot shape [512, 256] in
three variants: full SDPA, attention stubbed to identity (x = v), and — if
available — the custom Pallas encoder-attention kernel. The gap between
full and stubbed bounds what an attention kernel can buy (VERDICT r2
weak #4: device MFU 0.43 vs padded tokens)."""

from __future__ import annotations

import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import time

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.models import bert, common


def timed(fn, params, ids, mask, n=8):
    out = fn(params, ids, mask)
    jax.block_until_ready(out)
    start = time.perf_counter()
    for _ in range(n):
        out = fn(params, ids, mask)
    jax.block_until_ready(out)
    return (time.perf_counter() - start) / n


def main() -> None:
    B, S = 512, 256
    cfg = bert.BertConfig(dtype='bfloat16')
    params = jax.device_put(bert.init(jax.random.PRNGKey(0), cfg))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B, S)), jnp.int32)
    mask = jnp.ones((B, S), jnp.int32)

    full = jax.jit(lambda p, i, m: bert.apply(p, cfg, i, m))
    t_full = timed(full, params, ids, mask)

    orig_sdpa = common.sdpa
    common.sdpa = lambda q, k, v, **kw: v  # stub
    try:
        stub = jax.jit(lambda p, i, m: bert.apply(p, cfg, i, m))
        t_stub = timed(stub, params, ids, mask)
    finally:
        common.sdpa = orig_sdpa

    tokens = B * S
    flops = 2 * 110e6 * tokens
    print(f'full forward:    {t_full*1e3:7.1f} ms  mfu={flops/t_full/197e12:.3f}')
    print(f'attention=ident: {t_stub*1e3:7.1f} ms  mfu={flops/t_stub/197e12:.3f}')
    print(f'attention cost:  {(t_full-t_stub)*1e3:7.1f} ms '
          f'({(t_full-t_stub)/t_full:.1%} of forward)')

    try:
        from distllm_tpu.ops.encoder_attention import encoder_attention  # noqa: F401

        common.sdpa = None  # ensure unused
        fast = jax.jit(
            lambda p, i, m: bert.apply(p, cfg, i, m, attn_impl='pallas')
        )
        t_fast = timed(fast, params, ids, mask)
        print(f'pallas kernel:   {t_fast*1e3:7.1f} ms  '
              f'mfu={flops/t_fast/197e12:.3f}')
    except Exception as exc:  # kernel not built yet / no attn_impl arg
        print('pallas variant skipped:', repr(exc)[:200])
    finally:
        common.sdpa = orig_sdpa


if __name__ == '__main__':
    main()
