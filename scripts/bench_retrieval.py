"""Retrieval at production scale: exact vs ubinary tiers, with recall.

Justifies (or refutes) TpuIndexV2's exact-only design at the reference's
production sizes (tens of millions of chunk embeddings — ref
``examples/scaling/polaris/.../nodes256.yaml`` embeds lit-scale corpora;
``FaissIndexV2`` offers HNSW for that regime, ``distllm/rag/search.py:229-250``).

Measures, on whatever backend JAX resolves (CPU host or the TPU chip):

1. **Exact fp32 tier** (``ops/topk.topk_inner_product``): query latency at
   1M/2M/4M x 768. A 16 GiB v5e holds ~4-5M x 768 fp32 rows on-chip; past
   that the corpus must shard over a mesh (``data`` axis) or drop to the
   binary tier — this prints the HBM budget alongside the latency.
2. **ubinary tier** (``ops/topk.hamming_topk`` + fp32 rescore): packed
   sign-bits are corpus/32 bytes (10M x 768 = 960 MB — fits ONE chip to
   ~100M rows), with sentence-transformers-style oversampled rescore.
3. **Recall@k of the ubinary tier vs exact ground truth** on the same 10M
   corpus — hardware-independent quality evidence (ground truth via
   chunked host matmul).

Prints one JSON line per measurement. No faiss/hnswlib exists in this
environment for a CPU-graph comparison; the exact numbers and the recall
table are the decision evidence (docs/retrieval_at_scale.md).
"""

from __future__ import annotations

import argparse
import json
import time

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from distllm_tpu.ops.topk import (  # noqa: E402
    SCAN_CHUNK_BITS,
    SCAN_CHUNK_INT8,
    group_rows,
    hamming_topk,
    int8_topk,
    pack_sign_bits,
    quantize_int8_rows,
    topk_inner_product,
)

CHUNK = 1 << 18  # corpus generation/ground-truth chunk (256k rows)


def _emit(**fields) -> None:
    print(json.dumps(fields), flush=True)


def _gen_chunk(rng: np.random.Generator, rows: int, dim: int) -> np.ndarray:
    x = rng.standard_normal((rows, dim), dtype=np.float32)
    x /= np.linalg.norm(x, axis=1, keepdims=True)
    return x


def _planted_queries(corpus_rows: np.ndarray, n: int, dim: int,
                     noise_norm: float) -> np.ndarray:
    """Queries = noisy copies of corpus rows: gives the corpus real
    nearest-neighbor structure (pure-random vectors have none, which makes
    any recall number a meaningless floor). ``noise_norm`` is the expected
    L2 norm of the added noise relative to the unit source vector:
    0.5 puts the true neighbor's IP around 1/sqrt(1.25) ~ 0.89 — a
    realistic hard retrieval regime."""
    rng = np.random.default_rng(3)
    src = corpus_rows[rng.integers(0, len(corpus_rows), size=n)]
    sigma = noise_norm / np.sqrt(dim)
    q = src + sigma * rng.standard_normal((n, dim), dtype=np.float32)
    return q / np.linalg.norm(q, axis=1, keepdims=True)


def _sync(x) -> None:
    # On the tunneled TPU block_until_ready does not block; a tiny host
    # fetch does (see tests/conftest notes).
    np.asarray(jax.tree.leaves(x)[0][0])


def _device_corpus(n: int, dim: int, seed: int) -> tuple:
    """Stream a [n, dim] corpus straight into a device buffer chunk-wise
    (donated dynamic-update-slice, same pattern as TpuIndexV2's single-
    device load): host RSS stays O(CHUNK), device peak O(n) — the array
    being measured. Returns (corpus, first_rows) with the first rows kept
    on host for query planting."""
    update = jax.jit(
        lambda buf, part, lo: jax.lax.dynamic_update_slice(buf, part, (lo, 0)),
        donate_argnums=0,
    )
    rng = np.random.default_rng(seed)
    buf = jnp.zeros((n, dim), jnp.float32)
    first_rows = None
    for lo in range(0, n, CHUNK):
        chunk = _gen_chunk(rng, min(CHUNK, n - lo), dim)
        if first_rows is None:
            first_rows = chunk[:4096].copy()
        buf = update(buf, chunk, lo)
    return buf, first_rows


def bench_exact(n_queries: int, sizes: list[int], dim: int, top_k: int,
                trials: int) -> None:
    for n in sizes:
        corpus_bytes = n * dim * 4
        # Per-size rebuild keeps device peak at O(n), not O(max + n).
        corpus, first_rows = _device_corpus(n, dim, seed=1)
        q = jnp.asarray(
            _planted_queries(first_rows, n_queries, dim, noise_norm=0.5)
        )
        _sync(corpus)
        # warmup compile
        s, i = topk_inner_product(q, corpus, top_k)
        _sync((s, i))
        times = []
        for _ in range(trials):
            t0 = time.perf_counter()
            s, i = topk_inner_product(q, corpus, top_k)
            _sync((s, i))
            times.append(time.perf_counter() - t0)
        best = min(times)
        # Through the serving tunnel a single call is dominated by the
        # ~64 ms host<->device round trip; chain 8 async dispatches with
        # ONE final sync so the RTT amortizes and the per-call number
        # approaches the device time (same method as probe_decode).
        reps = 8
        t0 = time.perf_counter()
        outs = [topk_inner_product(q, corpus, top_k) for _ in range(reps)]
        _sync(outs[-1])
        chained = (time.perf_counter() - t0) / reps
        _emit(
            tier='exact_fp32', rows=n, dim=dim, batch=n_queries,
            top_k=top_k, latency_ms=round(best * 1e3, 1),
            latency_chained_ms=round(chained * 1e3, 1),
            queries_per_s=round(n_queries / chained, 1),
            corpus_gib=round(corpus_bytes / 2**30, 2),
            platform=jax.default_backend(),
        )
        del corpus


def bench_ubinary(rows: int, dim: int, n_queries: int, top_k: int,
                  rescore_multiplier: int, trials: int,
                  scratch: str) -> None:
    """Build packed bits + exact ground truth chunk-wise (host RSS stays
    O(chunk) + O(packed)); the fp32 corpus goes to a disk memmap — the
    faithful stand-in for the production index's arrow-mmap'd dataset,
    which is where rescore candidates are gathered from. Then time
    hamming + gather + rescore, and score recall vs the ground truth."""
    import os

    rng = np.random.default_rng(2)
    # Queries planted from the first chunk's rows (the chunk loop below
    # re-generates the same stream from the same seed).
    first = _gen_chunk(np.random.default_rng(2), min(CHUNK, rows), dim)
    queries = _planted_queries(first, n_queries, dim, noise_norm=0.5)
    del first

    mmap_path = os.path.join(scratch, f'bench_retrieval_{rows}x{dim}.f32')
    corpus_mm = np.lib.format.open_memmap(
        mmap_path, mode='w+', dtype=np.float32, shape=(rows, dim)
    )
    packed_parts = []
    gt_scores = None  # running exact top-k for ground truth
    gt_idx = None
    t_build = time.perf_counter()
    for lo in range(0, rows, CHUNK):
        n = min(CHUNK, rows - lo)
        chunk = _gen_chunk(rng, n, dim)
        corpus_mm[lo:lo + n] = chunk
        packed_parts.append(pack_sign_bits(chunk))
        scores = queries @ chunk.T  # [B, n] exact ground truth
        k = min(top_k, n)
        part = np.argpartition(-scores, k - 1, axis=1)[:, :k]
        part_scores = np.take_along_axis(scores, part, axis=1)
        part_idx = part + lo
        if gt_scores is None:
            gt_scores, gt_idx = part_scores, part_idx
        else:
            cat_s = np.concatenate([gt_scores, part_scores], axis=1)
            cat_i = np.concatenate([gt_idx, part_idx], axis=1)
            keep = np.argpartition(-cat_s, top_k - 1, axis=1)[:, :top_k]
            gt_scores = np.take_along_axis(cat_s, keep, axis=1)
            gt_idx = np.take_along_axis(cat_i, keep, axis=1)
        del chunk
    corpus_mm.flush()
    packed = np.concatenate(packed_parts)
    packed_parts.clear()
    build_secs = time.perf_counter() - t_build
    _emit(tier='ubinary_build', rows=rows, dim=dim,
          packed_gib=round(packed.nbytes / 2**30, 3),
          build_secs=round(build_secs, 1))

    oversample = top_k * rescore_multiplier
    # The exact nearest neighbor per query (= the planted source): the
    # meaningful quality target. The other top-k ground-truth rows of a
    # synthetic corpus are random near-ties no quantizer can rank, so the
    # overlap recall@k is reported but top1_hit is the headline.
    gt_top1 = np.take_along_axis(
        gt_idx, np.argmax(gt_scores, axis=1, keepdims=True), axis=1
    )[:, 0]

    def measure(tier: str, cand_fn, extra: dict) -> None:
        cand = cand_fn()  # warmup compile
        _sync(cand)
        times, scan_times = [], []
        recall = top1_hit = None
        for _ in range(trials):
            t0 = time.perf_counter()
            cand = np.asarray(cand_fn())
            t1 = time.perf_counter()
            # Gather candidates from the disk memmap exactly the way the
            # production path gathers from the arrow mmap (sorted access).
            flat = cand.reshape(-1)
            order_back = np.argsort(np.argsort(flat))
            vectors = corpus_mm[np.sort(flat)][order_back]
            vectors = vectors.reshape(*cand.shape, dim)
            rescored = np.einsum('bh,boh->bo', queries, vectors)
            order = np.argsort(-rescored, axis=1)[:, :top_k]
            got_idx = np.take_along_axis(cand, order, axis=1)
            times.append(time.perf_counter() - t0)
            scan_times.append(t1 - t0)
            hits = sum(
                len(set(map(int, got_idx[b])) & set(map(int, gt_idx[b])))
                for b in range(len(queries))
            )
            recall = hits / (len(queries) * top_k)
            top1_hit = float(
                np.mean(
                    [gt_top1[b] in got_idx[b] for b in range(len(queries))]
                )
            )
        best = min(times)
        _emit(
            tier=tier, rows=rows, dim=dim, batch=n_queries,
            top_k=top_k, oversample=oversample,
            latency_ms=round(best * 1e3, 1),
            scan_ms=round(min(scan_times) * 1e3, 1),
            queries_per_s=round(n_queries / best, 1),
            recall_at_k=round(recall, 4),
            top1_hit=round(top1_hit, 4),
            platform=jax.default_backend(),
            **extra,
        )

    try:
        # Grouped [G, C, ...] layout (ops/topk.group_rows): the serving
        # layout — hamming/int8 scans run as ONE lax.scan dispatch.
        corpus_bits = jax.device_put(group_rows(packed, SCAN_CHUNK_BITS))
        query_bits = jnp.asarray(pack_sign_bits(queries))
        measure(
            'ubinary_rescore',
            lambda: hamming_topk(
                query_bits, corpus_bits, oversample, n_valid=rows
            )[1],
            {'packed_gib': round(packed.nbytes / 2**30, 3)},
        )
        del corpus_bits

        # int8 tier: quantize from the memmap AFTER the ubinary phase so
        # codes (~corpus/4 bytes) never coexist with it in host RAM, and
        # its build cost is timed on its own, not inside 'ubinary_build'.
        t_q = time.perf_counter()
        code_host = np.empty((rows, dim), np.int8)
        scale_host = np.empty((rows,), np.float32)
        for lo in range(0, rows, CHUNK):
            hi = min(lo + CHUNK, rows)
            code_host[lo:hi], scale_host[lo:hi] = quantize_int8_rows(
                np.asarray(corpus_mm[lo:hi])
            )
        int8_build_secs = time.perf_counter() - t_q
        codes = jax.device_put(group_rows(code_host, SCAN_CHUNK_INT8))
        scales = jax.device_put(group_rows(scale_host, SCAN_CHUNK_INT8))
        codes_gib = round(code_host.nbytes / 2**30, 3)
        del code_host, scale_host
        queries_dev = jnp.asarray(queries)
        measure(
            'int8_rescore',
            lambda: int8_topk(
                queries_dev, codes, scales, oversample, n_valid=rows
            )[1],
            {'codes_gib': codes_gib,
             'build_secs': round(int8_build_secs, 1)},
        )
    finally:
        del corpus_mm
        os.unlink(mmap_path)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument('--dim', type=int, default=768)
    ap.add_argument('--queries', type=int, default=32)
    ap.add_argument('--topk', type=int, default=10)
    ap.add_argument('--trials', type=int, default=3)
    ap.add_argument('--exact-sizes', type=str, default='1000000,2000000,4000000')
    ap.add_argument('--ubinary-rows', type=int, default=10_000_000)
    ap.add_argument('--rescore-multiplier', type=int, default=4)
    ap.add_argument('--skip-exact', action='store_true')
    ap.add_argument('--skip-ubinary', action='store_true')
    ap.add_argument('--scratch', type=str, default='/tmp')
    args = ap.parse_args()

    if not args.skip_exact:
        sizes = [int(s) for s in args.exact_sizes.split(',') if s]
        bench_exact(args.queries, sizes, args.dim, args.topk, args.trials)
    if not args.skip_ubinary:
        bench_ubinary(args.ubinary_rows, args.dim, args.queries, args.topk,
                      args.rescore_multiplier, args.trials, args.scratch)


if __name__ == '__main__':
    main()
