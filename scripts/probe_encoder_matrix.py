"""Encoder-attention backend matrix: XLA SDPA vs Pallas kernel, by shape.

VERDICT r3 weak #6: at the bench's hot shape the two backends tie, so the
kernel must either show a shape regime where it wins (then `auto` routes
there) or default off. This sweeps the regimes the embed pipeline actually
serves — BERT-base across the fine bucket ladder, ESM2-650M protein
lengths, ModernBERT long buckets with the sliding-window bias — and prints
one JSON line per (family, S, backend) with ms/forward and tokens/s.

Token budget per forward is held ~constant (B*S ~= 128k) so lines compare
like-for-like. shape_supported gates the Pallas rows (whole-[S, N*Hd]
slices must fit VMEM; e.g. ESM2-650M tops out at S=512).
"""

from __future__ import annotations

import json
import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import time

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.models import bert, esm2, modernbert
from distllm_tpu.ops.encoder_attention import shape_supported

TOKEN_BUDGET = 1 << 17


def timed(fn, *args, n=6):
    out = fn(*args)
    jax.tree.leaves(out)[0].block_until_ready()
    np.asarray(jax.tree.leaves(out)[0][0, 0])  # tunnel-safe sync
    start = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0][0, 0])
    return (time.perf_counter() - start) / n


def emit(**kw):
    print(json.dumps(kw), flush=True)


def sweep(family: str, cfg, module, seqs):
    params = jax.device_put(module.init(jax.random.PRNGKey(0), cfg))
    rng = np.random.default_rng(0)
    for s in seqs:
        b = max(8, TOKEN_BUDGET // s)
        ids = jnp.asarray(
            rng.integers(4, cfg.vocab_size, size=(b, s)), jnp.int32
        )
        mask = jnp.ones((b, s), jnp.int32)
        backends = ['xla']
        if shape_supported(s, cfg.hidden_size, cfg.num_heads, 2,
                           has_bias='modernbert' in family):
            backends.append('pallas')
        for impl in backends:
            fn = jax.jit(
                lambda p, i, m, impl=impl: module.apply(
                    p, cfg, i, m, attn_impl=impl
                )
            )
            try:
                sec = timed(fn, params, ids, mask)
            except Exception as exc:  # Mosaic reject etc. — record, move on
                emit(family=family, seq=s, batch=b, backend=impl,
                     error=repr(exc)[:200])
                continue
            emit(
                family=family, seq=s, batch=b, backend=impl,
                ms=round(sec * 1e3, 1),
                tokens_per_s=round(b * s / sec),
                platform=jax.default_backend(),
            )
    del params


def main() -> None:
    bert_cfg = bert.BertConfig(dtype='bfloat16')
    sweep('bert-base', bert_cfg, bert, (160, 224, 256, 320, 352, 512))

    esm_cfg = esm2.Esm2Config(  # 650M dims (t33)
        vocab_size=33, hidden_size=1280, num_layers=33, num_heads=20,
        intermediate_size=5120, dtype='bfloat16',
    )
    sweep('esm2-650m', esm_cfg, esm2, (256, 512, 1024))

    mb_cfg = modernbert.ModernBertConfig(dtype='bfloat16')
    sweep('modernbert-base', mb_cfg, modernbert, (256, 512, 1024))


if __name__ == '__main__':
    main()
