"""Generation-loop breakdown on the real chip.

bf16 7B at batch 32 measured 605 tok/s (BENCH r3 interim) against a
~1,800 tok/s weight-bandwidth roofline (14.5 GB reads / 819 GB/s * batch
32 * 16-step window => >=283 ms/window floor). This instruments the
pipelined loop to see where the other ~550 ms/window goes: host-side
window planning (numpy input builds + device_put), dispatch gaps, or the
token fetch. Small mode (DISTLLM_BENCH_SMALL=1) runs tiny dims on CPU to
keep the instrumentation itself tested.
"""

from __future__ import annotations

import os
import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

import time

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import jax
import numpy as np

from distllm_tpu.generate.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distllm_tpu.models import mistral


def main() -> None:
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
        )
        engine_cfg = EngineConfig(
            block_size=16, num_blocks=128, max_num_seqs=8, max_model_len=256,
            decode_steps=8, pipeline_depth=2,
        )
        n_prompts, gen_tokens = 16, 32
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')
        engine_cfg = EngineConfig(
            block_size=16, num_blocks=712, max_num_seqs=32, max_model_len=512,
            decode_steps=16, pipeline_depth=2, attn_backend='pallas',
        )
        n_prompts, gen_tokens = 96, 128

    params = mistral.init_on_device(jax.random.PRNGKey(0), model_cfg)

    class _Tok:
        eos_id = None

    engine = LLMEngine(model_cfg, params, _Tok(), engine_cfg, own_params=True)
    engine.warmup()

    rng = np.random.default_rng(0)
    prompts = [
        list(rng.integers(1, model_cfg.vocab_size, size=int(n)))
        for n in rng.integers(32, 192, size=n_prompts)
    ]
    sampling = SamplingParams(
        temperature=0.5, top_p=0.95, min_p=0.1, max_tokens=gen_tokens
    )

    # Wrap the loop's phases with timers.
    stats = {'dispatch_s': 0.0, 'fetch_s': 0.0, 'n_fetch': 0}
    orig_dispatch = engine._dispatch_window
    orig_process = engine._process_window

    def timed_dispatch(carried):
        t0 = time.perf_counter()
        out = orig_dispatch(carried)
        stats['dispatch_s'] += time.perf_counter() - t0
        return out

    def timed_process(window):
        t0 = time.perf_counter()
        out = orig_process(window)
        stats['fetch_s'] += time.perf_counter() - t0
        stats['n_fetch'] += 1
        return out

    engine._dispatch_window = timed_dispatch
    engine._process_window = timed_process

    start = time.perf_counter()
    outs = engine.generate_ids(prompts, sampling)
    elapsed = time.perf_counter() - start
    n_tokens = sum(len(o) for o in outs)

    t = engine.telemetry
    windows = t.get('decode_windows', 0)
    print(f'tok/s: {n_tokens / elapsed:.1f}  ({n_tokens} tokens in {elapsed:.2f}s)')
    print(f'windows: {windows}  prefills: {t.get("prefill_dispatches")}  '
          f'overshoot: {t.get("overshoot_frac")}')
    if windows:
        print(f'per-window: total {elapsed / windows * 1e3:.1f} ms | '
              f'host dispatch {stats["dispatch_s"] / windows * 1e3:.1f} ms | '
              f'fetch wait {stats["fetch_s"] / max(1, stats["n_fetch"]) * 1e3:.1f} ms')
    # Shape metadata survives donation, so count from the live tree.
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(params)
    )
    bw = 819e9 if not small else None
    if bw:
        floor_s = engine_cfg.decode_steps * 2 * n_params / bw
        print(f'roofline window floor {floor_s * 1e3:.0f} ms '
              f'(weights {2 * n_params / 1e9:.1f} GB x {engine_cfg.decode_steps} steps @ 819 GB/s)')


if __name__ == '__main__':
    main()
