"""Bisect the decode-window program's HBM footprint via AOT memory analysis."""

from __future__ import annotations

import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import argparse

import jax
import jax.numpy as jnp

from distllm_tpu.models import mistral


def analyze(num_steps, attn_backend, num_blocks=488, b=24, sample=True):
    cfg = mistral.MistralConfig(dtype='bfloat16')
    L, bs, kv, hd = cfg.num_layers, 16, cfg.num_kv_heads, cfg.head_size
    R = (512 + bs - 1) // bs
    shapes = dict(
        params=jax.eval_shape(lambda: mistral.init_on_device(jax.random.PRNGKey(0), cfg)),
        ids=jax.ShapeDtypeStruct((b,), jnp.int32),
        pos=jax.ShapeDtypeStruct((b,), jnp.int32),
        ctx=jax.ShapeDtypeStruct((b,), jnp.int32),
        k=jax.ShapeDtypeStruct((L, num_blocks, bs, kv, hd), jnp.bfloat16),
        v=jax.ShapeDtypeStruct((L, num_blocks, bs, kv, hd), jnp.bfloat16),
        bt=jax.ShapeDtypeStruct((b, R), jnp.int32),
        steps=jax.ShapeDtypeStruct((b,), jnp.int32),
        f=jax.ShapeDtypeStruct((b,), jnp.float32),
        tk=jax.ShapeDtypeStruct((b,), jnp.int32),
        sd=jax.ShapeDtypeStruct((b,), jnp.uint32),
    )

    def fn(params, ids, pos, ctx, k, v, bt, steps, t, tp, mp, tk, sd):
        return mistral.decode_loop(
            params, cfg, ids, pos, k, v, bt, ctx, steps, t, tp, mp, tk, sd,
            num_steps=num_steps, attn_backend=attn_backend,
            max_table_positions=512,
        )

    lowered = jax.jit(fn, donate_argnums=(4, 5)).lower(
        shapes['params'], shapes['ids'], shapes['pos'], shapes['ctx'],
        shapes['k'], shapes['v'], shapes['bt'], shapes['steps'],
        shapes['f'], shapes['f'], shapes['f'], shapes['tk'], shapes['sd'],
    )
    compiled = lowered.compile()
    try:
        ma = compiled.memory_analysis()
        print(f'steps={num_steps} backend={attn_backend}: '
              f'args {ma.argument_size_in_bytes/2**30:.2f}G '
              f'out {ma.output_size_in_bytes/2**30:.2f}G '
              f'temp {ma.temp_size_in_bytes/2**30:.2f}G '
              f'alias {ma.alias_size_in_bytes/2**30:.2f}G')
    except Exception as e:
        print('no memory_analysis:', e)


if __name__ == '__main__':
    p = argparse.ArgumentParser()
    p.add_argument('--steps', type=int, default=8)
    p.add_argument('--backend', default='xla')
    p.add_argument('--b', type=int, default=24)
    p.add_argument('--num-blocks', type=int, default=488)
    args = p.parse_args()
    analyze(args.steps, args.backend, num_blocks=args.num_blocks, b=args.b)
