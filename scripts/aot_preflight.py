"""Compile-only preflight of the serving path against a v5e topology.

Round 2's blind spot: the Pallas decode kernel only failed ON the chip
(Mosaic lowering + HBM budgeting are invisible to CPU interpret tests).
The locally installed libtpu can build a COMPILE-ONLY PJRT topology
(``jax.experimental.topologies``) with no hardware attached, so every
serving executable — bf16 batch-32 and int8 batch-128 fused decode
windows, both attention backends, with the engine's AUTO-layout
compile — can be validated for lowering errors and HBM fit before a
single chip-second is spent. Run before benching; see also
tests/test_aot_tpu.py for the small-dims CI version.
"""

import os
os.environ.pop('JAX_PLATFORMS', None)
import numpy as np
import jax
jax.config.update('jax_platforms', 'cpu')
import jax.numpy as jnp
from jax.experimental import topologies
from jax.experimental.layout import Format, Layout
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
import pathlib, sys
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import time

# Seed the repo's persistent compilation cache: if the runtime produces
# matching keys, the bench's multi-minute warmup reuses these compiles.
try:
    jax.config.update(
        'jax_compilation_cache_dir',
        str(pathlib.Path(__file__).resolve().parent.parent / '.jax_cache'),
    )
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)
except Exception:
    pass

topo = topologies.get_topology_desc(platform='tpu', topology_name='v5e:2x2x1')
mesh = Mesh(np.asarray(topo.devices[:1]).reshape(1), ('x',))
s = NamedSharding(mesh, P())

def sds(shape, dtype):
    return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=s)

from distllm_tpu.models import mistral
from distllm_tpu.ops.quantization import quantize_pytree_abstract

mcfg = mistral.MistralConfig(dtype='bfloat16')
mshapes = jax.eval_shape(lambda: mistral.init_on_device(jax.random.PRNGKey(0), mcfg))
bs = 16

def window_args(params_tree, B, nb, R):
    kshape = (mcfg.num_layers, nb, bs, mcfg.num_kv_heads, mcfg.head_size)
    return (
        params_tree, sds((B,), jnp.int32), sds((B,), jnp.int32),
        sds((B,), jnp.int32), sds(kshape, jnp.bfloat16),
        sds(kshape, jnp.bfloat16), sds((B, R), jnp.int32),
        sds((B,), jnp.int32), sds((B,), jnp.float32),
        sds((B,), jnp.float32), sds((B,), jnp.float32),
        sds((B,), jnp.int32), sds((B,), jnp.uint32),
    )

# Match the engine's decode_layer_unroll so the seeded cache keys hit at
# serve time; export DISTLLM_PREFLIGHT_LAYER_UNROLL=0 when serving with
# decode_layer_unroll=False (the escape hatch from the longer compile).
_LAYER_UNROLL = os.environ.get('DISTLLM_PREFLIGHT_LAYER_UNROLL', '1') != '0'

failures: list[str] = []


def compile_window(params_tree, B, nb, R, backend, label):
    t = time.perf_counter()
    try:
        fn = lambda p, i, po, c, k, v, bt, sl, tmp, tp, mp, tk, sd: \
            mistral.decode_loop(
                p, mcfg, i, po, k, v, bt, c, sl, tmp, tp, mp, tk, sd,
                num_steps=16, attn_backend=backend, max_table_positions=512,
                sampling_top_window=64, layer_unroll=_LAYER_UNROLL)
        jitted = jax.jit(fn, donate_argnums=(4, 5),
                         in_shardings=(Format(Layout.AUTO),) + (Format(),) * 12)
        compiled = jitted.lower(*window_args(params_tree, B, nb, R)).compile()
        mem = compiled.memory_analysis()
        tmp_b = getattr(mem, 'temp_size_in_bytes', None)
        print(f'{label}: AOT OK ({time.perf_counter()-t:.0f}s) '
              f'temp={tmp_b/1e9 if tmp_b else "?"}GB', flush=True)
    except Exception as exc:
        print(f'{label}: FAILED {repr(exc)[:400]}', flush=True)
        failures.append(label)

bf16_params = jax.tree.map(lambda x: sds(x.shape, x.dtype), mshapes)
compile_window(bf16_params, 32, 712, 32, 'pallas', 'bf16 B=32 pallas AUTO-layout')
compile_window(bf16_params, 32, 712, 32, 'xla', 'bf16 B=32 xla AUTO-layout')

qparams = quantize_pytree_abstract(mshapes, make_leaf=sds)
compile_window(qparams, 128, 2840, 32, 'pallas', 'int8 B=128 pallas AUTO-layout')
compile_window(qparams, 128, 2840, 32, 'xla', 'int8 B=128 xla AUTO-layout')
print('SINGLE-CHIP CASES DONE', flush=True)


# ---- multi-chip lowering: SP ring attention + TP decode on real v5e devices
# (the CPU virtual mesh exercises semantics; this validates the TPU/ICI
# lowering of the same programs).
def compile_multichip() -> None:
    from distllm_tpu.ops.ring_attention import ring_attention

    t = time.perf_counter()
    try:
        devs = np.asarray(topo.devices).reshape(1, 2, 2)[:, :, :1]
        sp_mesh = Mesh(devs.reshape(1, 2), ('data', 'seq'))
        rs = NamedSharding(sp_mesh, P(None, 'seq', None, None))
        ms = NamedSharding(sp_mesh, P(None, 'seq'))
        B, S, N, H = 2, 256, 8, 128
        jax.jit(
            lambda q, k, v, m: ring_attention(
                q, k, v, sp_mesh, kv_mask=m, causal=True
            )
        ).lower(
            jax.ShapeDtypeStruct((B, S, N, H), jnp.bfloat16, sharding=rs),
            jax.ShapeDtypeStruct((B, S, N, H), jnp.bfloat16, sharding=rs),
            jax.ShapeDtypeStruct((B, S, N, H), jnp.bfloat16, sharding=rs),
            jax.ShapeDtypeStruct((B, S), jnp.bool_, sharding=ms),
        ).compile()
        print(f'SP ring attention 2-dev v5e: AOT OK '
              f'({time.perf_counter()-t:.0f}s)', flush=True)
    except Exception as exc:
        print(f'SP ring attention: FAILED {repr(exc)[:400]}', flush=True)
        failures.append('ring')

    t = time.perf_counter()
    try:
        tp_mesh = Mesh(np.asarray(topo.devices[:2]).reshape(2), ('model',))
        repl = NamedSharding(tp_mesh, P())
        kvs = NamedSharding(tp_mesh, P(None, None, None, 'model'))
        from distllm_tpu.parallel.sharding import shard_pytree  # noqa: F401
        specs = mistral.param_specs(mcfg)
        def spec_sharding(spec, leaf):
            return jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=NamedSharding(tp_mesh, spec)
            )
        tp_params = jax.tree.map(
            spec_sharding, specs, mshapes,
            is_leaf=lambda x: isinstance(x, P),
        )
        B = 8
        ksh = (mcfg.num_layers, 64, bs, mcfg.num_kv_heads, mcfg.head_size)
        def r(shape, dtype):
            return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=repl)
        jax.jit(
            lambda p, i, po, c, k, v, bt, sl, tmp, tp_, mp, ky:
                mistral.decode_loop(
                    p, mcfg, i, po, k, v, bt, c, sl, tmp, tp_, mp, ky,
                    num_steps=4, attn_backend='xla', max_table_positions=512,
                    sampling_top_window=64, layer_unroll=_LAYER_UNROLL),
            donate_argnums=(4, 5),
        ).lower(
            tp_params, r((B,), jnp.int32), r((B,), jnp.int32),
            r((B,), jnp.int32),
            jax.ShapeDtypeStruct(ksh, jnp.bfloat16, sharding=kvs),
            jax.ShapeDtypeStruct(ksh, jnp.bfloat16, sharding=kvs),
            r((B, 32), jnp.int32), r((B,), jnp.int32), r((B,), jnp.float32),
            r((B,), jnp.float32), r((B,), jnp.float32), r((2,), jnp.uint32),
        ).compile()
        print(f'TP=2 decode window v5e: AOT OK '
              f'({time.perf_counter()-t:.0f}s)', flush=True)
    except Exception as exc:
        print(f'TP=2 decode window: FAILED {repr(exc)[:400]}', flush=True)
        failures.append('tp')


compile_multichip()
print('MULTICHIP DONE', flush=True)


# ---- embed-stage executables: the bench's other warmup set. Mirrors
# JaxEncoder.pooled_forward's fused graph (encode -> mean pool -> fp32).
def compile_embed_set() -> None:
    from distllm_tpu.embed import get_pooler
    from distllm_tpu.models import bert
    from distllm_tpu.ops.quantization import quantize_pytree_abstract

    cfg = bert.BertConfig(dtype='bfloat16')
    host = bert.init(jax.random.PRNGKey(0), cfg)
    f32_params = jax.tree.map(lambda x: sds(np.shape(x), jnp.float32), host)
    del host
    int8_params = quantize_pytree_abstract(f32_params, make_leaf=sds)
    pooler = get_pooler({'name': 'mean'})

    def fused(p, ids, mask):
        pooled = pooler.pool(bert.apply(p, cfg, ids, mask), mask)
        return pooled.astype(jnp.float32)

    for label, params in (('f32', f32_params), ('int8', int8_params)):
        for S in (160, 192, 224, 256, 288, 320, 352):
            t = time.perf_counter()
            try:
                jax.jit(fused).lower(
                    params, sds((512, S), jnp.int32), sds((512, S), jnp.int32)
                ).compile()
                print(f'embed fused {label} S={S}: AOT OK '
                      f'({time.perf_counter()-t:.0f}s)', flush=True)
            except Exception as exc:
                print(f'embed fused {label} S={S}: FAILED '
                      f'{repr(exc)[:300]}', flush=True)
                failures.append(f'embed-{label}-{S}')


compile_embed_set()
print('EMBED SET DONE' + (f' ({len(failures)} FAILED)' if failures else ''),
      flush=True)
sys.exit(1 if failures else 0)
