"""Device-rate probe v2: in-jit repetition, slope timing.

probe_bw.py times single dispatches; on the axon tunnel every number it
prints is the ~64 ms round trip, not the device (its own log proves it:
identical times for a 64 MiB sum and a 1024-row matmul). Here every
measured op runs R times INSIDE one jitted ``lax.fori_loop`` with a
data dependency that defeats CSE/hoisting, and the device time per op is
the slope between two R values — the RTT and dispatch costs cancel.

What it measures (the calibration numbers every roofline claim rests on):

- HBM stream-read bandwidth (512 MiB sum per iteration),
- decode-regime matmul weight-stream rate at M=32/128 (bf16 and
  int8-weight scale-after-dot),
- the serving sampler (top_k(64)+full-vocab logsumexp over [B, 32k]) —
  per-step cost inside the decode window,
- paged KV scatter+gather at serving dims.
"""

from __future__ import annotations

import pathlib as _pl
import sys as _sys

_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np


def slope(make_fn, r1=4, r2=20):
    """Seconds per iteration from the (r2, r1) slope; RTT cancels."""
    f1, f2 = make_fn(r1), make_fn(r2)
    out = f1()
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    out = f2()
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]

    def timed(f, n=3):
        t0 = time.perf_counter()
        for _ in range(n):
            o = f()
        np.asarray(jax.tree.leaves(o)[0]).ravel()[:1]
        return (time.perf_counter() - t0) / n

    return max(1e-9, (timed(f2) - timed(f1)) / (r2 - r1))


def main() -> None:
    dev = jax.devices()[0]
    print(f'device: {dev.device_kind}')

    # --- HBM stream read ------------------------------------------------
    big = jnp.ones((512 * 1024 * 1024 // 4,), jnp.float32)

    def make_sum(r):
        @jax.jit
        def f(x):
            def body(_, acc):
                return jnp.sum(x + acc * 1e-30)

            return jax.lax.fori_loop(0, r, body, 0.0)

        return functools.partial(f, big)

    per = slope(make_sum)
    print(f'stream read 512 MiB: {per * 1e3:7.2f} ms/iter -> '
          f'{big.nbytes / per / 1e9:6.0f} GB/s')

    # --- decode matmul weight stream ------------------------------------
    for m in (32, 128):
        for name, wdtype in (('bf16', jnp.bfloat16), ('int8', jnp.int8)):
            k = n = 8192
            w = (jnp.ones((k, n), wdtype))
            s = jnp.ones((1, n), jnp.float32)
            x0 = jnp.ones((m, k), jnp.bfloat16)

            def make_mm(r, w=w, s=s, x0=x0, int8=(wdtype == jnp.int8)):
                @jax.jit
                def f(x, w, s):
                    def body(_, xc):
                        y = jax.lax.dot_general(
                            xc, w.astype(jnp.bfloat16) if int8 else w,
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        )
                        if int8:
                            y = y * s
                        return (xc + y.astype(jnp.bfloat16) * 1e-30)

                    return jax.lax.fori_loop(0, r, body, x)

                return functools.partial(f, x0, w, s)

            per = slope(make_mm)
            print(f'[{m:3d}x{k}x{n}] {name} matmul: {per * 1e6:8.1f} us/iter'
                  f' -> weight stream {w.nbytes / per / 1e9:6.0f} GB/s')

    # --- serving sampler -------------------------------------------------
    from distllm_tpu.ops.sampling import sample_tokens

    for b, v in ((32, 32000), (128, 32000)):
        logits0 = jnp.asarray(
            np.random.default_rng(0).standard_normal((b, v)), jnp.float32
        )
        temp = jnp.full((b,), 0.5, jnp.float32)
        top_p = jnp.full((b,), 0.95, jnp.float32)
        min_p = jnp.full((b,), 0.1, jnp.float32)

        def make_samp(r, logits0=logits0, temp=temp, top_p=top_p,
                      min_p=min_p):
            @jax.jit
            def f(lg, key):
                def body(i, carry):
                    lg_c, key_c = carry
                    key_c, sub = jax.random.split(key_c)
                    tok = sample_tokens(
                        lg_c, sub, temp, top_p, min_p, top_window=64
                    )
                    lg_c = lg_c + tok[:, None].astype(jnp.float32) * 1e-30
                    return (lg_c, key_c)

                return jax.lax.fori_loop(
                    0, r, body, (lg, key)
                )[0]

            return functools.partial(f, logits0, jax.random.PRNGKey(0))

        per = slope(make_samp)
        print(f'sampler tw=64 [B={b:3d}, V={v}]: {per * 1e6:8.1f} us/step'
              f' ({per * 16 * 1e3:5.1f} ms per 16-step window)')

    # --- lm_head + sampler combo (the per-step tail after the layers) ---
    for b in (32, 128):
        h0 = jnp.ones((b, 4096), jnp.bfloat16)
        wlm = jnp.ones((4096, 32000), jnp.bfloat16)
        temp = jnp.full((b,), 0.5, jnp.float32)
        top_p = jnp.full((b,), 0.95, jnp.float32)
        min_p = jnp.full((b,), 0.1, jnp.float32)

        def make_tail(r, h0=h0, wlm=wlm, temp=temp, top_p=top_p,
                      min_p=min_p):
            @jax.jit
            def f(h, w, key):
                def body(i, carry):
                    hc, key_c = carry
                    key_c, sub = jax.random.split(key_c)
                    lg = jax.lax.dot_general(
                        hc, w, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    tok = sample_tokens(
                        lg, sub, temp, top_p, min_p, top_window=64
                    )
                    hc = hc + tok[:, None].astype(jnp.bfloat16) * 1e-30
                    return (hc, key_c)

                return jax.lax.fori_loop(0, r, body, (h, key))[0]

            return functools.partial(f, h0, wlm, jax.random.PRNGKey(0))

        per = slope(make_tail)
        print(f'lm_head+sampler [B={b:3d}]: {per * 1e6:8.1f} us/step'
              f' ({per * 16 * 1e3:5.1f} ms per 16-step window)')


if __name__ == '__main__':
    main()
