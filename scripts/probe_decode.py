"""Isolated decode-window microbench at 7B dims on the real chip.

probe_gen times the full serving loop; this times ONE fused decode window
dispatch in isolation across the knobs that matter, to localize the gap
between the measured window time and the ~283 ms weight-streaming floor
(14.5 GB x 16 steps / 819 GB/s):

- attention backend: pallas vs xla
- window length: decode_steps 1 / 8 / 16 / 32 (per-token cost should fall
  as dispatch overhead amortizes; if it doesn't, the per-step compute is
  the problem, not dispatch)
- sampler: top-64 window vs exact full-vocab sort (the 32k bitonic sort
  per step is a prime suspect)
- layer scan rolled vs unrolled at the serving window (the materialized
  weight-slice hypothesis, scripts/probe_decode_hlo.py)
"""

from __future__ import annotations

import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.models import mistral


def main() -> None:
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
        )
        batch, num_blocks, steps_list = 8, 128, (1, 8)
        backends = ('xla',)
    else:
        cfg = mistral.MistralConfig(dtype='bfloat16')
        batch, num_blocks, steps_list = 32, 712, (1, 8, 16, 32)
        backends = ('pallas', 'xla')

    block_size = 16
    max_blocks = 512 // block_size
    params = mistral.init_on_device(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    kshape = (cfg.num_layers, num_blocks, block_size, cfg.num_kv_heads,
              cfg.head_size)

    rng = np.random.default_rng(0)
    ctx = 160  # mid-run context length
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, size=(batch,)), jnp.int32)
    positions = jnp.full((batch,), ctx - 1, jnp.int32)
    context_lens = jnp.full((batch,), ctx, jnp.int32)
    rows = np.zeros((batch, max_blocks), np.int32)
    used = -(-ctx // block_size) + 3
    for b in range(batch):
        rows[b, :used] = 1 + (np.arange(used) * batch + b) % (num_blocks - 1)
    block_tables = jnp.asarray(rows)
    temp = jnp.full((batch,), 0.5, jnp.float32)
    top_p = jnp.full((batch,), 0.95, jnp.float32)
    min_p = jnp.full((batch,), 0.1, jnp.float32)
    top_k = jnp.zeros((batch,), jnp.int32)
    seeds = jnp.ones((batch,), jnp.uint32)

    weight_gb = 2 * n_params / 1e9
    print(f'batch={batch} ctx={ctx} weights={weight_gb:.1f} GB')
    cases = [(be, ns, 64, False) for be in backends for ns in steps_list]
    # Sampler ablation: exact 32k sort at the serving window length.
    cases.append((backends[0], steps_list[-1], 0, False))
    # The rolled-vs-unrolled A/B at the SERVING window length (16 — the
    # shape behind the r3 845 ms measurement and the 283 ms floor; the
    # materialized weight-slice hypothesis, scripts/probe_decode_hlo.py):
    # unrolled should approach the floor if the slices were the gap.
    serving_steps = 16 if 16 in steps_list else steps_list[-1]
    for be in backends:
        cases.append((be, serving_steps, 64, True))
    for backend, num_steps, top_window, unroll in cases:
            fn = jax.jit(
                lambda p, i, po, c, k, v, bt, sl, t, tp, mp, tk, sd,
                       ns=num_steps,
                       be=backend, tw=top_window, un=unroll: mistral.decode_loop(
                    p, cfg, i, po, k, v, bt, c, sl, t, tp, mp, tk, sd,
                    num_steps=ns, attn_backend=be, max_table_positions=512,
                    sampling_top_window=tw, layer_unroll=un,
                ),
                donate_argnums=(4, 5),
            )
            steps_left = jnp.full((batch,), num_steps, jnp.int32)
            # Fresh caches per case: donation deletes them on dispatch, so
            # a mid-case failure (the flaky-chip scenario this probe
            # exists for) must not cascade 'Array deleted' into the rest.
            k_cache = jnp.zeros(kshape, jnp.bfloat16)
            v_cache = jnp.zeros(kshape, jnp.bfloat16)
            try:
                t0 = time.perf_counter()
                out = fn(params, ids, positions, context_lens, k_cache,
                         v_cache, block_tables, steps_left, temp, top_p,
                         min_p, top_k, seeds)
                tokens, k_cache, v_cache, _ = out
                np.asarray(tokens)
                compile_s = time.perf_counter() - t0
                # Chain 4 windows without per-call host syncs (donated
                # caches chain naturally); one final fetch, so the ~68 ms
                # tunnel round trip amortizes instead of padding each call.
                n_reps = 4
                t0 = time.perf_counter()
                outs = []
                for _ in range(n_reps):
                    tokens, k_cache, v_cache, _ = fn(
                        params, ids, positions, context_lens, k_cache,
                        v_cache, block_tables, steps_left, temp, top_p,
                        min_p, top_k, seeds)
                    outs.append(tokens)
                for t in outs:
                    np.asarray(t)
                best = (time.perf_counter() - t0) / n_reps
                floor = num_steps * 2 * n_params / 819e9
                print(f'{backend:6s} steps={num_steps:2d} tw={top_window:2d}'
                      f' unroll={int(unroll)}:'
                      f' {best*1e3:7.1f} ms'
                      f' ({best/num_steps*1e3:6.2f} ms/step,'
                      f' {batch*num_steps/best:7.0f} tok/s,'
                      f' floor {floor*1e3:5.0f} ms, x{best/floor:4.1f})',
                      flush=True)
            except Exception as exc:
                print(f'{backend:6s} steps={num_steps:2d} tw={top_window:2d}'
                      f' unroll={int(unroll)}:'
                      f' FAILED {repr(exc)[:200]}', flush=True)


if __name__ == '__main__':
    main()
