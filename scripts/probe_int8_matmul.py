"""A/B the int8 matmul tiers against bf16 at the 7B decode shapes.

Run on the chip after the r5 run-1 finding (int8 decode windows 6x off
their floor): for each decode matmul shape of Mistral-7B this times

- bf16 dense (the bandwidth baseline: weight bytes = 2/elem),
- the OLD dequantize-then-dot formulation (what run 1 served),
- the XLA scale-after-dot tier,
- the Pallas in-VMEM-dequant kernel (weight bytes = 1/elem -> should beat
  bf16 by ~2x when weight-streaming bound).

Each case reports ms/call and achieved weight-stream GB/s. Small mode
(DISTLLM_BENCH_SMALL=1) runs tiny shapes on CPU (interpret for pallas)
to keep the probe itself tested.
"""

from __future__ import annotations

import os
import pathlib as _pl
import sys as _sys

_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import time

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.ops import quantized_matmul as qmm
from distllm_tpu.ops.quantization import quantize_int8


def _time(fn, *args, reps=64):
    """ms/call with the ~66 ms tunnel RTT amortized: queue `reps` async
    dispatches, host-sync ONCE on the last output. Per-call sync would
    measure the tunnel, not the kernel (first version of this probe did —
    every case reported exactly the RTT)."""
    out = fn(*args)
    np.asarray(out[0, :1])  # compile + settle
    t0 = time.perf_counter()
    for _ in range(reps - 1):
        out = fn(*args)
    out = fn(*args)
    np.asarray(out[0, :1])
    return (time.perf_counter() - t0) / reps


def main() -> None:
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    interpret = jax.default_backend() != 'tpu'
    if small:
        shapes = [(8, 512, 256)]
    else:
        # Mistral-7B decode matmuls at serving batches 32 and 128:
        # qkv [4096->6144 fused q+k+v], o [4096->4096],
        # gate/up [4096->14336], down [14336->4096], lm_head [4096->32000].
        shapes = [
            (b, k, n)
            for b in (32, 128)
            for k, n in [
                (4096, 4096),
                (4096, 14336),
                (14336, 4096),
                (4096, 32000),
            ]
        ]

    rng = np.random.default_rng(0)
    for m, k, n in shapes:
        x = jnp.asarray(
            rng.standard_normal((m, k)).astype(np.float32), jnp.bfloat16
        )
        w = rng.standard_normal((k, n)).astype(np.float32) * 0.02
        qt = quantize_int8(w)
        wd = jnp.asarray(w, jnp.bfloat16)
        del w

        bf16 = jax.jit(lambda a, b: a @ b)
        old = jax.jit(
            lambda a, q, s: a @ (q.astype(a.dtype) * s.astype(a.dtype))
        )
        xla = jax.jit(qmm.int8_matmul_xla)
        cases = [
            ('bf16', lambda: _time(bf16, x, wd), 2),
            ('old-dequant', lambda: _time(old, x, qt.q, qt.scale), 1),
            ('xla-scale-after', lambda: _time(xla, x, qt.q, qt.scale), 1),
        ]
        if qmm.pallas_supported(m, k, n):
            pallas = jax.jit(
                lambda a, q, s: qmm.int8_matmul_pallas(
                    a, q, s, interpret=interpret
                )
            )
            cases.append(
                ('pallas', lambda: _time(pallas, x, qt.q, qt.scale), 1)
            )
        print(f'[{m:4d}x{k:5d}x{n:5d}]', flush=True)
        for name, run, bytes_per_w in cases:
            try:
                sec = run()
                gbs = k * n * bytes_per_w / sec / 1e9
                print(
                    f'  {name:16s} {sec * 1e6:9.1f} us'
                    f'  weight-stream {gbs:7.1f} GB/s',
                    flush=True,
                )
            except Exception as exc:
                print(f'  {name:16s} FAILED {repr(exc)[:160]}', flush=True)


if __name__ == '__main__':
    main()
