#!/usr/bin/env python
"""distlint — static analysis for distllm-tpu serving invariants.

Thin wrapper so the analyzer runs without an installed package::

    python scripts/distlint.py            # text findings, exit 1 if any
    python scripts/distlint.py --json     # stable JSON report
    python scripts/distlint.py --list-rules

The implementation lives in ``distllm_tpu/analysis/`` (see
``docs/static_analysis.md``); tier-1 enforces the same rules via
``tests/test_lint.py``.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from distllm_tpu.analysis.cli import main  # noqa: E402

if __name__ == '__main__':
    sys.exit(main())
