"""int8 decode-window A/B: which quantized-matmul tier should serve?

Same isolated-window methodology as probe_decode.py (the only timing that
amortizes the ~66 ms tunnel dispatch: one jitted 16-step unrolled window,
4 windows chained, one host sync) but with int8-quantized weights, at the
serving batch (128, 2840 blocks — bench gen_q dims) and the bf16 batch
(32) for cross-reference.

Context (chipback_r05): run 1 served int8 via dequant-before-dot at
1242 ms/window; run 2 picked up the Pallas in-VMEM-dequant kernel and got
SLOWER (2046 ms). The isolated-matmul probe can't see why (dispatch-bound
at 1.3 ms/call), so this times the real window per tier. Floor at batch
128: 16 steps x 7.25 GB int8 / 819 GB/s = 142 ms + ~60 ms KV reads.
"""

from __future__ import annotations

import os
import pathlib as _pl
import sys as _sys

_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import time

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.models import mistral
from distllm_tpu.ops import quantized_matmul as qmm
from distllm_tpu.ops.quantization import quantize_pytree


def main() -> None:
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    if small:
        cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
        )
        batches = ((8, 128),)
        cases = (('xla', 'xla'),)
    else:
        cfg = mistral.MistralConfig(dtype='bfloat16')
        batches = ((32, 712), (128, 2840))
        # First sweep (05:52 log) settled the qmm tier: xla scale-after-dot
        # beats the pallas dequant kernel at every serving shape. Remaining
        # question is the ATTENTION backend at int8 batches: the xla paged
        # path materializes a [B, 512, kv, 128] gather per layer-step,
        # which scales with batch and is the prime suspect for batch 128
        # sitting 7x off the weight floor.
        cases = (('xla', 'xla'), ('xla', 'pallas'))

    block_size = 16
    max_blocks = 512 // block_size
    params = mistral.init_on_device(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    params = quantize_pytree(
        params, mode='int8', out_dtype=cfg.dtype, delete_source=True
    )
    int8_gb = n_params / 1e9
    print(f'int8 weights ~{int8_gb:.1f} GB', flush=True)

    num_steps = 16
    ctx = 160
    rng = np.random.default_rng(0)
    for batch, num_blocks in batches:
        kshape = (cfg.num_layers, num_blocks, block_size, cfg.num_kv_heads,
                  cfg.head_size)
        ids = jnp.asarray(
            rng.integers(1, cfg.vocab_size, size=(batch,)), jnp.int32
        )
        positions = jnp.full((batch,), ctx - 1, jnp.int32)
        context_lens = jnp.full((batch,), ctx, jnp.int32)
        rows = np.zeros((batch, max_blocks), np.int32)
        used = -(-ctx // block_size) + 3
        for b in range(batch):
            rows[b, :used] = 1 + (np.arange(used) * batch + b) % (
                num_blocks - 1
            )
        block_tables = jnp.asarray(rows)
        temp = jnp.full((batch,), 0.5, jnp.float32)
        top_p = jnp.full((batch,), 0.95, jnp.float32)
        min_p = jnp.full((batch,), 0.1, jnp.float32)
        steps_left = jnp.full((batch,), num_steps, jnp.int32)
        top_k = jnp.zeros((batch,), jnp.int32)
        seeds = jnp.ones((batch,), jnp.uint32)

        for qmm_backend, attn_backend in cases:
            qmm.set_default_backend(qmm_backend)
            fn = jax.jit(
                lambda p, i, po, c, k, v, bt, sl, t, tp, mp, tk, sd, ab=attn_backend: (
                    mistral.decode_loop(
                        p, cfg, i, po, k, v, bt, c, sl, t, tp, mp, tk, sd,
                        num_steps=num_steps, attn_backend=ab,
                        max_table_positions=512, sampling_top_window=64,
                        layer_unroll=True,
                    )
                ),
                donate_argnums=(4, 5),
            )
            k_cache = jnp.zeros(kshape, jnp.bfloat16)
            v_cache = jnp.zeros(kshape, jnp.bfloat16)
            try:
                t0 = time.perf_counter()
                tokens, k_cache, v_cache, _ = fn(
                    params, ids, positions, context_lens, k_cache, v_cache,
                    block_tables, steps_left, temp, top_p, min_p, top_k,
                    seeds,
                )
                np.asarray(tokens)
                compile_s = time.perf_counter() - t0
                n_reps = 4
                t0 = time.perf_counter()
                outs = []
                for _ in range(n_reps):
                    tokens, k_cache, v_cache, _ = fn(
                        params, ids, positions, context_lens, k_cache,
                        v_cache, block_tables, steps_left, temp, top_p,
                        min_p, top_k, seeds,
                    )
                    outs.append(tokens)
                for t in outs:
                    np.asarray(t)
                best = (time.perf_counter() - t0) / n_reps
                floor = num_steps * n_params / 819e9
                print(
                    f'batch={batch:3d} qmm={qmm_backend:6s}'
                    f' attn={attn_backend:6s}:'
                    f' {best * 1e3:7.1f} ms/window'
                    f' ({batch * num_steps / best:7.0f} tok/s,'
                    f' int8 floor {floor * 1e3:4.0f} ms, x{best / floor:4.1f},'
                    f' compile {compile_s:.0f} s)',
                    flush=True,
                )
            except Exception as exc:
                print(
                    f'batch={batch:3d} qmm={qmm_backend:6s}'
                    f' attn={attn_backend:6s}:'
                    f' FAILED {repr(exc)[:200]}',
                    flush=True,
                )
            finally:
                qmm.set_default_backend('auto')
        del k_cache, v_cache


if __name__ == '__main__':
    main()
