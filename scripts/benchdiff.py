"""Bench trajectory gate: diff any set of BENCH_r*.json records.

The repo carries one official bench record per round (``BENCH_r01.json``
.. ``BENCH_r05.json``) plus interim chipback fragments, and until now the
only way to read the trajectory was eyeballing JSON — which is how a
184 → 830 tok/s improvement and two all-zero rounds coexisted with no
gate noticing either. This script turns the record pile into a gate:

- load any set of record files (the driver-contract JSON: ``{"n", "cmd",
  "rc", "parsed": {...}}``, or a bare metrics object), oldest first;
- extract the numeric metrics from each record's ``parsed`` payload
  (records that died before emitting — ``parsed: null`` — contribute an
  explicitly empty column, not a crash);
- emit a markdown trajectory table (one row per metric, one column per
  round, delta column for the newest round);
- **gate**: compare the newest record against the most recent prior
  record carrying each gated metric; exit nonzero when a throughput /
  MFU / goodput metric fell (or a latency / warmup metric rose) by more
  than ``--threshold`` (default 5%). Metrics present earlier but missing
  from the newest record are reported as *lost* — a warning by default
  (the r03–r05 tail is known-bad), a failure under ``--strict-missing``.

Usage::

    python scripts/benchdiff.py BENCH_r01.json BENCH_r02.json
    python scripts/benchdiff.py BENCH_r*.json --markdown TRAJECTORY.md
    python scripts/benchdiff.py r02.json candidate.json --threshold 0.03
    python scripts/benchdiff.py BENCH_r*.json --emit-baseline baseline.json

``--emit-baseline`` distills the newest record that carried metrics into
the **baseline envelope** the runtime regression sentinel consumes
(``distllm_tpu/observability/sentinel.py``; arm a server with
``DISTLLM_BASELINE=<path>``). Record parsing and gate directions live in
``distllm_tpu.observability.baseline`` — SHARED with the sentinel, so
the offline gate and the runtime sentinel can never disagree on what a
record says; this script re-exports them for its library consumers.

Runs in the fast test tier over the real r01/r02 records
(``tests/test_benchdiff.py``); dependency-free (no jax import).
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from distllm_tpu.observability.baseline import (  # noqa: E402
    envelope_from_records,
    extract_metrics,
    gate_direction,
    load_record,
)

__all__ = [
    'diff_records',
    'envelope_from_records',
    'extract_metrics',
    'format_markdown',
    'gate_direction',
    'load_record',
    'main',
]


def diff_records(
    records: list[dict], threshold: float
) -> tuple[list[dict], list[str]]:
    """Gate the NEWEST record against the most recent prior value of each
    gated metric. Returns ``(regressions, lost)``:

    - regressions: ``{'key', 'prior', 'prior_name', 'current', 'delta'}``
      for each gated metric that moved in the bad direction by more than
      ``threshold`` (fractional);
    - lost: gated metric keys present in some prior record but absent
      from the newest one.

    Comparison is newest-vs-most-recent-prior (not first-vs-last): the
    gate answers "did the round under review regress?", and older rounds'
    internal history is the table's job, not the gate's.
    """
    if len(records) < 2:
        return [], []
    current = records[-1]
    regressions: list[dict] = []
    lost: list[str] = []
    gated_keys = sorted({
        key
        for record in records
        for key in record['metrics']
        if gate_direction(key) is not None
    })
    for key in gated_keys:
        prior = prior_name = None
        for record in reversed(records[:-1]):
            if key in record['metrics']:
                prior = record['metrics'][key]
                prior_name = record['name']
                break
        if prior is None:
            continue  # brand-new metric: nothing to regress against
        if key not in current['metrics']:
            lost.append(key)
            continue
        value = current['metrics'][key]
        if prior == 0:
            continue  # no meaningful relative delta off a zero baseline
        delta = (value - prior) / abs(prior)
        bad = (
            delta < -threshold
            if gate_direction(key) == 'higher'
            else delta > threshold
        )
        if bad:
            regressions.append({
                'key': key,
                'prior': prior,
                'prior_name': prior_name,
                'current': value,
                'delta': delta,
            })
    return regressions, lost


def _format_value(value: float | None) -> str:
    if value is None:
        return '—'
    if not math.isfinite(value):  # belt-and-braces: extraction drops these
        return str(value)
    if value == int(value) and abs(value) < 1e12:
        return str(int(value))
    return f'{value:.6g}'


def format_markdown(records: list[dict], threshold: float) -> str:
    """The trajectory table plus the gate verdicts, as markdown."""
    keys = sorted({key for r in records for key in r['metrics']})
    names = [r['name'] for r in records]
    lines = [
        '# Bench trajectory',
        '',
        '| metric | ' + ' | '.join(names) + ' | Δ newest | gate |',
        '| --- |' + ' --- |' * (len(names) + 2),
    ]
    regressions, lost = diff_records(records, threshold)
    regressed = {r['key']: r for r in regressions}
    for key in keys:
        values = [r['metrics'].get(key) for r in records]
        prior = next(
            (v for v in reversed(values[:-1]) if v is not None), None
        )
        current = values[-1]
        if current is None:
            delta = 'lost' if prior is not None else '—'
        elif prior in (None, 0):
            delta = 'new'
        else:
            delta = f'{(current - prior) / abs(prior):+.1%}'
        direction = gate_direction(key)
        if direction is None:
            gate = ''
        elif key in regressed:
            gate = '**REGRESSED**'
        elif key in lost:
            gate = 'lost'
        else:
            gate = 'ok'
        lines.append(
            f'| {key} | '
            + ' | '.join(_format_value(v) for v in values)
            + f' | {delta} | {gate} |'
        )
    errors = [(r['name'], r['error']) for r in records if r.get('error')]
    if errors:
        lines.append('')
        for name, error in errors:
            lines.append(f'- `{name}`: {error}')
    lines.append('')
    if regressions:
        lines.append(
            f'**{len(regressions)} regression(s)** beyond '
            f'{threshold:.0%} in `{records[-1]["name"]}`:'
        )
        for reg in regressions:
            lines.append(
                f'- `{reg["key"]}`: {_format_value(reg["prior"])} '
                f'(`{reg["prior_name"]}`) → {_format_value(reg["current"])} '
                f'({reg["delta"]:+.1%})'
            )
    elif lost:
        lines.append(
            f'No regressions among reported metrics; {len(lost)} gated '
            f'metric(s) missing from `{records[-1]["name"]}`: '
            + ', '.join(f'`{k}`' for k in lost)
        )
    else:
        lines.append(f'No regressions beyond {threshold:.0%}.')
    return '\n'.join(lines) + '\n'


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        'records', nargs='+',
        help='record files, oldest first (BENCH_r01.json BENCH_r02.json ...)',
    )
    parser.add_argument(
        '--threshold', type=float, default=0.05,
        help='fractional regression threshold (default 0.05 = 5%%)',
    )
    parser.add_argument(
        '--markdown', type=str, default=None,
        help='also write the trajectory table to this path',
    )
    parser.add_argument(
        '--strict-missing', action='store_true',
        help='treat gated metrics missing from the newest record as '
             'regressions (off by default: the r03-r05 tail is known-bad)',
    )
    parser.add_argument(
        '--emit-baseline', type=str, default=None, metavar='PATH',
        help='write the baseline envelope (newest record with metrics) '
             'for the runtime regression sentinel; works with any record '
             'count — zero usable records emits an empty envelope the '
             'sentinel disarms on (counted), never a crash',
    )
    args = parser.parse_args(argv)

    records = [load_record(path) for path in args.records]
    if args.emit_baseline is not None:
        envelope = envelope_from_records(records)
        Path(args.emit_baseline).write_text(
            json.dumps(envelope, indent=2) + '\n'
        )
        print(
            f'baseline envelope -> {args.emit_baseline} '
            f'({len(envelope["metrics"])} metric(s) from '
            f'{envelope["source"] or "no usable record"})'
        )
        if len(records) < 2:
            return 0  # envelope-only invocation: nothing to diff
    if len(records) < 2:
        print('need at least two records to diff', file=sys.stderr)
        return 2
    report = format_markdown(records, args.threshold)
    sys.stdout.write(report)
    if args.markdown:
        Path(args.markdown).write_text(report)
    regressions, lost = diff_records(records, args.threshold)
    if regressions:
        return 1
    if lost and args.strict_missing:
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
