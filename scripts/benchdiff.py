"""Bench trajectory gate: diff any set of BENCH_r*.json records.

The repo carries one official bench record per round (``BENCH_r01.json``
.. ``BENCH_r05.json``) plus interim chipback fragments, and until now the
only way to read the trajectory was eyeballing JSON — which is how a
184 → 830 tok/s improvement and two all-zero rounds coexisted with no
gate noticing either. This script turns the record pile into a gate:

- load any set of record files (the driver-contract JSON: ``{"n", "cmd",
  "rc", "parsed": {...}}``, or a bare metrics object), oldest first;
- extract the numeric metrics from each record's ``parsed`` payload
  (records that died before emitting — ``parsed: null`` — contribute an
  explicitly empty column, not a crash);
- emit a markdown trajectory table (one row per metric, one column per
  round, delta column for the newest round);
- **gate**: compare the newest record against the most recent prior
  record carrying each gated metric; exit nonzero when a throughput /
  MFU / goodput metric fell (or a latency / warmup metric rose) by more
  than ``--threshold`` (default 5%). Metrics present earlier but missing
  from the newest record are reported as *lost* — a warning by default
  (the r03–r05 tail is known-bad), a failure under ``--strict-missing``.

Usage::

    python scripts/benchdiff.py BENCH_r01.json BENCH_r02.json
    python scripts/benchdiff.py BENCH_r*.json --markdown TRAJECTORY.md
    python scripts/benchdiff.py r02.json candidate.json --threshold 0.03

Runs in the fast test tier over the real r01/r02 records
(``tests/test_benchdiff.py``); dependency-free (no jax import).
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from pathlib import Path

# Direction of "better" per gated metric. Matching is by substring /
# suffix on the flattened key; anything unmatched is informational only
# (shown in the table, never gated) — counts, batch sizes, cache-entry
# bookkeeping must not fail a round. 'mfu_measured' / 'bw_util_measured'
# gate the per-kind XLA-measured roofline columns the gen_kernel A/B
# stage records (gen_kernel_{xla,pallas}_{mfu,bw_util}_measured,
# docs/observability.md "Measured vs analytic MFU") so a kernel
# regression — measured utilization falling on the same workload — trips
# the trajectory gate even when tok/s noise hides it.
_LOWER_BETTER_TOKENS = ('ttft', 'tpot', 'queue_wait', 'warmup_secs')
_HIGHER_BETTER_SUFFIXES = ('value', 'mfu', 'vs_baseline')
# 'promotion_overlap' gates the gen_tier stage's KV-tier prefetch
# efficiency (1 - blocking wait / promotion span, docs/prefix_caching.md
# "Tier hierarchy"): overlap falling means host→device promotions stopped
# hiding behind decode windows. The stage's warm-TTFT metrics gate
# lower-better via the 'ttft' token (gen_tier_warm_ttft_s /
# gen_tier_cold_ttft_s), and gen_tier_warm_ttft_speedup higher-better via
# the 'speedup' override above, so a tier regression trips the gate from
# either side. Raw spill/promotion COUNTS stay informational — workload-
# dependent volume, not quality.
#
# 'recoveries' gates the gen_chaos stage (docs/resilience.md): fewer
# recoveries on the SAME deterministic fault schedule means injected
# faults stopped being survived — requests started failing (or the
# schedule stopped firing) instead of retrying back to identical tokens.
# Goodput-under-fault gates through the existing 'goodput' token
# (gen_chaos_goodput_tokens). Shed counts/rates stay INFORMATIONAL by
# design: shed volume is offered-load policy, not quality — a round that
# sheds more under a heavier schedule is not a regression ('shed_rate'
# deliberately matches no gated token).
# 'greedy_match' gates the gen_kvq stage's ACCURACY arm (docs/serving.md
# "Quantized KV cache"): the fraction of the int8-KV arm's greedy tokens
# matching the bf16-KV arm's on the same workload. Falling match fraction
# is a QUALITY regression — the compression got lossier — and trips the
# trajectory gate exactly like a throughput fall; the stage records the
# divergence rather than asserting it away, and this token is what keeps
# that honesty enforceable round over round. Direction rule: higher is
# better (1.0 = bit-identical streams), so the generic higher-better
# machinery applies; a tolerance is the gate --threshold, not a
# stage-side epsilon.
_HIGHER_BETTER_TOKENS = (
    'goodput', 'accept_rate', 'hit_rate', 'tok_s', 'mfu_measured',
    'bw_util_measured', 'promotion_overlap', 'recoveries', 'greedy_match',
)


def gate_direction(key: str) -> str | None:
    """``'higher'`` / ``'lower'`` for gated metrics, ``None`` for
    informational ones. Lower-better tokens win ties (``gen_load_ttft_s``
    is a latency even though the stage also reports values) — EXCEPT
    ``speedup``, which outranks them: speedups are ratios-of-latencies
    named after their numerator (``gen_prefix_ttft_speedup``,
    ``gen_kernel_speedup``), so the 'ttft' substring alone would gate a
    warm-start IMPROVEMENT as a regression."""
    k = key.lower()
    if 'speedup' in k:
        return 'higher'
    if any(token in k for token in _LOWER_BETTER_TOKENS):
        return 'lower'
    if k.endswith(_HIGHER_BETTER_SUFFIXES):
        return 'higher'
    if any(token in k for token in _HIGHER_BETTER_TOKENS):
        return 'higher'
    return None


def extract_metrics(parsed) -> dict[str, float]:
    """Numeric metrics from one record's parsed payload (flat dict in;
    bools and non-numerics dropped; ``None``/missing payload → empty)."""
    if not isinstance(parsed, dict):
        return {}
    out: dict[str, float] = {}
    for key, value in parsed.items():
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        # bench records round-trip NaN/inf through json (allow_nan): a
        # degenerate 0/0 mfu must not crash the gate, and NaN compares
        # False against every threshold — drop it as "not reported"
        # rather than let it silently pass.
        if not math.isfinite(value):
            continue
        out[key] = float(value)
    return out


def load_record(path: str | Path) -> dict:
    """One record file → ``{'name', 'metrics', 'error'}``. Accepts the
    driver-contract wrapper (``parsed`` payload) or a bare metrics
    object; unreadable/unparseable files become an empty record with the
    error noted — the gate must be able to diff across a crashed round."""
    path = Path(path)
    name = path.stem.replace('BENCH_', '')
    try:
        doc = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        return {'name': name, 'metrics': {}, 'error': repr(exc)[:200]}
    payload = doc.get('parsed', doc) if isinstance(doc, dict) else None
    metrics = extract_metrics(payload)
    error = None
    if isinstance(payload, dict) and payload.get('error'):
        error = str(payload['error'])[:200]
    elif not metrics:
        error = 'no metrics in record (crashed before emitting?)'
    return {'name': name, 'metrics': metrics, 'error': error}


def diff_records(
    records: list[dict], threshold: float
) -> tuple[list[dict], list[str]]:
    """Gate the NEWEST record against the most recent prior value of each
    gated metric. Returns ``(regressions, lost)``:

    - regressions: ``{'key', 'prior', 'prior_name', 'current', 'delta'}``
      for each gated metric that moved in the bad direction by more than
      ``threshold`` (fractional);
    - lost: gated metric keys present in some prior record but absent
      from the newest one.

    Comparison is newest-vs-most-recent-prior (not first-vs-last): the
    gate answers "did the round under review regress?", and older rounds'
    internal history is the table's job, not the gate's.
    """
    if len(records) < 2:
        return [], []
    current = records[-1]
    regressions: list[dict] = []
    lost: list[str] = []
    gated_keys = sorted({
        key
        for record in records
        for key in record['metrics']
        if gate_direction(key) is not None
    })
    for key in gated_keys:
        prior = prior_name = None
        for record in reversed(records[:-1]):
            if key in record['metrics']:
                prior = record['metrics'][key]
                prior_name = record['name']
                break
        if prior is None:
            continue  # brand-new metric: nothing to regress against
        if key not in current['metrics']:
            lost.append(key)
            continue
        value = current['metrics'][key]
        if prior == 0:
            continue  # no meaningful relative delta off a zero baseline
        delta = (value - prior) / abs(prior)
        bad = (
            delta < -threshold
            if gate_direction(key) == 'higher'
            else delta > threshold
        )
        if bad:
            regressions.append({
                'key': key,
                'prior': prior,
                'prior_name': prior_name,
                'current': value,
                'delta': delta,
            })
    return regressions, lost


def _format_value(value: float | None) -> str:
    if value is None:
        return '—'
    if not math.isfinite(value):  # belt-and-braces: extraction drops these
        return str(value)
    if value == int(value) and abs(value) < 1e12:
        return str(int(value))
    return f'{value:.6g}'


def format_markdown(records: list[dict], threshold: float) -> str:
    """The trajectory table plus the gate verdicts, as markdown."""
    keys = sorted({key for r in records for key in r['metrics']})
    names = [r['name'] for r in records]
    lines = [
        '# Bench trajectory',
        '',
        '| metric | ' + ' | '.join(names) + ' | Δ newest | gate |',
        '| --- |' + ' --- |' * (len(names) + 2),
    ]
    regressions, lost = diff_records(records, threshold)
    regressed = {r['key']: r for r in regressions}
    for key in keys:
        values = [r['metrics'].get(key) for r in records]
        prior = next(
            (v for v in reversed(values[:-1]) if v is not None), None
        )
        current = values[-1]
        if current is None:
            delta = 'lost' if prior is not None else '—'
        elif prior in (None, 0):
            delta = 'new'
        else:
            delta = f'{(current - prior) / abs(prior):+.1%}'
        direction = gate_direction(key)
        if direction is None:
            gate = ''
        elif key in regressed:
            gate = '**REGRESSED**'
        elif key in lost:
            gate = 'lost'
        else:
            gate = 'ok'
        lines.append(
            f'| {key} | '
            + ' | '.join(_format_value(v) for v in values)
            + f' | {delta} | {gate} |'
        )
    errors = [(r['name'], r['error']) for r in records if r.get('error')]
    if errors:
        lines.append('')
        for name, error in errors:
            lines.append(f'- `{name}`: {error}')
    lines.append('')
    if regressions:
        lines.append(
            f'**{len(regressions)} regression(s)** beyond '
            f'{threshold:.0%} in `{records[-1]["name"]}`:'
        )
        for reg in regressions:
            lines.append(
                f'- `{reg["key"]}`: {_format_value(reg["prior"])} '
                f'(`{reg["prior_name"]}`) → {_format_value(reg["current"])} '
                f'({reg["delta"]:+.1%})'
            )
    elif lost:
        lines.append(
            f'No regressions among reported metrics; {len(lost)} gated '
            f'metric(s) missing from `{records[-1]["name"]}`: '
            + ', '.join(f'`{k}`' for k in lost)
        )
    else:
        lines.append(f'No regressions beyond {threshold:.0%}.')
    return '\n'.join(lines) + '\n'


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        'records', nargs='+',
        help='record files, oldest first (BENCH_r01.json BENCH_r02.json ...)',
    )
    parser.add_argument(
        '--threshold', type=float, default=0.05,
        help='fractional regression threshold (default 0.05 = 5%%)',
    )
    parser.add_argument(
        '--markdown', type=str, default=None,
        help='also write the trajectory table to this path',
    )
    parser.add_argument(
        '--strict-missing', action='store_true',
        help='treat gated metrics missing from the newest record as '
             'regressions (off by default: the r03-r05 tail is known-bad)',
    )
    args = parser.parse_args(argv)

    records = [load_record(path) for path in args.records]
    if len(records) < 2:
        print('need at least two records to diff', file=sys.stderr)
        return 2
    report = format_markdown(records, args.threshold)
    sys.stdout.write(report)
    if args.markdown:
        Path(args.markdown).write_text(report)
    regressions, lost = diff_records(records, args.threshold)
    if regressions:
        return 1
    if lost and args.strict_missing:
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
