"""Embed-stage breakdown on the real chip: host tokenize vs device compute
vs end-to-end, plus padding-waste accounting — decides where the remaining
throughput gap lives (VERDICT r2 weak #4)."""

from __future__ import annotations

import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import time

import jax
import numpy as np

from distllm_tpu.embed import get_pooler
from distllm_tpu.embed.embedders.full_sequence import compute_embeddings
from distllm_tpu.embed.encoders.base import JaxEncoder
from distllm_tpu.models import bert
from distllm_tpu.models.tokenizer import WhitespaceTokenizer


def main() -> None:
    rng = np.random.default_rng(0)
    cfg = bert.BertConfig(
        vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
        intermediate_size=3072, max_position_embeddings=512, dtype='bfloat16',
    )
    params = bert.init(jax.random.PRNGKey(0), cfg)
    tokenizer = WhitespaceTokenizer(vocab_size=cfg.vocab_size, model_max_length=512)
    encoder = JaxEncoder(
        config=None, apply_fn=bert.apply, model_cfg=cfg,
        params=jax.device_put(params), tokenizer=tokenizer,
        embedding_size=cfg.hidden_size,
    )
    pooler = get_pooler({'name': 'mean'})
    batch_size = 512

    vocab = [f'tok{i}' for i in range(5000)]
    texts = []
    for _ in range(2048):
        n = int(rng.integers(120, 260))
        texts.append(' '.join(rng.choice(vocab, size=n)))

    # Warm.
    compute_embeddings(texts, encoder, pooler, batch_size)

    # 1. End-to-end.
    start = time.perf_counter()
    compute_embeddings(texts, encoder, pooler, batch_size)
    e2e = time.perf_counter() - start
    print(f'end-to-end: {e2e*1e3:.0f} ms  ({2048/e2e:.0f} emb/s)')

    # 2. Host tokenize only (sorted order, same batching).
    order = sorted(range(len(texts)), key=lambda i: len(texts[i].split()))
    start = time.perf_counter()
    batches = []
    for lo in range(0, len(texts), batch_size):
        idx = order[lo:lo + batch_size]
        b = encoder.tokenizer([texts[i] for i in idx])
        batches.append((idx, b.pad_batch_to(batch_size, pad_id=0)))
    tok = time.perf_counter() - start
    total_padded = sum(b.input_ids.size for _, b in batches)
    total_real = sum(int(b.attention_mask.sum()) for _, b in batches)
    print(f'tokenize only: {tok*1e3:.0f} ms; padded tokens {total_padded} '
          f'real {total_real} (waste {1 - total_real/total_padded:.1%})')
    for _, b in batches:
        print('  batch shape', b.input_ids.shape)

    # 3. Device only (pre-tokenized batches, async dispatch, one final sync).
    fused = encoder.pooled_forward(pooler, False)
    outs = [fused(b) for _, b in batches]  # warm every shape
    np.asarray(outs[-1])
    start = time.perf_counter()
    outs = [fused(b) for _, b in batches]
    for o in outs:
        np.asarray(o)
    dev = time.perf_counter() - start
    print(f'device only: {dev*1e3:.0f} ms  ({2048/dev:.0f} emb/s)')
    flops = 2 * 110e6 * total_real
    print(f'device MFU vs real tokens: {flops/dev/197e12:.3f} '
          f'(vs padded: {2*110e6*total_padded/dev/197e12:.3f})')


if __name__ == '__main__':
    main()
