"""Does the prefill executable tolerate the decode-window's preferred
weight layouts without inserting layout-conversion copies?"""

from __future__ import annotations

import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import jax
import jax.numpy as jnp
from jax.experimental.layout import Format, Layout

from distllm_tpu.models import mistral

cfg = mistral.MistralConfig(dtype='bfloat16')
L, bs, kv, hd = cfg.num_layers, 16, cfg.num_kv_heads, cfg.head_size
b, num_blocks, R = 32, 712, 32
params_sh = jax.eval_shape(
    lambda: mistral.init_on_device(jax.random.PRNGKey(0), cfg)
)
S = jax.ShapeDtypeStruct
shapes = [
    params_sh, S((b,), jnp.int32), S((b,), jnp.int32), S((b,), jnp.int32),
    S((L, num_blocks, bs, kv, hd), jnp.bfloat16),
    S((L, num_blocks, bs, kv, hd), jnp.bfloat16),
    S((b, R), jnp.int32), S((b,), jnp.int32),
    S((b,), jnp.float32), S((b,), jnp.float32), S((b,), jnp.float32),
    S((b,), jnp.int32), S((b,), jnp.uint32),
]


def window(params, ids, pos, ctx, k, v, bt, steps, t, tp, mp, tk, sd):
    return mistral.decode_loop(
        params, cfg, ids, pos, k, v, bt, ctx, steps, t, tp, mp, tk, sd,
        num_steps=16, attn_backend='xla', max_table_positions=512,
    )


in_sh = (Format(Layout.AUTO),) + (Format(),) * 12
compiled = jax.jit(window, donate_argnums=(4, 5), in_shardings=in_sh).lower(
    *shapes
).compile()
fmts = compiled.input_formats[0][0]
ma = compiled.memory_analysis()
print(f'decode window: temp {ma.temp_size_in_bytes/2**30:.2f}G')


def prefill_fn(params, ids, mask, last_pos):
    hidden, k, v = mistral.prefill(params, cfg, ids, mask)
    last_hidden = jnp.take_along_axis(hidden, last_pos[:, None, None], axis=1)
    return mistral.logits(params, cfg, last_hidden)[:, 0], k, v


for pb, bucket in ((4, 512), (8, 256)):
    pshapes = [
        params_sh, S((pb, bucket), jnp.int32), S((pb, bucket), jnp.int32),
        S((pb,), jnp.int32),
    ]
    c_default = jax.jit(prefill_fn).lower(*pshapes).compile()
    c_decode_fmt = jax.jit(prefill_fn, in_shardings=(fmts, Format(), Format(), Format())).lower(*pshapes).compile()
    ma_d = c_default.memory_analysis()
    ma_f = c_decode_fmt.memory_analysis()
    print(f'prefill b={pb} S={bucket}: default-layout temp '
          f'{ma_d.temp_size_in_bytes/2**30:.2f}G | decode-layout temp '
          f'{ma_f.temp_size_in_bytes/2**30:.2f}G')
