"""Open-loop load-generator CLI (docs/observability.md).

Builds a randomly initialized Mistral-family engine at the requested dims,
warms it, replays a deterministic seeded Poisson workload through
``distllm_tpu.generate.loadgen``, and prints one JSON report line:
TTFT/TPOT/queue-wait p50/p95/p99, goodput, warm-prefix hits, the
per-window-kind MFU / bandwidth-utilization summary, and a compact
metric-history excerpt (``loadgen_history_*``: the sampled tok/s series
plus the SLO burn-rate gauges — docs/observability.md "Metric history &
sampling").

Examples::

    # CPU smoke (tiny dims, tens of requests)
    JAX_PLATFORMS=cpu python scripts/loadgen.py --small --requests 24

    # chip-scale open-loop run, 7B dims, 512 requests at 16 rps
    python scripts/loadgen.py --requests 512 --rate 16 --slo 2.0

    # HTTP client mode: same Poisson workload against a running
    # chat_server or the multi-replica router (docs/routing.md) — no
    # in-process engine, TTFT measured from the SCHEDULED arrival
    # (coordinated-omission corrected)
    python scripts/loadgen.py --endpoint http://127.0.0.1:8000 \
        --requests 64 --rate 8

The bench's checkpointed ``gen_load`` stage wraps the same machinery; this
CLI exists for interactive what-if runs against one engine config.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--requests', type=int, default=64)
    parser.add_argument('--rate', type=float, default=8.0,
                        help='Poisson arrival rate, requests/second')
    parser.add_argument('--sessions', type=int, default=4)
    parser.add_argument('--warm-fraction', type=float, default=0.5)
    parser.add_argument('--prefix-tokens', type=int, default=32)
    parser.add_argument('--slo', type=float, default=0.0,
                        help='TTFT SLO seconds (0 = no goodput accounting)')
    parser.add_argument(
        '--temperature', type=float, default=0.0,
        help='sampling temperature for every request (0 = greedy; > 0 '
             'drives the sampled decode/verification paths — outputs stay '
             'deterministic per (seed, schedule), docs/speculative.md)')
    parser.add_argument(
        '--top-p', type=float, default=1.0,
        help='nucleus filtering for sampled requests (1.0 disables)')
    parser.add_argument('--small', action='store_true',
                        help='tiny model dims (CPU smoke) instead of 7B')
    parser.add_argument('--max-num-seqs', type=int, default=None)
    parser.add_argument('--no-attribution', action='store_true')
    parser.add_argument(
        '--cache-blocks', type=int, default=None,
        help='paged-pool size override (blocks); size it below the warm '
             'working set to force prefix-cache eviction/spill '
             '(LoadgenConfig.cache_blocks)')
    parser.add_argument(
        '--host-tier-bytes', type=int, default=0,
        help='host-RAM KV tier byte budget (0 = tier off; '
             'docs/prefix_caching.md "Tier hierarchy")')
    parser.add_argument(
        '--disk-tier-dir', type=str, default=None,
        help='optional disk KV tier directory (persists spilled blocks '
             'across engine restarts; needs --host-tier-bytes)')
    parser.add_argument(
        '--history-interval', type=float, default=0.5,
        help='metric-history sampler tick, seconds; the report carries a '
             'compact excerpt (tok/s series + burn-rate gauges) from the '
             'retained history (docs/observability.md)')
    parser.add_argument(
        '--endpoint', type=str, default=None,
        help='drive an OpenAI-compatible HTTP endpoint (chat_server or '
             'the router, docs/routing.md) instead of building an '
             'in-process engine; engine flags are ignored in this mode')
    parser.add_argument(
        '--timeout', type=float, default=120.0,
        help='per-request HTTP timeout seconds (endpoint mode only)')
    args = parser.parse_args(argv)

    if args.endpoint:
        # HTTP client mode: no engine, no jax — the workload builder and
        # the asyncio driver are all this path needs.
        from distllm_tpu.generate.loadgen import (
            LoadgenConfig,
            build_workload,
            run_http_loadgen,
        )

        load_cfg = LoadgenConfig(
            seed=args.seed,
            num_requests=args.requests,
            rate_rps=args.rate,
            num_sessions=args.sessions,
            warm_fraction=args.warm_fraction,
            prefix_tokens=args.prefix_tokens,
            temperature=args.temperature,
            top_p=args.top_p,
        )
        report = run_http_loadgen(
            args.endpoint,
            build_workload(load_cfg),
            slo_s=args.slo,
            timeout_s=args.timeout,
        )
        fragment = report.to_fragment('loadgen_http_')
        fragment['loadgen_http_endpoint'] = args.endpoint
        if report.by_replica:
            fragment['loadgen_http_by_replica'] = report.by_replica
        print(json.dumps(fragment))
        return 0

    import jax

    if os.environ.get('JAX_PLATFORMS'):
        jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])

    from distllm_tpu.generate.engine.engine import EngineConfig, LLMEngine
    from distllm_tpu.generate.loadgen import (
        LoadgenConfig,
        build_workload,
        run_loadgen,
    )
    from distllm_tpu.models import mistral

    if args.small:
        model_cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
        )
        max_num_seqs, num_blocks, max_model_len = 4, 160, 256
        decode_steps = 4
    else:
        model_cfg = mistral.MistralConfig(dtype='bfloat16')  # 7B defaults
        max_num_seqs, num_blocks, max_model_len = 32, 712, 512
        decode_steps = 16
    if args.max_num_seqs:
        max_num_seqs = args.max_num_seqs

    load_cfg = LoadgenConfig(
        seed=args.seed,
        num_requests=args.requests,
        rate_rps=args.rate,
        num_sessions=args.sessions,
        warm_fraction=args.warm_fraction,
        prefix_tokens=args.prefix_tokens,
        vocab_size=model_cfg.vocab_size,
        temperature=args.temperature,
        top_p=args.top_p,
        cache_blocks=args.cache_blocks,
    )
    engine_cfg = EngineConfig(
        block_size=16,
        num_blocks=load_cfg.cache_blocks or num_blocks,
        host_kv_tier_bytes=args.host_tier_bytes,
        disk_kv_tier_dir=args.disk_tier_dir,
        max_num_seqs=max_num_seqs,
        max_model_len=max_model_len,
        decode_steps=decode_steps,
        pipeline_depth=2,
        sampling_top_window=64,
        enable_prefix_cache=True,
        ttft_slo_s=args.slo,
        attribution=not args.no_attribution,
    )

    class _Tok:
        eos_id = None

    params = mistral.init_on_device(jax.random.PRNGKey(0), model_cfg)
    engine = LLMEngine(model_cfg, params, _Tok(), engine_cfg, own_params=True)
    engine.warmup()

    # The CLI owns the process history sampler for the run (the scripted-
    # run ownership convention, docs/observability.md), so the report can
    # carry a time-resolved excerpt, not just end-of-run aggregates.
    from distllm_tpu.observability.history import (
        HistorySampler,
        get_metrics_history,
        history_excerpt,
    )
    from distllm_tpu.observability.slo import install_slo_observer

    history = get_metrics_history()
    slo_observer = install_slo_observer(history)
    workload = build_workload(load_cfg)
    with HistorySampler(history, interval_s=args.history_interval):
        report = run_loadgen(engine, workload)
        history.sample_once()  # fold the tail before the excerpt reads
    history.remove_observer(slo_observer)
    fragment = report.to_fragment('loadgen_')
    for key, value in history_excerpt(history).items():
        fragment[f'loadgen_history_{key}'] = value
    fragment['loadgen_device'] = str(jax.devices()[0].device_kind)
    if engine.kv_tier is not None:
        for key, value in engine.tier_summary().items():
            fragment[f'loadgen_tier_{key}'] = value
    print(json.dumps(fragment))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
