"""Embed-forward ablation: where do the non-matmul cycles go?

The AOT census of the bench's fused embed graph (B=512, S=256 bf16
BERT-base) shows the exact-erf GELU lowering as fp32 elementwise chains
over the [B, S, 3072] intermediate and fp32 LayerNorm stats — VPU work
and conversion traffic that may explain the 0.58-0.63 steady-state MFU
plateau (BENCH_NOTES_r03.md). This measures the forward with each
suspect ablated, on the real chip:

- full         : production graph
- act=identity : MLP activation removed (upper bound on GELU cost)
- act=tanh-gelu: approximate GELU (bf16-friendly polynomial, no erf)
- ln=bf16      : LayerNorm stats in bf16 instead of fp32

Numerics changes here are DIAGNOSTIC ONLY — production keeps HF-parity
numerics unless a measured win justifies a documented knob.
"""

from __future__ import annotations

import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.models import bert, common


def timed(fn, *args, n=8):
    out = fn(*args)
    np.asarray(out[0, 0])  # tunnel-safe sync
    start = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    np.asarray(out[0, 0])
    return (time.perf_counter() - start) / n


def main() -> None:
    B, S = 512, 256
    cfg = bert.BertConfig(dtype='bfloat16')
    params = jax.device_put(bert.init(jax.random.PRNGKey(0), cfg))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B, S)), jnp.int32)
    mask = jnp.ones((B, S), jnp.int32)
    n_params = sum(
        int(np.prod(np.shape(x))) for x in jax.tree.leaves(params)
    )
    flops = 2 * n_params * B * S

    def run(label, **patches):
        saved = {}
        try:
            for name, value in patches.items():
                saved[name] = getattr(common, name)
                setattr(common, name, value)
            if patches:  # activation table caches the function objects
                common.ACTIVATIONS['gelu'] = common.gelu
            fn = jax.jit(lambda p, i, m: bert.apply(p, cfg, i, m))
            sec = timed(fn, params, ids, mask)
        finally:
            for name, value in saved.items():
                setattr(common, name, value)
            common.ACTIVATIONS['gelu'] = common.gelu
        from bench import _chip_peak_flops

        peak = _chip_peak_flops(jax.devices()[0])
        mfu = round(flops / sec / peak, 3) if peak else None
        print(json.dumps({
            'variant': label, 'ms': round(sec * 1e3, 1),
            'mfu': mfu, 'platform': jax.default_backend(),
        }), flush=True)

    run('full')
    run('act_identity', gelu=lambda x: x)
    run('act_tanh_gelu', gelu=lambda x: jax.nn.gelu(x, approximate=True))

    orig_ln = common.layer_norm

    def ln_bf16(x, scale, bias, eps):
        return orig_ln(x.astype(jnp.bfloat16), scale, bias, eps)

    run('ln_bf16', layer_norm=ln_bf16)


if __name__ == '__main__':
    main()
