"""Validate the Pallas paged-attention kernel compiled on the real TPU:
correctness vs the XLA path, then a timing comparison at bench shapes."""

from __future__ import annotations

import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import time

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.ops.paged_attention import (
    paged_attention_pallas,
    paged_attention_xla,
)


def run(b, heads, kv, hd, bs, nblocks, mb, window=None, dtype=jnp.bfloat16):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(b, heads, hd)), dtype)
    k_cache = jnp.asarray(rng.normal(size=(nblocks, bs, kv, hd)), dtype)
    v_cache = jnp.asarray(rng.normal(size=(nblocks, bs, kv, hd)), dtype)
    # Distinct random block tables per sequence (blocks 1..nblocks-1).
    bt = np.zeros((b, mb), np.int32)
    ctx = rng.integers(1, mb * bs, size=(b,)).astype(np.int32)
    for i in range(b):
        need = -(-int(ctx[i]) // bs)
        bt[i, :need] = rng.choice(np.arange(1, nblocks), size=need, replace=False)
    bt = jnp.asarray(bt)
    ctx = jnp.asarray(ctx)

    f_xla = jax.jit(
        lambda *a: paged_attention_xla(*a, sliding_window=window)
    )
    f_pl = jax.jit(
        lambda *a: paged_attention_pallas(*a, sliding_window=window)
    )
    out_x = np.asarray(f_xla(q, k_cache, v_cache, bt, ctx), np.float32)
    out_p = np.asarray(f_pl(q, k_cache, v_cache, bt, ctx), np.float32)
    err = np.max(np.abs(out_x - out_p))
    print(f'b={b} heads={heads} kv={kv} hd={hd} bs={bs} mb={mb} '
          f'window={window}: max abs err = {err:.4f}')
    assert err < 0.1, 'MISMATCH'

    def bench(f, n=20):
        s = np.asarray(f(q, k_cache, v_cache, bt, ctx)).sum()  # warm+sync
        start = time.perf_counter()
        for _ in range(n):
            out = f(q, k_cache, v_cache, bt, ctx)
        np.asarray(out)
        return (time.perf_counter() - start) / n, s

    tx, _ = bench(f_xla)
    tp, _ = bench(f_pl)
    print(f'  xla {1e3*tx:.2f} ms   pallas {1e3*tp:.2f} ms   '
          f'(one layer-equivalent call)')


if __name__ == '__main__':
    # Small correctness shapes (head_dim must be 128-aligned compiled).
    run(4, 8, 4, 128, 16, 32, 8)
    run(4, 8, 4, 128, 16, 32, 8, window=40)
    # 7B decode shapes (one layer): batch 24, 32 heads, 8 kv, 128 hd.
    run(24, 32, 8, 128, 16, 488, 32)
    run(24, 32, 8, 128, 16, 488, 32, window=256)
    run(64, 32, 8, 128, 32, 512, 16)
