"""One-off probe: quantify the tunnel's dispatch/transfer latencies and the
real per-step decode cost at 7B dims, so engine design decisions (K-step
fused decode, on-device sampling) are grounded in measurements, not guesses.

Run: python scripts/probe_latency.py [--small]
"""

from __future__ import annotations

import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np


def t(fn, n=10, warmup=2):
    for _ in range(warmup):
        fn()
    start = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - start) / n


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--small', action='store_true')
    args = parser.parse_args()

    dev = jax.devices()[0]
    print(f'device: {dev.device_kind} ({dev.platform})')

    # 1. Host->device transfer latency (tiny array).
    small = np.zeros((24,), np.int32)
    print('h2d tiny (24 int32): %.2f ms' % (1e3 * t(
        lambda: jax.device_put(small).block_until_ready())))
    med = np.zeros((24, 32), np.int32)
    print('h2d small (24x32): %.2f ms' % (1e3 * t(
        lambda: jax.device_put(med).block_until_ready())))

    # 2. Trivial dispatch latency.
    x = jax.device_put(np.ones((8, 128), np.float32))
    f = jax.jit(lambda a: a + 1)
    f(x).block_until_ready()
    print('jit dispatch (tiny add): %.2f ms' % (1e3 * t(
        lambda: f(x).block_until_ready())))

    # 3. Device->host fetch latency.
    y = f(x)
    print('d2h fetch (8x128): %.2f ms' % (1e3 * t(lambda: np.asarray(y))))

    # 4. Chained dispatches without sync (pipeline depth test).
    def chain(n):
        z = x
        for _ in range(n):
            z = f(z)
        z.block_until_ready()
    print('10 chained dispatches + 1 sync: %.2f ms' % (1e3 * t(lambda: chain(10))))

    # 5. Matmul throughput sanity (HBM roofline probe): read 1 GiB of weights.
    w = jax.device_put(np.zeros((16384, 16384), jnp.bfloat16))  # 512 MiB
    v = jax.device_put(np.zeros((8, 16384), jnp.bfloat16))
    mm = jax.jit(lambda a, b: a @ b)
    mm(v, w).block_until_ready()
    dt = t(lambda: mm(v, w).block_until_ready())
    print('bf16 [8,16k]@[16k,16k]: %.2f ms -> %.0f GB/s eff' % (
        1e3 * dt, 16384 * 16384 * 2 / dt / 1e9))

    # 6. 7B decode step (the engine's current per-token dispatch).
    from distllm_tpu.generate.engine.engine import EngineConfig, LLMEngine
    from distllm_tpu.models import mistral
    from distllm_tpu.ops.sampling import sample_tokens

    if args.small:
        cfg = mistral.MistralConfig(
            vocab_size=2048, hidden_size=256, num_layers=4, num_heads=8,
            num_kv_heads=4, intermediate_size=512, dtype='bfloat16')
    else:
        cfg = mistral.MistralConfig(dtype='bfloat16')
    params = mistral.init_on_device(jax.random.PRNGKey(0), cfg)
    jax.block_until_ready(params)

    ecfg = EngineConfig(block_size=16, num_blocks=488, max_num_seqs=24,
                        max_model_len=512)

    class _Tok:
        eos_id = None

    engine = LLMEngine(cfg, params, _Tok(), ecfg)
    b = ecfg.max_num_seqs
    R = engine.max_blocks_per_seq
    ids = jnp.zeros((b,), jnp.int32)
    pos = jnp.full((b,), 200, jnp.int32)
    bt = jnp.zeros((b, R), jnp.int32)
    ctx = jnp.full((b,), 200, jnp.int32)

    logits, engine.kv.k, engine.kv.v = engine._decode(
        engine.params, ids, pos, engine.kv.k, engine.kv.v, bt, ctx)
    jax.block_until_ready(logits)

    def one_decode():
        out, engine.kv.k, engine.kv.v = engine._decode(
            engine.params, ids, pos, engine.kv.k, engine.kv.v, bt, ctx)
        jax.block_until_ready(out)
    print('decode step (device only, b=%d): %.2f ms' % (b, 1e3 * t(one_decode)))

    # 7. Sampling dispatch cost.
    key = jax.random.PRNGKey(0)
    temp = jnp.full((b,), 0.5, jnp.float32)
    topp = jnp.full((b,), 0.95, jnp.float32)
    minp = jnp.full((b,), 0.1, jnp.float32)
    sample = jax.jit(sample_tokens)
    sample(logits, key, temp, topp, minp).block_until_ready()
    print('sample dispatch (b=%d, V=%d): %.2f ms' % (
        b, cfg.vocab_size, 1e3 * t(
            lambda: sample(logits, key, temp, topp, minp).block_until_ready())))

    # 8. Full engine.step() as shipped (host-side assembly + transfers).
    rng = np.random.default_rng(0)
    from distllm_tpu.generate.engine.engine import SamplingParams
    for n in rng.integers(32, 192, size=24):
        engine.add_request(list(rng.integers(1, cfg.vocab_size, size=int(n))),
                           SamplingParams(max_tokens=4096))
    engine.step()  # admit + prefill
    print('engine.step() end-to-end: %.2f ms' % (1e3 * t(
        lambda: engine.step(), n=20)))


if __name__ == '__main__':
    main()
