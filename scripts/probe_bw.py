"""Focused probe: HBM bandwidth + per-dispatch overhead on the axon TPU.

NOTE: on the axon backend ``block_until_ready`` returns immediately; the only
reliable sync is fetching a (tiny) result to host, so every timed op reduces
to a scalar and the timer ends on ``float(...)``.
"""

import sys as _sys
import pathlib as _pl
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import time

import jax
import jax.numpy as jnp
import numpy as np


def t(fn, n=10, warmup=3):
    for _ in range(warmup):
        fn()
    start = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - start) / n


def main() -> None:
    dev = jax.devices()[0]
    print(f'device: {dev.device_kind}')

    # Pure round-trip latency: dispatch + scalar fetch.
    x = jax.device_put(jnp.ones((8, 128), jnp.float32))
    f1 = jax.jit(lambda a: (a * 1.000001).sum())
    float(f1(x))
    print('round trip (tiny op + scalar fetch): %.2f ms' % (1e3 * t(
        lambda: float(f1(x)))))

    # HBM read bandwidth via reduction.
    for mb in (64, 512):
        n = mb * 1024 * 1024 // 2
        w = jax.device_put(jnp.zeros((n // 1024, 1024), jnp.bfloat16))
        red = jax.jit(lambda a: jnp.sum(a.astype(jnp.float32)))
        float(red(w))
        dt = t(lambda: float(red(w)))
        print(f'sum over {mb} MiB: {1e3*dt:.2f} ms -> {mb/1024/dt:.0f} GB/s')
        del w

    # Matmul with different M (decode is M=batch).
    k = 8192
    w = jax.device_put(jnp.zeros((k, k), jnp.bfloat16))  # 128 MiB
    for m in (8, 24, 128, 1024):
        v = jax.device_put(jnp.zeros((m, k), jnp.bfloat16))
        mm = jax.jit(lambda a, b: (a @ b).astype(jnp.float32).sum())
        float(mm(v, w))
        dt = t(lambda: float(mm(v, w)))
        gb = k * k * 2 / 1e9
        print(f'[{m},{k}]@[{k},{k}] +sum: {1e3*dt:.2f} ms -> {gb/dt:.0f} GB/s, '
              f'{2*m*k*k/dt/1e12:.2f} TF/s')
        del v

    # Dispatch overhead: N separate tiny dispatches, one sync at the end.
    f = jax.jit(lambda a: a * 1.000001)
    f(x)

    def sep(n):
        z = x
        for _ in range(n):
            z = f(z)
        return float(z.sum())

    for n in (1, 10, 50):
        dt = t(lambda: sep(n), n=5)
        print(f'{n} chained dispatches + sync: {1e3*dt:.2f} ms '
              f'({1e3*dt/n:.2f} ms/dispatch)')

    g = jax.jit(
        lambda a: jax.lax.fori_loop(0, 50, lambda i, z: z * 1.000001, a).sum()
    )
    float(g(x))
    print('fori_loop(50) one dispatch + sync: %.2f ms' % (1e3 * t(
        lambda: float(g(x)))))

    # Host->device transfer (sync'd by using the value).
    h = np.zeros((24, 32), np.int32)
    add = jax.jit(lambda a: a.sum())
    float(add(jax.device_put(h)))
    print('h2d (24x32) + use + fetch: %.2f ms' % (1e3 * t(
        lambda: float(add(jax.device_put(h))))))

    # Donated big-buffer scatter (KV-cache-like), sync via tiny probe output.
    kv = jax.device_put(jnp.zeros((32, 488, 16, 8, 128), jnp.bfloat16))
    upd = jax.jit(
        lambda c: (c.at[:, 1, 0].set(1.0), c[0, 1, 0, 0, 0]),
        donate_argnums=0,
    )

    def run_upd():
        nonlocal kv
        kv, probe = upd(kv)
        return float(probe)
    run_upd()
    print('donated KV scatter (0.94 GiB buffer): %.2f ms' % (1e3 * t(run_upd)))


if __name__ == '__main__':
    main()
