"""Localize the decode-window gap WITHOUT hardware: AOT cost analysis.

r3 measured the bf16 batch-32 fused 16-step window at ~845 ms on chip vs
the ~283 ms weight-streaming floor (BENCH_NOTES_r03.md) and the chip died
before scripts/probe_decode.py could run. The compiled executable itself
can testify meanwhile: compile the exact serving window against the v5e
topology (libtpu, no chip) and read

- ``cost_analysis()`` bytes accessed -> a bandwidth-bound time prediction
  (bytes / 819 GB/s). If this lands near the floor, the compiled graph is
  fine and the gap is runtime-side (dispatch stalls, host latency). If it
  lands near the measured 845 ms, the extra HBM traffic is IN the graph —
  and the HLO says which ops carry it.
- HLO op census: copies / transposes / all-to-alls and the largest
  fusions, to name the traffic carriers.

Prints JSON lines; pure local compile, safe while the tunnel is down.
"""

from __future__ import annotations

import json
import os

os.environ.pop('JAX_PLATFORMS', None)
import collections  # noqa: E402
import pathlib  # noqa: E402
import re  # noqa: E402
import sys  # noqa: E402

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
import jax.numpy as jnp  # noqa: E402
from jax.experimental import topologies  # noqa: E402
from jax.experimental.layout import Format, Layout  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from distllm_tpu.models import mistral  # noqa: E402

HBM_BW = 819e9  # v5e
PEAK_BF16 = 197e12


def main() -> None:
    topo = topologies.get_topology_desc(
        platform='tpu', topology_name='v5e:2x2x1'
    )
    mesh = Mesh(np.asarray(topo.devices[:1]).reshape(1), ('x',))
    shard = NamedSharding(mesh, P())

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=shard)

    mcfg = mistral.MistralConfig(dtype='bfloat16')
    mshapes = jax.eval_shape(
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), mcfg)
    )
    mshapes = jax.tree.map(
        lambda x: sds(x.shape, x.dtype), mshapes
    )
    n_params = sum(
        int(np.prod(x.shape)) for x in jax.tree.leaves(mshapes)
    )
    bs, B, nb, R, steps = 16, 32, 712, 32, 16
    kshape = (mcfg.num_layers, nb, bs, mcfg.num_kv_heads, mcfg.head_size)
    args = (
        mshapes, sds((B,), jnp.int32), sds((B,), jnp.int32),
        sds((B,), jnp.int32), sds(kshape, jnp.bfloat16),
        sds(kshape, jnp.bfloat16), sds((B, R), jnp.int32),
        sds((B,), jnp.int32), sds((B,), jnp.float32),
        sds((B,), jnp.float32), sds((B,), jnp.float32),
        sds((B,), jnp.int32), sds((B,), jnp.uint32),
    )
    floor_s = steps * 2 * n_params / HBM_BW

    for backend, unroll in (
        ('pallas', False), ('pallas', True), ('xla', False), ('xla', True)
    ):
        def fn(p, i, po, c, k, v, bt, sl, tmp, tp, mp, tk, sd, be=backend,
               un=unroll):
            return mistral.decode_loop(
                p, mcfg, i, po, k, v, bt, c, sl, tmp, tp, mp, tk, sd,
                num_steps=steps, attn_backend=be, max_table_positions=512,
                sampling_top_window=64, layer_unroll=un,
            )

        jitted = jax.jit(
            fn, donate_argnums=(4, 5),
            in_shardings=(Format(Layout.AUTO),) + (Format(),) * 12,
        )
        compiled = jitted.lower(*args).compile()
        cost = compiled.cost_analysis() or {}
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = cost.get('flops')
        bytes_accessed = cost.get('bytes accessed')
        out = {
            'backend': backend,
            'layer_unroll': unroll,
            'window_steps': steps,
            'batch': B,
            'floor_ms': round(floor_s * 1e3, 1),
            'flops': flops,
            'bytes_accessed': bytes_accessed,
        }
        if bytes_accessed:
            out['bw_bound_ms'] = round(bytes_accessed / HBM_BW * 1e3, 1)
            out['vs_floor'] = round(bytes_accessed / HBM_BW / floor_s, 2)
        if flops:
            out['compute_bound_ms'] = round(flops / PEAK_BF16 * 1e3, 1)
        # HLO census: name the heavy traffic if any.
        hlo = compiled.as_text()
        ops = collections.Counter(
            m.group(1)
            for m in re.finditer(r'^\s*\S+ = \S+ (\w+)\(', hlo, re.M)
        )
        out['hlo_ops'] = {
            k: v for k, v in ops.most_common(12)
        }
        # Big tensors in copy/transpose ops (layout churn suspects).
        copies = re.findall(
            r'= (\S+) copy\(', hlo
        ) + re.findall(r'= (\S+) transpose\(', hlo)
        big = [c for c in copies if _tensor_bytes(c) > 50e6]
        out['big_copy_transposes'] = big[:8]
        mem = compiled.memory_analysis()
        if mem is not None:
            out['temp_gb'] = round(
                getattr(mem, 'temp_size_in_bytes', 0) / 1e9, 3
            )
        print(json.dumps(out), flush=True)


_DTYPE_BYTES = {'f32': 4, 'bf16': 2, 's32': 4, 'u32': 4, 's8': 1, 'u8': 1,
                'pred': 1, 'f16': 2, 's64': 8, 'u64': 8}


def _tensor_bytes(shape_str: str) -> float:
    m = re.match(r'(\w+?)\[([\d,]*)\]', shape_str)
    if not m:
        return 0.0
    dtype, dims = m.groups()
    n = 1
    for d in dims.split(','):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


if __name__ == '__main__':
    main()
