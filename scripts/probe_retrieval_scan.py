"""Quantized-scan formulation A/B at production scale, clean-room timing.

Times the PUBLIC topk entry points (outputs fetched, so nothing dead-code
eliminates) with chained async calls and one final sync, one process on
the chip. Variants:

- hamming int32: unpack + int8xint8->int32 dot (current)
- hamming bf16:  unpack to bf16, bf16xbf16->f32 dot (exact for 0/1 bits)
- int8 int32:    int8xint8->int32 chunked scan (current)
- int8 bf16:     codes converted to bf16 in-graph, f32 accumulate
                 (|err| <= ~0.5% relative; the fp32 rescore absorbs it)

Hypothesis under test: XLA TPU emulates integer dots (the 10M ubinary
scan measured seconds, not the ~50 ms its byte traffic predicts); bf16
keeps the scan on the native MXU path.
"""

from __future__ import annotations

import os
import pathlib as _pl
import sys as _sys
import time

_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import functools

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.ops import topk as topk_mod
from distllm_tpu.ops.topk import (
    _chunk_candidates,
    _unpack_bits,
    hamming_topk,
    int8_topk,
    pack_sign_bits,
    quantize_int8_rows,
)

CHUNK_GEN = 1 << 18


def hamming_topk_bf16(query_bits, corpus_bits, k, chunk_size=1 << 18):
    """bf16-dot formulation of the Hamming scan (candidate A/B twin)."""
    n = corpus_bits.shape[0]
    k = min(k, n)
    approx = n >= topk_mod.APPROX_TOPK_MIN_ROWS
    qu = _unpack_bits(query_bits).astype(jnp.bfloat16)
    q_pop = jnp.sum(qu.astype(jnp.float32), axis=1)

    @functools.partial(jax.jit, static_argnums=(3,))
    def chunk_distances(q_unpacked, q_popcount, corpus_chunk, chunk_k):
        cu = _unpack_bits(corpus_chunk).astype(jnp.bfloat16)
        dots = jax.lax.dot_general(
            q_unpacked, cu, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        c_pop = jnp.sum(cu.astype(jnp.float32), axis=1)
        distances = q_popcount[:, None] + c_pop[None, :] - 2.0 * dots
        return _chunk_candidates(-distances, chunk_k, approx)

    best_neg = best_idx = None
    for start in range(0, n, chunk_size):
        chunk = corpus_bits[start : start + chunk_size]
        neg, idx = chunk_distances(qu, q_pop, chunk, min(k, chunk.shape[0]))
        idx = idx + start
        if best_neg is None:
            best_neg, best_idx = neg, idx
        else:
            cat_n = jnp.concatenate([best_neg, neg], axis=1)
            cat_i = jnp.concatenate([best_idx, idx], axis=1)
            best_neg, pos = jax.lax.top_k(cat_n, k)
            best_idx = jnp.take_along_axis(cat_i, pos, axis=1)
    return (-best_neg).astype(jnp.int32), best_idx


def int8_topk_bf16(queries, codes, scales, k, chunk_size=1 << 19):
    """bf16-scored int8 scan (codes convert to bf16 in-graph)."""
    n = codes.shape[0]
    k = min(k, n)
    approx = n >= topk_mod.APPROX_TOPK_MIN_ROWS
    qf = queries.astype(jnp.bfloat16)

    @functools.partial(jax.jit, static_argnums=(3,))
    def chunk_topk(q, codes_part, scales_part, chunk_k):
        raw = jax.lax.dot_general(
            q, codes_part.astype(jnp.bfloat16), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return _chunk_candidates(raw * scales_part[None, :], chunk_k, approx)

    best_s = best_i = None
    for start in range(0, n, chunk_size):
        cp = codes[start : start + chunk_size]
        sp = scales[start : start + chunk_size]
        s, i = chunk_topk(qf, cp, sp, min(k, cp.shape[0]))
        i = i + start
        if best_s is None:
            best_s, best_i = s, i
        else:
            cat_s = jnp.concatenate([best_s, s], axis=1)
            cat_i = jnp.concatenate([best_i, i], axis=1)
            best_s, pos = jax.lax.top_k(cat_s, k)
            best_i = jnp.take_along_axis(cat_i, pos, axis=1)
    return best_s, best_i


def hamming_topk_tall(query_bits, corpus_bits, k, chunk_size=1 << 18):
    """Swapped-orientation bf16 scan: corpus is the tall LHS (M=C rows,
    N=32 queries), so each chunk streams through the MXU in its natural
    row-major layout instead of being transposed as an [N, K] RHS."""
    n = corpus_bits.shape[0]
    k = min(k, n)
    approx = n >= topk_mod.APPROX_TOPK_MIN_ROWS
    qu = _unpack_bits(query_bits).astype(jnp.bfloat16)
    q_pop = jnp.sum(qu.astype(jnp.float32), axis=1)

    @functools.partial(jax.jit, static_argnums=(3,))
    def chunk_distances(q_unpacked, q_popcount, corpus_chunk, chunk_k):
        cu = _unpack_bits(corpus_chunk).astype(jnp.bfloat16)
        dots = jax.lax.dot_general(
            cu, q_unpacked, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [C, B]
        c_pop = jnp.sum(cu.astype(jnp.float32), axis=1)
        distances = (
            q_popcount[None, :] + c_pop[:, None] - 2.0 * dots
        ).T  # [B, C]
        return _chunk_candidates(-distances, chunk_k, approx)

    best_neg = best_idx = None
    for start in range(0, n, chunk_size):
        chunk = corpus_bits[start : start + chunk_size]
        neg, idx = chunk_distances(qu, q_pop, chunk, min(k, chunk.shape[0]))
        idx = idx + start
        if best_neg is None:
            best_neg, best_idx = neg, idx
        else:
            cat_n = jnp.concatenate([best_neg, neg], axis=1)
            cat_i = jnp.concatenate([best_idx, idx], axis=1)
            best_neg, pos = jax.lax.top_k(cat_n, k)
            best_idx = jnp.take_along_axis(cat_i, pos, axis=1)
    return (-best_neg).astype(jnp.int32), best_idx


def int8_topk_tall(queries, codes, scales, k, chunk_size=1 << 19):
    """Swapped-orientation bf16-scored int8 scan (codes as tall LHS)."""
    n = codes.shape[0]
    k = min(k, n)
    approx = n >= topk_mod.APPROX_TOPK_MIN_ROWS
    qf = queries.astype(jnp.bfloat16)

    @functools.partial(jax.jit, static_argnums=(3,))
    def chunk_topk(q, codes_part, scales_part, chunk_k):
        raw = jax.lax.dot_general(
            codes_part.astype(jnp.bfloat16), q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [C, B]
        scores = (raw * scales_part[:, None]).T  # [B, C]
        return _chunk_candidates(scores, chunk_k, approx)

    best_s = best_i = None
    for start in range(0, n, chunk_size):
        cp = codes[start : start + chunk_size]
        sp = scales[start : start + chunk_size]
        s, i = chunk_topk(qf, cp, sp, min(k, cp.shape[0]))
        i = i + start
        if best_s is None:
            best_s, best_i = s, i
        else:
            cat_s = jnp.concatenate([best_s, s], axis=1)
            cat_i = jnp.concatenate([best_i, i], axis=1)
            best_s, pos = jax.lax.top_k(cat_s, k)
            best_i = jnp.take_along_axis(cat_i, pos, axis=1)
    return best_s, best_i


def timed_chain(fn, reps=4):
    outs = fn()  # compile + settle
    np.asarray(outs[1]).ravel()[:1]
    t0 = time.perf_counter()
    all_outs = [fn() for _ in range(reps)]
    for o in all_outs:
        np.asarray(o[1]).ravel()[:1]
    return (time.perf_counter() - t0) / reps


def main() -> None:
    small = bool(os.environ.get('DISTLLM_BENCH_SMALL'))
    rows = (1 << 20) if small else 10_000_000
    dim = 768
    k = 40
    rng = np.random.default_rng(0)
    print(f'rows={rows} dim={dim} k={k}', flush=True)

    packed_parts, code_parts, scale_parts = [], [], []
    queries = None
    for lo in range(0, rows, CHUNK_GEN):
        n = min(CHUNK_GEN, rows - lo)
        chunk = rng.standard_normal((n, dim)).astype(np.float32)
        chunk /= np.linalg.norm(chunk, axis=1, keepdims=True)
        if queries is None:
            queries = chunk[:32] + 0.5 * rng.standard_normal(
                (32, dim)
            ).astype(np.float32) / np.sqrt(dim)
        packed_parts.append(pack_sign_bits(chunk))
        c, s = quantize_int8_rows(chunk)
        code_parts.append(c)
        scale_parts.append(s)
    packed = jax.device_put(np.concatenate(packed_parts))
    packed_parts.clear()
    q_bits = jnp.asarray(pack_sign_bits(queries))
    q_dev = jnp.asarray(queries)

    t = timed_chain(lambda: hamming_topk(q_bits, packed, k))
    print(f'hamming int32-dot: {t * 1e3:8.1f} ms/scan', flush=True)
    t = timed_chain(lambda: hamming_topk_bf16(q_bits, packed, k))
    print(f'hamming bf16-dot : {t * 1e3:8.1f} ms/scan', flush=True)
    t = timed_chain(lambda: hamming_topk_tall(q_bits, packed, k))
    print(f'hamming bf16-tall: {t * 1e3:8.1f} ms/scan', flush=True)
    del packed

    codes = jax.device_put(np.concatenate(code_parts))
    scales = jax.device_put(np.concatenate(scale_parts))
    code_parts.clear()
    scale_parts.clear()
    t = timed_chain(lambda: int8_topk(q_dev, codes, scales, k))
    print(f'int8 int32-dot   : {t * 1e3:8.1f} ms/scan', flush=True)
    sa, ia = int8_topk(q_dev, codes, scales, k)
    t = timed_chain(lambda: int8_topk_bf16(q_dev, codes, scales, k))
    print(f'int8 bf16-dot    : {t * 1e3:8.1f} ms/scan', flush=True)
    t = timed_chain(lambda: int8_topk_tall(q_dev, codes, scales, k))
    print(f'int8 bf16-tall   : {t * 1e3:8.1f} ms/scan', flush=True)
    sb, ib = int8_topk_bf16(q_dev, codes, scales, k)
    overlap = np.mean([
        len(set(map(int, np.asarray(ia)[b])) &
            set(map(int, np.asarray(ib)[b]))) / k
        for b in range(32)
    ])
    print(f'int8 bf16 vs int32 candidate overlap@{k}: {overlap:.3f}',
          flush=True)


if __name__ == '__main__':
    main()
