"""Second embed breakdown: per-bucket-shape MFU and numpy-vs-device input
cost. probe_attn showed the bare forward at [512, 256] hits 0.642 MFU with
attention ~free, while the pipeline measures 0.432 vs padded tokens — this
isolates whether the gap is (a) odd bucket shapes, (b) host->device input
transfer per dispatch, or (c) the fused pooling epilogue."""

from __future__ import annotations

import pathlib as _pl
import sys as _sys
_sys.path.insert(0, str(_pl.Path(__file__).resolve().parent.parent))

from distllm_tpu.utils import apply_platform_env

apply_platform_env()

import time

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.embed import get_pooler
from distllm_tpu.embed.encoders.base import JaxEncoder
from distllm_tpu.models import bert
from distllm_tpu.models.tokenizer import WhitespaceTokenizer


def main() -> None:
    cfg = bert.BertConfig(dtype='bfloat16')
    params = jax.device_put(bert.init(jax.random.PRNGKey(0), cfg))
    tokenizer = WhitespaceTokenizer(vocab_size=cfg.vocab_size,
                                    model_max_length=512)
    encoder = JaxEncoder(
        config=None, apply_fn=bert.apply, model_cfg=cfg, params=params,
        tokenizer=tokenizer, embedding_size=cfg.hidden_size,
    )
    pooler = get_pooler({'name': 'mean'})
    fused = encoder.pooled_forward(pooler, False)
    rng = np.random.default_rng(0)
    B = 512

    class Batch:
        def __init__(self, ids, mask):
            self.input_ids = ids
            self.attention_mask = mask

    for S in (160, 224, 256, 320):
        ids_np = rng.integers(1, cfg.vocab_size, size=(B, S)).astype(np.int32)
        mask_np = np.ones((B, S), np.int32)
        b_np = Batch(ids_np, mask_np)
        b_dev = Batch(jnp.asarray(ids_np), jnp.asarray(mask_np))
        jax.block_until_ready(fused(b_dev))  # warm

        for name, b in (('dev', b_dev), ('np ', b_np)):
            n = 6
            outs = [fused(b) for _ in range(2)]
            jax.block_until_ready(outs)
            start = time.perf_counter()
            outs = [fused(b) for _ in range(n)]
            jax.block_until_ready(outs)
            dt = (time.perf_counter() - start) / n
            mfu = 2 * 110e6 * B * S / dt / 197e12
            print(f'S={S} {name} inputs: {dt*1e3:6.1f} ms/batch  '
                  f'mfu(padded)={mfu:.3f}')


def switching() -> None:
    """Dispatch the four shapes round-robin: is executable switching the
    hidden cost that single-pass runs pay?"""
    cfg = bert.BertConfig(dtype='bfloat16')
    params = jax.device_put(bert.init(jax.random.PRNGKey(0), cfg))
    tokenizer = WhitespaceTokenizer(vocab_size=cfg.vocab_size,
                                    model_max_length=512)
    encoder = JaxEncoder(
        config=None, apply_fn=bert.apply, model_cfg=cfg, params=params,
        tokenizer=tokenizer, embedding_size=cfg.hidden_size,
    )
    pooler = get_pooler({'name': 'mean'})
    fused = encoder.pooled_forward(pooler, False)
    rng = np.random.default_rng(0)
    B = 512

    class Batch:
        def __init__(self, ids, mask):
            self.input_ids = ids
            self.attention_mask = mask

    shapes = (160, 224, 256, 320)
    batches = []
    for S in shapes:
        ids = jnp.asarray(rng.integers(1, cfg.vocab_size, size=(B, S)), jnp.int32)
        batches.append(Batch(ids, jnp.ones((B, S), jnp.int32)))
        jax.block_until_ready(fused(batches[-1]))
    tokens = B * sum(shapes)
    for trial in range(3):
        start = time.perf_counter()
        outs = [fused(b) for b in batches]
        jax.block_until_ready(outs)
        dt = time.perf_counter() - start
        print(f'round-robin pass {trial}: {dt*1e3:6.1f} ms  '
              f'mfu={2*110e6*tokens/dt/197e12:.3f}')


if __name__ == '__main__':
    import sys
    if '--switching' in sys.argv:
        switching()
    else:
        main()
