"""Multi-replica router CLI (docs/routing.md).

Fronts N chat_server replicas with the prefix-affinity router
(``distllm_tpu/router/``): OpenAI-compatible ``POST /v1/chat/completions``
in, cache-aware replica pick + proxy out, ``GET /health`` and
``GET /metrics`` (``distllm_router_*`` series) on the side.

Examples::

    # two replicas, prefix-affinity routing (the default policy)
    python scripts/router.py --replica http://127.0.0.1:8001 \
        --replica http://127.0.0.1:8002 --port 8000

    # round-robin baseline for an A/B
    python scripts/router.py --replica http://127.0.0.1:8001 \
        --replica http://127.0.0.1:8002 --policy round_robin

    # everything from a YAML RouterConfig
    python scripts/router.py --config router.yaml

The router process is stateless across restarts: affinity maps re-learn
from the ``X-Distllm-Prefix-Digest`` response headers within a few
requests per session.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--config', type=str, default=None,
                        help='YAML RouterConfig (replicas, policy, knobs)')
    parser.add_argument('--replica', action='append', default=None,
                        metavar='URL',
                        help='replica base URL (repeatable); overrides the '
                             'config file list when given')
    parser.add_argument('--policy', type=str, default=None,
                        choices=('prefix_affinity', 'least_loaded',
                                 'round_robin'))
    parser.add_argument('--host', default='0.0.0.0')
    parser.add_argument('--port', type=int, default=8000)
    args = parser.parse_args(argv)

    from aiohttp import web

    from distllm_tpu.router import RouterConfig, build_router_app

    config = (
        RouterConfig.from_yaml(args.config) if args.config else RouterConfig()
    )
    if args.replica:
        config = config.model_copy(update={'replicas': tuple(args.replica)})
    if args.policy:
        config = config.model_copy(update={'policy': args.policy})
    if not config.replicas:
        parser.error('at least one --replica (or a config with replicas) '
                     'is required')
    web.run_app(build_router_app(config), host=args.host, port=args.port)
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
