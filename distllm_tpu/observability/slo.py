"""Multi-window multi-burn-rate SLO engine over the metric history.

SRE-workbook style alerting over the existing request-SLO counters
(``distllm_request_slo_total{outcome=met|missed}``, the TTFT SLO the
engine already judges): the **burn rate** of a trailing window is

    (missed / finished in the window) / (1 - objective)

— 1.0 means the replica is spending its error budget exactly at the
sustainable rate, 10 means ten times too fast. Single-window alerts
are either slow (long window) or flappy (short window); the standard
fix is **window pairs**: alert only when BOTH the short and the long
window of a pair burn past the pair's threshold — the short window
proves it is happening *now*, the long window proves it is not a blip.

Defaults (window labels owned by ``instruments.SLO_BURN_WINDOW_LABELS``):

- **page pair** — 60 s / 600 s at burn ≥ 6.0 (a fast, real burn);
- **warn pair** — 300 s / 3600 s at burn ≥ 1.0 (budget is being spent
  faster than sustainable, but not on fire).

:func:`slo_status` renders the ok/warn/page verdict plus per-window
burn rates, the goodput fraction, and uptime — the per-replica signal
feed the multi-replica router (ROADMAP item 2) polls. Installed as a
history observer (:func:`install_slo_observer`), every sampler tick
also refreshes the pre-registered ``distllm_slo_burn_rate{window}``
gauges so burn rates are scrape-visible without any JSON endpoint.

``GET /debug/slo`` / ``slo.json`` schema — ``distllm-slo/v1``::

    {"schema": "distllm-slo/v1", "objective": 0.99, "verdict": "ok",
     "burn_rates": {"60s": 0.0, ...},
     "windows": {"60s": {"met": N, "missed": N, "burn_rate": x}, ...},
     "pairs": [{"short": "60s", "long": "600s", "threshold": 6.0,
                "verdict": "page", "firing": false}, ...],
     "goodput_fraction": 0.98, "uptime_s": 123.4}

No traffic in a window reads as burn 0.0 (an idle replica is not
burning budget); ``goodput_fraction`` is None until tokens flow.
"""

from __future__ import annotations

import time

from distllm_tpu.observability import instruments as _metrics
from distllm_tpu.observability.history import MetricsHistory

SLO_SCHEMA = 'distllm-slo/v1'

#: Default objective: 99% of finished requests meet the TTFT SLO.
DEFAULT_OBJECTIVE = 0.99

#: (short_label, long_label, burn threshold, verdict) — labels must come
#: from instruments.SLO_BURN_WINDOW_LABELS (the single owner of the
#: gauge's window label set).
DEFAULT_PAIRS = (
    ('60s', '600s', 6.0, 'page'),
    ('300s', '3600s', 1.0, 'warn'),
)


def _window_seconds(label: str) -> float:
    if not label.endswith('s'):
        raise ValueError(f'window label must end in "s": {label!r}')
    return float(label[:-1])


def burn_rate(
    history: MetricsHistory,
    window_s: float,
    *,
    objective: float = DEFAULT_OBJECTIVE,
    now: float | None = None,
) -> dict:
    """One window's burn: ``{'met', 'missed', 'total', 'burn_rate'}``.
    Zero traffic burns nothing (0.0) — the idle replica is healthy."""
    if not 0.0 < objective < 1.0:
        raise ValueError(f'objective must be in (0, 1), got {objective}')
    met = history.counter_window(
        'distllm_request_slo_total', window_s,
        labels={'outcome': 'met'}, now=now,
    )['delta']
    missed = history.counter_window(
        'distllm_request_slo_total', window_s,
        labels={'outcome': 'missed'}, now=now,
    )['delta']
    total = met + missed
    rate = (missed / total) if total > 0 else 0.0
    return {
        'met': met,
        'missed': missed,
        'total': total,
        'burn_rate': rate / (1.0 - objective),
    }


def update_burn_gauges(
    history: MetricsHistory,
    *,
    objective: float = DEFAULT_OBJECTIVE,
    now: float | None = None,
) -> dict[str, float]:
    """Refresh ``distllm_slo_burn_rate{window}`` for every catalogued
    window; returns the label → burn mapping it set."""
    burns: dict[str, float] = {}
    for label in _metrics.SLO_BURN_WINDOW_LABELS:
        burn = burn_rate(
            history, _window_seconds(label), objective=objective, now=now
        )['burn_rate']
        _metrics.SLO_BURN_RATE.labels(window=label).set(burn)
        burns[label] = burn
    return burns


def slo_status(
    history: MetricsHistory | None = None,
    *,
    objective: float = DEFAULT_OBJECTIVE,
    pairs=DEFAULT_PAIRS,
    now: float | None = None,
) -> dict:
    """The ok/warn/page verdict document (module docstring schema).
    Verdict: ``page`` if any page pair fires (both its windows burn past
    threshold), else ``warn`` if any warn pair fires, else ``ok``."""
    if history is None:
        from distllm_tpu.observability.history import get_metrics_history
        history = get_metrics_history()
    now = time.time() if now is None else float(now)
    windows: dict[str, dict] = {}
    for label in _metrics.SLO_BURN_WINDOW_LABELS:
        windows[label] = burn_rate(
            history, _window_seconds(label), objective=objective, now=now
        )
    pair_docs = []
    verdict = 'ok'
    for short, long_, threshold, pair_verdict in pairs:
        firing = (
            windows[short]['burn_rate'] >= threshold
            and windows[long_]['burn_rate'] >= threshold
        )
        pair_docs.append({
            'short': short,
            'long': long_,
            'threshold': threshold,
            'verdict': pair_verdict,
            'firing': firing,
        })
        if firing:
            if pair_verdict == 'page':
                verdict = 'page'
            elif verdict != 'page':
                verdict = 'warn'
    # Goodput fraction over the longest window: tokens from SLO-met
    # requests over all generated tokens — the quality-adjusted share.
    long_s = max(
        _window_seconds(label)
        for label in _metrics.SLO_BURN_WINDOW_LABELS
    )
    good = history.counter_window(
        'distllm_engine_goodput_tokens_total', long_s, now=now
    )['delta']
    generated = history.counter_window(
        'distllm_engine_generated_tokens_total', long_s, now=now
    )['delta']
    return {
        'schema': SLO_SCHEMA,
        'objective': objective,
        'verdict': verdict,
        'burn_rates': {
            label: windows[label]['burn_rate'] for label in windows
        },
        'windows': windows,
        'pairs': pair_docs,
        'goodput_fraction': (good / generated) if generated > 0 else None,
        'uptime_s': _metrics.SERVER_UPTIME.value,
    }


def install_slo_observer(
    history: MetricsHistory, *, objective: float = DEFAULT_OBJECTIVE
):
    """Attach the burn-gauge refresh to the sampler loop; returns the
    observer so callers can ``remove_observer`` it."""

    def _observer(h: MetricsHistory, now: float) -> None:
        update_burn_gauges(h, objective=objective, now=now)

    history.add_observer(_observer)
    return _observer
