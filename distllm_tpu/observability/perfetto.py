"""Perfetto / Chrome trace-event export of the observability state
(ISSUE 10 tentpole).

:func:`to_trace_events` renders the flight ring + span ring + per-request
lifecycles as one Chrome trace-event JSON document — the format both
``chrome://tracing`` and https://ui.perfetto.dev open directly — so "where
did the time go" becomes a scroll instead of a probe-script investigation:

- one track per engine window kind (``prefill`` / ``decode`` / ``mixed`` /
  ``spec``), each dispatch a complete slice with its flight fields
  (batch, tokens, MFU, bandwidth utilization, host/put/dispatch/fetch
  split) as args;
- a ``startup`` track of compile-phase slices (``compile`` flight
  records from ``observability/startup.py``): backend init, every warmup
  shape, the weight-layout migration — a wedged init finally shows which
  shape it died in;
- a ``host`` track whose slices are the gaps *between* windows — the
  host-side time the chip sat idle, the exact quantity the r5 serving-gap
  hunt had to reconstruct by hand;
- one track per request (keyed by the propagated ``X-Request-Id`` when
  present), showing the whole enqueue → finish lifecycle with nested
  TTFT and queue-wait slices;
- server/application spans from the trace ring: spans stamped with a
  ``request_id`` land on that request's track (server → engine
  correlation in one glance), the rest on per-thread tracks;
- a ``history`` group of counter tracks (ph ``'C'``) rendered from the
  metric-history ring (``observability/history.py``) — tok/s, SLO burn
  rates, queue depth, KV occupancy — so the load/latency shape scrubs
  alongside the slices instead of living in a separate JSON document.

Served at ``GET /debug/perfetto`` by the chat server, written as
``perfetto.json`` into every debug bundle, and merged across hosts by
``observability.aggregate --perfetto`` (one process group per host).

Everything is dependency-free; records are plain dicts (what
``FlightRecorder.snapshot`` / ``Span.to_dict`` / the JSONL dumps give),
so crash bundles from a dead process replay identically.
"""

from __future__ import annotations

import json
from pathlib import Path

from distllm_tpu.observability.instruments import (
    FLIGHT_KINDS,
    TRACE_EVENT_CATEGORIES,
)

# Fixed tid layout: window-kind tracks first (stable ordering in the UI),
# then the startup / host-gap tracks, then dynamically allocated request /
# thread tracks.
_KIND_TIDS = {'prefill': 1, 'decode': 2, 'mixed': 3, 'spec': 4}
_STARTUP_TID = 8
_HOST_TID = 9
_EVENT_TID = 10
_HISTORY_TID = 11
_REQUEST_TID_BASE = 100
_THREAD_TID_BASE = 10_000

# Metric-history series rendered as Perfetto counter tracks (ph 'C'):
# (history series key, counter track name, value column in the rendered
# snapshot points — counters are [t, delta, rate], gauges [t, value]).
# A curated subset, not the whole ring: the load/latency shape an
# incident reader scrubs the trace against.
_HISTORY_TRACK_SERIES = (
    ('distllm_engine_generated_tokens_total', 'tok/s', 2),
    ('distllm_slo_burn_rate{window=60s}', 'slo_burn:60s', 1),
    ('distllm_slo_burn_rate{window=600s}', 'slo_burn:600s', 1),
    ('distllm_scheduler_queue_depth', 'queue_depth', 1),
    ('distllm_kv_cache_occupancy_ratio', 'kv_occupancy', 1),
)

# Flight fields that become their own event structure rather than args.
_STEP_META = ('kind', 't_wall', 'duration_s')


def _slice(name, ts_us, dur_us, pid, tid, args=None, *, cat) -> dict:
    event = {
        'name': str(name),
        'cat': cat,
        'ph': 'X',
        'ts': round(ts_us, 3),
        'dur': round(max(0.0, dur_us), 3),
        'pid': pid,
        'tid': tid,
    }
    if args:
        event['args'] = args
    return event


def _instant(name, ts_us, pid, tid, args=None, *, cat) -> dict:
    event = {
        'name': str(name),
        'cat': cat,
        'ph': 'i',
        's': 't',
        'ts': round(ts_us, 3),
        'pid': pid,
        'tid': tid,
    }
    if args:
        event['args'] = args
    return event


def _meta(name, value, pid, tid=None) -> dict:
    event = {
        'name': name,
        'ph': 'M',
        'ts': 0,
        'pid': pid,
        'args': {'name': value},
    }
    if tid is not None:
        event['tid'] = tid
    return event


def trace_time_origin(flight_records, spans=()) -> float | None:
    """Earliest wall-clock second any record/span covers (slice starts,
    not record times), or ``None`` when there is nothing to render. The
    multi-host merge computes ONE origin across every host's captures so
    their tracks share a timeline."""
    starts: list[float] = []
    for record in flight_records:
        t_wall = record.get('t_wall')
        if not isinstance(t_wall, (int, float)):
            continue
        dur = record.get('duration_s') or record.get('e2e_s') or 0.0
        starts.append(float(t_wall) - float(dur or 0.0))
    for span in spans:
        wall = span.get('wall_time_s')
        if isinstance(wall, (int, float)):
            starts.append(float(wall))
    return min(starts) if starts else None


def to_trace_events(
    flight_records,
    spans=(),
    *,
    pid: int = 1,
    process_name: str = 'distllm',
    time_origin_s: float | None = None,
    history=None,
) -> dict:
    """Render flight records + span dicts into a Chrome trace-event doc.

    ``flight_records`` are ``FlightRecorder.snapshot()`` dicts (or parsed
    ``flight.jsonl`` lines); ``spans`` are ``Span.to_dict()`` dicts (or
    parsed ``traces.jsonl`` lines); ``history`` (optional) is a
    ``MetricsHistory`` or its ``snapshot()`` document, rendered as
    counter tracks (ph ``'C'``, the ``history`` category) for the
    curated ``_HISTORY_TRACK_SERIES`` — tok/s, burn rates, queue depth,
    KV occupancy over the trace window. Returns
    ``{'traceEvents': [...], 'displayTimeUnit': 'ms'}`` with every track's
    events in non-decreasing ``ts`` order — the invariant the exporter
    tests pin. Unknown/torn records are skipped, never fatal: this runs
    inside debug bundles for dying processes.
    """
    origin = time_origin_s
    if origin is None:
        origin = trace_time_origin(flight_records, spans) or 0.0

    def us(wall_s: float) -> float:
        return (float(wall_s) - origin) * 1e6

    events: list[dict] = []
    meta: list[dict] = [_meta('process_name', process_name, pid)]
    request_tids: dict[str, int] = {}
    thread_tids: dict[int, int] = {}

    def request_tid(key: str) -> int:
        tid = request_tids.get(key)
        if tid is None:
            tid = _REQUEST_TID_BASE + len(request_tids)
            request_tids[key] = tid
            meta.append(_meta('thread_name', f'request {key}', pid, tid))
        return tid

    # ---- engine step tracks + the host-gap track -----------------------
    windows: list[tuple[float, float]] = []  # (start_s, end_s)
    for record in flight_records:
        kind = record.get('kind')
        t_wall = record.get('t_wall')
        if kind not in FLIGHT_KINDS or not isinstance(t_wall, (int, float)):
            continue
        args = {
            k: v for k, v in record.items()
            if k not in _STEP_META and v is not None
        }
        if kind in _KIND_TIDS:
            duration = float(record.get('duration_s') or 0.0)
            start = float(t_wall) - duration
            windows.append((start, float(t_wall)))
            events.append(_slice(
                kind, us(start), duration * 1e6,
                pid, _KIND_TIDS[kind], args, cat='engine_step',
            ))
        elif kind == 'compile':
            # Startup track: one slice per compile phase (warmup shapes,
            # backend init, layout migration — observability/startup.py).
            # Deliberately NOT a host-gap window: the gap track measures
            # serving-loop idleness, not the compile ladder.
            duration = float(record.get('duration_s') or 0.0)
            start = float(t_wall) - duration
            name = f"{record.get('phase', 'compile')}:{record.get('shape', '')}"
            events.append(_slice(
                name, us(start), duration * 1e6,
                pid, _STARTUP_TID, args, cat='startup',
            ))
        elif kind == 'request':
            e2e = record.get('e2e_s')
            if not isinstance(e2e, (int, float)):
                continue  # pre-attribution record: no reconstructable start
            key = str(
                record.get('trace_id') or f"rid-{record.get('request_id')}"
            )
            tid = request_tid(key)
            start = float(t_wall) - float(e2e)
            events.append(_slice(
                key, us(start), float(e2e) * 1e6, pid, tid, args,
                cat='request',
            ))
            ttft = record.get('ttft_s')
            if isinstance(ttft, (int, float)):
                events.append(_slice(
                    'ttft', us(start), float(ttft) * 1e6,
                    pid, tid, cat='request',
                ))
            queue_wait = record.get('queue_wait_s')
            if isinstance(queue_wait, (int, float)):
                events.append(_slice(
                    'queue_wait', us(start),
                    float(queue_wait) * 1e6, pid, tid, cat='request',
                ))
        else:  # preempt / event: instants on their own track
            events.append(_instant(
                kind, us(float(t_wall)), pid, _EVENT_TID,
                args, cat='engine_event',
            ))

    windows.sort()
    prev_end = None
    for start, end in windows:
        if prev_end is not None and start > prev_end:
            events.append(_slice(
                'host_gap', us(prev_end),
                (start - prev_end) * 1e6, pid, _HOST_TID, cat='host_gap',
            ))
        prev_end = end if prev_end is None else max(prev_end, end)

    # ---- spans ---------------------------------------------------------
    for span in spans:
        name = span.get('name')
        wall = span.get('wall_time_s')
        duration = span.get('duration_s')
        if (
            name is None
            or not isinstance(wall, (int, float))
            or not isinstance(duration, (int, float))
        ):
            continue  # open span / torn line
        attrs = span.get('attributes') or {}
        args = {
            'tags': span.get('tags') or [],
            'status': span.get('status'),
            **{k: v for k, v in attrs.items() if v is not None},
        }
        rid = attrs.get('request_id')
        if rid is not None:
            tid = request_tid(str(rid))
        else:
            ident = int(span.get('thread_id') or 0)
            tid = thread_tids.get(ident)
            if tid is None:
                tid = _THREAD_TID_BASE + len(thread_tids)
                thread_tids[ident] = tid
                meta.append(_meta(
                    'thread_name', f'spans (thread {ident})', pid, tid,
                ))
        events.append(_slice(
            name, us(float(wall)), float(duration) * 1e6, pid, tid,
            args, cat='span',
        ))

    # ---- metric-history counter tracks ---------------------------------
    if history is not None:
        snap = history if isinstance(history, dict) else history.snapshot()
        hist_series = snap.get('series', {}) if isinstance(snap, dict) else {}
        emitted_any = False
        for key, track_name, value_index in _HISTORY_TRACK_SERIES:
            entry = hist_series.get(key)
            if not isinstance(entry, dict):
                continue
            for point in entry.get('points', ()):
                try:
                    t_point = float(point[0])
                    value = point[value_index]
                except (TypeError, ValueError, IndexError):
                    continue
                if isinstance(value, bool) or not isinstance(
                    value, (int, float)
                ):
                    continue
                emitted_any = True
                events.append({
                    'name': track_name,
                    'cat': 'history',
                    'ph': 'C',
                    'ts': round(us(t_point), 3),
                    'pid': pid,
                    'tid': _HISTORY_TID,
                    'args': {'value': round(float(value), 6)},
                })
        if emitted_any:
            meta.append(_meta(
                'thread_name', 'history (metric counters)',
                pid, _HISTORY_TID,
            ))

    for kind, tid in sorted(_KIND_TIDS.items(), key=lambda kv: kv[1]):
        meta.append(_meta('thread_name', f'engine:{kind}', pid, tid))
    meta.append(_meta('thread_name', 'startup (compile phases)',
                      pid, _STARTUP_TID))
    meta.append(_meta('thread_name', 'host (gaps between windows)',
                      pid, _HOST_TID))
    meta.append(_meta('thread_name', 'engine events', pid, _EVENT_TID))

    # Per-track non-decreasing ts; wider slices first at equal ts so
    # nested children (ttft inside a request slice) follow their parent.
    events.sort(key=lambda e: (e['tid'], e['ts'], -e.get('dur', 0.0)))
    return {'traceEvents': meta + events, 'displayTimeUnit': 'ms'}


def merge_host_traces(hosts: list[tuple[str, list, list]]) -> dict:
    """Merge per-host captures into ONE trace with per-host track groups.

    ``hosts`` is ``[(host_name, flight_records, spans), ...]`` (what
    ``aggregate.py --perfetto`` builds from any mix of ``flight.jsonl`` /
    ``traces.jsonl`` dumps). Each host becomes its own process group
    (pid), and every host shares a single time origin so cross-host skew
    reads directly off the timeline.
    """
    origins = [
        origin
        for _, records, spans in hosts
        if (origin := trace_time_origin(records, spans)) is not None
    ]
    origin = min(origins) if origins else 0.0
    merged: list[dict] = []
    for i, (name, records, spans) in enumerate(hosts):
        doc = to_trace_events(
            records, spans, pid=i + 1, process_name=str(name),
            time_origin_s=origin,
        )
        merged.extend(doc['traceEvents'])
    return {'traceEvents': merged, 'displayTimeUnit': 'ms'}


def validate_trace_events(doc: dict) -> list[str]:
    """Structural validation of a trace-event document; returns a list of
    violations (empty = valid). The invariants the exporter tests (and
    the ``GET /debug/perfetto`` round-trip test) assert:

    - the document is JSON-serializable with a ``traceEvents`` list;
    - every event has ``ph``/``pid``/``ts`` and a registered ``cat``
      (non-metadata events);
    - duration events are complete ``X`` slices (or properly matched
      ``B``/``E`` pairs) with non-negative ``dur``;
    - per ``(pid, tid)`` track, ``ts`` is non-decreasing.
    """
    problems: list[str] = []
    try:
        json.dumps(doc)
    except (TypeError, ValueError) as exc:
        return [f'not JSON-serializable: {exc!r}']
    events = doc.get('traceEvents')
    if not isinstance(events, list):
        return ['traceEvents is not a list']
    last_ts: dict[tuple, float] = {}
    open_stacks: dict[tuple, list[str]] = {}
    for i, event in enumerate(events):
        ph = event.get('ph')
        if ph == 'M':
            continue
        for field in ('ph', 'pid', 'ts'):
            if field not in event:
                problems.append(f'event {i} missing {field!r}')
        if event.get('cat') not in TRACE_EVENT_CATEGORIES:
            problems.append(
                f'event {i} has unregistered cat {event.get("cat")!r}'
            )
        key = (event.get('pid'), event.get('tid'))
        ts = event.get('ts', 0.0)
        if key in last_ts and ts < last_ts[key]:
            problems.append(
                f'event {i}: ts {ts} < previous {last_ts[key]} on track '
                f'{key}'
            )
        last_ts[key] = ts
        if ph == 'X':
            if event.get('dur', -1.0) < 0:
                problems.append(f'event {i}: X slice with negative dur')
        elif ph == 'B':
            open_stacks.setdefault(key, []).append(event.get('name', ''))
        elif ph == 'E':
            stack = open_stacks.get(key) or []
            if not stack:
                problems.append(f'event {i}: E with no open B on {key}')
            else:
                stack.pop()
        elif ph not in ('i', 'I', 'C', 'M'):
            problems.append(f'event {i}: unknown ph {ph!r}')
    for key, stack in open_stacks.items():
        if stack:
            problems.append(f'unclosed B events on track {key}: {stack}')
    return problems


def dump_trace(path: str | Path, flight_records, spans=(), **kwargs) -> int:
    """Write one trace-event JSON file; returns the event count."""
    doc = to_trace_events(flight_records, spans, **kwargs)
    Path(path).write_text(json.dumps(doc))
    return len(doc['traceEvents'])
