"""Startup & compile-phase attribution (ISSUE 11 tentpole).

Three official bench rounds (r03–r05) died inside backend init or the
warmup compile ladder — the single most expensive startup phase, 22–45
minutes cold for int8 — and left *nothing* behind: no spans, no flight
records, no hint of which shape the process was compiling when it
stopped. This module makes startup attributable the same way ISSUE 3
made the serving loop attributable:

- :class:`CompileWatcher` — a process-wide watcher whose ``phase(kind,
  shape)`` context manager times one startup phase (a warmup shape, the
  weight-layout migration, backend init, ...) and emits a ``compile``
  flight-ring record per phase, plus the
  ``distllm_compile_seconds{kind,shape}`` histogram and
  ``distllm_compile_cache_hits_total`` counter. Phase kinds are
  registered in ``instruments.COMPILE_PHASES`` (enforced by
  ``tests/test_lint.py``) so the startup schema cannot fragment.
- **cache-hit marking** — a phase is marked ``cache_hit`` when its
  (kind, shape) already completed in this process (re-warmup fast path)
  or when the phase added zero new entries to a configured persistent
  compilation cache (an AOT-preflight-seeded cold start).
- **dead-phase attribution** — the watcher tracks the phase currently
  *in progress*; ``state()`` (written into every debug bundle as
  ``startup.json``) names it, so an init-stall bundle — the r03/r04
  failure mode — says *which shape* the process died in instead of
  arriving empty.
- :func:`record_backend_init` — wraps the first ``jax.devices()`` touch
  in a ``backend_init`` phase; later calls are near-instant and marked
  as cache hits, so it is safe to call from every engine constructor.

Rendering: ``compile`` records get a dedicated *startup* track in the
Perfetto export (``observability/perfetto.py``), beside the serving
window tracks. Phase durations are host wall time around the dispatch —
on TPU, compilation happens inside the traced call, so a cold phase's
duration IS its compile time (plus a negligible dummy execution).

Everything here is dependency-free and safe to import on any backend.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import sys
import threading
import time

from distllm_tpu.observability import instruments as _metrics
from distllm_tpu.observability.flight import FlightRecorder, get_flight_recorder

# Completed-phase summaries kept for state()/debug bundles; a bench run's
# whole warmup ladder is tens of phases, so this never truncates in
# practice — it only bounds a pathological caller.
_MAX_PHASES = 256


class CompileWatcher:
    """Times startup/compile phases into flight records + metric series.

    One watcher serves the whole process (:func:`get_compile_watcher`);
    tests inject their own ``recorder`` for isolation. Thread-safe: the
    engine thread, the aiohttp event loop, and bundle dumps may touch it
    at once — though phases themselves are expected to run sequentially
    (startup is single-threaded), so ``active`` is a single slot.
    """

    def __init__(self, recorder: FlightRecorder | None = None) -> None:
        self._recorder = recorder
        self._lock = threading.Lock()
        self._seen: set[tuple[str, str, str]] = set()  # guarded by self._lock
        self._phases: list[dict] = []  # guarded by self._lock
        self._active: dict | None = None  # guarded by self._lock
        self._scopes = itertools.count()

    def new_scope(self, prefix: str = 'engine') -> str:
        """A fresh dedup namespace for :meth:`phase`'s ``scope`` — one
        per engine instance, so rebuilt engines start cold."""
        return f'{prefix}-{next(self._scopes)}'

    @property
    def recorder(self) -> FlightRecorder:
        return (
            self._recorder
            if self._recorder is not None
            else get_flight_recorder()
        )

    @staticmethod
    def _persistent_cache_entries() -> int | None:
        """Entry count of jax's persistent compilation cache dir, or
        ``None`` when no cache is configured / jax is not imported.
        Before/after deltas per phase reveal whether a cold start HIT the
        preflight-seeded cache or re-lowered everything (the same signal
        bench.py's ``warm_start`` field reports per stage)."""
        jax = sys.modules.get('jax')
        if jax is None:
            return None
        try:
            cache_dir = jax.config.jax_compilation_cache_dir
        except Exception:
            return None
        if not cache_dir:
            return None
        try:
            return len(os.listdir(cache_dir))
        except OSError:
            return None

    @contextlib.contextmanager
    def phase(self, kind: str, shape: str, *, compiles: bool = True,
              scope: str = '', **fields):
        """Time one startup phase; yields a mutable fields dict the body
        may enrich (platform, entry counts, ...). On exit — success OR
        failure — one ``compile`` flight record lands in the ring and
        ``distllm_compile_seconds{kind,shape}`` observes the duration;
        failures carry an ``error`` field and never count as cache hits.
        The phase is visible via :meth:`state` while in progress, which
        is what lets a bundle dumped mid-stall name the dead phase.

        ``compiles=False`` declares a phase that does real work but no
        XLA compilation (backend init, weight migration, pool
        allocation): such phases can only be cache hits via the
        process-repeat path. Without the flag, a cold first run with a
        persistent cache dir configured would mark every non-compiling
        phase as a "hit" (zero new cache entries), poisoning exactly the
        warm-start evidence the counter exists to provide.

        ``scope`` namespaces the process-repeat dedup: each engine
        passes its own scope, because a SECOND engine in one process
        (bench A/B stages, the quantization fallback ladder) builds new
        jit wrappers whose warmup really recompiles — the same (kind,
        shape) under a fresh scope must not read as a hit. The
        persistent-cache-delta signal is deliberately scope-free (that
        cache IS shared)."""
        entry: dict = {'phase': kind, 'shape': shape, **fields}
        entries_before = self._persistent_cache_entries()
        with self._lock:
            seen = (scope, kind, shape) in self._seen
            self._active = {**entry, 't_start_wall': time.time()}
        t0 = time.monotonic()
        error: str | None = None
        try:
            yield entry
        except BaseException as exc:
            error = repr(exc)[:300]
            raise
        finally:
            duration_s = time.monotonic() - t0
            entries_after = self._persistent_cache_entries()
            persistent_delta = (
                entries_after - entries_before
                if entries_before is not None and entries_after is not None
                else None
            )
            cache_hit = error is None and (
                seen or (compiles and persistent_delta == 0)
            )
            entry['duration_s'] = round(duration_s, 6)
            entry['cache_hit'] = cache_hit
            if persistent_delta is not None:
                entry['persistent_cache_delta'] = persistent_delta
            if error is not None:
                entry['error'] = error
            with self._lock:
                self._active = None
                if error is None:
                    self._seen.add((scope, kind, shape))
                self._phases.append({**entry, 't_wall': time.time()})
                del self._phases[:-_MAX_PHASES]
            try:
                self.recorder.record('compile', **entry)
            except Exception:
                pass  # a full disk must not turn startup fatal
            _metrics.COMPILE_SECONDS.labels(kind=kind, shape=shape).observe(
                duration_s
            )
            if cache_hit:
                _metrics.COMPILE_CACHE_HITS.inc()

    def state(self) -> dict:
        """Snapshot for debug bundles: the completed phase list plus the
        phase currently in progress (``None`` between phases). A bundle
        dumped during a wedged init shows ``active`` naming the exact
        (kind, shape) the process is stuck compiling."""
        with self._lock:
            return {
                'active': dict(self._active) if self._active else None,
                'phases': [dict(p) for p in self._phases],
            }


_default_watcher = CompileWatcher()


def get_compile_watcher() -> CompileWatcher:
    """The process-wide compile watcher (what engines and bundles use)."""
    return _default_watcher


def record_backend_init(watcher: CompileWatcher | None = None):
    """Time the jax backend/device init as a ``backend_init`` phase.

    The first call in a process pays (and attributes) the real PJRT
    client init — the phase r03/r04 died in, previously invisible; later
    calls return in microseconds and are marked as cache hits. Returns
    the device list. Exceptions propagate (a dead backend is fatal to
    the caller) but the phase record lands first, with the error.
    """
    watcher = watcher if watcher is not None else _default_watcher
    import jax

    with watcher.phase('backend_init', 'devices', compiles=False) as fields:
        devices = jax.devices()
        fields['platform'] = devices[0].platform
        fields['device_kind'] = getattr(devices[0], 'device_kind', '')
        fields['num_devices'] = len(devices)
    return devices
