"""Shared BENCH-record parsing + the sentinel baseline envelope.

One parser for two consumers, so they can never disagree on what a
record says:

- the offline trajectory gate (``scripts/benchdiff.py``) diffs
  BENCH_r*.json records round over round and exits nonzero on a
  regression;
- the runtime regression sentinel (``observability/sentinel.py``)
  compares *live* history windows against a **baseline envelope**
  distilled from the newest record that actually carried metrics
  (``scripts/benchdiff.py --emit-baseline``).

Everything here is dependency-free (no jax, no registry import): the
benchdiff CLI runs it standalone, and the sentinel imports it inside a
serving process.

Record shape: the driver-contract JSON ``{"n", "cmd", "rc",
"parsed": {...}}`` or a bare metrics object; records that died before
emitting (``parsed: null``) parse to an explicitly empty metrics dict,
never a crash — the gate and the sentinel both must survive a crashed
round.
"""

from __future__ import annotations

import json
import math
from pathlib import Path

ENVELOPE_SCHEMA = 'distllm-baseline-envelope/v1'

# Direction of "better" per gated metric. Matching is by substring /
# suffix on the flattened key; anything unmatched is informational only
# (shown in the benchdiff table, never gated or sentinelled) — counts,
# batch sizes, cache-entry bookkeeping must not fail a round.
# 'mfu_measured' / 'bw_util_measured' gate the per-kind XLA-measured
# roofline columns the gen_kernel A/B stage records
# (gen_kernel_{xla,pallas}_{mfu,bw_util}_measured,
# docs/observability.md "Measured vs analytic MFU") so a kernel
# regression — measured utilization falling on the same workload — trips
# the trajectory gate even when tok/s noise hides it.
_LOWER_BETTER_TOKENS = ('ttft', 'tpot', 'queue_wait', 'warmup_secs')
_HIGHER_BETTER_SUFFIXES = ('value', 'mfu', 'vs_baseline')
# 'promotion_overlap' gates the gen_tier stage's KV-tier prefetch
# efficiency (1 - blocking wait / promotion span, docs/prefix_caching.md
# "Tier hierarchy"): overlap falling means host→device promotions stopped
# hiding behind decode windows. The stage's warm-TTFT metrics gate
# lower-better via the 'ttft' token (gen_tier_warm_ttft_s /
# gen_tier_cold_ttft_s), and gen_tier_warm_ttft_speedup higher-better via
# the 'speedup' override, so a tier regression trips the gate from
# either side. Raw spill/promotion COUNTS stay informational — workload-
# dependent volume, not quality.
#
# 'recoveries' gates the gen_chaos stage (docs/resilience.md): fewer
# recoveries on the SAME deterministic fault schedule means injected
# faults stopped being survived — requests started failing (or the
# schedule stopped firing) instead of retrying back to identical tokens.
# Goodput-under-fault gates through the existing 'goodput' token
# (gen_chaos_goodput_tokens). Shed counts/rates stay INFORMATIONAL by
# design: shed volume is offered-load policy, not quality — a round that
# sheds more under a heavier schedule is not a regression ('shed_rate'
# deliberately matches no gated token).
# 'greedy_match' gates the gen_kvq stage's ACCURACY arm (docs/serving.md
# "Quantized KV cache"): the fraction of the int8-KV arm's greedy tokens
# matching the bf16-KV arm's on the same workload. Falling match fraction
# is a QUALITY regression — the compression got lossier — and trips the
# trajectory gate exactly like a throughput fall; the stage records the
# divergence rather than asserting it away, and this token is what keeps
# that honesty enforceable round over round. Direction rule: higher is
# better (1.0 = bit-identical streams), so the generic higher-better
# machinery applies; a tolerance is the gate --threshold, not a
# stage-side epsilon.
_HIGHER_BETTER_TOKENS = (
    'goodput', 'accept_rate', 'hit_rate', 'tok_s', 'mfu_measured',
    'bw_util_measured', 'promotion_overlap', 'recoveries', 'greedy_match',
)


def gate_direction(key: str) -> str | None:
    """``'higher'`` / ``'lower'`` for gated metrics, ``None`` for
    informational ones. Lower-better tokens win ties (``gen_load_ttft_s``
    is a latency even though the stage also reports values) — EXCEPT
    ``speedup``, which outranks them: speedups are ratios-of-latencies
    named after their numerator (``gen_prefix_ttft_speedup``,
    ``gen_kernel_speedup``), so the 'ttft' substring alone would gate a
    warm-start IMPROVEMENT as a regression."""
    k = key.lower()
    if 'speedup' in k:
        return 'higher'
    if any(token in k for token in _LOWER_BETTER_TOKENS):
        return 'lower'
    if k.endswith(_HIGHER_BETTER_SUFFIXES):
        return 'higher'
    if any(token in k for token in _HIGHER_BETTER_TOKENS):
        return 'higher'
    return None


def extract_metrics(parsed) -> dict[str, float]:
    """Numeric metrics from one record's parsed payload (flat dict in;
    bools and non-numerics dropped; ``None``/missing payload → empty)."""
    if not isinstance(parsed, dict):
        return {}
    out: dict[str, float] = {}
    for key, value in parsed.items():
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        # bench records round-trip NaN/inf through json (allow_nan): a
        # degenerate 0/0 mfu must not crash the gate, and NaN compares
        # False against every threshold — drop it as "not reported"
        # rather than let it silently pass.
        if not math.isfinite(value):
            continue
        out[key] = float(value)
    return out


def load_record(path: str | Path) -> dict:
    """One record file → ``{'name', 'metrics', 'error'}``. Accepts the
    driver-contract wrapper (``parsed`` payload) or a bare metrics
    object; unreadable/unparseable files become an empty record with the
    error noted — the gate must be able to diff across a crashed round."""
    path = Path(path)
    name = path.stem.replace('BENCH_', '')
    try:
        doc = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        return {'name': name, 'metrics': {}, 'error': repr(exc)[:200]}
    payload = doc.get('parsed', doc) if isinstance(doc, dict) else None
    metrics = extract_metrics(payload)
    error = None
    if isinstance(payload, dict) and payload.get('error'):
        error = str(payload['error'])[:200]
    elif not metrics:
        error = 'no metrics in record (crashed before emitting?)'
    return {'name': name, 'metrics': metrics, 'error': error}


# ------------------------------------------------- the baseline envelope
# Sentinel metric → record keys that can supply its baseline, best first.
# The names mirror instruments.SENTINEL_METRIC_LABELS (single owner of
# the counter label set); this table owns only the record-key mapping.
# gen_load / gen_history keys are loadgen-measured serving numbers (the
# closest analog of live traffic); the bare gen_value / gen_mfu keys are
# the official per-round record's throughput columns, kept as fallbacks
# so even an r02-era record yields a usable envelope.
ENVELOPE_SOURCE_KEYS: dict[str, tuple[str, ...]] = {
    'tok_s': ('gen_load_tok_s', 'gen_history_tok_s', 'gen_value'),
    'ttft_p95_s': ('gen_load_ttft_p95', 'gen_history_ttft_p95'),
    'tpot_p95_s': ('gen_load_tpot_p95', 'gen_history_tpot_p95'),
    'mfu_measured': ('gen_kernel_xla_mfu_measured', 'gen_mfu'),
    'bw_util_measured': ('gen_kernel_xla_bw_util_measured',),
}


def build_envelope(metrics: dict[str, float], *, source: str = '') -> dict:
    """Distill one record's flat metrics into the baseline envelope the
    runtime sentinel consumes. Metrics with no source key present are
    simply absent (the sentinel skips them); an all-absent envelope is
    valid and disarms the sentinel (counted), never raises."""
    envelope_metrics: dict[str, dict] = {}
    for name, candidates in sorted(ENVELOPE_SOURCE_KEYS.items()):
        for key in candidates:
            if key in metrics:
                envelope_metrics[name] = {
                    'value': float(metrics[key]),
                    'direction': gate_direction(name),
                    'from_key': key,
                }
                break
    return {
        'schema': ENVELOPE_SCHEMA,
        'source': source,
        'metrics': envelope_metrics,
    }


def envelope_from_records(records: list[dict]) -> dict:
    """Envelope from the NEWEST record carrying any envelope-source
    metric — exactly the record benchdiff would gate against. Zero
    usable records (the r03–r05 tail, or an empty history) yields an
    empty envelope, not a crash."""
    for record in reversed(records):
        envelope = build_envelope(
            record.get('metrics') or {}, source=record.get('name', '')
        )
        if envelope['metrics']:
            return envelope
    return {'schema': ENVELOPE_SCHEMA, 'source': '', 'metrics': {}}


def load_envelope(path: str | Path) -> dict | None:
    """Read an envelope file; ``None`` on missing/unreadable/wrong-schema
    (the sentinel turns that into a counted disarm, never a raise)."""
    try:
        doc = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError, TypeError):
        return None
    if not isinstance(doc, dict) or doc.get('schema') != ENVELOPE_SCHEMA:
        return None
    metrics = doc.get('metrics')
    if not isinstance(metrics, dict):
        return None
    clean: dict[str, dict] = {}
    for name, entry in metrics.items():
        if not isinstance(entry, dict):
            continue
        value = entry.get('value')
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        if not math.isfinite(value):
            continue
        clean[str(name)] = {
            'value': float(value),
            'direction': entry.get('direction') or gate_direction(name),
            'from_key': entry.get('from_key', ''),
        }
    return {
        'schema': ENVELOPE_SCHEMA,
        'source': str(doc.get('source', '')),
        'metrics': clean,
    }
