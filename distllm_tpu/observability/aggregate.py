"""Roll up multi-host worker logs into one stats table.

Fabric workers on every TPU host emit ``[timer]`` lines (see ``timer.py``)
into their own stdout/log files. This module merges any number of those
captures into a single ``{tags: TimeStats}`` view — the multi-host
aggregation the reference could only do by hand — and renders it as a
fixed-width table whose columns (count / total / mean / p50 / p95 / max)
match what ``distllm_stage_duration_seconds`` exposes over ``/metrics``.

CLI::

    python -m distllm_tpu.observability.aggregate run/logs/*.txt
"""

from __future__ import annotations

import argparse
from pathlib import Path


def aggregate_lines(captures: list[str]) -> dict[tuple[str, ...], object]:
    """Merge multiple log captures (strings) into one stats dict."""
    # Lazy import: timer.py imports this package at module load.
    from distllm_tpu.timer import TimeLogger, TimeStats

    logger = TimeLogger()
    merged: dict[tuple[str, ...], TimeStats] = {}
    for capture in captures:
        for tags, stats in logger.parse_lines(capture).items():
            entry = merged.setdefault(tags, TimeStats(tags=tags))
            entry.elapsed_s.extend(stats.elapsed_s)
            entry.start_ns.extend(stats.start_ns)
            entry.end_ns.extend(stats.end_ns)
    return merged


def aggregate_logs(paths: list[str | Path]) -> dict[tuple[str, ...], object]:
    """Merge ``[timer]`` lines from many log files into one stats dict."""
    return aggregate_lines([Path(p).read_text() for p in paths])


def format_stats_table(stats: dict[tuple[str, ...], object]) -> str:
    """Fixed-width table, one row per tag set, sorted by total time desc."""
    header = ('tags', 'count', 'total_s', 'mean_s', 'p50_s', 'p95_s', 'max_s')
    rows = [header]
    ordered = sorted(
        stats.values(), key=lambda s: s.total_s, reverse=True
    )
    for entry in ordered:
        rows.append(
            (
                ','.join(entry.tags) or '-',
                str(entry.count),
                f'{entry.total_s:.3f}',
                f'{entry.mean_s:.3f}',
                f'{entry.p50_s:.3f}',
                f'{entry.p95_s:.3f}',
                f'{entry.max_s:.3f}',
            )
        )
    widths = [max(len(row[i]) for row in rows) for i in range(len(header))]
    lines = []
    for i, row in enumerate(rows):
        lines.append(
            '  '.join(cell.ljust(widths[j]) for j, cell in enumerate(row)).rstrip()
        )
        if i == 0:
            lines.append('  '.join('-' * w for w in widths))
    return '\n'.join(lines)


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.observability.instruments import log_event

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('logs', nargs='+', type=Path, help='worker log files')
    args = parser.parse_args(argv)
    stats = aggregate_logs(args.logs)
    if not stats:
        log_event(
            f'No [timer] lines found in {len(args.logs)} files',
            component='aggregate',
        )
        return 1
    log_event(format_stats_table(stats), component='aggregate')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
