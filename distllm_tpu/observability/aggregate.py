"""Roll up multi-host worker logs into one stats table (and one trace).

Fabric workers on every TPU host emit ``[timer]`` lines (see ``timer.py``)
into their own stdout/log files, and every process can dump its span ring
as JSONL (``observability.dump_traces``, the bench debug bundles'
``traces.jsonl``/``flight.jsonl``). This module merges any number of those
captures — both formats, freely mixed — into a single ``{tags: TimeStats}``
view, the multi-host aggregation the reference could only do by hand, and
renders it as a fixed-width table whose cross-host percentile columns
(count / total / mean / p50 / p95 / p99 / max) match what
``distllm_stage_duration_seconds`` exposes over ``/metrics``.

``--perfetto OUT.json`` additionally merges every input's flight-JSONL and
span-JSONL records into ONE combined Perfetto/Chrome trace with a process
group per input file (``observability.perfetto.merge_host_traces``) — the
multi-host timeline view: open it at https://ui.perfetto.dev and read
cross-host skew straight off the shared clock.

CLI::

    python -m distllm_tpu.observability.aggregate run/logs/*.txt \\
        run/bundles/*/traces.jsonl \\
        run/bundles/*/flight.jsonl --perfetto combined.json
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path


def _merge_span_lines(capture: str, add) -> None:
    """Fold span-JSONL records (``TraceBuffer.dump_jsonl`` format) and
    timed flight-ring records (``FlightRecorder.dump_jsonl``) into the
    aggregation via ``add(tags, elapsed_s, start_ns, end_ns)``. A record
    keys by its ``tags`` tuple (falling back to ``(name,)`` / ``(kind,)``)
    so Timer-shim spans merge with their own ``[timer]`` lines; JSON lines
    without a duration and torn lines are skipped."""
    for line in capture.splitlines():
        line = line.strip()
        if not line.startswith('{'):
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn final line from a killed process
        if not isinstance(record, dict):
            continue
        name = record.get('name') or record.get('kind')
        duration = record.get('duration_s')
        if name is None or duration is None:
            continue
        tags = tuple(record.get('tags') or ()) or (str(name),)
        add(
            tags,
            float(duration),
            int(record.get('start_ns') or 0),
            int(record.get('end_ns') or 0),
        )


def aggregate_lines(captures: list[str]) -> dict[tuple[str, ...], object]:
    """Merge multiple log captures (strings) into one stats dict.

    Each capture may hold ``[timer]`` lines, span-JSONL records, or both.
    ``timer.Timer`` emits BOTH formats for every timed region, so the same
    measurement commonly arrives twice (stdout log + trace dump of the
    same process); measurements with real clock bounds are deduplicated on
    ``(tags, start_ns, end_ns)`` across all captures and formats.
    Zero/absent bounds (hand-written lines, flight records) are exempt —
    distinct measurements there would otherwise collapse.
    """
    # Lazy import: timer.py imports this package at module load.
    from distllm_tpu.timer import TimeLogger, TimeStats

    logger = TimeLogger()
    merged: dict[tuple[str, ...], TimeStats] = {}
    seen: set[tuple] = set()

    def add(tags, elapsed_s, start_ns, end_ns):
        if start_ns and end_ns:
            key = (tags, start_ns, end_ns)
            if key in seen:
                return
            seen.add(key)
        entry = merged.setdefault(tags, TimeStats(tags=tags))
        entry.elapsed_s.append(elapsed_s)
        entry.start_ns.append(start_ns)
        entry.end_ns.append(end_ns)

    for capture in captures:
        for tags, stats in logger.parse_lines(capture).items():
            for elapsed, start, end in zip(
                stats.elapsed_s, stats.start_ns, stats.end_ns
            ):
                add(tags, elapsed, start, end)
        _merge_span_lines(capture, add)
    return merged


def aggregate_logs(paths: list[str | Path]) -> dict[tuple[str, ...], object]:
    """Merge ``[timer]`` lines and span-JSONL dumps from many files."""
    return aggregate_lines([Path(p).read_text() for p in paths])


def format_stats_table(stats: dict[tuple[str, ...], object]) -> str:
    """Fixed-width table, one row per tag set, sorted by total time desc."""
    header = ('tags', 'count', 'total_s', 'mean_s', 'p50_s', 'p95_s',
              'p99_s', 'max_s')
    rows = [header]
    ordered = sorted(
        stats.values(), key=lambda s: s.total_s, reverse=True
    )
    for entry in ordered:
        rows.append(
            (
                ','.join(entry.tags) or '-',
                str(entry.count),
                f'{entry.total_s:.3f}',
                f'{entry.mean_s:.3f}',
                f'{entry.p50_s:.3f}',
                f'{entry.p95_s:.3f}',
                f'{entry.p99_s:.3f}',
                f'{entry.max_s:.3f}',
            )
        )
    widths = [max(len(row[i]) for row in rows) for i in range(len(header))]
    lines = []
    for i, row in enumerate(rows):
        lines.append(
            '  '.join(cell.ljust(widths[j]) for j, cell in enumerate(row)).rstrip()
        )
        if i == 0:
            lines.append('  '.join('-' * w for w in widths))
    return '\n'.join(lines)


def load_host_capture(path: str | Path) -> tuple[list[dict], list[dict]]:
    """Split one JSONL capture into ``(flight_records, span_dicts)``.

    Flight records carry ``kind``; span dumps carry ``name``/``span_id``.
    A file may freely mix both (a concatenated bundle); torn lines and
    non-JSON lines (``[timer]`` text) are skipped.
    """
    flight: list[dict] = []
    spans: list[dict] = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line.startswith('{'):
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn final line from a killed process
        if not isinstance(record, dict):
            continue
        if 'kind' in record:
            flight.append(record)
        elif 'span_id' in record or 'start_ns' in record:
            spans.append(record)
    return flight, spans


def host_label(path: str | Path, seen: 'set[str] | None' = None) -> str:
    """Readable per-process label for one capture file.

    Multi-replica captures conventionally land as
    ``<replica-id>/flight.jsonl`` (the bench) or
    ``capture-<host>.jsonl`` — a bare ``Path(path).name`` collapses the
    former to N identical ``flight.jsonl`` process groups, which is
    exactly the unreadable-merge bug this fixes. Generic stems
    (``flight``, ``spans``, ``capture``, ``trace``) take their parent
    directory as the host/replica id; distinctive stems keep it. A
    label already in ``seen`` gets the stem appended, then an index —
    every input must stay distinguishable in the merged trace.
    """
    p = Path(path)
    stem = p.stem
    generic = stem.lower() in ('flight', 'spans', 'capture', 'trace', 'log')
    label = (
        p.parent.name if generic and p.parent.name not in ('', '.') else stem
    )
    if seen is None:
        return label
    if label in seen and label != stem:
        label = f'{label}/{stem}'
    base, n = label, 2
    while label in seen:
        label = f'{base}#{n}'
        n += 1
    seen.add(label)
    return label


def write_combined_perfetto(
    paths: list[str | Path], out: str | Path
) -> int:
    """Merge every input's flight/span JSONL records into one Perfetto
    trace (a process group per input file, shared time origin); returns
    how many inputs contributed renderable records. Process groups are
    named by :func:`host_label` (host/replica id parsed from the capture
    path), so a 3-replica merge reads ``replica-0 / replica-1 /
    replica-2``, not three ``flight.jsonl``."""
    from distllm_tpu.observability.perfetto import merge_host_traces

    hosts = []
    seen: set[str] = set()
    for path in paths:
        flight, spans = load_host_capture(path)
        if flight or spans:
            hosts.append((host_label(path, seen), flight, spans))
    doc = merge_host_traces(hosts)
    Path(out).write_text(json.dumps(doc))
    return len(hosts)


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.observability.instruments import log_event

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('logs', nargs='+', type=Path, help='worker log files')
    parser.add_argument(
        '--perfetto', type=Path, default=None, metavar='OUT.json',
        help='also merge flight/span JSONL inputs into one combined '
             'Perfetto trace (per-host track groups)',
    )
    args = parser.parse_args(argv)
    stats = aggregate_logs(args.logs)
    if args.perfetto is not None:
        contributed = write_combined_perfetto(args.logs, args.perfetto)
        log_event(
            f'[aggregate] wrote combined Perfetto trace for {contributed} '
            f'host capture(s) to {args.perfetto}',
            component='aggregate',
        )
    if not stats:
        log_event(
            f'No [timer] lines found in {len(args.logs)} files',
            component='aggregate',
        )
        return 1
    log_event(format_stats_table(stats), component='aggregate')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
