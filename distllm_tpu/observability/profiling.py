"""Bounded, error-safe programmatic profiler capture (ISSUE 11 tentpole).

``jax.profiler.start_trace`` / ``stop_trace`` are the ground-truth device
attribution tool (XPlane + TensorBoard format), but raw use has two
serving-path hazards this helper removes:

- **unbounded captures** — a started trace that is never stopped grows
  until the process dies; every capture here auto-stops after
  ``max_seconds`` via a daemon timer;
- **fatal errors** — on backends without profiler support,
  ``start_trace`` raises and previously could kill a whole bench stage.
  Every profiler call here is caught; failures land in
  :meth:`ProfilerCapture.state` (and debug bundles, via ``startup.json``)
  and in ``distllm_profiler_captures_total{outcome}``, never in the
  caller's stack.

One capture may be active at a time (jax's profiler is a process-global
session); concurrent starts are *rejected*, not queued. Consumers:

- ``GET /debug/xprof?seconds=N`` on the chat server — on-demand blocking
  capture of a live serving process, returns the trace directory;
- ``bench.py``'s ``DISTLLM_BENCH_PROFILE`` stage profiling — routed
  through :meth:`start`/:meth:`stop` so an unsupported-backend error
  downgrades to a telemetry note instead of a dead stage;
- debug bundles — the capture state (active/last_error/total) rides
  ``startup.json`` so a bundle says whether a capture was in flight.

Dependency-free at import time; jax is imported lazily inside the calls.
"""

from __future__ import annotations

import math
import threading
import time

from distllm_tpu.observability import instruments as _metrics

# Hard ceiling on any capture: profiler traces of a busy engine grow at
# tens of MB/s, and an operator typo ("seconds=3600") must not fill the
# disk of a serving host.
MAX_CAPTURE_SECONDS = 1800.0


def _clamp_seconds(value, default: float = 60.0) -> float:
    """Clamp into (0.1, MAX_CAPTURE_SECONDS]. NaN/inf would slide through
    ``min``/``max`` unchanged and later crash ``Timer``/``sleep`` — a
    malformed duration must degrade to the default, never raise."""
    try:
        value = float(value)
    except (TypeError, ValueError):
        value = default
    if not math.isfinite(value) or value <= 0:
        value = default
    return min(max(value, 0.1), MAX_CAPTURE_SECONDS)


class ProfilerCapture:
    """At-most-one bounded ``jax.profiler`` trace session."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._active: dict | None = None  # guarded by self._lock
        self._timer: threading.Timer | None = None  # guarded by self._lock
        self._last_error: str | None = None  # guarded by self._lock
        self._captures = 0  # guarded by self._lock

    def start(self, log_dir, max_seconds: float = 60.0) -> bool:
        """Begin a capture into ``log_dir``; returns whether it started.

        ``False`` means rejected (one already active) or the backend's
        profiler failed — both recorded in :meth:`state` and the outcome
        counter, neither raised. A started capture auto-stops after
        ``max_seconds`` (clamped to :data:`MAX_CAPTURE_SECONDS`).
        """
        return self._start(log_dir, max_seconds) is None

    def _start(self, log_dir, max_seconds: float) -> tuple[str, str] | None:
        """``None`` on success, else ``(outcome, message)`` with outcome
        ``'rejected'`` or ``'error'`` — returned to the caller directly
        so classification never round-trips through the shared
        ``_last_error`` slot (a concurrent stop-flush error could
        overwrite it between write and read)."""
        max_seconds = _clamp_seconds(max_seconds)
        with self._lock:
            if self._active is not None:
                message = (
                    f'capture already active in {self._active["log_dir"]}'
                )
                self._last_error = message
                _metrics.PROFILER_CAPTURES.labels(outcome='rejected').inc()
                return 'rejected', message
            # Reserve the slot before the (slow, lock-free) profiler call
            # so two concurrent starts cannot both reach start_trace.
            self._active = {
                'log_dir': str(log_dir),
                'started_wall_s': time.time(),
                'max_seconds': max_seconds,
            }
        try:
            import jax

            jax.profiler.start_trace(str(log_dir))
        except Exception as exc:
            message = repr(exc)[:300]
            with self._lock:
                self._active = None
                self._last_error = message
            _metrics.PROFILER_CAPTURES.labels(outcome='error').inc()
            return 'error', message
        timer = threading.Timer(max_seconds, self.stop)
        timer.daemon = True
        with self._lock:
            self._timer = timer
        timer.start()
        return None

    def stop(self) -> bool:
        """Stop the active capture; returns whether one was stopped.

        Idempotent (the auto-stop timer and an explicit caller may race);
        profiler flush errors are swallowed into :meth:`state`.
        """
        with self._lock:
            if self._active is None:
                return False
            self._active = None
            timer, self._timer = self._timer, None
        if timer is not None:
            timer.cancel()
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:
            with self._lock:
                self._last_error = repr(exc)[:300]
            _metrics.PROFILER_CAPTURES.labels(outcome='error').inc()
            return False
        with self._lock:
            self._captures += 1
        _metrics.PROFILER_CAPTURES.labels(outcome='ok').inc()
        return True

    def capture(self, log_dir, seconds: float) -> dict:
        """Blocking convenience for ``GET /debug/xprof``: start, sleep,
        stop; returns ``{'ok', 'rejected', 'trace_dir', 'error'}``. Runs
        in an executor thread server-side — the event loop never sleeps.
        """
        seconds = _clamp_seconds(seconds, default=1.0)
        # The auto-stop bound is a BACKSTOP against a wedged sleep/stop,
        # not a twin deadline: armed at exactly ``seconds`` it would race
        # the deliberate stop below and turn a clean capture into a
        # spurious failure (observed live on /debug/xprof).
        failure = self._start(log_dir, max_seconds=seconds + 30.0)
        if failure is not None:
            outcome, message = failure
            return {
                'ok': False,
                'rejected': outcome == 'rejected',
                'trace_dir': str(log_dir),
                'error': message,
            }
        time.sleep(seconds)
        ok = self.stop()
        with self._lock:
            error = None if ok else self._last_error
        return {
            'ok': ok,
            'rejected': False,
            'trace_dir': str(log_dir),
            'error': error,
        }

    def state(self) -> dict:
        """Snapshot for bundles/endpoints: the active capture (or None),
        the last profiler error, and the lifetime completed count."""
        with self._lock:
            return {
                'active': dict(self._active) if self._active else None,
                'last_error': self._last_error,
                'captures_total': self._captures,
            }


_default_capture = ProfilerCapture()


def get_profiler_capture() -> ProfilerCapture:
    """The process-wide capture slot (jax's profiler is process-global)."""
    return _default_capture
