"""Engine-to-endpoint metrics & tracing (ISSUE 1 tentpole).

Three layers, all dependency-free:

- :mod:`~distllm_tpu.observability.metrics` — ``Counter`` / ``Gauge`` /
  ``Histogram`` registry with Prometheus text exposition;
- :mod:`~distllm_tpu.observability.tracing` — ``Span`` records + a bounded
  in-memory trace ring dumpable to JSONL (``timer.Timer`` is a shim over
  this: every timer emits both the legacy ``[timer]`` line and a span);
- :mod:`~distllm_tpu.observability.instruments` — the catalog of well-known
  series (engine, KV cache, scheduler, HTTP, fabric workers) plus the
  ``log_event`` stdout funnel;
- :mod:`~distllm_tpu.observability.flight` — the flight-recorder layer
  (ISSUE 3 tentpole): bounded per-engine-step ring, stall watchdog, debug
  bundles, crash-proof ``RunRecord`` + ``Deadline`` for the bench contract;
- :mod:`~distllm_tpu.observability.perfetto` — Perfetto/Chrome trace-event
  export of the flight + span rings and per-request lifecycles (ISSUE 10
  tentpole; ``GET /debug/perfetto``, ``perfetto.json`` in bundles);
- :mod:`~distllm_tpu.observability.roofline` — the analytic FLOPs/bytes
  cost model behind ``distllm_engine_mfu`` and the weight-stream
  bandwidth-utilization gauges;
- :mod:`~distllm_tpu.observability.startup` — startup & compile-phase
  attribution (ISSUE 11 tentpole): the ``compile`` flight kind,
  ``distllm_compile_seconds`` series, and dead-phase state for bundles;
- :mod:`~distllm_tpu.observability.xla_cost` — measured executable cost
  from ``compiled.cost_analysis()`` behind the
  ``distllm_engine_mfu_measured`` gauges and the analytic-vs-measured
  calibration ratios;
- :mod:`~distllm_tpu.observability.profiling` — the bounded
  ``jax.profiler`` capture helper (``GET /debug/xprof``,
  ``DISTLLM_BENCH_PROFILE``);
- :mod:`~distllm_tpu.observability.history` — the bounded metric-history
  ring + background sampler (ISSUE 18 tentpole): retained time series
  over the live registry (``GET /debug/history``, ``history.json`` in
  bundles, the Perfetto ``history`` counter track);
- :mod:`~distllm_tpu.observability.slo` — multi-window multi-burn-rate
  SLO engine over the history (``distllm_slo_burn_rate{window}``,
  ``slo_status()`` ok/warn/page, ``GET /debug/slo``);
- :mod:`~distllm_tpu.observability.baseline` — BENCH-record parsing +
  the baseline envelope, shared with ``scripts/benchdiff.py`` so the
  offline gate and the runtime sentinel can never disagree on parsing;
- :mod:`~distllm_tpu.observability.sentinel` — the runtime regression
  sentinel: live history windows vs the baseline envelope, firing the
  ``regression`` flight kind + ``distllm_sentinel_regressions_total``.

``aggregate`` (imported lazily to avoid a cycle with ``timer``) rolls
multi-host ``[timer]`` logs into one stats table. Metric names and
conventions are documented in ``docs/observability.md``.
"""

from __future__ import annotations

from distllm_tpu.observability.baseline import (
    build_envelope,
    envelope_from_records,
    load_envelope,
)
from distllm_tpu.observability.flight import (
    Deadline,
    FlightRecorder,
    RunRecord,
    StallWatchdog,
    dump_debug_bundle,
    get_flight_recorder,
)
from distllm_tpu.observability.history import (
    HistorySampler,
    MetricsHistory,
    get_metrics_history,
    history_excerpt,
)
from distllm_tpu.observability.instruments import log_event
from distllm_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    log_buckets,
    quantile_from_cumulative,
    render_prometheus,
)
from distllm_tpu.observability.perfetto import (
    merge_host_traces,
    to_trace_events,
    validate_trace_events,
)
from distllm_tpu.observability.profiling import (
    ProfilerCapture,
    get_profiler_capture,
)
from distllm_tpu.observability.roofline import CostModel, device_peaks
from distllm_tpu.observability.sentinel import (
    RegressionSentinel,
    get_regression_sentinel,
    install_regression_sentinel,
)
from distllm_tpu.observability.slo import (
    install_slo_observer,
    slo_status,
    update_burn_gauges,
)
from distllm_tpu.observability.startup import (
    CompileWatcher,
    get_compile_watcher,
    record_backend_init,
)
from distllm_tpu.observability.xla_cost import XlaCost, price_callable
from distllm_tpu.observability.tracing import (
    Span,
    TraceBuffer,
    begin_span,
    current_request_id,
    dump_traces,
    end_span,
    get_trace_buffer,
    request_scope,
    span,
)

__all__ = [
    'CompileWatcher',
    'CostModel',
    'Counter',
    'Deadline',
    'FlightRecorder',
    'Gauge',
    'Histogram',
    'HistorySampler',
    'MetricsHistory',
    'MetricsRegistry',
    'ProfilerCapture',
    'RegressionSentinel',
    'RunRecord',
    'Span',
    'StallWatchdog',
    'TraceBuffer',
    'XlaCost',
    'begin_span',
    'build_envelope',
    'current_request_id',
    'device_peaks',
    'dump_debug_bundle',
    'dump_traces',
    'end_span',
    'envelope_from_records',
    'get_compile_watcher',
    'get_flight_recorder',
    'get_metrics_history',
    'get_profiler_capture',
    'get_registry',
    'get_regression_sentinel',
    'get_trace_buffer',
    'history_excerpt',
    'install_regression_sentinel',
    'install_slo_observer',
    'load_envelope',
    'log_buckets',
    'log_event',
    'merge_host_traces',
    'price_callable',
    'quantile_from_cumulative',
    'record_backend_init',
    'render_prometheus',
    'request_scope',
    'slo_status',
    'span',
    'to_trace_events',
    'update_burn_gauges',
    'validate_trace_events',
]
