"""Engine-to-endpoint metrics & tracing (ISSUE 1 tentpole).

Three layers, all dependency-free:

- :mod:`~distllm_tpu.observability.metrics` — ``Counter`` / ``Gauge`` /
  ``Histogram`` registry with Prometheus text exposition;
- :mod:`~distllm_tpu.observability.tracing` — ``Span`` records + a bounded
  in-memory trace ring dumpable to JSONL (``timer.Timer`` is a shim over
  this: every timer emits both the legacy ``[timer]`` line and a span);
- :mod:`~distllm_tpu.observability.instruments` — the catalog of well-known
  series (engine, KV cache, scheduler, HTTP, fabric workers) plus the
  ``log_event`` stdout funnel;
- :mod:`~distllm_tpu.observability.flight` — the flight-recorder layer
  (ISSUE 3 tentpole): bounded per-engine-step ring, stall watchdog, debug
  bundles, crash-proof ``RunRecord`` + ``Deadline`` for the bench contract.

``aggregate`` (imported lazily to avoid a cycle with ``timer``) rolls
multi-host ``[timer]`` logs into one stats table. Metric names and
conventions are documented in ``docs/observability.md``.
"""

from __future__ import annotations

from distllm_tpu.observability.flight import (
    Deadline,
    FlightRecorder,
    RunRecord,
    StallWatchdog,
    dump_debug_bundle,
    get_flight_recorder,
)
from distllm_tpu.observability.instruments import log_event
from distllm_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    log_buckets,
    render_prometheus,
)
from distllm_tpu.observability.tracing import (
    Span,
    TraceBuffer,
    begin_span,
    dump_traces,
    end_span,
    get_trace_buffer,
    span,
)

__all__ = [
    'Counter',
    'Deadline',
    'FlightRecorder',
    'Gauge',
    'Histogram',
    'MetricsRegistry',
    'RunRecord',
    'Span',
    'StallWatchdog',
    'TraceBuffer',
    'begin_span',
    'dump_debug_bundle',
    'dump_traces',
    'end_span',
    'get_flight_recorder',
    'get_registry',
    'get_trace_buffer',
    'log_buckets',
    'log_event',
    'render_prometheus',
    'span',
]
