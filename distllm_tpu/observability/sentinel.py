"""Runtime regression sentinel: live history vs the BENCH baseline.

The offline trajectory gate (``scripts/benchdiff.py``) only speaks
after a round completes; this module connects that baseline to runtime.
A :class:`RegressionSentinel` loads a **baseline envelope** — the
distilled tok/s, TTFT/TPOT quantile, and measured-roofline numbers of
the newest BENCH record, written by ``benchdiff.py --emit-baseline``
through the SAME extraction code (``observability/baseline.py``), so
gate and sentinel can never disagree on parsing — and, on every history
tick, compares each envelope metric against the live trailing window.

A live window that degrades past ``threshold`` (default 20% — looser
than the offline gate's 5% because live windows are noisy) fires ONE
``regression`` flight record and one
``distllm_sentinel_regressions_total{metric}`` count, then latches
until the metric recovers (no once-per-tick alarm storms). Windows
with no traffic never fire — a quantile over zero observations is
``None``, not a division.

Degraded modes are counted, never raised: a missing/unreadable envelope
disarms the sentinel (``distllm_sentinel_armed`` 0,
``distllm_sentinel_disarmed_total{reason}``) and serving proceeds.
"""

from __future__ import annotations

import threading
import time

from distllm_tpu.observability import instruments as _metrics
from distllm_tpu.observability.baseline import load_envelope
from distllm_tpu.observability.flight import get_flight_recorder
from distllm_tpu.observability.history import MetricsHistory

SENTINEL_SCHEMA = 'distllm-sentinel/v1'

#: Default comparison window and degradation threshold.
DEFAULT_WINDOW_S = 30.0
DEFAULT_THRESHOLD = 0.20


def _live_tok_s(history: MetricsHistory, window_s: float, now):
    win = history.counter_window(
        'distllm_engine_generated_tokens_total', window_s, now=now
    )
    if not win['delta']:
        # Idle window: zero tokens because nothing was asked for is not a
        # throughput regression (a wedge WITH queued work is the stall
        # watchdog's jurisdiction, not the sentinel's).
        return None
    return win['rate']


def _live_ttft_p95(history: MetricsHistory, window_s: float, now):
    return history.window_quantile(
        'distllm_request_ttft_seconds', 0.95, window_s, now=now
    )


def _live_tpot_p95(history: MetricsHistory, window_s: float, now):
    return history.window_quantile(
        'distllm_request_tpot_seconds', 0.95, window_s, now=now
    )


def _live_mfu(history: MetricsHistory, window_s: float, now):
    return history.gauge_window(
        'distllm_engine_mfu_measured', window_s,
        labels={'kind': 'decode'}, agg='max', now=now,
    )


def _live_bw_util(history: MetricsHistory, window_s: float, now):
    return history.gauge_window(
        'distllm_engine_bandwidth_utilization_measured', window_s,
        labels={'kind': 'decode'}, agg='max', now=now,
    )


# Live extractor per envelope metric. Keys mirror
# instruments.SENTINEL_METRIC_LABELS (the counter's pre-registered label
# set); an envelope metric with no extractor here is ignored. The
# measured-roofline gauges compare their window MAX (the best dispatch
# the window saw) so co-scheduled slow kinds don't read as kernel decay.
LIVE_EXTRACTORS = {
    'tok_s': _live_tok_s,
    'ttft_p95_s': _live_ttft_p95,
    'tpot_p95_s': _live_tpot_p95,
    'mfu_measured': _live_mfu,
    'bw_util_measured': _live_bw_util,
}
if set(LIVE_EXTRACTORS) != set(_metrics.SENTINEL_METRIC_LABELS):
    raise RuntimeError(
        'sentinel extractors out of sync with SENTINEL_METRIC_LABELS'
    )


class RegressionSentinel:
    """Latched live-window comparisons against a baseline envelope.

    Construct with an envelope dict (``baseline.load_envelope`` /
    ``build_envelope`` output) or arm later; :meth:`evaluate` runs one
    comparison pass and returns the regressions that fired *this call*;
    :meth:`install` attaches it to a history's observer list so the
    sampler drives it.
    """

    def __init__(
        self,
        history: MetricsHistory,
        *,
        envelope: dict | None = None,
        threshold: float = DEFAULT_THRESHOLD,
        window_s: float = DEFAULT_WINDOW_S,
        recorder=None,
    ) -> None:
        if threshold <= 0:
            raise ValueError('threshold must be > 0')
        if window_s <= 0:
            raise ValueError('window_s must be > 0')
        self.history = history
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self._recorder = recorder
        self._lock = threading.Lock()
        self._metrics: dict[str, dict] = {}  # guarded by self._lock
        self._degraded: set[str] = set()  # guarded by self._lock (episode latch)
        self._source = ''  # guarded by self._lock
        self._fired_total = 0  # guarded by self._lock
        if envelope is not None:
            self.arm(envelope)
        else:
            _metrics.SENTINEL_ARMED.set(0.0)  # not yet armed; not a counted disarm

    # ------------------------------------------------------------- arming
    def arm(self, envelope: dict | None) -> bool:
        """Install an envelope; returns armed state. An empty or invalid
        envelope degrades to a counted disarm, never a raise."""
        metrics = (envelope or {}).get('metrics') or {}
        usable = {
            name: entry
            for name, entry in metrics.items()
            if name in LIVE_EXTRACTORS
        }
        if not usable:
            reason = 'empty' if envelope else 'no_baseline'
            self.disarm(reason)
            return False
        with self._lock:
            self._metrics = usable
            self._degraded = set()
            self._source = str((envelope or {}).get('source', ''))
        _metrics.SENTINEL_ARMED.set(1.0)
        return True

    def arm_from_file(self, path) -> bool:
        """``load_envelope`` + :meth:`arm`; missing/unreadable counts as
        ``no_baseline`` and the sentinel stays disarmed."""
        envelope = load_envelope(path)
        if envelope is None:
            self.disarm('no_baseline')
            return False
        return self.arm(envelope)

    def disarm(self, reason: str) -> None:
        with self._lock:
            self._metrics = {}
            self._degraded = set()
        _metrics.SENTINEL_ARMED.set(0.0)
        _metrics.SENTINEL_DISARMED.labels(reason=reason).inc()

    @property
    def armed(self) -> bool:
        with self._lock:
            return bool(self._metrics)

    # ---------------------------------------------------------- evaluation
    def evaluate(self, now: float | None = None) -> list[dict]:
        """One comparison pass. Returns the regression events that fired
        on THIS call (newly entered degradation episodes); recovered
        metrics unlatch silently."""
        now = time.time() if now is None else float(now)
        with self._lock:
            baseline_metrics = dict(self._metrics)
        fired: list[dict] = []
        for name, entry in sorted(baseline_metrics.items()):
            baseline = entry['value']
            direction = entry.get('direction') or 'higher'
            if baseline <= 0:
                continue  # no meaningful relative comparison
            live = LIVE_EXTRACTORS[name](self.history, self.window_s, now)
            if live is None:
                continue  # no traffic in the window: never a false fire
            if direction == 'higher':
                degraded = live < baseline * (1.0 - self.threshold)
            else:
                degraded = live > baseline * (1.0 + self.threshold)
            with self._lock:
                newly = degraded and name not in self._degraded
                if degraded:
                    self._degraded.add(name)
                else:
                    self._degraded.discard(name)
                if newly:
                    self._fired_total += 1
            if newly:
                event = {
                    'metric': name,
                    'baseline': baseline,
                    'live': live,
                    'direction': direction,
                    'threshold': self.threshold,
                    'window_s': self.window_s,
                    'baseline_key': entry.get('from_key', ''),
                }
                _metrics.SENTINEL_REGRESSIONS.labels(metric=name).inc()
                recorder = (
                    self._recorder
                    if self._recorder is not None
                    else get_flight_recorder()
                )
                recorder.record('regression', **event)
                _metrics.log_event(
                    f'[sentinel] {name} degraded past '
                    f'{self.threshold:.0%}: baseline {baseline:.4g} -> '
                    f'live {live:.4g} over {self.window_s:.0f}s',
                    component='sentinel',
                )
                fired.append(event)
        return fired

    def install(self) -> 'RegressionSentinel':
        """Attach to the history's observer list (sampler-driven)."""
        self.history.add_observer(self._observe)
        return self

    def uninstall(self) -> None:
        self.history.remove_observer(self._observe)

    def _observe(self, history: MetricsHistory, now: float) -> None:
        self.evaluate(now)

    # -------------------------------------------------------------- status
    def status(self, now: float | None = None) -> dict:
        """Bundle/debug document: armed state, envelope, live values,
        and which metrics are currently latched degraded."""
        now = time.time() if now is None else float(now)
        with self._lock:
            baseline_metrics = dict(self._metrics)
            degraded = sorted(self._degraded)
            source = self._source
            fired_total = self._fired_total
        live = {
            name: LIVE_EXTRACTORS[name](self.history, self.window_s, now)
            for name in sorted(baseline_metrics)
        }
        return {
            'schema': SENTINEL_SCHEMA,
            'armed': bool(baseline_metrics),
            'source': source,
            'threshold': self.threshold,
            'window_s': self.window_s,
            'baseline': baseline_metrics,
            'live': live,
            'degraded': degraded,
            'fired_total': fired_total,
        }


_default_sentinel: RegressionSentinel | None = None
_default_sentinel_lock = threading.Lock()


def get_regression_sentinel() -> RegressionSentinel | None:
    """The process-wide sentinel, if one was installed (chat_server arms
    it from DISTLLM_BASELINE; None until then)."""
    return _default_sentinel


def install_regression_sentinel(
    history: MetricsHistory,
    *,
    baseline_path=None,
    envelope: dict | None = None,
    threshold: float = DEFAULT_THRESHOLD,
    window_s: float = DEFAULT_WINDOW_S,
) -> RegressionSentinel:
    """Create + install the process-wide sentinel (replacing any prior
    one). Arms from ``envelope`` if given, else ``baseline_path`` (a
    missing file is the counted disarmed mode)."""
    global _default_sentinel
    sentinel = RegressionSentinel(
        history, envelope=envelope, threshold=threshold, window_s=window_s
    )
    if envelope is None and baseline_path is not None:
        sentinel.arm_from_file(baseline_path)
    sentinel.install()
    with _default_sentinel_lock:
        previous = _default_sentinel
        _default_sentinel = sentinel
    if previous is not None:
        previous.uninstall()
    return sentinel
