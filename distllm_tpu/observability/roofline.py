"""Analytic roofline accounting for the serving engine (ISSUE 10 tentpole).

Turns per-window wall times into *utilization* — the number that tells an
operator whether a slow serving loop is leaving silicon on the table or is
already at the hardware's edge. Two rooflines matter here, matching the
engine's measured regimes (docs/serving.md):

- **compute (MFU)** — achieved matmul FLOP/s over the chip's bf16 peak.
  Prefill lives on this roof: one weight pass amortized over the whole
  padded batch.
- **weight-stream bandwidth** — bytes of weights read from HBM per second
  over the chip's HBM peak. Decode lives on this roof: every scan step of
  a fused window re-reads the entire weight set to emit one token per row,
  so a decode window's byte cost is ``decode_steps x weight_bytes``
  regardless of batch — the exact reason the mixed/speculative windows
  exist (ride or skip weight passes).

The model is deliberately a *weight-stream* roofline: attention KV traffic
and activation bytes are omitted (at serving batches on this family they
are second-order next to 13.5 GiB of weights per pass, and omitting them
makes the bandwidth-utilization gauge a conservative lower bound). FLOPs
use the classic ``2 * n_params`` per scored token (matmuls only).

Costs come from the engine's *actual* parameter tree — ``sum(leaf.size)``
and ``sum(leaf.nbytes)`` over ``jax.tree.leaves`` — so quantized codes,
migrated layouts, and MoE trees are all priced as the bytes that really
stream, with no per-architecture formula to drift.

Peaks come from a device-kind table (TPU generations), overridable with
``DISTLLM_PEAK_FLOPS`` / ``DISTLLM_PEAK_BW_BYTES`` for new silicon. On
non-TPU backends (the CPU smoke tier) order-of-magnitude placeholder peaks
keep the gauges populated — the *absolute* CPU numbers are meaningless,
but the per-kind ratios and the plumbing they exercise are exactly what
the smoke tests pin.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

# device_kind prefix -> (bf16 peak FLOP/s, HBM bandwidth bytes/s).
# Matched case-insensitively by prefix, longest prefix wins.
DEVICE_PEAKS: dict[str, tuple[float, float]] = {
    'TPU v4': (275e12, 1.2288e12),
    'TPU v5 lite': (197e12, 8.19e11),
    'TPU v5e': (197e12, 8.19e11),
    'TPU v5p': (459e12, 2.765e12),
    'TPU v5': (459e12, 2.765e12),
    'TPU v6 lite': (918e12, 1.64e12),
    'TPU v6e': (918e12, 1.64e12),
}

# Order-of-magnitude placeholders for backends not in the table (CPU smoke
# runs): a few-core server class machine. Documented as placeholders —
# utilization numbers on such backends exercise the plumbing, not the
# silicon.
FALLBACK_PEAKS = (1e12, 1e11)


def device_peaks(device) -> tuple[float, float]:
    """``(peak_flops, peak_hbm_bytes_per_s)`` for a jax device.

    Env overrides ``DISTLLM_PEAK_FLOPS`` / ``DISTLLM_PEAK_BW_BYTES`` win
    over the table (new silicon, calibrated numbers); unknown kinds fall
    back to :data:`FALLBACK_PEAKS`.
    """
    kind = (getattr(device, 'device_kind', '') or '').lower()
    flops = bw = None
    best = -1
    for name, (f, b) in DEVICE_PEAKS.items():
        if kind.startswith(name.lower()) and len(name) > best:
            best, flops, bw = len(name), f, b
    if flops is None:
        flops, bw = FALLBACK_PEAKS
    env_flops = os.environ.get('DISTLLM_PEAK_FLOPS')
    env_bw = os.environ.get('DISTLLM_PEAK_BW_BYTES')
    if env_flops:
        flops = float(env_flops)
    if env_bw:
        bw = float(env_bw)
    return flops, bw


@dataclass(frozen=True)
class StepCost:
    """Analytic cost of one engine step: matmul FLOPs + HBM weight bytes."""

    flops: float
    hbm_bytes: float


class CostModel:
    """Per-window-kind FLOPs/bytes model for one engine's weight set.

    Built once per engine from the live parameter tree; ``step_cost``
    prices each flight-recorded step kind from the fields the engine
    already records (tokens, batch, draft counts). The engine divides by
    the window's wall time and the device peaks to publish
    ``distllm_engine_mfu{kind}`` and
    ``distllm_engine_bandwidth_utilization{kind}``.
    """

    def __init__(
        self,
        n_params: float,
        weight_bytes: float,
        decode_steps: int,
        peak_flops: float,
        peak_hbm_bytes: float,
    ) -> None:
        if n_params <= 0 or weight_bytes <= 0:
            raise ValueError('cost model needs a non-empty parameter tree')
        self.n_params = float(n_params)
        self.weight_bytes = float(weight_bytes)
        self.decode_steps = max(1, int(decode_steps))
        self.peak_flops = float(peak_flops)
        self.peak_hbm_bytes = float(peak_hbm_bytes)

    @classmethod
    def from_params(
        cls, params, decode_steps: int, device=None, num_devices: int = 1
    ) -> 'CostModel':
        """Price the ACTUAL weight set: quantized codes, scales, migrated
        layouts — whatever is in the tree is what streams from HBM.

        ``num_devices`` is the number of chips the params are sharded
        over (the engine passes the TP mesh size): leaf ``size``/
        ``nbytes`` report GLOBAL extents, so the aggregate peaks must
        scale with the mesh or every healthy multi-chip deployment would
        read ``num_devices``x too high.
        """
        import jax

        leaves = jax.tree.leaves(params)
        n_params = sum(getattr(x, 'size', 0) for x in leaves)
        weight_bytes = sum(getattr(x, 'nbytes', 0) for x in leaves)
        if device is None:
            device = jax.devices()[0]
        peak_flops, peak_bw = device_peaks(device)
        scale = max(1, int(num_devices))
        return cls(n_params, weight_bytes, decode_steps,
                   peak_flops * scale, peak_bw * scale)

    def step_cost(
        self,
        kind: str,
        *,
        tokens: int = 0,
        batch: int = 0,
        draft_tokens: int = 0,
        prefill_tokens: int = 0,
    ) -> StepCost | None:
        """Cost of one recorded step, or ``None`` for kinds with no
        dispatch behind them (``request``/``preempt``/``event``).

        - ``prefill``: one weight pass scoring ``tokens`` positions.
        - ``decode``/``mixed``: ``decode_steps`` weight passes (the fused
          scan re-reads the weights every step, frozen slots included);
          FLOPs cover generated tokens plus any ridden chunk positions.
        - ``spec``: ONE weight pass scoring every row's span —
          ``batch + draft_tokens`` positions (plus ridden chunks) — the
          whole speculative trade made visible: decode-scan bytes down by
          ``decode_steps``x, FLOPs up by the span width.
        """
        two_np = 2.0 * self.n_params
        if kind == 'prefill':
            return StepCost(two_np * tokens, self.weight_bytes)
        if kind in ('decode', 'mixed'):
            return StepCost(
                two_np * (tokens + prefill_tokens),
                self.weight_bytes * self.decode_steps,
            )
        if kind == 'spec':
            positions = batch + draft_tokens + prefill_tokens
            return StepCost(two_np * positions, self.weight_bytes)
        return None

    def utilization(
        self, cost: StepCost, duration_s: float
    ) -> tuple[float, float]:
        """``(mfu, bandwidth_utilization)`` for a step that took
        ``duration_s`` — uncapped ratios (a >1.0 reading means the model
        or the peak table is wrong for this chip; clamping would hide
        that)."""
        if duration_s <= 0:
            return 0.0, 0.0
        return (
            cost.flops / duration_s / self.peak_flops,
            cost.hbm_bytes / duration_s / self.peak_hbm_bytes,
        )
