"""Measured executable cost from XLA's own analysis (ISSUE 11 tentpole).

The analytic roofline (``observability/roofline.py``) prices engine steps
from the parameter tree — ``2 x n_params`` FLOPs per scored position,
weight bytes per pass. That is a *model*, and ROADMAP item 1 (the Pallas
ragged-attention kernel) needs *measured* device cost truth before it can
claim a win over it: a kernel that cuts real HBM traffic moves
``cost_analysis()`` bytes, not the hand math. This module prices each
compiled serving executable via ``compiled.cost_analysis()`` — previously
used only by the offline ``scripts/probe_decode_hlo.py`` census — and
publishes the measured twins of the analytic gauges:

- ``distllm_engine_mfu_measured{kind}`` /
  ``distllm_engine_bandwidth_utilization_measured{kind}`` — per-dispatch
  utilization from what XLA compiled, beside the analytic gauges;
- ``distllm_engine_roofline_flops_ratio{kind}`` /
  ``distllm_engine_roofline_bytes_ratio{kind}`` — measured / analytic
  per dispatch, so calibration drift is a visible number instead of a
  probe-script investigation. FLOPs near 1.0 = calibrated; bytes > 1.0
  is expected (KV + activation traffic the weight-stream model omits),
  and a jump means the compiled graph carries traffic the model cannot
  see (layout churn, materialized slices — the r03 845 ms window).

Pricing happens once per executable at warmup (``LLMEngine.warmup``);
the per-dispatch gauges then cost two multiplies. AOT-compiled
executables (the TPU auto-layout decode window) are priced for free;
``jax.jit`` wrappers are priced by ``lower().compile()``, which the
engine only does when the compile is cheap or cached (non-TPU backends,
or a persistent compilation cache is configured) — never a second
multi-minute unrolled compile on a cold TPU.

Only the jax imports are lazy; the module itself is dependency-free.
"""

from __future__ import annotations

from dataclasses import dataclass

from distllm_tpu.observability import instruments as _metrics


@dataclass(frozen=True)
class XlaCost:
    """Per-invocation cost of one compiled executable, as XLA measured
    it: total FLOPs and total HBM bytes accessed (inputs + outputs +
    temporaries). ``source`` records how it was obtained (``aot`` = a
    pre-compiled executable, ``lowered`` = jit wrapper re-lowered)."""

    flops: float
    bytes_accessed: float
    source: str

    def to_dict(self) -> dict:
        return {
            'flops': self.flops,
            'bytes_accessed': self.bytes_accessed,
            'source': self.source,
        }


def normalize_cost_analysis(raw) -> dict:
    """``cost_analysis()`` returns a dict on recent jax and ``[dict]`` on
    older versions (scripts/probe_decode_hlo.py handles the same split);
    collapse both to one dict, ``{}`` when absent."""
    if isinstance(raw, list):
        raw = raw[0] if raw else {}
    return raw if isinstance(raw, dict) else {}


def executable_cost(compiled, source: str = 'aot') -> XlaCost | None:
    """Price a compiled executable; ``None`` when the backend reports no
    FLOPs (cost analysis unsupported)."""
    try:
        cost = normalize_cost_analysis(compiled.cost_analysis())
    except Exception:
        return None
    flops = cost.get('flops')
    if not isinstance(flops, (int, float)) or flops <= 0:
        return None
    bytes_accessed = cost.get('bytes accessed')
    if not isinstance(bytes_accessed, (int, float)) or bytes_accessed < 0:
        bytes_accessed = 0.0
    return XlaCost(float(flops), float(bytes_accessed), source)


def price_callable(fn, *args) -> XlaCost | None:
    """Price whatever will actually run: an AOT-compiled executable
    directly, or a ``jax.jit`` wrapper via ``lower(*args).compile()``
    (identical HLO to the wrapper's own compile, so a configured
    persistent compilation cache makes it a disk hit). Returns ``None``
    on any failure — pricing is telemetry, never load-bearing."""
    if hasattr(fn, 'cost_analysis'):
        return executable_cost(fn, source='aot')
    try:
        compiled = fn.lower(*args).compile()
    except Exception:
        return None
    return executable_cost(compiled, source='lowered')


def publish_measured(
    kind: str,
    cost: XlaCost,
    duration_s: float,
    peak_flops: float,
    peak_hbm_bytes: float,
) -> tuple[float, float]:
    """Set the measured utilization gauges for one dispatch; returns
    ``(mfu, bw_util)`` (uncapped, mirroring the analytic gauges: a >1.0
    reading indicts the peak table, and clamping would hide that)."""
    if duration_s <= 0 or peak_flops <= 0 or peak_hbm_bytes <= 0:
        return 0.0, 0.0
    mfu = cost.flops / duration_s / peak_flops
    bw_util = cost.bytes_accessed / duration_s / peak_hbm_bytes
    _metrics.ENGINE_MFU_MEASURED.labels(kind=kind).set(mfu)
    _metrics.ENGINE_BW_UTIL_MEASURED.labels(kind=kind).set(bw_util)
    return mfu, bw_util


def record_calibration(
    kind: str, analytic_flops: float, analytic_bytes: float, cost: XlaCost
) -> tuple[float | None, float | None]:
    """Set the measured/analytic ratio gauges for one dispatch; returns
    ``(flops_ratio, bytes_ratio)`` (``None`` where the analytic side is
    zero — nothing to calibrate against)."""
    flops_ratio = bytes_ratio = None
    if analytic_flops > 0:
        flops_ratio = cost.flops / analytic_flops
        _metrics.ENGINE_ROOFLINE_FLOPS_RATIO.labels(kind=kind).set(
            flops_ratio
        )
    if analytic_bytes > 0 and cost.bytes_accessed > 0:
        bytes_ratio = cost.bytes_accessed / analytic_bytes
        _metrics.ENGINE_ROOFLINE_BYTES_RATIO.labels(kind=kind).set(
            bytes_ratio
        )
    return flops_ratio, bytes_ratio
