"""Bounded metric history: retained time series over the live registry.

The registry (``metrics.py``) is instantaneous — a scrape says what the
counters read *now*, nothing about five minutes ago — and every other
telemetry layer is offline (benchdiff gates after the run, Perfetto is
post-mortem). This module is the retention layer in between (ISSUE 18
tentpole): a dependency-free, bounded, thread-safe time-series ring that
periodically folds a full ``MetricsRegistry.collect()`` snapshot into
per-series point deques, so a serving process can answer "is this
replica getting slower right now" from its own memory.

Per-kind storage:

- **counters** — per-tick deltas with the covering interval, so any
  trailing window reads back as an exact rate
  (``counter_window('distllm_engine_generated_tokens_total', 60)``);
- **gauges** — sampled values (mean/last/min/max over a window);
- **histograms** — per-tick *delta* cumulative-bucket vectors; window
  quantiles sum the vectors and run the existing
  :func:`~distllm_tpu.observability.metrics.quantile_from_cumulative`
  delta estimator, so a ``window_quantile(..., 0.95, 60)`` covers only
  the observations of the last minute.

:class:`HistorySampler` is the background thread (the StallWatchdog
daemon pattern: Event-driven loop, ``start()``/``stop()`` with a joined
shutdown, context manager). Overhead is bounded and measured: every
tick is counted in ``distllm_history_samples_total`` and timed into
``distllm_history_sample_duration_seconds``; ``tests/test_history.py``
asserts a full-catalog tick stays under 50 ms (typically well under
5 ms), so the default 1 s interval costs well under 1% of one core.

Observers (the SLO burn-rate engine and the regression sentinel)
register via :meth:`MetricsHistory.add_observer` and run after each
tick, outside the ring lock; an observer that raises is counted
(``distllm_history_sample_errors_total``) and never kills the sampler.

Snapshot JSON schema (``GET /debug/history``, ``history.json`` in debug
bundles) — ``distllm-history/v1``::

    {"schema": "distllm-history/v1", "capacity": 512, "samples": N,
     "interval_hint_s": 1.0, "quantiles": [0.5, 0.95, 0.99],
     "series": {
       "<name>": {"kind": "counter", "points": [[t, delta, rate], ...]},
       "<name>{label=value}": {"kind": "gauge", "points": [[t, value], ...]},
       "<name>": {"kind": "histogram",
                  "points": [[t, count_delta, rate, p50, p95, p99], ...]}}}

Series keys are ``name`` or ``name{label=value,...}`` with label pairs
sorted by label name; histogram quantile columns follow the
``quantiles`` list and are ``null`` for ticks with no observations
(the delta estimator returns ``None`` on an empty interval — never a
divide-by-zero).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from distllm_tpu.observability import instruments as _metrics
from distllm_tpu.observability.metrics import (
    MetricsRegistry,
    get_registry,
    quantile_from_cumulative,
)

HISTORY_SCHEMA = 'distllm-history/v1'
DEFAULT_QUANTILES = (0.5, 0.95, 0.99)
SAMPLER_THREAD_NAME = 'distllm-history-sampler'


def series_key(name: str, labels: dict | None = None) -> str:
    """Canonical history key for one child series: ``name`` or
    ``name{label=value,...}`` with pairs sorted by label name."""
    if not labels:
        return name
    inner = ','.join(f'{k}={labels[k]}' for k in sorted(labels))
    return f'{name}{{{inner}}}'


class MetricsHistory:
    """Bounded per-series rings over periodic registry snapshots.

    ``capacity`` bounds every series deque (oldest points evicted
    first); at the default 1 s interval the default 512 points retain
    ~8.5 minutes — enough to cover the longest default burn-rate window
    pair's short side and every sentinel window. All reads and writes
    are guarded by one lock; observer callbacks run outside it so they
    can call the window helpers without deadlocking.
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        *,
        capacity: int = 512,
        quantiles: tuple[float, ...] = DEFAULT_QUANTILES,
    ) -> None:
        if capacity < 2:
            raise ValueError('capacity must be >= 2')
        self._registry = registry if registry is not None else get_registry()
        self.capacity = int(capacity)
        self.quantiles = tuple(quantiles)
        self._lock = threading.Lock()
        self._series: dict[str, dict] = {}  # guarded by self._lock
        self._prev: dict[str, tuple] = {}  # guarded by self._lock (t, payload per series)
        self._samples = 0  # guarded by self._lock
        self._observers: list = []  # guarded by self._lock
        self.interval_hint_s: float | None = None  # advisory, set by the sampler

    # ------------------------------------------------------------ sampling
    def add_observer(self, fn) -> None:
        """Register ``fn(history, now)`` to run after every tick (outside
        the ring lock; exceptions are counted and swallowed)."""
        with self._lock:
            self._observers.append(fn)

    def remove_observer(self, fn) -> None:
        with self._lock:
            if fn in self._observers:
                self._observers.remove(fn)

    def sample_once(self, now: float | None = None) -> None:
        """Fold one full registry snapshot into the rings and run the
        observers. Safe from any thread; one tick per call."""
        t_start = time.monotonic()
        now = time.time() if now is None else float(now)
        families = self._registry.collect()
        with self._lock:
            for family in families:
                name = family['name']
                kind = family['kind']
                labelnames = family['labelnames']
                for child in family['children']:
                    labels = dict(zip(labelnames, child['labels']))
                    key = series_key(name, labels)
                    prev = self._prev.get(key)
                    if kind == 'counter':
                        value = child['value']
                        self._prev[key] = (now, value)
                        if prev is None:
                            continue  # first sighting: no interval yet
                        dt = now - prev[0]
                        if dt <= 0:
                            continue
                        delta = max(0.0, value - prev[1])
                        self._ring(key, 'counter').append((now, dt, delta))
                    elif kind == 'gauge':
                        self._ring(key, 'gauge').append((now, child['value']))
                    else:  # histogram
                        cumulative = list(child['cumulative'])
                        self._prev[key] = (now, cumulative)
                        if prev is None:
                            self._series.setdefault(key, {
                                'kind': 'histogram',
                                'buckets': tuple(child['buckets']),
                                'points': deque(maxlen=self.capacity),
                            })
                            continue
                        dt = now - prev[0]
                        if dt <= 0:
                            continue
                        delta_cum = [
                            max(0, a - b)
                            for a, b in zip(cumulative, prev[1])
                        ]
                        entry = self._series.setdefault(key, {
                            'kind': 'histogram',
                            'buckets': tuple(child['buckets']),
                            'points': deque(maxlen=self.capacity),
                        })
                        entry['points'].append(
                            (now, dt, delta_cum[-1], delta_cum)
                        )
            self._samples += 1
            observers = list(self._observers)
        for fn in observers:
            try:
                fn(self, now)
            except Exception:
                _metrics.HISTORY_SAMPLE_ERRORS.inc()
        _metrics.HISTORY_SAMPLES.inc()
        _metrics.HISTORY_SAMPLE_SECONDS.observe(time.monotonic() - t_start)

    def _ring(self, key: str, kind: str) -> deque:
        # distlint: disable=lock-discipline -- internal helper only reached from sample_once's locked section (callers hold self._lock)
        entry = self._series.setdefault(
            key, {'kind': kind, 'points': deque(maxlen=self.capacity)}
        )
        return entry['points']

    # ------------------------------------------------------------- queries
    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    def _points(
        self, name, labels, since, until
    ) -> tuple[str, list, dict] | None:
        key = series_key(name, labels)
        with self._lock:
            entry = self._series.get(key)
            if entry is None:
                return None
            points = [p for p in entry['points'] if since <= p[0] <= until]
            return key, points, entry

    def counter_window(
        self,
        name: str,
        window_s: float,
        *,
        labels: dict | None = None,
        now: float | None = None,
    ) -> dict:
        """Exact trailing-window counter aggregate:
        ``{'delta', 'rate', 'covered_s', 'points'}`` (``rate`` is None
        when the window holds no covered interval)."""
        now = time.time() if now is None else float(now)
        found = self._points(name, labels, now - window_s, now)
        pts = found[1] if found else []
        delta = sum(p[2] for p in pts)
        covered = sum(p[1] for p in pts)
        return {
            'delta': delta,
            'rate': (delta / covered) if covered > 0 else None,
            'covered_s': covered,
            'points': len(pts),
        }

    def counter_rate(
        self,
        name: str,
        window_s: float,
        *,
        labels: dict | None = None,
        now: float | None = None,
    ) -> float | None:
        return self.counter_window(
            name, window_s, labels=labels, now=now
        )['rate']

    def gauge_window(
        self,
        name: str,
        window_s: float,
        *,
        labels: dict | None = None,
        agg: str = 'mean',
        now: float | None = None,
    ) -> float | None:
        """Trailing-window gauge aggregate (``mean``/``last``/``min``/
        ``max``); None when the window holds no samples."""
        now = time.time() if now is None else float(now)
        found = self._points(name, labels, now - window_s, now)
        pts = found[1] if found else []
        if not pts:
            return None
        values = [p[1] for p in pts]
        if agg == 'mean':
            return sum(values) / len(values)
        if agg == 'last':
            return values[-1]
        if agg == 'min':
            return min(values)
        if agg == 'max':
            return max(values)
        raise ValueError(f'unknown agg {agg!r}')

    def window_quantile(
        self,
        name: str,
        q: float,
        window_s: float,
        *,
        labels: dict | None = None,
        now: float | None = None,
    ) -> float | None:
        """Quantile over ONLY the observations of the trailing window:
        sums the per-tick delta cumulative vectors and runs the shared
        delta estimator. None on an empty window (never a division)."""
        now = time.time() if now is None else float(now)
        found = self._points(name, labels, now - window_s, now)
        if found is None:
            return None
        _, pts, entry = found
        buckets = entry.get('buckets')
        if not pts or not buckets:
            return None
        summed = [0] * len(pts[0][3])
        for p in pts:
            for i, c in enumerate(p[3]):
                summed[i] += c
        return quantile_from_cumulative(buckets, summed, q)

    # ------------------------------------------------------------ snapshot
    def snapshot(
        self, *, limit: int | None = None, prefix: str | None = None
    ) -> dict:
        """The stable ``distllm-history/v1`` JSON document (see module
        docstring). ``limit`` trims each series to its newest N points;
        ``prefix`` filters series keys (``/debug/history?prefix=``)."""
        with self._lock:
            series_items = [
                (key, entry['kind'], list(entry['points']),
                 entry.get('buckets'))
                for key, entry in sorted(self._series.items())
                if prefix is None or key.startswith(prefix)
            ]
            samples = self._samples
        out_series: dict[str, dict] = {}
        for key, kind, points, buckets in series_items:
            if limit is not None:
                points = points[-limit:]
            if kind == 'counter':
                rendered = [
                    [p[0], p[2], (p[2] / p[1]) if p[1] > 0 else 0.0]
                    for p in points
                ]
            elif kind == 'gauge':
                rendered = [[p[0], p[1]] for p in points]
            else:
                rendered = []
                for p in points:
                    row = [p[0], p[2], (p[2] / p[1]) if p[1] > 0 else 0.0]
                    for q in self.quantiles:
                        row.append(
                            quantile_from_cumulative(buckets, p[3], q)
                        )
                    rendered.append(row)
            out_series[key] = {'kind': kind, 'points': rendered}
        return {
            'schema': HISTORY_SCHEMA,
            'capacity': self.capacity,
            'samples': samples,
            'interval_hint_s': self.interval_hint_s,
            'quantiles': list(self.quantiles),
            'series': out_series,
        }

    def clear(self) -> None:
        """Drop all retained points and delta state (tests)."""
        with self._lock:
            self._series.clear()
            self._prev.clear()
            self._samples = 0


def history_excerpt(
    history: MetricsHistory,
    *,
    window_s: float = 60.0,
    max_points: int = 30,
    now: float | None = None,
) -> dict:
    """Compact excerpt for LoadReport fragments (``scripts/loadgen.py``):
    the tok/s series tail, the trailing-window token rate, and the
    current burn-rate gauges — a time-resolved record where the report
    would otherwise carry only end-of-run aggregates."""
    now = time.time() if now is None else float(now)
    tok = history.counter_window(
        'distllm_engine_generated_tokens_total', window_s, now=now
    )
    snap = history.snapshot(
        limit=max_points, prefix='distllm_engine_generated_tokens_total'
    )
    tok_series = snap['series'].get(
        'distllm_engine_generated_tokens_total', {'points': []}
    )
    burn: dict[str, float] = {}
    for window in _metrics.SLO_BURN_WINDOW_LABELS:
        value = history.gauge_window(
            'distllm_slo_burn_rate',
            window_s,
            labels={'window': window},
            agg='last',
            now=now,
        )
        if value is not None:
            burn[window] = value
    return {
        'window_s': window_s,
        'tok_s': tok['rate'],
        'tok_points': [
            [round(p[0], 3), round(p[2], 3)] for p in tok_series['points']
        ],
        'burn_rates': burn,
        'samples': history.samples,
    }


# ---------------------------------------------------------------- sampler
class HistorySampler:
    """Daemon thread ticking :meth:`MetricsHistory.sample_once` every
    ``interval_s`` (the StallWatchdog pattern: Event-paced loop,
    ``start()``/``stop()`` with a joined shutdown, context manager).
    A tick that raises is counted and never kills the thread. Exactly
    one sampler should own a history at a time — the chat server owns
    the process singleton in serving, the engine only when
    ``EngineConfig.history_interval_s`` > 0, bench/loadgen own it in
    scripted runs."""

    def __init__(
        self,
        history: MetricsHistory | None = None,
        *,
        interval_s: float = 1.0,
        name: str = SAMPLER_THREAD_NAME,
    ) -> None:
        if interval_s <= 0:
            raise ValueError('interval_s must be > 0')
        self.history = (
            history if history is not None else get_metrics_history()
        )
        self.interval_s = float(interval_s)
        self.name = name
        self._stop_event = threading.Event()
        self._thread: threading.Thread | None = None

    def _run(self) -> None:
        while not self._stop_event.wait(self.interval_s):
            try:
                self.history.sample_once()
            except Exception:
                _metrics.HISTORY_SAMPLE_ERRORS.inc()

    def start(self) -> 'HistorySampler':
        if self._thread is not None:
            raise RuntimeError('sampler already started')
        self.history.interval_hint_s = self.interval_s
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._run, name=self.name, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent; joins the thread (no leak after shutdown — the
        gen_history smoke asserts no live thread carries our name)."""
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def __enter__(self) -> 'HistorySampler':
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()


_default_history = MetricsHistory()


def get_metrics_history() -> MetricsHistory:
    """The process-wide history ring (what ``/debug/history`` serves)."""
    return _default_history
