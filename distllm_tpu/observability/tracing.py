"""In-process span tracing with a bounded ring buffer.

The trace layer that subsumes ``timer.Timer`` (ISSUE 1 tentpole): a
:class:`Span` records one timed region (name + tags + outcome + nanosecond
bounds + parent linkage), completed spans land in a process-wide
:class:`TraceBuffer` ring (old spans are evicted, memory stays bounded), and
the buffer dumps to JSONL for offline analysis. Nesting is tracked with a
thread-local stack, so spans opened inside other spans carry
``parent_id`` automatically — including across the engine's worker thread
vs. event loop split (each thread has its own stack, as it should:
cross-thread parentage would be a lie).

The clock is ``time.monotonic_ns`` to match ``timer.Timer``; ``wall_time_s``
is captured once at span start so dumps can be correlated with external
logs.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from pathlib import Path

_span_ids = itertools.count(1)
_local = threading.local()

# Request-scoped trace propagation (docs/observability.md): the chat server
# binds the inbound X-Request-Id here for the duration of one request's
# work, every span opened inside the scope is stamped with it, and the
# engine copies it onto the Request lifecycle (flight records included) —
# so one id correlates server middleware, RAG retrieval, engine dispatches,
# and the response the client got it echoed in. A ContextVar (not a plain
# thread-local): the binding must survive explicit Context.run handoffs
# while staying isolated between concurrently served requests.
# The natural identifier spelling below once had to be 'distllm-request-id'
# purely to dodge the legacy metric-name lint, which scanned every string in
# the package; the distlint rule is scoped to registration/exposition
# contexts, so non-metric identifiers no longer dictate naming.
_request_id = contextvars.ContextVar('distllm_request_id', default=None)


def current_request_id() -> str | None:
    """The request id bound by the innermost :func:`request_scope`."""
    return _request_id.get()


@contextmanager
def request_scope(request_id: str | None):
    """Bind ``request_id`` as the current request for this context.

    Spans opened inside the scope carry ``request_id`` in their
    attributes, and ``LLMEngine.add_request`` stamps it onto the request's
    lifecycle (``trace_id``). ``None`` is a no-op scope so call sites can
    pass an optional id through unconditionally.
    """
    if request_id is None:
        yield
        return
    token = _request_id.set(str(request_id))
    try:
        yield
    finally:
        _request_id.reset(token)


def _stack() -> list['Span']:
    stack = getattr(_local, 'stack', None)
    if stack is None:
        stack = _local.stack = []
    return stack


@dataclass
class Span:
    """One timed region. ``status`` is ``'ok'`` or ``'error'``."""

    name: str
    tags: tuple[str, ...] = ()
    span_id: int = 0
    parent_id: int | None = None
    start_ns: int = 0
    end_ns: int | None = None
    status: str = 'ok'
    error: str | None = None
    wall_time_s: float = 0.0
    attributes: dict[str, object] = field(default_factory=dict)
    # Opening thread's ident: the Perfetto exporter keys a track per
    # thread so concurrently open spans from different threads don't
    # render as one impossibly overlapping stack.
    thread_id: int = 0

    @property
    def duration_s(self) -> float:
        if self.end_ns is None:
            raise RuntimeError(f'span {self.name!r} has not finished')
        return (self.end_ns - self.start_ns) / 1e9

    def to_dict(self) -> dict[str, object]:
        record: dict[str, object] = {
            'name': self.name,
            'tags': list(self.tags),
            'span_id': self.span_id,
            'parent_id': self.parent_id,
            'start_ns': self.start_ns,
            'end_ns': self.end_ns,
            'duration_s': self.duration_s if self.end_ns is not None else None,
            'status': self.status,
            'wall_time_s': self.wall_time_s,
            'thread_id': self.thread_id,
        }
        if self.error is not None:
            record['error'] = self.error
        if self.attributes:
            record['attributes'] = dict(self.attributes)
        return record


class TraceBuffer:
    """Bounded ring of completed spans (oldest evicted first)."""

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 1:
            raise ValueError('capacity must be >= 1')
        self.capacity = capacity
        self._spans: deque[Span] = deque(maxlen=capacity)  # guarded by self._lock
        self._lock = threading.Lock()
        self._recorded = 0  # guarded by self._lock

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            self._recorded += 1

    def snapshot(self, limit: int | None = None) -> list[Span]:
        """Most recent spans, oldest first (``limit`` trims from the old
        end)."""
        with self._lock:
            spans = list(self._spans)
        if limit is not None:
            spans = spans[-limit:]
        return spans

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    @property
    def total_recorded(self) -> int:
        """Lifetime record count (survives ring eviction)."""
        with self._lock:
            return self._recorded

    def dump_jsonl(self, path: str | Path) -> int:
        """Write one JSON object per span; returns the number written."""
        spans = self.snapshot()
        with open(path, 'w') as handle:
            for span in spans:
                handle.write(json.dumps(span.to_dict()) + '\n')
        return len(spans)


_default_buffer = TraceBuffer()


def get_trace_buffer() -> TraceBuffer:
    """The process-wide trace ring (what ``/debug/traces`` serves)."""
    return _default_buffer


def dump_traces(path: str | Path) -> int:
    return _default_buffer.dump_jsonl(path)


def begin_span(name: str, *tags: str, **attributes: object) -> Span:
    """Open a span and push it on the thread-local nesting stack.

    Prefer the :func:`span` context manager; ``begin_span``/``end_span``
    exist for shims (``timer.Timer``) whose start/stop are separate calls.
    """
    stack = _stack()
    parent = stack[-1].span_id if stack else None
    record = Span(
        name=name,
        tags=tuple(str(t) for t in tags),
        span_id=next(_span_ids),
        parent_id=parent,
        start_ns=time.monotonic_ns(),
        wall_time_s=time.time(),
        attributes=dict(attributes),
        thread_id=threading.get_ident(),
    )
    rid = _request_id.get()
    if rid is not None and 'request_id' not in record.attributes:
        record.attributes['request_id'] = rid
    stack.append(record)
    return record


def end_span(
    record: Span,
    status: str = 'ok',
    error: BaseException | str | None = None,
    buffer: TraceBuffer | None = None,
) -> Span:
    """Close a span, pop it from the nesting stack, record it."""
    record.end_ns = time.monotonic_ns()
    record.status = status
    if error is not None:
        record.error = repr(error) if isinstance(error, BaseException) else str(error)
    stack = _stack()
    if record in stack:  # tolerate out-of-order shim stops
        stack.remove(record)
    # NOT `buffer or ...`: an empty TraceBuffer is falsy (it has __len__).
    target = _default_buffer if buffer is None else buffer
    target.record(record)
    return record


def abandon_span(record: Span) -> None:
    """Drop an open span from the nesting stack without recording it.

    For shims whose start/stop are separate calls (``timer.Timer``): a
    re-``start()`` with no intervening ``stop()`` must not leave the stale
    span on the thread-local stack, where it would parent every later span
    and grow the stack unboundedly.
    """
    stack = _stack()
    if record in stack:
        stack.remove(record)


@contextmanager
def span(name: str, *tags: str, buffer: TraceBuffer | None = None,
         **attributes: object):
    """Trace a region::

        with span('prefill', 'bucket-128', batch=4) as s:
            ...

    Exceptions mark the span ``status='error'`` (with the exception repr)
    and propagate.
    """
    record = begin_span(name, *tags, **attributes)
    try:
        yield record
    except BaseException as exc:
        end_span(record, status='error', error=exc, buffer=buffer)
        raise
    end_span(record, status='ok', buffer=buffer)
