"""Catalog of the well-known metric series (name = contract).

Every series the serving stack emits is declared here, in one place, so (a)
``docs/observability.md`` has a single source of truth, (b) importing this
module pre-registers the engine/scheduler/KV series with zero values —
``GET /metrics`` exposes the full schema from the first scrape, before any
traffic — and (c) call sites cannot typo a metric name into a fresh series.

Naming follows Prometheus conventions: ``distllm_`` prefix, ``_total``
suffix on counters, base units (seconds, bytes, ratios in [0, 1]).
"""

from __future__ import annotations

from distllm_tpu import __version__
from distllm_tpu.observability.metrics import get_registry, log_buckets

_registry = get_registry()

# --------------------------------------------------------------- engine
ENGINE_GENERATED_TOKENS = _registry.counter(
    'distllm_engine_generated_tokens_total',
    'Tokens emitted by the generation engine (token throughput source).',
)
ENGINE_PROMPT_TOKENS = _registry.counter(
    'distllm_engine_prompt_tokens_total',
    'Prompt tokens accepted into the engine via add_request.',
)
ENGINE_REQUESTS_ADDED = _registry.counter(
    'distllm_engine_requests_added_total',
    'Requests submitted to the engine.',
)
ENGINE_REQUESTS_FINISHED = _registry.counter(
    'distllm_engine_requests_finished_total',
    'Requests that reached a stop condition.',
)
ENGINE_PREFILL_DISPATCHES = _registry.counter(
    'distllm_engine_prefill_dispatches_total',
    'Batched prefill dispatches (one padded jit call each).',
)
ENGINE_DECODE_WINDOWS = _registry.counter(
    'distllm_engine_decode_windows_total',
    'Fused decode-window dispatches.',
)
ENGINE_OVERSHOOT_TOKENS = _registry.counter(
    'distllm_engine_overshoot_tokens_total',
    'Post-EOS tokens discarded by the pipelined one-window-late design.',
)
ENGINE_PREFILL_BATCH = _registry.histogram(
    'distllm_engine_prefill_batch_size',
    'Requests per batched prefill dispatch (padding rows excluded).',
    buckets=(1, 2, 4, 8, 16, 32, 64),
)
ENGINE_DECODE_UTILIZATION = _registry.histogram(
    'distllm_engine_decode_window_utilization',
    'Fraction of decode-window slots generating tokens (batch occupancy).',
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
)
ATTN_BACKEND_INFO = _registry.gauge(
    'distllm_engine_attn_backend_info',
    'Resolved paged-attention kernel backend serving this engine '
    "(EngineConfig.attn_backend after 'auto' resolution, pinned at "
    'construction; docs/serving.md "Attention kernel backends"). Exactly '
    'one backend label reads 1.',
    labelnames=('backend',),
)
# The resolvable (non-'auto') backend labels. This tuple is the single
# owner: ops.paged_attention derives its legal selector set from it
# (``ATTN_BACKENDS = ('auto', *ATTN_BACKEND_LABELS)``) and the engine's
# gauge loop iterates it, so a new kernel tier cannot leave the scrape
# schema or the 'exactly one label reads 1' invariant behind. Lives here
# (not in ops) because this module must stay importable without jax.
ATTN_BACKEND_LABELS = ('xla', 'pallas', 'interpret')
for _backend in ATTN_BACKEND_LABELS:
    ATTN_BACKEND_INFO.labels(backend=_backend)

KV_CACHE_DTYPE_INFO = _registry.gauge(
    'distllm_engine_kv_cache_dtype_info',
    'RESOLVED storage dtype of the paged KV pool '
    "(EngineConfig.kv_cache_dtype after 'auto' resolution, pinned at "
    'construction; docs/serving.md "Quantized KV cache"). Exactly one '
    'dtype label reads 1.',
    labelnames=('dtype',),
)
# Canonical jnp dtype names for the resolvable pool dtypes, plus a
# catch-all for model dtypes outside the usual set ('auto' follows the
# model). Same single-owner discipline as ATTN_BACKEND_LABELS.
KV_CACHE_DTYPE_LABELS = ('bfloat16', 'float32', 'int8', 'other')
for _dtype in KV_CACHE_DTYPE_LABELS:
    KV_CACHE_DTYPE_INFO.labels(dtype=_dtype)

ENGINE_KV_DISPATCH_BYTES = _registry.gauge(
    'distllm_engine_kv_dispatch_bytes',
    'XLA-measured bytes accessed per serving dispatch, by dispatch kind '
    '(cost_analysis on the compiled executable — the roofline numerator; '
    'docs/observability.md "Measured vs analytic MFU"). The int8 KV '
    'pool shows here as the decode/mixed kinds dropping by roughly the '
    'KV stream share.',
    labelnames=('kind',),
)

# ------------------------------------------------------------- KV cache
KV_BLOCKS_TOTAL = _registry.gauge(
    'distllm_kv_cache_blocks_total',
    'Allocatable KV-cache blocks (pool size minus the reserved trash block).',
)
KV_BLOCKS_IN_USE = _registry.gauge(
    'distllm_kv_cache_blocks_in_use',
    'KV-cache blocks currently owned by running/admitted sequences.',
)
KV_OCCUPANCY = _registry.gauge(
    'distllm_kv_cache_occupancy_ratio',
    'KV-cache block occupancy, in_use / total (0..1).',
)
KV_HBM_BYTES = _registry.gauge(
    'distllm_kv_cache_hbm_bytes',
    'Device memory held by the paged K/V pool arrays.',
)

# ----------------------------------------------------------- prefix cache
PREFIX_HIT_TOKENS = _registry.counter(
    'distllm_prefix_cache_hit_tokens_total',
    'Prompt tokens served from cached KV blocks (prefill skipped).',
)
PREFIX_LOOKUP_TOKENS = _registry.counter(
    'distllm_prefix_cache_lookup_tokens_total',
    'Prompt tokens submitted while the prefix cache was enabled '
    '(hit rate = hit_tokens / lookup_tokens).',
)
PREFIX_CACHED_BLOCKS = _registry.gauge(
    'distllm_prefix_cache_blocks',
    'KV blocks currently held by the prefix cache (referenced + evictable).',
)
PREFIX_EVICTABLE_BLOCKS = _registry.gauge(
    'distllm_prefix_cache_evictable_blocks',
    'Cached blocks with zero request references (LRU eviction candidates).',
)
PREFIX_SHARED_BLOCKS = _registry.gauge(
    'distllm_prefix_cache_shared_blocks',
    'Cached blocks referenced by two or more live requests right now.',
)
PREFIX_EVICTIONS = _registry.counter(
    'distllm_prefix_cache_evictions_total',
    'Cached blocks evicted (LRU) back to the allocator under pressure.',
)
PREFIX_COW_COPIES = _registry.counter(
    'distllm_prefix_cache_cow_copies_total',
    'Copy-on-write block copies (full-cover aligned prefix hits).',
)

# --------------------------------------------- prefix-cache tier hierarchy
# HBM -> host-RAM -> disk -> peer spill/promote tiers (EngineConfig.
# host_kv_tier_bytes / disk_kv_tier_dir / peer_kv_endpoints;
# docs/prefix_caching.md "Tier hierarchy", docs/routing.md "Peer KV
# tier"). Label values are the fixed TIER_LABELS below.
TIER_LABELS = ('hbm', 'host', 'disk', 'peer')
PREFIX_TIER_HITS = _registry.counter(
    'distllm_prefix_tier_hits_total',
    'Prefix-cache block lookups served per tier: hbm = live paged-pool '
    'blocks (no work), host = host-RAM pool (async promotion), disk = '
    'persisted spill files (load + promotion).',
    labelnames=('tier',),
)
PREFIX_TIER_MISSES = _registry.counter(
    'distllm_prefix_tier_misses_total',
    'Prefix-cache lookup walks that stopped at this tier — the lowest '
    'tier consulted found nothing, so the remaining prompt re-prefills.',
    labelnames=('tier',),
)
PREFIX_TIER_SPILLS = _registry.counter(
    'distllm_prefix_tier_spills_total',
    'KV blocks spilled INTO each tier (host = device→host fetch of an '
    'evicted block, disk = write-through persistence of that spill).',
    labelnames=('tier',),
)
PREFIX_TIER_PROMOTIONS = _registry.counter(
    'distllm_prefix_tier_promotions_total',
    'KV blocks promoted OUT of each tier toward the device pool (host = '
    'async device_put back into paged blocks, disk = file load into the '
    'host pool).',
    labelnames=('tier',),
)
PREFIX_TIER_BYTES = _registry.gauge(
    'distllm_prefix_tier_bytes',
    'Bytes of spilled KV currently held per tier (hbm KV bytes are '
    'tracked by distllm_kv_cache_hbm_bytes).',
    labelnames=('tier',),
)
PREFIX_TIER_EVICTIONS = _registry.counter(
    'distllm_prefix_tier_evictions_total',
    'Blocks evicted from each tier under its own pressure: hbm = '
    'pool-pressure LRU eviction out of the device cache (spilled when a '
    'host tier exists, dropped otherwise), host = host-pool byte-budget '
    'LRU, disk = disk byte-budget LRU (always a final drop).',
    labelnames=('tier',),
)
PREFIX_TIER_DROPPED_BLOCKS = _registry.counter(
    'distllm_prefix_tier_dropped_blocks_total',
    'Evicted KV blocks dropped outright — no lower tier existed to catch '
    'them, so the prefix must fully re-prefill on its next arrival. The '
    'attributable cost of cache pressure in incident bundles.',
)
PREFIX_TIER_ERRORS = _registry.counter(
    'distllm_prefix_tier_errors_total',
    'Tier operations that failed and degraded instead of raising into '
    'the serving path: disk = unreadable/corrupt/truncated .kvblock '
    'files or write IO errors (the entry is dropped and the prefix '
    'falls through to cold prefill), host = a failed async promotion '
    'transfer (the request falls back to cold prefill), peer = a '
    'sibling replica fetch that timed out, errored, or returned a '
    'corrupt payload (endpoint backs off, prefix prefills cold).',
    labelnames=('tier',),
)
for _tier in TIER_LABELS:
    PREFIX_TIER_HITS.labels(tier=_tier)
    PREFIX_TIER_MISSES.labels(tier=_tier)
    PREFIX_TIER_SPILLS.labels(tier=_tier)
    PREFIX_TIER_PROMOTIONS.labels(tier=_tier)
    PREFIX_TIER_BYTES.labels(tier=_tier)
    PREFIX_TIER_EVICTIONS.labels(tier=_tier)
    PREFIX_TIER_ERRORS.labels(tier=_tier)
ENGINE_PREFILL_CHUNKS = _registry.counter(
    'distllm_engine_prefill_chunks_total',
    'Chunked-prefill dispatches (uncached tails split under '
    'prefill_chunk_tokens).',
)
ENGINE_PREFILL_CHUNK_TOKENS = _registry.histogram(
    'distllm_engine_prefill_chunk_tokens',
    'Valid tokens per chunked-prefill dispatch.',
    buckets=(16, 32, 64, 128, 256, 512, 1024, 2048),
)

# ------------------------------------------- mixed prefill+decode windows
MIXED_WINDOWS = _registry.counter(
    'distllm_engine_mixed_windows_total',
    'Decode-window dispatches that also carried prefill-chunk rows '
    '(EngineConfig.enable_mixed_batching; docs/serving.md).',
)
MIXED_PREFILL_TOKENS = _registry.counter(
    'distllm_engine_mixed_prefill_tokens_total',
    'Prefill-tail chunk tokens that rode decode windows instead of '
    'standalone prefill dispatches.',
)
MIXED_PREFILL_TOKENS_PER_WINDOW = _registry.histogram(
    'distllm_engine_mixed_prefill_tokens_per_window',
    'Valid prefill-chunk tokens folded into one mixed window '
    '(bounded by EngineConfig.max_window_prefill_tokens).',
    buckets=(1, 16, 32, 64, 128, 256, 512, 1024, 2048),
)
MIXED_PREFILL_ROWS = _registry.histogram(
    'distllm_engine_mixed_prefill_rows',
    'Prefill-chunk rows (requests) folded into one mixed window.',
    buckets=(1, 2, 4, 8),
)

# ------------------------------------- speculative (prompt-lookup) decoding
SPEC_WINDOWS = _registry.counter(
    'distllm_engine_spec_windows_total',
    'Speculative verify-window dispatches (EngineConfig.draft_k; '
    'docs/speculative.md).',
)
SPEC_DRAFT_TOKENS = _registry.counter(
    'distllm_engine_spec_draft_tokens_total',
    'Draft tokens proposed by the prompt-lookup drafter and scored by '
    'verify windows.',
)
SPEC_ACCEPTED_TOKENS = _registry.counter(
    'distllm_engine_spec_accepted_tokens_total',
    'Draft tokens accepted by the verification rule (greedy argmax '
    'comparison or sampled rejection sampling) — each one a decode token '
    'that skipped its weight pass.',
)
SPEC_SAMPLED_ROWS = _registry.counter(
    'distllm_engine_spec_sampled_rows_total',
    'Verify-window rows with temperature > 0 that carried drafts — the '
    'device-side rejection-sampling verification path '
    '(docs/speculative.md "Sampled verification").',
)
SPEC_RESAMPLED_TOKENS = _registry.counter(
    'distllm_engine_spec_resampled_tokens_total',
    'Residual resamples: sampled rows whose span stopped short of its '
    'drafts, emitting one correction token drawn from the normalized '
    'positive residual (p - q)+.',
)
SPEC_ACCEPT_RATE = _registry.histogram(
    'distllm_engine_spec_accept_rate',
    'Per-window draft acceptance rate (accepted / drafted; windows that '
    'drafted nothing are not observed).',
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
)

# ------------------------------------------------- request lifecycle (SLO)
REQUEST_TTFT = _registry.histogram(
    'distllm_request_ttft_seconds',
    'Time to first token: add_request -> first generated token fetched on '
    'the host (the latency a streaming client sees).',
)
REQUEST_TPOT = _registry.histogram(
    'distllm_request_tpot_seconds',
    'Time per output token after the first (decode steady-state), '
    'per finished request: (finish - first_token) / (output_tokens - 1).',
    buckets=log_buckets(1e-4, 10.0),
)
REQUEST_QUEUE_WAIT = _registry.histogram(
    'distllm_request_queue_wait_seconds',
    'Admission queue wait: add_request -> decode-slot admission.',
)
REQUEST_SLO = _registry.counter(
    'distllm_request_slo_total',
    'Finished requests vs the TTFT SLO (EngineConfig.ttft_slo_s), by '
    'outcome met/missed. Only counted when an SLO is configured.',
    labelnames=('outcome',),
)
GOODPUT_TOKENS = _registry.counter(
    'distllm_engine_goodput_tokens_total',
    'Output tokens from requests that met the TTFT SLO — goodput, the '
    'throughput that actually counted.',
)
ENGINE_STEPS = _registry.counter(
    'distllm_engine_steps_total',
    'Engine steps recorded by the flight recorder, by kind '
    '(prefill/decode/mixed/spec).',
    labelnames=('kind',),
)
ENGINE_STEP_SECONDS = _registry.histogram(
    'distllm_engine_step_duration_seconds',
    'Wall time per engine step, by kind: prefill = host-side dispatch of '
    'one padded prefill; decode/mixed = dispatch -> host fetch of one '
    'fused window (includes pipelined in-flight time).',
    labelnames=('kind',),
)

# ------------------------------------------- roofline / MFU attribution
ENGINE_MFU = _registry.gauge(
    'distllm_engine_mfu',
    'Model FLOPs utilization of the most recent engine step of each kind: '
    'analytic matmul FLOPs (2 x n_params per scored position, '
    'observability/roofline.py) over wall time and the chip bf16 peak.',
    labelnames=('kind',),
)
ENGINE_BW_UTIL = _registry.gauge(
    'distllm_engine_bandwidth_utilization',
    'Weight-stream HBM bandwidth utilization of the most recent engine '
    'step of each kind: weight bytes read (decode re-reads the full set '
    'every scan step) over wall time and the chip HBM peak.',
    labelnames=('kind',),
)
ENGINE_MFU_MEASURED = _registry.gauge(
    'distllm_engine_mfu_measured',
    'MFU of the most recent engine step of each kind priced from what XLA '
    'actually compiled: compiled.cost_analysis() FLOPs '
    '(observability/xla_cost.py) over wall time and the chip peak — the '
    'measured twin of distllm_engine_mfu.',
    labelnames=('kind',),
)
ENGINE_BW_UTIL_MEASURED = _registry.gauge(
    'distllm_engine_bandwidth_utilization_measured',
    'HBM bandwidth utilization of the most recent engine step of each '
    'kind from compiled.cost_analysis() bytes accessed — includes KV and '
    'activation traffic the analytic weight-stream model omits.',
    labelnames=('kind',),
)
ENGINE_ROOFLINE_FLOPS_RATIO = _registry.gauge(
    'distllm_engine_roofline_flops_ratio',
    'Measured / analytic FLOPs per dispatch of each kind '
    '(cost_analysis over the 2 x n_params model) — calibration drift of '
    'the analytic roofline, as a visible number (~1.0 = calibrated).',
    labelnames=('kind',),
)
ENGINE_ROOFLINE_BYTES_RATIO = _registry.gauge(
    'distllm_engine_roofline_bytes_ratio',
    'Measured / analytic HBM bytes per dispatch of each kind — >1.0 is '
    'expected (KV + activation traffic the weight-stream model omits); '
    'large jumps mean the compiled graph carries traffic the model '
    'cannot see (layout churn, materialized slices).',
    labelnames=('kind',),
)

# ------------------------------------- startup / compile-phase attribution
COMPILE_SECONDS = _registry.histogram(
    'distllm_compile_seconds',
    'Wall time per startup/compile phase (observability/startup.py), by '
    'phase kind and shape label — the warmup ladder, backend init, '
    'weight-layout migration, and quantization made attributable.',
    labelnames=('kind', 'shape'),
    buckets=log_buckets(1e-3, 3600.0),
)
COMPILE_CACHE_HITS = _registry.counter(
    'distllm_compile_cache_hits_total',
    'Compile phases served from a cache fast path: repeat (kind, shape) '
    'in this process, or zero new persistent-compilation-cache entries '
    'while a cache dir is configured.',
)

# ------------------------------------------------ profiler capture helper
PROFILER_CAPTURES = _registry.counter(
    'distllm_profiler_captures_total',
    'Bounded jax.profiler captures (observability/profiling.py; '
    'GET /debug/xprof, DISTLLM_BENCH_PROFILE), by outcome '
    'ok/error/rejected.',
    labelnames=('outcome',),
)
for _outcome in ('ok', 'error', 'rejected'):
    PROFILER_CAPTURES.labels(outcome=_outcome)

# Pre-create the fixed label sets so the full request-lifecycle schema is
# present in the very first scrape, before any traffic.
for _kind in ('prefill', 'decode', 'mixed', 'spec'):
    ENGINE_STEPS.labels(kind=_kind)
    ENGINE_STEP_SECONDS.labels(kind=_kind)
    ENGINE_MFU.labels(kind=_kind)
    ENGINE_BW_UTIL.labels(kind=_kind)
    ENGINE_MFU_MEASURED.labels(kind=_kind)
    ENGINE_BW_UTIL_MEASURED.labels(kind=_kind)
    ENGINE_ROOFLINE_FLOPS_RATIO.labels(kind=_kind)
    ENGINE_ROOFLINE_BYTES_RATIO.labels(kind=_kind)

# Catalog of FlightRecorder record kinds, mirroring the distllm_* metric-
# name catalog above: every ``kind`` the package ever passes to
# ``FlightRecorder.record`` / the engine's ``_record_step`` must be listed
# here (enforced by tests/test_lint.py). A kind minted at a call site
# would silently fragment the flight schema that debug bundles,
# ``/debug/flight``, and ``aggregate.py`` replay.
FLIGHT_KINDS = frozenset({
    'prefill',  # one padded prefill dispatch (batched or paged-context)
    'decode',   # one fused decode window, dispatch -> host fetch
    'mixed',    # decode window that also carried prefill-chunk rows
    'spec',     # speculative verify window (draft/accepted token fields;
                # sampled_rows/resampled_tokens when temperature > 0 rows
                # rode the rejection-sampling verifier, and
                # prefill_tokens/prefill_rows when chunk rows rode)
    'request',  # per-request lifecycle summary at finish
    'preempt',  # recompute preemption performed by prepare_decode
    'spill',    # evicted prefix blocks fetched device→host into the KV
                # tier (blocks/bytes/fetch_s — the audited spill sync)
    'promote',  # host-tier blocks promoted back into the paged pool
                # (blocks/tokens/put_s/wait_s/overlap; wait_s is the one
                # audited completion sync of the async prefetch)
    'peer_fetch',  # one .kvblock payload fetched from a sibling
                   # replica's KVBlockServer over the fabric
                   # (endpoint/blocks/bytes/fetch_s; docs/routing.md)
    'event',    # rare irregular events (scheduler exhaustion, ...)
    'compile',  # one startup/compile phase (observability/startup.py):
                # backend init, warmup ladder shapes, layout migration
    'fault',    # one injected fault firing (resilience/faults.py:
                # site/fired/call — the chaos schedule made attributable)
    'recovery', # one serving-loop retry after a failed dispatch
                # (status=retry with the error + involved rids) or the
                # first post-failure token (status=recovered)
    'quarantine',  # a request forced to terminal FAILED
                   # (reason=dispatch_failed|timeout, recorded error)
    'shed',     # a request refused at admission (predicted_ttft_s /
                # retry_after_s — the honest-backpressure record)
    'regression',  # runtime sentinel firing: a live history window
                   # degraded past threshold vs the BENCH baseline
                   # envelope (metric/baseline/live/window_s fields)
})

# Catalog of startup/compile phase kinds (observability/startup.py),
# mirroring FLIGHT_KINDS: every phase name passed to
# ``CompileWatcher.phase(...)`` must be listed here (enforced by
# tests/test_lint.py). A phase minted at a call site would fragment the
# startup schema that debug bundles and the Perfetto startup track replay.
COMPILE_PHASES = frozenset({
    'backend_init',       # first jax.devices() touch (PJRT client init)
    'quantize',           # weight-only quantization of the param tree
    'auto_layout',        # AOT decode-window compile with Layout.AUTO
    'migrate_params',     # destructive weight relayout into HBM
    'kv_allocate',        # paged K/V pool materialization
    'prefill',            # one (batch, bucket) prefill warmup shape
    'prefill_paged',      # paged-context prefill twin of that shape
    'cow_copy',           # prefix-cache copy-on-write block copy
    'tier_promote',       # KV-tier gather/scatter ladder (spill fetch +
                          # promotion write-back shapes)
    'decode_window',      # the fused decode window (+ merge helper)
    'mixed_window',       # one chunk-bucket mixed-window shape
    'spec_window',        # the speculative verify window
    'spec_mixed_window',  # one chunk-bucket spec-mixed shape
})
for _outcome in ('met', 'missed'):
    REQUEST_SLO.labels(outcome=_outcome)

# Catalog of Perfetto/Chrome trace-event categories, mirroring the
# distllm_* metric-name and FLIGHT_KINDS catalogs: every ``cat`` the
# trace-event exporter (observability/perfetto.py) emits must be listed
# here (enforced by tests/test_lint.py). A category minted at a call site
# would fragment the trace schema that Perfetto queries, the exporter
# validator, and downstream tooling filter on.
TRACE_EVENT_CATEGORIES = frozenset({
    'engine_step',   # one engine dispatch slice on its window-kind track
    'engine_event',  # instant marks (preemptions, scheduler events)
    'host_gap',      # idle gap between consecutive engine windows
    'request',       # per-request lifecycle slice + nested ttft/queue_wait
    'span',          # trace-ring spans (server middleware, RAG, stages)
    'startup',       # compile-phase slices on the dedicated startup track
    'history',       # metric-history counter track (ph "C" events from
                     # the history.py ring: tok/s, burn rates, queue
                     # depth, KV occupancy over the trace window)
})

# ------------------------------------------------- resilience / fault layer
# Fault injection, crash-domain recovery, and SLO-aware shedding
# (distllm_tpu/resilience/, engine recovery paths; docs/resilience.md).
# Nothing in the resilience layer degrades silently: every injected
# fault, retry, quarantine, timeout, and shed lands in one of these.
FAULT_SITE_LABELS = ('dispatch', 'device_put', 'tier_io',
                     'sched_exhausted', 'slow_window')
RESILIENCE_FAULTS = _registry.counter(
    'distllm_resilience_faults_injected_total',
    'Faults fired by the deterministic injector '
    '(distllm_tpu/resilience/faults.py), by catalogued site. Zero in '
    'production unless DISTLLM_FAULTS armed a chaos schedule.',
    labelnames=('site',),
)
RESILIENCE_RETRIES = _registry.counter(
    'distllm_resilience_window_retries_total',
    'Serving-loop retries after a failed dispatch (EngineConfig.'
    'max_dispatch_retries > 0): the loop rolled per-row state back and '
    're-dispatched with bounded backoff instead of propagating.',
)
RESILIENCE_RECOVERIES = _registry.counter(
    'distllm_resilience_recoveries_total',
    'Recoveries: the first token emitted after one or more failed '
    'dispatches — the retry ladder worked and serving resumed.',
)
RESILIENCE_QUARANTINED = _registry.counter(
    'distllm_resilience_quarantined_requests_total',
    'Requests forced to the terminal FAILED status with a recorded '
    'error, by reason: dispatch_failed = its dispatches kept failing '
    'past the retry budget (poison-request containment), timeout = it '
    'outlived EngineConfig.request_deadline_s (its KV blocks are freed '
    'instead of held forever).',
    labelnames=('reason',),
)
RESILIENCE_SHED = _registry.counter(
    'distllm_resilience_shed_requests_total',
    'Requests refused with honest backpressure instead of queueing past '
    'the TTFT SLO, by reason: overload = predicted TTFT busts '
    'ttft_slo_s at enqueue (429 + Retry-After), draining = the server '
    'is in the /drain lifecycle (503).',
    labelnames=('reason',),
)
RESILIENCE_PREDICTED_TTFT = _registry.histogram(
    'distllm_resilience_predicted_ttft_seconds',
    'Admission-time TTFT predictions (resilience/admission.py), '
    'admitted and shed alike — compare against the realized '
    'distllm_request_ttft_seconds to read the predictor\'s calibration.',
    buckets=log_buckets(1e-3, 600.0),
)
for _site in FAULT_SITE_LABELS:
    RESILIENCE_FAULTS.labels(site=_site)
for _reason in ('dispatch_failed', 'timeout'):
    RESILIENCE_QUARANTINED.labels(reason=_reason)
for _reason in ('overload', 'draining'):
    RESILIENCE_SHED.labels(reason=_reason)
SERVER_READY = _registry.gauge(
    'distllm_server_ready',
    'chat_server readiness for the multi-replica router to poll: 1 = '
    'admitting, 0 = draining (POST /drain) — /health mirrors it as the '
    '"ready" field and a 503 status while draining.',
)
SERVER_READY.set(1.0)

# ------------------------------------------------ build identity / uptime
# Standard fleet-observability identities (the multi-replica router and
# aggregate tooling key on them): a constant-1 info gauge carrying the
# package version label, and a seconds-since-boot gauge the chat server
# refreshes on every history tick and health probe.
BUILD_INFO = _registry.gauge(
    'distllm_build_info',
    'Constant 1 with the package version as a label — the standard '
    'build-identity series fleet tooling joins per-replica metrics on.',
    labelnames=('version',),
)
BUILD_INFO.labels(version=__version__).set(1.0)
SERVER_UPTIME = _registry.gauge(
    'distllm_server_uptime_seconds',
    'Seconds since this chat_server process built its app (refreshed on '
    'every history-sampler tick and /health probe; 0 until a server runs).',
)

# ------------------------------------- telemetry history (history.py ring)
HISTORY_SAMPLES = _registry.counter(
    'distllm_history_samples_total',
    'Completed history-sampler ticks (observability/history.py) — one '
    'full registry snapshot folded into the bounded time-series ring.',
)
HISTORY_SAMPLE_SECONDS = _registry.histogram(
    'distllm_history_sample_duration_seconds',
    'Wall time per history sampling tick — the overhead bound: '
    'tests/test_history.py asserts a full-catalog tick stays under 50 ms '
    '(typically well under 5 ms), so a 1 s sampling interval costs <1% '
    'of one core.',
    buckets=log_buckets(1e-5, 1.0),
)
HISTORY_SAMPLE_ERRORS = _registry.counter(
    'distllm_history_sample_errors_total',
    'History observer callbacks that raised (swallowed and counted — a '
    'broken SLO/sentinel observer must not kill the sampler thread).',
)

# --------------------------------------- SLO burn rate (observability/slo.py)
# The burn-rate windows, as label values ('<seconds>s'). This tuple is the
# single owner: slo.py derives its short/long window pairs from it and the
# gauge pre-registration below iterates it, so a new window cannot leave
# the scrape schema behind. Default pairing (SRE-workbook style): the fast
# pair (60s short, 600s long) pages, the slow pair (300s, 3600s) warns.
SLO_BURN_WINDOW_LABELS = ('60s', '300s', '600s', '3600s')
SLO_BURN_RATE = _registry.gauge(
    'distllm_slo_burn_rate',
    'TTFT-SLO error-budget burn rate per trailing window: '
    '(missed / finished in the window) / (1 - objective). 1.0 = burning '
    'exactly the budget; sustained >> 1 on both windows of a pair pages '
    '(docs/observability.md "SLO burn rates").',
    labelnames=('window',),
)
for _window in SLO_BURN_WINDOW_LABELS:
    SLO_BURN_RATE.labels(window=_window)

# --------------------------- runtime regression sentinel (sentinel.py)
# The live metrics the sentinel compares against the baseline envelope
# (scripts/benchdiff.py --emit-baseline). Single owner: sentinel.py's
# live-extractor table and the counter pre-registration both iterate it.
SENTINEL_METRIC_LABELS = (
    'tok_s', 'ttft_p95_s', 'tpot_p95_s', 'mfu_measured', 'bw_util_measured',
)
SENTINEL_REGRESSIONS = _registry.counter(
    'distllm_sentinel_regressions_total',
    'Live-window regressions detected by the runtime sentinel, by '
    'baseline metric: a trailing history window degraded past the '
    'sentinel threshold vs the BENCH baseline envelope. One count per '
    'degradation episode (latched until the metric recovers).',
    labelnames=('metric',),
)
for _metric in SENTINEL_METRIC_LABELS:
    SENTINEL_REGRESSIONS.labels(metric=_metric)
SENTINEL_ARMED = _registry.gauge(
    'distllm_sentinel_armed',
    '1 while the regression sentinel holds a baseline envelope with at '
    'least one comparable metric, 0 while disarmed (no baseline — the '
    'counted degraded mode, never a raise).',
)
SENTINEL_DISARMED = _registry.counter(
    'distllm_sentinel_disarmed_total',
    'Sentinel arm attempts that degraded to disarmed, by reason: '
    'no_baseline = envelope file missing/unreadable, empty = envelope '
    'parsed but carried no comparable metrics.',
    labelnames=('reason',),
)
for _reason in ('no_baseline', 'empty'):
    SENTINEL_DISARMED.labels(reason=_reason)

# -------------------------------------------------- watchdog / debug bundle
WATCHDOG_STALLS = _registry.counter(
    'distllm_watchdog_stalls_total',
    'StallWatchdog firings (no observed progress for the stall window).',
)
DEBUG_BUNDLES = _registry.counter(
    'distllm_debug_bundles_total',
    'Debug bundles dumped (watchdog stalls, stage failures, /debug/bundle).',
)

# ------------------------------------------------------------ scheduler
SCHED_QUEUE_DEPTH = _registry.gauge(
    'distllm_scheduler_queue_depth',
    'Requests waiting for admission (continuous-batching backlog).',
)
SCHED_RUNNING = _registry.gauge(
    'distllm_scheduler_running_requests',
    'Requests currently holding a decode slot.',
)
SCHED_ADMITTED = _registry.counter(
    'distllm_scheduler_admitted_total',
    'Waiting requests admitted to a decode slot.',
)
SCHED_DEFERRED = _registry.counter(
    'distllm_scheduler_deferred_total',
    'Admission attempts deferred (no free slot or insufficient blocks).',
)
SCHED_PREEMPTIONS = _registry.counter(
    'distllm_scheduler_preemptions_total',
    'Running requests recompute-preempted back to the waiting queue.',
)

# ------------------------------------------------- pipeline stages (Timer)
STAGE_SECONDS = _registry.histogram(
    'distllm_stage_duration_seconds',
    'Per-stage wall time from timer.Timer spans, labeled by lead tag.',
    labelnames=('stage', 'status'),
)

# ----------------------------------------------------------- HTTP server
HTTP_REQUESTS = _registry.counter(
    'distllm_http_requests_total',
    'HTTP requests served, by normalized path and status class.',
    labelnames=('path', 'status'),
)
HTTP_LATENCY = _registry.histogram(
    'distllm_http_request_duration_seconds',
    'End-to-end request latency, by normalized path.',
    labelnames=('path',),
    buckets=log_buckets(1e-3, 300.0),
)
HTTP_IN_FLIGHT = _registry.gauge(
    'distllm_http_requests_in_flight',
    'Requests currently being handled.',
)
HTTP_RESPONSES = _registry.counter(
    'distllm_http_responses_total',
    'Responses completed by this server process (all paths).',
)

# ---------------------------------------------- multi-replica router
# The prefix-affinity front-end (distllm_tpu/router/; docs/routing.md).
# Runs in its own process, so these series appear on the ROUTER's
# /metrics, not a replica's. Label tuples below are the single owners:
# router/app.py and the pre-registration loops both iterate them.
ROUTER_DECISION_LABELS = ('affinity', 'least_loaded', 'round_robin')
ROUTER_REQUESTS = _registry.counter(
    'distllm_router_requests_total',
    'Requests proxied to a replica, by the routing decision that picked '
    'it: affinity = the learned digest map matched the prompt prefix, '
    'least_loaded = no affinity signal so the lightest /loadinfo queue '
    'won, round_robin = the baseline rotation policy.',
    labelnames=('decision',),
)
ROUTER_RETRIES = _registry.counter(
    'distllm_router_retries_total',
    'In-flight requests retried once on a healthy peer after their '
    'first replica died mid-request (response carries '
    'X-Distllm-Router-Retry: 1).',
)
ROUTER_FAILURES = _registry.counter(
    'distllm_router_failures_total',
    'Requests the router could not serve: no replica in rotation, or '
    'the single retry also failed (client sees 502/503).',
)
ROUTER_UPSTREAM_REJECTIONS = _registry.counter(
    'distllm_router_upstream_rejections_total',
    'Replica 429 + Retry-After admission rejections propagated to the '
    'client untouched — backpressure is the replica\'s call, never '
    'retried elsewhere by the router.',
)
ROUTER_REPLICA_STATE_LABELS = ('healthy', 'draining', 'dead')
ROUTER_REPLICAS = _registry.gauge(
    'distllm_router_replicas',
    'Replicas per rotation state: healthy = receiving new requests, '
    'draining = finishing in-flight only (one-way; never rejoins), '
    'dead = failed /health (rejoins when probes recover).',
    labelnames=('state',),
)
ROUTER_AFFINITY_ENTRIES = _registry.gauge(
    'distllm_router_affinity_entries',
    'Digest entries currently held across all per-replica affinity LRU '
    'maps (bounded by RouterConfig.affinity_map_size each).',
)
ROUTER_PROXY_SECONDS = _registry.histogram(
    'distllm_router_proxy_seconds',
    'End-to-end proxy latency per routed request (replica pick + '
    'upstream round trip + relay), retries included.',
    buckets=log_buckets(1e-3, 300.0),
)
for _decision in ROUTER_DECISION_LABELS:
    ROUTER_REQUESTS.labels(decision=_decision)
for _state in ROUTER_REPLICA_STATE_LABELS:
    ROUTER_REPLICAS.labels(state=_state)

# -------------------------------------------------------- fabric workers
WORKER_HEARTBEATS = _registry.counter(
    'distllm_worker_heartbeats_total',
    'Heartbeats sent by this fabric worker.',
)
WORKER_TASKS = _registry.counter(
    'distllm_worker_tasks_total',
    'Fabric tasks executed, by outcome.',
    labelnames=('outcome',),
)
WORKER_TASK_SECONDS = _registry.histogram(
    'distllm_worker_task_duration_seconds',
    'Wall time per fabric task (heartbeats excluded).',
)

# ------------------------------------------------------------ log funnel
LOG_MESSAGES = _registry.counter(
    'distllm_log_messages_total',
    'Operator log lines emitted through observability.log_event.',
    labelnames=('component',),
)


def log_event(message: str, *, component: str = 'app') -> None:
    """The sanctioned stdout funnel: print + count.

    All operator-facing telemetry lines in ``distllm_tpu`` go through here
    (``tests/test_lint.py`` forbids raw ``print(`` outside ``timer.py`` and
    this package), so every emitted line is also visible as
    ``distllm_log_messages_total{component=...}`` in scrapes.
    """
    LOG_MESSAGES.labels(component=component).inc()
    print(message, flush=True)
