"""Flight recorder + crash-proof run records (ISSUE 3 tentpole).

Rounds 3–5 each had real measurements and an empty official record: the
bench composed its one JSON line only after the *last* stage, so any
timeout, wedge, or signal lost everything. This module is the layer that
makes "numbers or an explanation" a structural property instead of a hope:

- :class:`FlightRecorder` — a bounded, thread-safe ring of per-engine-step
  records (step kind, batch occupancy, token counts, duration, queue depth,
  KV occupancy). The serving engine appends one record per prefill dispatch
  / decode window / finished request; the ring is cheap enough to stay on
  in production and is what a debug bundle or ``/debug/flight`` replays
  after a crash — the black-box flight recorder of the title.
- :class:`StallWatchdog` — a daemon thread that watches any monotonic
  progress function (by default the process flight ring's record count) and
  fires a callback when progress stops for ``stall_s`` seconds. The default
  callback dumps a debug bundle; it never kills the watched work.
- :func:`dump_debug_bundle` — flight ring + metrics exposition + trace ring
  (+ best-effort ``jax.profiler`` device-memory capture) written to one
  directory, so a dead stage still explains itself.
- :class:`RunRecord` — an append-only JSONL run record plus an atomically
  rewritten composed snapshot. Each completed bench stage lands on disk the
  moment it finishes; the driver-contract line is composed from whatever
  the record holds at emission time (normal exit, deadline, or signal).
- :class:`Deadline` — a global wall-clock budget from which per-stage
  budgets and retry-ladder shares are derived.

Everything here is dependency-free and safe to import on any backend.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path

from distllm_tpu.observability import instruments as _metrics
from distllm_tpu.observability.metrics import render_prometheus
from distllm_tpu.observability.tracing import get_trace_buffer


class FlightRecorder:
    """Bounded ring of per-step flight records (oldest evicted first).

    A record is one dict: ``{'kind': ..., 't_wall': ..., **fields}``.
    Every ``kind`` the package emits is registered in
    ``instruments.FLIGHT_KINDS`` (``'prefill'``, ``'decode'``, ``'mixed'``
    — a decode window carrying prefill-chunk rows — ``'request'``,
    ``'preempt'``, ``'event'``; enforced by ``tests/test_lint.py`` so the
    flight schema cannot fragment). Appends are O(1) under a lock — safe
    from the engine thread, the aiohttp event loop, and watchdog threads
    at once.
    """

    def __init__(self, capacity: int = 4096) -> None:
        if capacity < 1:
            raise ValueError('capacity must be >= 1')
        self.capacity = capacity
        self._records: deque[dict] = deque(maxlen=capacity)  # guarded by self._lock
        self._lock = threading.Lock()
        self._recorded = 0  # guarded by self._lock
        self._last_record_monotonic = time.monotonic()  # guarded by self._lock

    def record(self, kind: str, **fields) -> dict:
        entry = {'kind': kind, 't_wall': time.time(), **fields}
        with self._lock:
            self._records.append(entry)
            self._recorded += 1
            self._last_record_monotonic = time.monotonic()
        return entry

    def snapshot(self, limit: int | None = None) -> list[dict]:
        """Most recent records, oldest first (``limit`` trims old ones)."""
        with self._lock:
            records = list(self._records)
        if limit is not None:
            records = records[-limit:]
        return records

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    @property
    def total_recorded(self) -> int:
        """Lifetime record count (survives ring eviction) — the progress
        signal :class:`StallWatchdog` monitors by default."""
        with self._lock:
            return self._recorded

    @property
    def seconds_since_last_record(self) -> float:
        with self._lock:
            return time.monotonic() - self._last_record_monotonic

    def dump_jsonl(self, path: str | Path) -> int:
        records = self.snapshot()
        with open(path, 'w') as handle:
            for entry in records:
                handle.write(json.dumps(entry, default=str) + '\n')
        return len(records)


_default_recorder = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    """The process-wide flight ring (what ``/debug/flight`` serves)."""
    return _default_recorder


# ------------------------------------------------------------ debug bundle
def dump_debug_bundle(
    directory: str | Path,
    *,
    reason: str = 'unspecified',
    recorder: FlightRecorder | None = None,
    extra: dict | None = None,
) -> dict[str, str]:
    """Write the full observability state to ``directory`` and return the
    written paths. Called by the watchdog on stall, by bench stages on
    failure/SIGTERM, and by ``GET /debug/bundle`` on demand.

    Contents: ``flight.jsonl`` (engine-step ring), ``metrics.prom``
    (Prometheus exposition snapshot), ``traces.jsonl`` (span ring),
    ``startup.json`` (compile-phase records + the phase currently in
    progress + profiler-capture state — an init-stall bundle names the
    dead phase instead of arriving empty), ``history.json`` (the
    metric-history ring: the minutes BEFORE the incident, not just the
    final values), ``slo.json`` (burn-rate status + regression-sentinel
    state), ``meta.json``
    (reason/pid/time/extra), and — best-effort, when a JAX backend is
    initialized and supports it — ``device_memory.prof``
    (``jax.profiler.save_device_memory_profile``). Every piece is written
    independently: a failure in one never loses the others.
    """
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    recorder = recorder if recorder is not None else _default_recorder
    paths: dict[str, str] = {}

    flight_path = directory / 'flight.jsonl'
    try:
        recorder.dump_jsonl(flight_path)
        paths['flight'] = str(flight_path)
    except Exception:
        pass
    metrics_path = directory / 'metrics.prom'
    try:
        metrics_path.write_text(render_prometheus())
        paths['metrics'] = str(metrics_path)
    except Exception:
        pass
    traces_path = directory / 'traces.jsonl'
    try:
        get_trace_buffer().dump_jsonl(traces_path)
        paths['traces'] = str(traces_path)
    except Exception:
        pass
    # Startup/compile attribution + profiler-capture state: the r03/r04
    # failure mode is a process wedged INSIDE backend init or a warmup
    # compile — the flight ring is empty then, but the compile watcher's
    # in-progress phase names exactly where it died. Lazy imports: both
    # modules import this one.
    startup_path = directory / 'startup.json'
    try:
        from distllm_tpu.observability.profiling import get_profiler_capture
        from distllm_tpu.observability.startup import get_compile_watcher

        startup_path.write_text(
            json.dumps(
                {
                    'compile': get_compile_watcher().state(),
                    'profiler': get_profiler_capture().state(),
                },
                default=str,
            )
        )
        paths['startup'] = str(startup_path)
    except Exception:
        pass
    # Metric history + SLO/sentinel state: the time-resolved twin of the
    # instantaneous metrics.prom snapshot — a bundle dumped mid-incident
    # shows the minutes BEFORE the stall, not just the final values.
    # Lazy imports (history/slo/sentinel import instruments, which sits
    # beside this module in the package).
    history_path = directory / 'history.json'
    try:
        from distllm_tpu.observability.history import get_metrics_history

        history_path.write_text(
            json.dumps(get_metrics_history().snapshot(), default=str)
        )
        paths['history'] = str(history_path)
    except Exception:
        pass
    slo_path = directory / 'slo.json'
    try:
        from distllm_tpu.observability.history import get_metrics_history
        from distllm_tpu.observability.sentinel import (
            get_regression_sentinel,
        )
        from distllm_tpu.observability.slo import slo_status

        sentinel = get_regression_sentinel()
        slo_path.write_text(
            json.dumps(
                {
                    'slo': slo_status(get_metrics_history()),
                    'sentinel': (
                        sentinel.status() if sentinel is not None else None
                    ),
                },
                default=str,
            )
        )
        paths['slo'] = str(slo_path)
    except Exception:
        pass
    # Perfetto/Chrome trace of the same state: drop flight.jsonl's raw
    # rings into https://ui.perfetto.dev without any conversion step —
    # the post-mortem view of where the dying process's time went.
    perfetto_path = directory / 'perfetto.json'
    try:
        from distllm_tpu.observability.history import get_metrics_history
        from distllm_tpu.observability.perfetto import dump_trace

        dump_trace(
            perfetto_path,
            recorder.snapshot(),
            [s.to_dict() for s in get_trace_buffer().snapshot()],
            history=get_metrics_history(),
        )
        paths['perfetto'] = str(perfetto_path)
    except Exception:
        pass
    # Optional device-memory capture: only when jax is already imported
    # (importing it here could initialize a backend inside a dying
    # process) and the backend supports the profiler.
    try:  # pragma: no cover - backend-dependent
        import sys

        jax = sys.modules.get('jax')
        if jax is not None:
            prof_path = directory / 'device_memory.prof'
            jax.profiler.save_device_memory_profile(str(prof_path))
            paths['device_memory'] = str(prof_path)
    except Exception:
        pass
    meta_path = directory / 'meta.json'
    try:
        meta_path.write_text(
            json.dumps(
                {
                    'reason': reason,
                    'pid': os.getpid(),
                    'wall_time_s': time.time(),
                    'flight_records': len(recorder),
                    **(extra or {}),
                },
                default=str,
            )
        )
        paths['meta'] = str(meta_path)
    except Exception:
        pass
    _metrics.DEBUG_BUNDLES.inc()
    return paths


# ---------------------------------------------------------------- watchdog
class StallWatchdog:
    """Detects stalled progress and dumps a debug bundle.

    ``progress_fn`` returns any value; the watchdog fires ``on_stall``
    when the value has not *changed* for ``stall_s`` seconds. The default
    progress function is the process flight ring's lifetime record count,
    so an engine that stops dispatching windows (wedged backend, deadlocked
    host loop) trips the dog without any engine-side wiring. The default
    ``on_stall`` dumps a bundle to ``bundle_dir`` and logs it — it never
    kills the watched work (the stage budget / deadline does that); it
    exists so the corpse carries evidence.

    Fires at most ``max_fires`` times (default 1) per arm; ``beat()``
    force-marks progress for work that is alive but quiet. Use as a
    context manager around a stage, or ``start()``/``stop()`` manually.
    """

    def __init__(
        self,
        stall_s: float,
        *,
        progress_fn=None,
        on_stall=None,
        bundle_dir: str | Path | None = None,
        poll_s: float | None = None,
        max_fires: int = 1,
        name: str = 'watchdog',
    ) -> None:
        if stall_s <= 0:
            raise ValueError('stall_s must be > 0')
        self.stall_s = stall_s
        self.name = name
        self._progress_fn = progress_fn or (
            lambda: _default_recorder.total_recorded
        )
        self._on_stall = on_stall
        self._bundle_dir = bundle_dir
        self._poll_s = poll_s if poll_s is not None else min(1.0, stall_s / 4)
        self._max_fires = max_fires
        self.fired = 0
        self._beats = 0
        self._stop_event = threading.Event()
        self._thread: threading.Thread | None = None

    def beat(self) -> None:
        """Mark progress explicitly (for work the ring cannot see)."""
        self._beats += 1

    def _fire(self) -> None:
        self.fired += 1
        _metrics.WATCHDOG_STALLS.inc()
        _metrics.log_event(
            f'[{self.name}] no progress for {self.stall_s:.0f}s — '
            'dumping debug bundle',
            component='watchdog',
        )
        if self._on_stall is not None:
            self._on_stall(self)
        elif self._bundle_dir is not None:
            paths = dump_debug_bundle(
                self._bundle_dir,
                reason=f'{self.name}: stalled for {self.stall_s:.0f}s',
            )
            _metrics.log_event(
                f'[{self.name}] debug bundle: '
                f'{paths.get("meta", self._bundle_dir)}',
                component='watchdog',
            )

    def _run(self) -> None:
        last = (self._progress_fn(), self._beats)
        last_change = time.monotonic()
        while not self._stop_event.wait(self._poll_s):
            try:
                current = (self._progress_fn(), self._beats)
            except Exception:
                continue  # a dying progress probe must not kill the dog
            if current != last:
                last = current
                last_change = time.monotonic()
                continue
            if (
                time.monotonic() - last_change >= self.stall_s
                and self.fired < self._max_fires
            ):
                try:
                    self._fire()
                except Exception:
                    pass  # the watchdog must survive its own handler
                last_change = time.monotonic()

    def start(self) -> 'StallWatchdog':
        if self._thread is not None:
            raise RuntimeError('watchdog already started')
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._run, name=self.name, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> 'StallWatchdog':
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()


# -------------------------------------------------------------- run record
class RunRecord:
    """Append-only on-disk run record with a composed snapshot.

    ``record(stage, fragment)`` appends one JSON line
    ``{"stage": ..., "t_wall": ..., "fragment": {...}}`` to ``path``
    (write + flush + fsync — the line is durable the moment the call
    returns) and atomically rewrites ``snapshot_path`` with the merged
    view of every fragment so far. A crash between stages loses nothing;
    a crash *mid-write* loses at most the in-flight stage (the JSONL
    reader skips a torn final line).

    ``compose()`` merges fragments in record order (later keys win) — the
    exact dict the bench's driver-contract line is built from.
    """

    def __init__(
        self, path: str | Path, snapshot_path: str | Path | None = None
    ) -> None:
        self.path = Path(path)
        self.snapshot_path = (
            Path(snapshot_path)
            if snapshot_path is not None
            else self.path.with_name(self.path.stem + '_snapshot.json')
        )
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()

    def record(self, stage: str, fragment: dict) -> None:
        line = json.dumps(
            {'stage': stage, 't_wall': time.time(), 'fragment': fragment},
            default=str,
        )
        with self._lock:
            with open(self.path, 'a') as handle:
                handle.write(line + '\n')
                handle.flush()
                os.fsync(handle.fileno())
        self.write_snapshot()

    def entries(self) -> list[dict]:
        """Replay the JSONL (torn/corrupt lines skipped, order kept)."""
        try:
            text = self.path.read_text()
        except OSError:
            return []
        out = []
        for line in text.splitlines():
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn final line from a mid-write crash
        return out

    def stages(self) -> list[str]:
        """Stage names in first-recorded order (duplicates collapsed)."""
        seen: list[str] = []
        for entry in self.entries():
            if entry.get('stage') not in seen:
                seen.append(entry.get('stage'))
        return seen

    def compose(self) -> dict:
        merged: dict = {}
        for entry in self.entries():
            fragment = entry.get('fragment')
            if isinstance(fragment, dict):
                merged.update(fragment)
        return merged

    def write_snapshot(self) -> None:
        """Atomically rewrite the composed snapshot (tmp + rename)."""
        tmp = self.snapshot_path.with_name(self.snapshot_path.name + '.tmp')
        try:
            tmp.write_text(json.dumps(self.compose(), default=str))
            os.replace(tmp, self.snapshot_path)
        except OSError:
            pass  # snapshot is a convenience view; the JSONL is the record


# ---------------------------------------------------------------- deadline
class Deadline:
    """A global wall-clock budget that derives per-stage shares.

    ``remaining()`` never goes below zero; ``budget(nominal, floor=...)``
    is the pattern bench stages use: spend up to ``nominal`` seconds but
    never past the deadline (minus a small reserve kept for composing and
    emitting the final record).
    """

    def __init__(self, total_s: float, reserve_s: float = 15.0) -> None:
        if total_s <= 0:
            raise ValueError('total_s must be > 0')
        self.total_s = float(total_s)
        self.reserve_s = float(reserve_s)
        self._start = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._start

    def remaining(self) -> float:
        return max(0.0, self.total_s - self.reserve_s - self.elapsed())

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def budget(self, nominal_s: float, floor_s: float = 0.0) -> float:
        """Clamp a nominal stage budget into the remaining window.

        Returns 0 when less than ``floor_s`` is left — the caller should
        skip the stage (and say so) rather than start doomed work.
        """
        remaining = self.remaining()
        if remaining < max(floor_s, 1e-9):
            return 0.0
        return min(float(nominal_s), remaining)
