"""Dependency-free metrics registry with Prometheus text exposition.

The measurement substrate for the serving stack (ISSUE 1 tentpole): a
process-wide registry of :class:`Counter` / :class:`Gauge` /
:class:`Histogram` instruments, each optionally labeled, rendered on demand
in the Prometheus text exposition format (version 0.0.4) by
:func:`render_prometheus` — no ``prometheus_client`` dependency (this image
has no egress; the format is small and stable).

Design notes:

- ``registry.counter(...)`` is get-or-create: re-instantiating an engine or
  server in one process returns the same instrument instead of raising, so
  call sites never need import-order gymnastics. A name collision across
  *types* (or differing label names) is a programming error and raises.
- Unlabeled instruments are used directly (``c.inc()``); labeled ones vend
  children via ``c.labels(stage='prefill').inc()``. A labeled series only
  renders once a child exists — pre-create children for series that must
  appear in scrapes from the first request (``instruments.py`` does).
- Histograms use **fixed log-scale buckets** (:func:`log_buckets`) so wide
  dynamic ranges (100 µs kernel dispatch .. minutes-long compile) stay
  resolvable with ~20 buckets; bucket counts are cumulative per the
  Prometheus histogram contract.
- Everything is guarded by per-instrument locks: the chat server observes
  from the aiohttp event loop while the engine thread pool increments
  token counters.
"""

from __future__ import annotations

import math
import re
import threading

_NAME_RE = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*$')
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*$')


def log_buckets(
    lo: float, hi: float, per_decade: int = 3
) -> tuple[float, ...]:
    """Fixed log-scale bucket ladder covering ``[lo, hi]``.

    ``per_decade`` points per power of ten (3 gives the classic
    1 / 2.15 / 4.64 ladder). Upper bounds are rounded to 6 significant
    digits so the ``le`` labels stay readable in scrapes.
    """
    if lo <= 0 or hi <= lo:
        raise ValueError(f'need 0 < lo < hi, got lo={lo} hi={hi}')
    buckets: list[float] = []
    exponent = math.log10(lo)
    while True:
        value = float(f'{10 ** exponent:.6g}')
        buckets.append(value)
        if value >= hi:
            break
        exponent += 1.0 / per_decade
    return tuple(buckets)


# Default for duration histograms: 100 µs .. ~100 s, 3 buckets per decade.
DEFAULT_DURATION_BUCKETS = log_buckets(1e-4, 100.0)


def quantile_from_cumulative(
    buckets: tuple[float, ...], cumulative: list[int], q: float
) -> float | None:
    """Quantile estimate by linear interpolation over cumulative bucket
    counts (the ``histogram_quantile`` estimator, so numbers read off a
    loadgen report match what the same expression over ``/metrics`` would
    say). ``cumulative`` has ``len(buckets) + 1`` entries, the last being
    the +Inf bucket. Returns ``None`` on an empty histogram. Ranks that
    land in the +Inf bucket clamp to the highest finite bound — an
    estimator cannot invent an upper edge the ladder never recorded.

    Also the delta-quantile building block: subtract two
    ``cumulative_counts()`` snapshots element-wise and pass the result, and
    the estimate covers only the observations between them (how the
    ``gen_load`` bench stage isolates its own traffic from warmup's).
    """
    if not 0.0 <= q <= 1.0:
        raise ValueError(f'quantile must be in [0, 1], got {q}')
    total = cumulative[-1]
    if total <= 0:
        return None
    rank = q * total
    for i, count in enumerate(cumulative):
        if count >= rank and count > 0:
            if i >= len(buckets):  # +Inf bucket: clamp to last finite edge
                return float(buckets[-1])
            lo = buckets[i - 1] if i > 0 else 0.0
            prev = cumulative[i - 1] if i > 0 else 0
            in_bucket = count - prev
            if in_bucket <= 0:
                return float(buckets[i])
            frac = (rank - prev) / in_bucket
            return float(lo + (buckets[i] - lo) * frac)
    return float(buckets[-1])


def _escape_label_value(value: str) -> str:
    return (
        value.replace('\\', '\\\\').replace('"', '\\"').replace('\n', '\\n')
    )


def _format_value(value: float) -> str:
    if value == math.inf:
        return '+Inf'
    if value == -math.inf:
        return '-Inf'
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _label_suffix(labelnames: tuple[str, ...], labelvalues: tuple[str, ...],
                  extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = list(zip(labelnames, labelvalues)) + list(extra)
    if not pairs:
        return ''
    inner = ','.join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in pairs
    )
    return '{' + inner + '}'


class _CounterChild:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0  # guarded by self._lock

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError('counters can only increase')
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _GaugeChild:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0  # guarded by self._lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _HistogramChild:
    def __init__(self, buckets: tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self.buckets = buckets
        self._counts = [0] * (len(buckets) + 1)  # guarded by self._lock (last slot = +Inf)
        self._sum = 0.0  # guarded by self._lock

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._sum += value
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative_counts(self) -> list[int]:
        """Bucket counts as cumulative totals (the exposition contract)."""
        with self._lock:
            out, running = [], 0
            for n in self._counts:
                running += n
                out.append(running)
            return out

    def quantile(self, q: float) -> float | None:
        """Estimated ``q``-quantile of the observed distribution
        (:func:`quantile_from_cumulative` over this child's counts)."""
        return quantile_from_cumulative(
            self.buckets, self.cumulative_counts(), q
        )


class _Metric:
    """Shared labeled-family machinery; vends per-labelset children."""

    kind = 'untyped'

    def __init__(
        self, name: str, help: str, labelnames: tuple[str, ...]
    ) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f'invalid metric name {name!r}')
        for label in labelnames:
            if not _LABEL_RE.match(label):
                raise ValueError(f'invalid label name {label!r}')
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple[str, ...], object] = {}  # guarded by self._lock
        self._lock = threading.Lock()
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        raise NotImplementedError

    def labels(self, **labelvalues: str):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f'{self.name} expects labels {self.labelnames}, '
                f'got {tuple(labelvalues)}'
            )
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            return child

    def _default_child(self):
        if self.labelnames:
            raise ValueError(
                f'{self.name} is labeled {self.labelnames}; use .labels()'
            )
        # distlint: disable=lock-discipline -- unlabeled families write {(): child} once in __init__ and never mutate again (labels() guards the mutating path); locking here would put a second lock acquisition on every inc/observe in the serving loop
        return self._children[()]

    def children(self) -> list[tuple[tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class Counter(_Metric):
    """Monotonic counter (``*_total`` naming convention)."""

    kind = 'counter'

    def _new_child(self) -> _CounterChild:
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class Gauge(_Metric):
    """Instantaneous value that can go up and down."""

    kind = 'gauge'

    def _new_child(self) -> _GaugeChild:
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class Histogram(_Metric):
    """Cumulative histogram over fixed log-scale buckets."""

    kind = 'histogram'

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: tuple[str, ...] = (),
        buckets: tuple[float, ...] | None = None,
    ) -> None:
        chosen = tuple(buckets) if buckets else DEFAULT_DURATION_BUCKETS
        if list(chosen) != sorted(chosen) or len(set(chosen)) != len(chosen):
            raise ValueError('histogram buckets must be strictly increasing')
        self.buckets = chosen
        super().__init__(name, help, labelnames)

    def _new_child(self) -> _HistogramChild:
        return _HistogramChild(self.buckets)

    def observe(self, value: float) -> None:
        self._default_child().observe(value)

    @property
    def count(self) -> int:
        return self._default_child().count

    @property
    def sum(self) -> float:
        return self._default_child().sum

    def quantile(self, q: float) -> float | None:
        """Estimated ``q``-quantile (linear interpolation over cumulative
        bucket counts; ``None`` while the histogram is empty). Labeled
        histograms expose the same method on each ``labels(...)`` child."""
        return self._default_child().quantile(q)

    def cumulative_counts(self) -> list[int]:
        """Cumulative bucket counts of the unlabeled series — snapshot
        two of these and difference them element-wise into
        :func:`quantile_from_cumulative` to get quantiles over just the
        observations in between (the loadgen report does)."""
        return self._default_child().cumulative_counts()


class MetricsRegistry:
    """Named collection of instruments with text exposition."""

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}  # guarded by self._lock
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f'{name} already registered as {existing.kind}'
                    )
                if existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f'{name} already registered with labels '
                        f'{existing.labelnames}'
                    )
                return existing
            metric = cls(name, help, tuple(labelnames), **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(
        self, name: str, help: str = '', labelnames: tuple[str, ...] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(
        self, name: str, help: str = '', labelnames: tuple[str, ...] = ()
    ) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = '',
        labelnames: tuple[str, ...] = (),
        buckets: tuple[float, ...] | None = None,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> list[dict]:
        """Structured snapshot of every instrument — the machine-readable
        twin of :meth:`render`, consumed by the history sampler
        (``history.py``) so it never has to re-parse exposition text.

        One dict per family: ``{'name', 'kind', 'labelnames',
        'children': [...]}``. Each child carries its label values plus
        ``value`` (counter/gauge) or ``buckets``/``cumulative``/``sum``
        (histogram, cumulative counts per the exposition contract).
        Per-child reads take the child locks; the snapshot is coherent
        per-child, not across the whole registry — the same guarantee a
        text scrape gives.
        """
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        families: list[dict] = []
        for metric in metrics:
            children = []
            for labelvalues, child in metric.children():
                if isinstance(child, _HistogramChild):
                    children.append({
                        'labels': labelvalues,
                        'buckets': child.buckets,
                        'cumulative': child.cumulative_counts(),
                        'sum': child.sum,
                    })
                else:
                    children.append({
                        'labels': labelvalues,
                        'value': child.value,
                    })
            families.append({
                'name': metric.name,
                'kind': metric.kind,
                'labelnames': metric.labelnames,
                'children': children,
            })
        return families

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def render(self) -> str:
        """Prometheus text exposition (format version 0.0.4)."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: list[str] = []
        for metric in metrics:
            if metric.help:
                lines.append(f'# HELP {metric.name} {metric.help}')
            lines.append(f'# TYPE {metric.name} {metric.kind}')
            for labelvalues, child in metric.children():
                if isinstance(child, _HistogramChild):
                    cumulative = child.cumulative_counts()
                    bounds = list(child.buckets) + [math.inf]
                    for bound, count in zip(bounds, cumulative):
                        suffix = _label_suffix(
                            metric.labelnames,
                            labelvalues,
                            extra=(('le', _format_value(bound)),),
                        )
                        lines.append(
                            f'{metric.name}_bucket{suffix} {count}'
                        )
                    base = _label_suffix(metric.labelnames, labelvalues)
                    lines.append(
                        f'{metric.name}_sum{base} '
                        f'{_format_value(child.sum)}'
                    )
                    lines.append(
                        f'{metric.name}_count{base} {cumulative[-1]}'
                    )
                else:
                    suffix = _label_suffix(metric.labelnames, labelvalues)
                    lines.append(
                        f'{metric.name}{suffix} '
                        f'{_format_value(child.value)}'
                    )
        return '\n'.join(lines) + '\n'


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (what ``/metrics`` exposes)."""
    return _default_registry


def render_prometheus() -> str:
    return _default_registry.render()
