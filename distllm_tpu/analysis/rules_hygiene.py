"""Hygiene rules migrated from the legacy ``tests/test_lint.py`` walks.

Four rules: unused imports (ruff F401 equivalent), the raw-``print``
telemetry ban, the ``.free(`` block-lifecycle ban (all three matching
the legacy tests bit-for-bit — same allowlists, same ``noqa`` handling —
so the migration cannot loosen the gate), and the
``swallowed-exception`` rule added with the resilience layer (ISSUE 15):
in engine/server/tier/resilience paths, an ``except`` that neither
re-raises nor emits telemetry is a silent degradation — exactly the
failure class "nothing degrades silently" forbids.
"""

from __future__ import annotations

import ast

from distllm_tpu.analysis.core import (
    Project,
    Rule,
    SourceFile,
    register,
)


@register
class UnusedImportRule(Rule):
    """No module may carry unused imports — the most common rot this repo
    can accumulate. ``# noqa: F401`` (or a blanket ``# noqa``) on the
    import line exempts deliberate side-effect imports, matching ruff."""

    id = 'unused-import'
    description = 'imported name is never used in the module'

    def applies(self, source: SourceFile) -> bool:
        # Package surfaces re-export by design.
        return not source.rel.endswith('__init__.py')

    @staticmethod
    def _imported_names(source: SourceFile):
        for node in source.nodes():
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split('.')[0]
                    yield node.lineno, name
            elif isinstance(node, ast.ImportFrom):
                if node.module == '__future__':
                    continue
                for alias in node.names:
                    if alias.name == '*':
                        continue
                    yield node.lineno, alias.asname or alias.name

    @staticmethod
    def _used_names(source: SourceFile) -> set[str]:
        used: set[str] = set()
        for node in source.nodes():
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                inner = node
                while isinstance(inner, ast.Attribute):
                    inner = inner.value
                if isinstance(inner, ast.Name):
                    used.add(inner.id)
            elif isinstance(node, ast.Assign):
                # Names re-exported via __all__ strings count as used.
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == '__all__':
                        for el in getattr(node.value, 'elts', []):
                            if isinstance(el, ast.Constant):
                                used.add(str(el.value))
        return used

    def check(self, source: SourceFile, project: Project):
        assert source.tree is not None
        used = self._used_names(source)
        for lineno, name in self._imported_names(source):
            if name in used:
                continue
            line = (
                source.lines[lineno - 1]
                if lineno - 1 < len(source.lines)
                else ''
            )
            # Only an F401 (or blanket) noqa exempts an unused import; a
            # noqa for an unrelated rule (e.g. E402) must not mask rot.
            if 'noqa: F401' in line or line.rstrip().endswith('# noqa'):
                continue  # deliberate side-effect import
            yield self.diag(source, lineno, f'unused import {name!r}')


@register
class RawPrintRule(Rule):
    """Telemetry goes through ``observability.log_event`` (counted,
    greppable), not bare ``print(`` — which bypasses the metrics registry
    and is invisible to scrapes. Only ``timer.py`` (the legacy ``[timer]``
    line emitter) and the ``observability`` package itself may print;
    anything else needs a justified suppression (e.g. a CLI whose stdout
    is the product)."""

    id = 'raw-print'
    description = 'bare print() telemetry outside the sanctioned emitters'

    _EXEMPT_PREFIXES = ('distllm_tpu/observability/',)
    _EXEMPT_FILES = ('distllm_tpu/timer.py',)

    def applies(self, source: SourceFile) -> bool:
        if not self.in_package(source):
            return False
        if source.rel in self._EXEMPT_FILES:
            return False
        return not source.rel.startswith(self._EXEMPT_PREFIXES)

    def check(self, source: SourceFile, project: Project):
        assert source.tree is not None
        for node in source.nodes():
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == 'print'
            ):
                yield self.diag(
                    source,
                    node.lineno,
                    'raw print( telemetry — use '
                    'distllm_tpu.observability.log_event',
                )


@register
class SwallowedExceptionRule(Rule):
    """In the serving-critical paths (engine, KV tiers, chat server,
    resilience layer), an ``except`` handler that neither re-raises nor
    emits ANY telemetry — ``log_event``, a metric ``.inc/.observe/.set``,
    a flight ``.record``, a ``logging`` call, or a ``self.telemetry``
    note — is a silent degradation: the exact failure class the
    resilience layer exists to forbid (ISSUE 15; a swallowed tier IO
    error was how a dead persistence tier could have served cold TTFT
    for weeks without a single scrapeable signal). Deliberate pure
    control-flow swallows (membership probes, best-effort cleanup)
    carry a justified ``# distlint: disable`` on the handler line.
    """

    id = 'swallowed-exception'
    description = (
        'except handler in a serving path that neither re-raises nor '
        'emits telemetry'
    )

    _SCOPE_PREFIXES = (
        'distllm_tpu/generate/engine/',
        'distllm_tpu/resilience/',
        # Multi-replica serving tier (docs/routing.md): the router is a
        # proxy on the request path — a swallowed proxy/probe error is a
        # replica silently leaving (or wrongly staying in) rotation.
        'distllm_tpu/router/',
    )
    _SCOPE_FILES = (
        'distllm_tpu/chat_server.py',
        # Peer KV transport and the HTTP loadgen driver: both absorb
        # network failures by design, so every absorb must be counted.
        'distllm_tpu/parallel/fabric.py',
        'distllm_tpu/generate/loadgen.py',
    )

    # Attribute calls that count as telemetry. Generous on purpose: the
    # rule exists to surface handlers with NO signal at all, and a
    # false "this is telemetry" match is strictly safer than forcing
    # noise suppressions onto every legitimately-instrumented handler.
    _TELEMETRY_ATTRS = frozenset({
        'inc', 'dec', 'observe', 'set', 'record', 'log_event',
        'warning', 'error', 'exception', 'critical', 'info', 'debug',
        'setdefault',  # the engine's telemetry.setdefault(...) notes
    })

    def applies(self, source: SourceFile) -> bool:
        return (
            source.rel.startswith(self._SCOPE_PREFIXES)
            or source.rel in self._SCOPE_FILES
        )

    @classmethod
    def _emits_signal(cls, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Name)
                    and func.id == 'log_event'
                ):
                    return True
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in cls._TELEMETRY_ATTRS
                ):
                    return True
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    # self.telemetry['key'] = ... / telemetry notes
                    if isinstance(tgt, ast.Subscript) and isinstance(
                        tgt.value, ast.Attribute
                    ) and tgt.value.attr == 'telemetry':
                        return True
        return False

    def check(self, source: SourceFile, project: Project):
        assert source.tree is not None
        for node in source.nodes():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if self._emits_signal(node):
                continue
            yield self.diag(
                source,
                node.lineno,
                'except handler swallows the error without re-raising '
                'or emitting telemetry (log_event / metric / flight '
                'record) — nothing may degrade silently in serving '
                'paths; add a signal or a justified suppression',
            )


@register
class DirectFreeRule(Rule):
    """KV blocks are freed ONLY by the allocator/scheduler/prefix-cache
    machinery (``generate/engine/kv_cache.py`` + the scheduler bindings).
    A stray ``allocator.free(...)`` anywhere else can double-free a block
    that the prefix cache still maps — corruption that surfaces as
    another request's KV, long after the bad call."""

    id = 'direct-free'
    description = '.free( call outside the allocator/cache modules'

    _ALLOWED = (
        'distllm_tpu/generate/engine/kv_cache.py',
        'distllm_tpu/generate/engine/scheduler.py',
    )

    def applies(self, source: SourceFile) -> bool:
        return self.in_package(source) and source.rel not in self._ALLOWED

    def check(self, source: SourceFile, project: Project):
        assert source.tree is not None
        for node in source.nodes():
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == 'free'
            ):
                yield self.diag(
                    source,
                    node.lineno,
                    'direct .free( call — route block lifecycle through '
                    'the scheduler/PrefixCache',
                )
