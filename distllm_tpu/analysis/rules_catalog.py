"""Catalog rules: every schema-bearing name the package emits must be
registered in the ``instruments.py`` catalogs.

Four rules, one per catalog: metric names, FlightRecorder kinds, trace
event categories, compile-phase kinds. A name minted at a call site would
silently fragment the schema that scrapes, debug bundles,
``aggregate.py``, and the Perfetto exporter replay — the catalog is the
contract, so the analyzer treats an uncatalogued name as an error.

The metric-name rule is *scoped* (the one behavioral change vs. the
legacy walk): it checks registration contexts — ``*.counter(...)`` /
``*.gauge(...)`` / ``*.histogram(...)`` call sites — and docstrings
(which double as operator documentation), not every string constant in
the package. The legacy everywhere-scan forced PR 7 to rename a
ContextVar to ``distllm-request-id`` purely because its natural
identifier spelling matched the metric-name regex; identifiers that are
not metrics no longer dictate naming.
"""

from __future__ import annotations

import ast
import re

from distllm_tpu.analysis.core import (
    Diagnostic,
    Project,
    Rule,
    SourceFile,
    register,
)

_METRIC_NAME_RE = re.compile(r'^distllm_[a-z0-9_]+$')
_EXPOSITION_SUFFIX_RE = re.compile(r'_(bucket|sum|count)$')
_WORD_RE = re.compile(r'[A-Za-z0-9_]+')


class _CatalogRule(Rule):
    """Shared plumbing: package scope + a "catalog parsed non-empty"
    project check (an empty catalog means the rule is broken, which must
    fail loudly rather than pass vacuously)."""

    catalog_label = ''

    def applies(self, source: SourceFile) -> bool:
        return self.in_package(source)

    def catalog(self, project: Project) -> frozenset[str]:
        raise NotImplementedError

    def check_project(self, project: Project):
        if not self.catalog(project):
            yield Diagnostic(
                rule_id=self.id,
                path=Project.INSTRUMENTS_REL,
                line=1,
                message=(
                    f'{self.catalog_label} catalog parse came back empty '
                    '— the rule is broken or instruments.py moved'
                ),
            )


def _docstrings(source):
    """Yield ``(lineno, text)`` for every docstring constant."""
    scopes = [
        node
        for node in (source.tree, *source.nodes())
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        )
    ]
    seen = set()
    for scope in scopes:
        if id(scope) in seen:
            continue
        seen.add(id(scope))
        body = getattr(scope, 'body', [])
        if (
            body
            and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)
        ):
            yield body[0].value.lineno, body[0].value.value


@register
class MetricNameCatalogRule(_CatalogRule):
    """Metric names are registered in the instruments.py catalog.

    Checked contexts: the first string argument of every
    ``*.counter/gauge/histogram(...)`` call (an ad-hoc registration would
    create a series the catalog and the first-scrape-full-schema guarantee
    know nothing about), and metric-shaped words inside docstrings (which
    document series and must not drift). Histogram references may use the
    exposition suffixes of a registered base name.
    """

    id = 'metric-name-catalog'
    description = 'metric name not registered in the instruments catalog'
    catalog_label = 'metric-name'

    def catalog(self, project: Project) -> frozenset[str]:
        return project.metric_catalog()

    @staticmethod
    def _is_registered(word: str, registered: frozenset[str]) -> bool:
        base = _EXPOSITION_SUFFIX_RE.sub('', word)
        return word in registered or base in registered

    @staticmethod
    def _string_constants(source: SourceFile) -> dict[str, str]:
        """``NAME = 'literal'`` bindings anywhere in the module, so a
        metric registered through a named constant
        (``registry.counter(_NAME, ...)``) is still checked — the legacy
        everywhere-scan caught the literal at its definition site; the
        scoped rule must not lose that registration."""
        out: dict[str, str] = {}
        for node in source.nodes():
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign):  # _NAME: Final = '...'
                target = node.target
            else:
                continue
            if not (
                isinstance(target, ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                continue
            # A name rebound to different strings is ambiguous: drop it.
            if target.id in out and out[target.id] != node.value.value:
                out[target.id] = ''
            else:
                out[target.id] = node.value.value
        return {k: v for k, v in out.items() if v}

    def check(self, source: SourceFile, project: Project):
        assert source.tree is not None
        registered = self.catalog(project)
        if not registered:
            return  # check_project already flagged the broken catalog
        constants = self._string_constants(source)
        # instruments.py registration call sites ARE the catalog, but its
        # docstrings still document series and must not drift (the loop
        # below runs for every file).
        is_catalog_file = source.rel == Project.INSTRUMENTS_REL
        for node in (() if is_catalog_file else source.nodes()):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ('counter', 'gauge', 'histogram')
                and node.args
            ):
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                name = first.value
            elif isinstance(first, ast.Name) and first.id in constants:
                name = constants[first.id]
            else:
                continue
            if not self._is_registered(name, registered):
                yield self.diag(
                    source,
                    node.lineno,
                    f'metric {name!r} registered at a call site but '
                    'missing from the instruments.py catalog',
                )
        for lineno, text in _docstrings(source):
            for word in _WORD_RE.findall(text):
                if (
                    not _METRIC_NAME_RE.match(word)
                    or word.startswith('distllm_tpu')
                    or word.endswith('_')  # doc glob, e.g. a *-suffix family
                ):
                    continue
                if not self._is_registered(word, registered):
                    yield self.diag(
                        source,
                        lineno,
                        f'docstring references metric {word!r} which is '
                        'not in the instruments.py catalog',
                    )


@register
class FlightKindCatalogRule(_CatalogRule):
    """Every FlightRecorder ``kind`` emitted in the package (a string
    literal — or a conditional between string literals — as the first
    argument of a ``.record(...)`` / ``_record_step(...)`` call) must be
    registered in ``instruments.FLIGHT_KINDS``. A kind minted at a call
    site would silently fragment the flight schema that debug bundles,
    ``/debug/flight``, and ``aggregate.py`` replay."""

    id = 'flight-kind-catalog'
    description = 'flight-record kind missing from instruments.FLIGHT_KINDS'
    catalog_label = 'flight-kind'

    def catalog(self, project: Project) -> frozenset[str]:
        return project.frozenset_catalog('FLIGHT_KINDS')

    def check(self, source: SourceFile, project: Project):
        assert source.tree is not None
        registered = self.catalog(project)
        if not registered:
            return
        for node in source.nodes():
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            name = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name)
                else None
            )
            if name not in ('record', '_record_step'):
                continue
            first = node.args[0]
            branches = (
                (first.body, first.orelse)
                if isinstance(first, ast.IfExp)
                else (first,)
            )
            for branch in branches:
                if not (
                    isinstance(branch, ast.Constant)
                    and isinstance(branch.value, str)
                ):
                    continue
                if branch.value not in registered:
                    yield self.diag(
                        source,
                        node.lineno,
                        f'flight kind {branch.value!r} is not registered '
                        'in instruments.FLIGHT_KINDS',
                    )


@register
class TraceCategoryCatalogRule(_CatalogRule):
    """Every trace-event category the package emits (a string literal
    passed as a ``cat=...`` keyword or a ``'cat': ...`` dict key) must be
    registered in ``instruments.TRACE_EVENT_CATEGORIES`` — a category
    minted at a call site would fragment the trace schema Perfetto
    queries, the exporter validator, and downstream tooling filter on."""

    id = 'trace-category-catalog'
    description = (
        'trace-event category missing from '
        'instruments.TRACE_EVENT_CATEGORIES'
    )
    catalog_label = 'trace-category'

    def catalog(self, project: Project) -> frozenset[str]:
        return project.frozenset_catalog('TRACE_EVENT_CATEGORIES')

    def check(self, source: SourceFile, project: Project):
        assert source.tree is not None
        registered = self.catalog(project)
        if not registered:
            return
        for node in source.nodes():
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (
                        kw.arg == 'cat'
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                        and kw.value.value not in registered
                    ):
                        yield self.diag(
                            source,
                            node.lineno,
                            f'trace category {kw.value.value!r} is not in '
                            'instruments.TRACE_EVENT_CATEGORIES',
                        )
            elif isinstance(node, ast.Dict):
                for key, value in zip(node.keys, node.values):
                    if (
                        isinstance(key, ast.Constant)
                        and key.value == 'cat'
                        and isinstance(value, ast.Constant)
                        and isinstance(value.value, str)
                        and value.value not in registered
                    ):
                        yield self.diag(
                            source,
                            value.lineno,
                            f'trace category {value.value!r} is not in '
                            'instruments.TRACE_EVENT_CATEGORIES',
                        )


@register
class CompilePhaseCatalogRule(_CatalogRule):
    """Every startup/compile phase the package opens (a string literal as
    the first argument of a ``.phase(...)`` call — ``CompileWatcher.phase``)
    must be registered in ``instruments.COMPILE_PHASES``; a phase minted
    at a call site would fragment the startup schema that debug bundles
    and the Perfetto startup track replay."""

    id = 'compile-phase-catalog'
    description = 'compile-phase kind missing from instruments.COMPILE_PHASES'
    catalog_label = 'compile-phase'

    def catalog(self, project: Project) -> frozenset[str]:
        return project.frozenset_catalog('COMPILE_PHASES')

    def check(self, source: SourceFile, project: Project):
        assert source.tree is not None
        registered = self.catalog(project)
        if not registered:
            return
        for node in source.nodes():
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            if not (
                isinstance(func, ast.Attribute) and func.attr == 'phase'
            ):
                continue
            first = node.args[0]
            if (
                isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and first.value not in registered
            ):
                yield self.diag(
                    source,
                    node.lineno,
                    f'compile phase {first.value!r} is not registered in '
                    'instruments.COMPILE_PHASES',
                )
