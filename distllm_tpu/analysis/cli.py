"""distlint CLI: text + JSON findings over the repo's lint surface.

``python scripts/distlint.py`` (or ``python -m distllm_tpu.analysis``)
runs every registered rule over the default source set — the same file
set and rules tier-1 enforces via ``tests/test_lint.py`` — and exits
nonzero on findings, so builders get the findings before pytest does.

The JSON output (``--json``) is a stable schema (``version`` bumps on
breaking change; ``tests/test_analysis.py`` pins it)::

    {
      "version": 1,
      "root": "/abs/repo",
      "files_analyzed": 210,
      "rules": [{"id": ..., "description": ..., "severity": ...}, ...],
      "diagnostics": [
        {"rule_id": ..., "path": ..., "line": ..., "severity": ...,
         "message": ...}, ...
      ],
      "summary": {"total": 3, "by_rule": {"raw-print": 3}}
    }
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from distllm_tpu.analysis.core import (
    META_RULE_IDS,
    RULES,
    analyze,
    default_source_paths,
    iter_rules,
    load_project,
)

JSON_SCHEMA_VERSION = 1


def _find_repo_root(start: Path) -> Path:
    """Walk up to the directory that contains the package (so the CLI
    works from any cwd inside the repo); from an unrelated cwd, fall
    back to the checkout this module itself lives in."""
    current = start.resolve()
    for candidate in (current, *current.parents):
        if (candidate / 'distllm_tpu').is_dir():
            return candidate
    return Path(__file__).resolve().parents[2]


def build_report(root: Path, paths=None, rule_ids=None) -> dict:
    """Run the analysis and shape the stable JSON document."""
    project = load_project(root, paths)
    rules = iter_rules(rule_ids)
    diagnostics = analyze(
        project, rules, audit_suppressions=rule_ids is None
    )
    by_rule: dict[str, int] = {}
    for diag in diagnostics:
        by_rule[diag.rule_id] = by_rule.get(diag.rule_id, 0) + 1
    return {
        'version': JSON_SCHEMA_VERSION,
        'root': str(Path(root).resolve()),
        'files_analyzed': len(project.files),
        'rules': [
            {
                'id': rule.id,
                'description': rule.description,
                'severity': rule.severity,
            }
            for rule in rules
        ],
        'diagnostics': [diag.to_dict() for diag in diagnostics],
        'summary': {
            'total': len(diagnostics),
            'by_rule': dict(sorted(by_rule.items())),
        },
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog='distlint',
        description=(
            'dependency-free static analysis for distllm-tpu serving '
            'invariants (docs/static_analysis.md)'
        ),
    )
    parser.add_argument(
        'paths', nargs='*',
        help='files to analyze (default: the whole lint surface)',
    )
    parser.add_argument(
        '--root', default=None,
        help='repo root (default: discovered from cwd)',
    )
    parser.add_argument(
        '--rules', default=None,
        help='comma-separated rule ids to run (default: all)',
    )
    parser.add_argument(
        '--json', action='store_true', dest='as_json',
        help='emit the JSON report instead of text lines',
    )
    parser.add_argument(
        '--list-rules', action='store_true',
        help='list registered rule ids and exit',
    )
    args = parser.parse_args(argv)

    root = (
        Path(args.root) if args.root else _find_repo_root(Path.cwd())
    )
    if args.list_rules:
        for rule in iter_rules():
            # distlint: disable=raw-print -- CLI stdout is the product here, not telemetry
            print(f'{rule.id:28s} {rule.description}')
        for meta_id in META_RULE_IDS:
            # distlint: disable=raw-print -- CLI stdout is the product here, not telemetry
            print(f'{meta_id:28s} (framework meta rule)')
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(',') if r.strip()]
        unknown = sorted(set(rule_ids) - set(RULES))
        if unknown:
            sys.stderr.write(
                f'unknown rule ids: {", ".join(unknown)} '
                f'(see --list-rules)\n'
            )
            return 2

    paths = [Path(p) for p in args.paths] if args.paths else None
    if paths is not None:
        missing = [p for p in paths if not p.is_file()]
        if missing:
            # Usage error, NOT exit 1 — a typo'd path must stay
            # distinguishable from "findings found".
            sys.stderr.write(
                'no such file: '
                + ', '.join(str(p) for p in missing) + '\n'
            )
            return 2
    else:
        resolved = default_source_paths(root)
        if not resolved:
            sys.stderr.write(f'no sources found under {root}\n')
            return 2

    report = build_report(root, paths, rule_ids)
    if args.as_json:
        # distlint: disable=raw-print -- CLI stdout is the product here, not telemetry
        print(json.dumps(report, indent=2))
    else:
        for diag in report['diagnostics']:
            # distlint: disable=raw-print -- CLI stdout is the product here, not telemetry
            print(
                f'{diag["path"]}:{diag["line"]}: {diag["severity"]}: '
                f'[{diag["rule_id"]}] {diag["message"]}'
            )
        total = report['summary']['total']
        checked = report['files_analyzed']
        # distlint: disable=raw-print -- CLI stdout is the product here, not telemetry
        print(
            f'distlint: {total} finding(s) across {checked} file(s)'
            if total
            else f'distlint: clean ({checked} files analyzed)'
        )
    return 1 if report['summary']['total'] else 0


if __name__ == '__main__':
    sys.exit(main())
