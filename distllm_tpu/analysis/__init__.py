"""distlint: dependency-free static analysis for TPU-serving invariants.

Public surface: the framework (:mod:`core`), the rule modules (imported
here for their registration side effects), and the CLI entry point. See
``docs/static_analysis.md`` for the rule table, suppression syntax, and
how to add a rule.
"""

from distllm_tpu.analysis.core import (
    META_RULE_IDS,
    RULES,
    Diagnostic,
    Project,
    Rule,
    SourceFile,
    Suppression,
    analyze,
    default_source_paths,
    iter_rules,
    load_project,
    register,
)
from distllm_tpu.analysis import rules_hygiene  # noqa: F401
from distllm_tpu.analysis import rules_catalog  # noqa: F401
from distllm_tpu.analysis import rules_tpu  # noqa: F401
from distllm_tpu.analysis.cli import JSON_SCHEMA_VERSION, build_report, main

__all__ = [
    'META_RULE_IDS',
    'RULES',
    'Diagnostic',
    'Project',
    'Rule',
    'SourceFile',
    'Suppression',
    'analyze',
    'default_source_paths',
    'iter_rules',
    'load_project',
    'register',
    'JSON_SCHEMA_VERSION',
    'build_report',
    'main',
]
