"""distlint core: single-parse static analysis with structured suppression.

The framework behind ``scripts/distlint.py`` and the tier-1 lint bridge
(``tests/test_lint.py``). Design constraints, in order:

- **dependency-free** — stdlib ``ast`` + ``tokenize`` only, importable on
  any backend (the same bar as the observability stack);
- **one parse per file** — every rule runs over a shared
  :class:`SourceFile` (AST + comment map built once), replacing the
  legacy ``test_lint.py`` pattern of re-walking the tree per rule;
- **suppression is structured and audited** — the only escape hatch is
  an inline ``# distlint: disable=<rule-id> -- <justification>`` comment;
  a suppression without a justification, naming an unknown rule, or
  matching no finding is itself a finding (the framework's meta rules),
  so the allowlist can never silently rot;
- **comments are read from the token stream**, never from raw line
  regexes — a suppression spelled inside a string literal (e.g. a test
  fixture snippet) is data, not a directive.

Rules subclass :class:`Rule` and register with :func:`register`; the
driver is :func:`analyze`. Cross-file context (the instruments.py
catalogs) lives on :class:`Project` and is computed lazily, once.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path

SEVERITIES = ('error', 'warning')

# Inline directive grammar. Only real COMMENT tokens are consulted, so
# these spellings inside string literals (fixtures, docs) are inert.
_DISABLE_RE = re.compile(
    r'distlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)'
    r'(?:\s+--\s*(.*\S))?\s*$'
)
_MARKER_RE = re.compile(r'distlint:\s*(hot-path|traced)\b')
_GUARDED_RE = re.compile(r'guarded by self\.([A-Za-z_][A-Za-z0-9_]*)')

# Meta rule ids the framework itself emits (not in the registry; they
# cannot be suppressed — the audit trail must not be able to hide itself).
SYNTAX_ERROR = 'syntax-error'
SUPPRESSION_UNJUSTIFIED = 'suppression-unjustified'
SUPPRESSION_UNUSED = 'suppression-unused'
SUPPRESSION_UNKNOWN_RULE = 'suppression-unknown-rule'
META_RULE_IDS = (
    SYNTAX_ERROR,
    SUPPRESSION_UNJUSTIFIED,
    SUPPRESSION_UNUSED,
    SUPPRESSION_UNKNOWN_RULE,
)


@dataclass(frozen=True)
class Diagnostic:
    """One finding: ``rule_id`` at ``path:line`` with a message."""

    rule_id: str
    path: str  # repo-relative posix path
    line: int
    message: str
    severity: str = 'error'

    @property
    def location(self) -> str:
        return f'{self.path}:{self.line}'

    def format(self) -> str:
        return (
            f'{self.location}: {self.severity}: '
            f'[{self.rule_id}] {self.message}'
        )

    def to_dict(self) -> dict[str, object]:
        return {
            'rule_id': self.rule_id,
            'path': self.path,
            'line': self.line,
            'severity': self.severity,
            'message': self.message,
        }


@dataclass
class Suppression:
    """One ``# distlint: disable=...`` directive.

    ``line`` is where the comment sits; ``target_line`` is the line whose
    findings it suppresses — the same line for a trailing comment, the
    next line for a standalone comment line (so long statements can carry
    the directive above themselves).
    """

    line: int
    target_line: int
    rule_ids: tuple[str, ...]
    justification: str
    hits: int = 0

    def matches(self, diag: Diagnostic) -> bool:
        return (
            diag.line == self.target_line and diag.rule_id in self.rule_ids
        )


class SourceFile:
    """One parsed source file: text, AST, comment map, directives.

    Built once per file per run; every rule reads from here. ``tree`` is
    ``None`` when the file does not parse (the driver emits a
    ``syntax-error`` diagnostic and skips rule dispatch for the file).
    """

    def __init__(self, rel: str, text: str, path: Path | None = None):
        self.rel = rel
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(text, filename=rel)
        except SyntaxError as exc:
            self.parse_error = exc
        # line -> comment text (without the leading '#'), from the token
        # stream so string-literal look-alikes never register.
        self.comments: dict[int, str] = {}
        # line -> True when the comment is the only thing on its line.
        self._standalone: dict[int, bool] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                line_no, col = tok.start
                self.comments[line_no] = tok.string.lstrip('#').strip()
                self._standalone[line_no] = not tok.line[:col].strip()
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # unparseable files already carry a syntax-error finding
        # Shared walk caches: rules iterate these instead of re-walking
        # the tree (the single-parse goal extends to single-walk).
        self._nodes: list[ast.AST] | None = None
        self._functions: list[tuple[str, ast.AST]] | None = None
        self.suppressions: list[Suppression] = []
        for line_no, comment in sorted(self.comments.items()):
            match = _DISABLE_RE.search(comment)
            if match is None:
                continue
            ids = tuple(
                part.strip() for part in match.group(1).split(',')
                if part.strip()
            )
            target = (
                line_no + 1 if self._standalone.get(line_no) else line_no
            )
            self.suppressions.append(
                Suppression(
                    line=line_no,
                    target_line=target,
                    rule_ids=ids,
                    justification=(match.group(2) or '').strip(),
                )
            )

    @classmethod
    def from_path(cls, path: Path, root: Path) -> 'SourceFile':
        try:
            rel = path.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:  # outside the root: keep the absolute spelling
            rel = path.resolve().as_posix()
        return cls(rel, path.read_text(), path=path)

    @classmethod
    def from_text(
        cls, text: str, rel: str = 'distllm_tpu/_fixture.py'
    ) -> 'SourceFile':
        """Build a virtual file (tests / fixtures). ``rel`` controls which
        path-scoped rules consider it theirs."""
        return cls(rel, text)

    # ---------------------------------------------------------- markers
    def markers(self, kind: str) -> set[int]:
        """Lines carrying ``# distlint: <kind>`` (``hot-path``/``traced``)."""
        out = set()
        for line_no, comment in self.comments.items():
            match = _MARKER_RE.search(comment)
            if match and match.group(1) == kind:
                out.add(line_no)
        return out

    def guarded_annotations(self) -> dict[int, str]:
        """Lines carrying ``# guarded by self.<lock>`` -> lock attr name."""
        out: dict[int, str] = {}
        for line_no, comment in self.comments.items():
            match = _GUARDED_RE.search(comment)
            if match:
                out[line_no] = match.group(1)
        return out

    # ---------------------------------------------------------- helpers
    def nodes(self) -> list[ast.AST]:
        """Every node of the tree, walked once and cached — rules iterate
        this instead of re-running ``ast.walk`` per rule."""
        if self._nodes is None:
            assert self.tree is not None
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def functions(self):
        """``(qualname, node)`` for every function/method, with
        ``Class.method`` / ``outer.<locals>.inner`` dotted qualnames
        (computed once, cached)."""
        if self._functions is not None:
            return self._functions

        out: list[tuple[str, ast.AST]] = []

        def visit(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = prefix + child.name
                    out.append((qual, child))
                    visit(child, qual + '.<locals>.')
                elif isinstance(child, ast.ClassDef):
                    visit(child, prefix + child.name + '.')
                else:
                    visit(child, prefix)

        assert self.tree is not None
        visit(self.tree, '')
        self._functions = out
        return out


class Project:
    """The analyzed file set plus lazily-computed cross-file context."""

    INSTRUMENTS_REL = 'distllm_tpu/observability/instruments.py'

    def __init__(self, root: Path, files: list[SourceFile]):
        self.root = Path(root)
        self.files = files
        self._by_rel = {f.rel: f for f in files}
        self._catalog_cache: dict[str, frozenset[str]] = {}

    def file(self, rel: str) -> SourceFile | None:
        return self._by_rel.get(rel)

    # ------------------------------------------------- catalog extraction
    def _instruments_tree(self) -> ast.Module | None:
        source = self.file(self.INSTRUMENTS_REL)
        if source is not None and source.tree is not None:
            return source.tree
        # Running on a path subset must not weaken catalog rules: fall
        # back to reading the catalog straight from the repo.
        path = self.root / self.INSTRUMENTS_REL
        try:
            return ast.parse(path.read_text(), filename=str(path))
        except (OSError, SyntaxError):
            return None

    def metric_catalog(self) -> frozenset[str]:
        """Metric names registered in instruments.py: the first string
        argument of every ``*.counter/gauge/histogram(...)`` call."""
        cached = self._catalog_cache.get('metrics')
        if cached is not None:
            return cached
        names: set[str] = set()
        tree = self._instruments_tree()
        if tree is not None:
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ('counter', 'gauge', 'histogram')
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    names.add(node.args[0].value)
        result = frozenset(names)
        self._catalog_cache['metrics'] = result
        return result

    def frozenset_catalog(self, name: str) -> frozenset[str]:
        """String members of a ``NAME = frozenset({...})`` assignment in
        instruments.py (flight kinds, trace categories, compile phases)."""
        cached = self._catalog_cache.get(name)
        if cached is not None:
            return cached
        members: set[str] = set()
        tree = self._instruments_tree()
        if tree is not None:
            for node in ast.walk(tree):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Name) and tgt.id == name):
                        continue
                    call = node.value  # frozenset({...})
                    if isinstance(call, ast.Call) and call.args:
                        members |= {
                            el.value
                            for el in getattr(call.args[0], 'elts', [])
                            if isinstance(el, ast.Constant)
                            and isinstance(el.value, str)
                        }
        result = frozenset(members)
        self._catalog_cache[name] = result
        return result


class Rule:
    """One invariant. Subclass, set the class attributes, implement
    :meth:`check`, and decorate with :func:`register`.

    ``check(source, project)`` yields :class:`Diagnostic` for one file;
    ``check_project(project)`` (optional) runs once per analysis for
    cross-file invariants (e.g. "the catalog parsed non-empty").
    """

    id: str = ''
    description: str = ''
    severity: str = 'error'

    def applies(self, source: SourceFile) -> bool:
        """Path scope; the default is every analyzed file."""
        return True

    def check(self, source: SourceFile, project: Project):
        raise NotImplementedError

    def check_project(self, project: Project):
        return ()

    # Shared scope helpers -------------------------------------------------
    @staticmethod
    def in_package(source: SourceFile) -> bool:
        return source.rel.startswith('distllm_tpu/')

    def diag(self, source: SourceFile, line: int, message: str) -> Diagnostic:
        return Diagnostic(
            rule_id=self.id,
            path=source.rel,
            line=line,
            message=message,
            severity=self.severity,
        )


RULES: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    """Class decorator: instantiate and add to the global registry."""
    rule = cls()
    if not rule.id:
        raise ValueError(f'{cls.__name__} has no id')
    if rule.id in RULES or rule.id in META_RULE_IDS:
        raise ValueError(f'duplicate rule id {rule.id!r}')
    if rule.severity not in SEVERITIES:
        raise ValueError(f'{rule.id}: bad severity {rule.severity!r}')
    RULES[rule.id] = rule
    return cls


def iter_rules(ids=None) -> list[Rule]:
    """Registered rules, optionally restricted to ``ids`` (order stable)."""
    if ids is None:
        return [RULES[key] for key in sorted(RULES)]
    unknown = sorted(set(ids) - set(RULES))
    if unknown:
        raise KeyError(f'unknown rule ids: {", ".join(unknown)}')
    return [RULES[key] for key in sorted(set(ids))]


# --------------------------------------------------------------- discovery
def default_source_paths(root: Path) -> list[Path]:
    """The repo's lint surface (mirrors the legacy test_lint SOURCES)."""
    root = Path(root)
    paths = (
        list((root / 'distllm_tpu').rglob('*.py'))
        + list((root / 'scripts').glob('*.py'))
        + list((root / 'tests').glob('*.py'))
    )
    for extra in ('bench.py', '__graft_entry__.py'):
        candidate = root / extra
        if candidate.exists():
            paths.append(candidate)
    return sorted(p for p in paths if '__pycache__' not in p.parts)


def load_project(root: Path, paths=None) -> Project:
    root = Path(root)
    if paths is None:
        paths = default_source_paths(root)
    files = [SourceFile.from_path(Path(p), root) for p in paths]
    return Project(root, files)


# ------------------------------------------------------------------ driver
def analyze(
    project: Project,
    rules: list[Rule] | None = None,
    *,
    audit_suppressions: bool = True,
) -> list[Diagnostic]:
    """Run ``rules`` (default: the full registry) over ``project``.

    Each file is parsed exactly once (at :class:`SourceFile` build time);
    rules share that tree. Suppressions are applied after all rules ran,
    then audited: unjustified, unknown-rule, and unused suppressions are
    appended as meta diagnostics. ``audit_suppressions=False`` skips the
    *unused* audit (for single-rule invocations where most directives
    legitimately match nothing).
    """
    if rules is None:
        rules = iter_rules()
    active_ids = {rule.id for rule in rules}
    raw: list[Diagnostic] = []
    for rule in rules:
        raw.extend(rule.check_project(project))
    for source in project.files:
        if source.tree is None:
            error = source.parse_error
            raw.append(
                Diagnostic(
                    rule_id=SYNTAX_ERROR,
                    path=source.rel,
                    line=getattr(error, 'lineno', 1) or 1,
                    message=f'file does not parse: {error}',
                )
            )
            continue
        for rule in rules:
            if rule.applies(source):
                raw.extend(rule.check(source, project))

    kept: list[Diagnostic] = []
    for diag in raw:
        source = project.file(diag.path)
        suppressed = False
        if source is not None and diag.rule_id not in META_RULE_IDS:
            for supp in source.suppressions:
                if supp.matches(diag):
                    supp.hits += 1
                    suppressed = True
        if not suppressed:
            kept.append(diag)

    known_ids = set(RULES) | set(META_RULE_IDS)
    for source in project.files:
        for supp in source.suppressions:
            if not supp.justification:
                kept.append(
                    Diagnostic(
                        rule_id=SUPPRESSION_UNJUSTIFIED,
                        path=source.rel,
                        line=supp.line,
                        message=(
                            'suppression without a justification — write '
                            '"# distlint: disable=<rule-id> -- <why>"'
                        ),
                    )
                )
            for rule_id in supp.rule_ids:
                if rule_id in META_RULE_IDS:
                    # Meta rules are unsuppressible by design; the dead
                    # directive would otherwise accumulate silently (it
                    # never matches and meta ids never enter the unused
                    # audit), misleading readers into thinking it works.
                    kept.append(
                        Diagnostic(
                            rule_id=SUPPRESSION_UNKNOWN_RULE,
                            path=source.rel,
                            line=supp.line,
                            message=(
                                f'suppression names meta rule {rule_id!r},'
                                ' which is not suppressible'
                            ),
                        )
                    )
                elif rule_id not in known_ids:
                    kept.append(
                        Diagnostic(
                            rule_id=SUPPRESSION_UNKNOWN_RULE,
                            path=source.rel,
                            line=supp.line,
                            message=(
                                f'suppression names unknown rule '
                                f'{rule_id!r}'
                            ),
                        )
                    )
            if (
                audit_suppressions
                and supp.hits == 0
                and supp.justification
                and all(rule_id in active_ids for rule_id in supp.rule_ids)
            ):
                kept.append(
                    Diagnostic(
                        rule_id=SUPPRESSION_UNUSED,
                        path=source.rel,
                        line=supp.line,
                        message=(
                            'suppression matched no finding '
                            f'({", ".join(supp.rule_ids)}) — the code is '
                            'clean; delete the directive'
                        ),
                    )
                )
    kept.sort(key=lambda d: (d.path, d.line, d.rule_id, d.message))
    return kept
