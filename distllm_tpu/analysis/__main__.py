"""``python -m distllm_tpu.analysis`` — the distlint CLI."""

import sys

from distllm_tpu.analysis.cli import main

sys.exit(main())
