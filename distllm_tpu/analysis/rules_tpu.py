"""TPU-serving rules: the hazard classes that cost serving throughput.

Four rules, all grounded in measured failure modes of this codebase:

- ``host-sync-in-hot-path`` — a blocking device→host transfer inside the
  engine window loop or a model dispatch path serializes the pipelined
  decode stream; this is exactly the host-gap / 830-vs-1101 tok/s class
  of regression the attribution layer (PR 7/8) measures *after the
  fact*. The analyzer bans the spellings up front; the designed fetch
  points carry justified suppressions.
- ``traced-python-branch`` — an ``if``/``while``/``assert`` on a traced
  array value inside jit/pallas-reachable code either raises a
  ConcretizationTypeError or, worse, silently bakes one trace-time
  branch into the compiled executable (the silent-recompile / tracer-
  leak bug class).
- ``lock-discipline`` — attributes annotated ``# guarded by self._lock``
  may only be touched inside a matching ``with`` block: a static race
  detector for the shared state the engine thread, the aiohttp event
  loop, and watchdog threads all touch.
- ``nondeterminism-in-dispatch`` — ``time.*``/``random.*`` calls inside
  traced functions execute ONCE at trace time and bake a constant into
  the executable: the code reads as dynamic but is frozen, and
  recompiles silently resample it.

Traced-function discovery is shared: a function is traced when it is
decorated with / wrapped by ``jax.jit`` (including ``functools.partial``
forms), passed to ``pallas_call``, marked ``# distlint: traced`` on its
``def`` line, or referenced by name from an already-traced function in
the same module (a same-module transitive closure — ``lax.scan`` bodies
and helper layers are reached without a call-graph database).
"""

from __future__ import annotations

import ast

from distllm_tpu.analysis.core import (
    Diagnostic,
    Project,
    Rule,
    SourceFile,
    register,
)

# Attribute reads that never concretize a traced array: branching on
# these stays host-side/static and must not trip traced-python-branch.
_STATIC_ATTRS = frozenset(
    {'shape', 'dtype', 'ndim', 'size', 'sharding', 'format'}
)

# Call roots whose results are device values (for host-sync tracking)
# when dotted from jnp/jax, e.g. jnp.zeros(...), jax.random.split(...).
_DEVICE_MODULES = ('jnp', 'jax', 'lax')

# Method/attribute call names whose results are device values in this
# codebase: the engine's jitted executables and device-side helpers.
_DEVICE_CALL_NAMES = frozenset(
    {
        '_sample_device',
        '_sample',
        '_merge_ids',
        '_put',
        '_put_many',
        '_scatter_tokens',
        '_write_prefill',
        '_cow_copy',
    }
)
_DEVICE_CALL_SUFFIXES = ('_window', '_fn', '_paged', '_prefill')


def _func_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _dotted_root(node: ast.AST) -> str | None:
    """The leftmost name of a dotted expression (``jnp`` for
    ``jnp.sum(x)``, ``self`` for ``self._decode_window``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _static_attr_leaves(expr: ast.AST) -> set[ast.AST]:
    """AST nodes under a static attribute access (``x.shape`` etc.):
    reading these never concretizes the array, so a name seen only there
    must neither trip a branch check nor propagate trackedness."""
    leaves: set[ast.AST] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            for leaf in ast.walk(node.value):
                leaves.add(leaf)
    return leaves


# --------------------------------------------------- traced-function index
class TracedIndex:
    """Which functions in a module are jit/pallas-reachable.

    Seeds: ``@jax.jit``-style decorators, ``jax.jit(f)`` / ``pallas_call
    (f, ...)`` wrap sites anywhere in the module, and ``# distlint:
    traced`` markers on ``def`` lines. The closure step marks every
    module-local function referenced *by name* from a traced function's
    body — deliberately reference-based, not call-based, so scan/cond
    bodies passed as values are reached.
    """

    _JIT_NAMES = frozenset({'jit', 'pjit'})
    # Unary wrappers: the function operand is args[0] (plus pallas_call's
    # kernel= keyword).
    _WRAP_NAMES = frozenset({'jit', 'pjit', 'pallas_call', 'checkpoint',
                             'remat', 'custom_vjp', 'vmap', 'grad',
                             'shard_map', 'scan'})
    # Control-flow combinators take function operands at varying
    # positions (while_loop(cond, body), fori_loop(lo, hi, body),
    # cond(pred, true_fn, false_fn), switch(i, [branches...])) — every
    # argument that resolves to a module function is seeded; the other
    # operands are arrays and cannot collide with function names.
    _CONTROL_FLOW_NAMES = frozenset({'cond', 'while_loop', 'fori_loop',
                                     'switch'})

    @classmethod
    def for_source(cls, source: SourceFile) -> 'TracedIndex':
        """Per-file cache: both traced rules share one index build."""
        cached = getattr(source, '_traced_index', None)
        if cached is None:
            cached = source._traced_index = cls(source)
        return cached

    def __init__(self, source: SourceFile):
        self.functions: dict[str, ast.AST] = {}
        by_name: dict[str, list[str]] = {}
        for qual, node in source.functions():
            self.functions[qual] = node
            by_name.setdefault(node.name, []).append(qual)
        traced: set[str] = set()
        marker_lines = source.markers('traced')
        for qual, node in self.functions.items():
            if node.lineno in marker_lines:
                traced.add(qual)
            for deco in node.decorator_list:
                if self._is_jit_expr(deco):
                    traced.add(qual)
        # `k = functools.partial(f, ...)` / `k = f` bindings anywhere in
        # the module, so a wrap site spelled `pallas_call(k, ...)` still
        # seeds `f` (the repo's real kernels bind the partial on its own
        # line before the call).
        aliases: dict[str, str] = {}
        for node in source.nodes():
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            value = node.value
            if (
                isinstance(value, ast.Call)
                and _func_name(value.func) == 'partial'
                and value.args
            ):
                value = value.args[0]
            if isinstance(value, ast.Name) and value.id != tgt.id:
                aliases[tgt.id] = value.id
        for node in source.nodes():
            if not isinstance(node, ast.Call):
                continue
            name = _func_name(node.func)
            if name in self._WRAP_NAMES:
                candidates = list(node.args[:1]) + [
                    kw.value for kw in node.keywords if kw.arg == 'kernel'
                ]
            elif name in self._CONTROL_FLOW_NAMES:
                candidates = []
                for arg in node.args:
                    if isinstance(arg, (ast.List, ast.Tuple)):
                        candidates.extend(arg.elts)  # switch branch lists
                    else:
                        candidates.append(arg)
            else:
                continue
            for arg in candidates:
                # Unwrap functools.partial(kernel, ...) wrap sites.
                if (
                    isinstance(arg, ast.Call)
                    and _func_name(arg.func) == 'partial'
                    and arg.args
                ):
                    arg = arg.args[0]
                if isinstance(arg, ast.Name):
                    target = arg.id
                    for _ in range(len(aliases)):
                        if target in by_name or target not in aliases:
                            break
                        target = aliases[target]
                    traced.update(by_name.get(arg.id, ()))
                    traced.update(by_name.get(target, ()))
        # Transitive same-module closure over name references.
        changed = True
        while changed:
            changed = False
            for qual in list(traced):
                node = self.functions.get(qual)
                if node is None:
                    continue
                for ref in ast.walk(node):
                    if not isinstance(ref, ast.Name):
                        continue
                    for callee in by_name.get(ref.id, ()):
                        if callee not in traced and callee != qual:
                            traced.add(callee)
                            changed = True
        self.traced = traced

    def _is_jit_expr(self, deco: ast.AST) -> bool:
        name = _func_name(deco)
        if name in self._JIT_NAMES:
            return True
        if isinstance(deco, ast.Call):
            callee = _func_name(deco.func)
            if callee in self._JIT_NAMES:
                return True
            if callee == 'partial' and deco.args:
                return _func_name(deco.args[0]) in self._JIT_NAMES
        return False

    def traced_functions(self):
        for qual in sorted(self.traced):
            yield qual, self.functions[qual]


def _fixpoint_derived_names(fn: ast.AST, expr_is_derived) -> set[str]:
    """The shared derived-name fixpoint: repeatedly sweep ``fn``'s
    assignments (Assign / AugAssign / AnnAssign / walrus), marking every
    target name whose value ``expr_is_derived(expr, derived)`` judges
    derived, until no new names appear. Both trackers (traced-value and
    device-value) are this loop with a different predicate — keep them
    from diverging by keeping the machinery in one place."""
    derived: set[str] = set()
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            targets: list[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if node.value is None:
                    continue
                targets, value = [node.target], node.value
            elif isinstance(node, ast.NamedExpr):
                targets, value = [node.target], node.value
            else:
                continue
            if not expr_is_derived(value, derived):
                continue
            for tgt in targets:
                for leaf in ast.walk(tgt):
                    if (
                        isinstance(leaf, ast.Name)
                        and leaf.id not in derived
                    ):
                        derived.add(leaf.id)
                        changed = True
    return derived


def _isinstance_arg_names(expr: ast.AST) -> set[ast.AST]:
    """``ast.Name`` nodes appearing inside ``isinstance(...)`` arguments.
    ``isinstance`` inspects the PYTHON type of its operand — for traced
    code that is the pytree-container class (the ``QuantizedKV``-vs-bare-
    array dispatch in ops/paged_attention.py), resolved at trace time and
    never concretizing a tracer — so these occurrences are static exactly
    like ``.shape``/``.dtype`` attribute reads."""
    names: set[ast.AST] = set()
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Call)
            and _dotted_root(node.func) == 'isinstance'
        ):
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        names.add(sub)
    return names


def _jnp_derived_names(fn: ast.AST) -> set[str]:
    """Names bound (directly or transitively) from ``jnp.*``/``lax.*``/
    ``jax.*`` expressions inside ``fn``. Parameters are deliberately NOT
    assumed traced — branching on config objects threaded through traced
    code is normal; only locally device-derived values are tracked."""

    def expr_is_derived(expr: ast.AST, derived: set[str]) -> bool:
        # isinstance results are static bools (trace-time type dispatch),
        # so `quantized = isinstance(cache, QuantizedKV)` must not mark
        # `quantized` as device-derived.
        statics = _static_attr_leaves(expr) | _isinstance_arg_names(expr)
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                root = _dotted_root(node.func)
                if root in _DEVICE_MODULES:
                    return True
            elif (
                isinstance(node, ast.Name)
                and node.id in derived
                and node not in statics
            ):
                return True
        return False

    return _fixpoint_derived_names(fn, expr_is_derived)


def _test_uses_traced_value(test: ast.AST, derived: set[str]) -> bool:
    """True when evaluating ``test`` concretizes a tracked array: either
    a direct ``jnp.*``/``lax.*`` call, or a tracked name used as a value
    (not merely via a static attribute like ``.shape`` or an
    ``isinstance`` type dispatch)."""
    static_bases = _static_attr_leaves(test) | _isinstance_arg_names(test)
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            root = _dotted_root(node.func)
            if root in ('jnp', 'lax'):
                return True
        if (
            isinstance(node, ast.Name)
            and node.id in derived
            and node not in static_bases
        ):
            return True
    return False


@register
class TracedPythonBranchRule(Rule):
    """No Python ``if``/``while``/``assert`` on a traced array value
    inside jit/pallas-reachable functions — concretizing a tracer either
    raises at trace time or silently freezes one branch into the
    executable. Use ``jnp.where`` / ``lax.cond`` / ``lax.while_loop``."""

    id = 'traced-python-branch'
    description = 'Python control flow on a traced array value'

    def applies(self, source: SourceFile) -> bool:
        return self.in_package(source)

    def check(self, source: SourceFile, project: Project):
        index = TracedIndex.for_source(source)
        for qual, fn in index.traced_functions():
            derived = _jnp_derived_names(fn)
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    kind = 'if' if isinstance(node, ast.If) else 'while'
                    if _test_uses_traced_value(node.test, derived):
                        yield self.diag(
                            source,
                            node.lineno,
                            f'`{kind}` on a traced array value in traced '
                            f'function {qual!r} — use lax.cond/'
                            'lax.while_loop/jnp.where',
                        )
                elif isinstance(node, ast.Assert):
                    if _test_uses_traced_value(node.test, derived):
                        yield self.diag(
                            source,
                            node.lineno,
                            f'`assert` on a traced array value in traced '
                            f'function {qual!r} — use '
                            'checkify or a static check',
                        )


@register
class NondeterminismInDispatchRule(Rule):
    """No ``time.*`` / ``random.*`` / ``np.random.*`` calls inside traced
    functions: they run once at trace time, baking that sample into the
    compiled executable — the code reads as dynamic but is frozen, and a
    silent recompile resamples it. Use ``jax.random`` with explicit keys
    (device-side) or hoist the host call out of the traced region."""

    id = 'nondeterminism-in-dispatch'
    description = 'host time/random call inside a traced function'

    _ROOTS = frozenset({'time', 'random'})

    def applies(self, source: SourceFile) -> bool:
        return self.in_package(source)

    def check(self, source: SourceFile, project: Project):
        index = TracedIndex.for_source(source)
        for qual, fn in index.traced_functions():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if not isinstance(func, ast.Attribute):
                    continue
                root = _dotted_root(func)
                spelled = None
                if root in self._ROOTS:
                    spelled = f'{root}.{func.attr}'
                elif (
                    root in ('np', 'numpy')
                    and isinstance(func.value, ast.Attribute)
                    and func.value.attr == 'random'
                ):
                    spelled = f'{root}.random.{func.attr}'
                if spelled is not None:
                    yield self.diag(
                        source,
                        node.lineno,
                        f'{spelled}() inside traced function {qual!r} '
                        'runs once at trace time and bakes a constant '
                        'into the executable',
                    )


# ------------------------------------------------------ host-sync-in-hot-path
@register
class HostSyncInHotPathRule(Rule):
    """No blocking device→host transfer inside the engine window loop or
    a model dispatch path. Flags ``np.asarray``/``np.array``/
    ``jax.device_get`` calls, ``.item()``/``.tolist()``/
    ``.block_until_ready()`` method calls, and ``float()``/``int()``/
    ``bool()`` of a device-derived value. The designed fetch points (one
    per processed window) carry justified suppressions — everything else
    is a stray sync that re-serializes the pipelined dispatch stream."""

    id = 'host-sync-in-hot-path'
    description = 'blocking device→host sync inside a serving hot path'

    # Built-in hot-path designations: the engine window loop and the
    # model-side dispatch entry points. Extend with `# distlint:
    # hot-path` on a def line.
    HOT_PATHS: dict[str, tuple[str, ...]] = {
        'distllm_tpu/generate/engine/engine.py': (
            'LLMEngine.step',
            'LLMEngine._dispatch_window',
            'LLMEngine._dispatch_spec_window',
            'LLMEngine._process_window',
            'LLMEngine._process_spec_window',
            'LLMEngine._process_chunk_entries',
            'LLMEngine._run_to_completion',
            # The pipelined loop body behind _run_to_completion's
            # recovery wrapper (ISSUE 15), plus the recovery/deadline
            # helpers that run between windows: none may add a stray
            # sync (time.sleep backoff is host-only, not a device sync).
            'LLMEngine._serve_pipelined',
            'LLMEngine._recover',
            'LLMEngine._expire_deadlines',
            'LLMEngine._sample_device',
            'LLMEngine._window_kmax',
            'LLMEngine._window_budget',
            'LLMEngine._reserve_shortfall',
            # KV-tier spill/promotion (docs/prefix_caching.md "Tier
            # hierarchy"): runs inside the serving loop under pool
            # pressure. Exactly three designed syncs — the spill's K/V
            # fetch pair and the promotion-completion probe — each with
            # a justified suppression; anything else added here would
            # re-serialize the async prefetch the tier exists for.
            'LLMEngine._spill_blocks',
            'LLMEngine._spill_chunk',
            'LLMEngine._begin_promotion',
            'LLMEngine._finish_promotions',
            'LLMEngine._evict_cached_blocks',
            # Quantize-at-write landing site (docs/serving.md "Quantized
            # KV cache"): the prefill scatter that computes per-block
            # absmax scales on device. Entirely jit-traced — any host
            # sync added here would fire per admitted prefill.
            '_write_prefill_all_layers',
            '_write_prefill_all_layers_quantized',
        ),
        'distllm_tpu/models/mistral.py': (
            'mixed_window',
            'spec_window',
            'decode_step',
            'decode_loop',
            'prefill_paged',
        ),
        # The quantize-at-write / rescale-on-append path (docs/serving.md
        # "Quantized KV cache"): these run inside every traced serving
        # dispatch that touches an int8 pool, so a stray sync here
        # serializes every window — same contract as the engine loop.
        'distllm_tpu/ops/paged_attention.py': (
            'quantize_kv_rows',
            '_rescale_int8_blocks',
            '_gather_kv_blocks',
            'write_token_kv',
            '_write_token_kv_quantized',
            'write_chunk_kv',
            '_write_chunk_kv_quantized',
            'write_prefill_kv',
            '_write_prefill_kv_quantized',
        ),
        # Device sampling and sampled speculative verification
        # (docs/speculative.md "Sampled verification"): these trace into
        # every decode/mixed/spec dispatch, so any host sync here fires
        # once per window — the packed verify result has exactly one
        # audited fetch point in the engine, not inside these kernels.
        'distllm_tpu/ops/sampling.py': (
            'fold_row_keys',
            'filter_logits',
            'sample_tokens',
            'sample_tokens_windowed',
            'verify_spans',
        ),
        # Peer KV handoff (docs/routing.md "Peer KV tier"): the tier walk
        # and the fabric fetch/serve run inside the serving loop's
        # promotion path (and, server-side, concurrent WITH a sibling's
        # loop). All host/zmq/numpy work by design — a device sync added
        # here would stall a replica on its PEER's traffic.
        'distllm_tpu/generate/engine/kv_cache.py': (
            'PeerKVTier.contains',
            'PeerKVTier.get',
            'HostKVTier.lookup',
            'HostKVTier.get',
            'HostKVTier.contains_local',
            'HostKVTier.encoded_local',
        ),
        'distllm_tpu/parallel/fabric.py': (
            'KVBlockServer._serve',
            'KVBlockClient.request',
        ),
    }

    _SYNC_CALLS = frozenset({'asarray', 'array', 'device_get'})
    _SYNC_METHODS = frozenset({'item', 'tolist', 'block_until_ready'})
    _CASTS = frozenset({'float', 'int', 'bool'})

    def applies(self, source: SourceFile) -> bool:
        return self.in_package(source)

    def check_project(self, project: Project):
        """Every HOT_PATHS entry must resolve to a real function — a
        rename would otherwise silently drop hot-path coverage, the same
        silent-rot class the suppression-unused audit closes for
        directives. Files absent from a path-subset run are skipped."""
        for rel, prefixes in self.HOT_PATHS.items():
            source = project.file(rel)
            if source is None or source.tree is None:
                continue
            bases = {
                qual.split('.<locals>.')[0] for qual, _ in source.functions()
            }
            for prefix in prefixes:
                if prefix not in bases:
                    yield Diagnostic(
                        rule_id=self.id,
                        path=rel,
                        line=1,
                        message=(
                            f'HOT_PATHS entry {prefix!r} resolves to no '
                            'function in this file — stale after a '
                            'rename; update HostSyncInHotPathRule.'
                            'HOT_PATHS or coverage silently shrinks'
                        ),
                    )

    def _hot_functions(self, source: SourceFile):
        prefixes = self.HOT_PATHS.get(source.rel, ())
        marker_lines = source.markers('hot-path')
        hot: list[tuple[str, ast.AST]] = []
        for qual, node in source.functions():
            base = qual.split('.<locals>.')[0]
            if base in prefixes or node.lineno in marker_lines:
                hot.append((qual, node))
        # Nested functions inherit their enclosing hot path (the window
        # loop's process_one/drain_one closures) — handled by the
        # `.split('.<locals>.')[0]` base match above.
        return hot

    @staticmethod
    def _device_derived_names(fn: ast.AST) -> set[str]:
        """Names bound from device-producing calls: jnp/jax expressions,
        the engine's jitted executables (``self._decode_window`` et al.),
        and anything derived from those. An ``np.asarray(...)`` result is
        HOST data — the sync is flagged at the asarray itself, and
        downstream ``int()`` of the host copy is free."""

        def call_is_device(node: ast.Call) -> bool:
            root = _dotted_root(node.func)
            if root in _DEVICE_MODULES:
                return not (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in ('device_get',)
                )
            name = _func_name(node.func)
            if name is None:
                return False
            if name in _DEVICE_CALL_NAMES:
                return True
            return name.endswith(_DEVICE_CALL_SUFFIXES)

        def expr_is_derived(expr: ast.AST, derived: set[str]) -> bool:
            statics = _static_attr_leaves(expr)
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    name = _func_name(node.func)
                    root = _dotted_root(node.func)
                    if root in ('np', 'numpy') or name in ('asarray',):
                        return False  # host copy: tracking stops here
                    if call_is_device(node):
                        return True
                elif (
                    isinstance(node, ast.Name)
                    and node.id in derived
                    and node not in statics
                ):
                    return True
            return False

        return _fixpoint_derived_names(fn, expr_is_derived)

    @staticmethod
    def _host_derived_names(fn: ast.AST) -> set[str]:
        """Names bound from host copies (``np.*``/``asarray`` results and
        anything derived from those with no device data flowing in).
        ``.item()``/``.tolist()`` of these is free — the sync already
        happened (and was flagged or suppressed) at the fetch point."""

        def expr_is_derived(expr: ast.AST, derived: set[str]) -> bool:
            has_host = False
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    root = _dotted_root(node.func)
                    name = _func_name(node.func)
                    if root in ('np', 'numpy') or name == 'asarray':
                        has_host = True
                    elif (
                        root in _DEVICE_MODULES
                        or name in _DEVICE_CALL_NAMES
                        or (name or '').endswith(_DEVICE_CALL_SUFFIXES)
                    ):
                        return False  # device data flows in
                elif isinstance(node, ast.Name) and node.id in derived:
                    has_host = True
            return has_host

        return _fixpoint_derived_names(fn, expr_is_derived)

    def check(self, source: SourceFile, project: Project):
        seen: set[int] = set()
        for qual, fn in self._hot_functions(source):
            derived = self._device_derived_names(fn)
            host = self._host_derived_names(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if id(node) in seen:
                    continue
                seen.add(id(node))
                func = node.func
                # np.asarray / np.array / jax.device_get
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in self._SYNC_CALLS
                    and _dotted_root(func) in ('np', 'numpy', 'jax')
                ):
                    yield self.diag(
                        source,
                        node.lineno,
                        f'{_dotted_root(func)}.{func.attr}() in hot path '
                        f'{qual!r} blocks on device→host transfer',
                    )
                    continue
                # .item() / .tolist() / .block_until_ready() — skipped
                # when the receiver is a pure host copy (the sync already
                # happened at the tracked-and-suppressed fetch point);
                # unknown receivers stay flagged, conservatively.
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in self._SYNC_METHODS
                    and not node.args
                ):
                    receiver = func.value
                    recv_host = any(
                        isinstance(leaf, ast.Name) and leaf.id in host
                        for leaf in ast.walk(receiver)
                    )
                    recv_device = any(
                        isinstance(leaf, ast.Name) and leaf.id in derived
                        for leaf in ast.walk(receiver)
                    )
                    if recv_host and not recv_device:
                        continue
                    yield self.diag(
                        source,
                        node.lineno,
                        f'.{func.attr}() in hot path {qual!r} blocks on '
                        'device→host transfer',
                    )
                    continue
                # jax.block_until_ready(x) function form
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == 'block_until_ready'
                ):
                    yield self.diag(
                        source,
                        node.lineno,
                        f'block_until_ready() in hot path {qual!r} '
                        'blocks the dispatch stream',
                    )
                    continue
                # float()/int()/bool() of a device-derived value
                if (
                    isinstance(func, ast.Name)
                    and func.id in self._CASTS
                    and len(node.args) == 1
                ):
                    arg = node.args[0]
                    uses_device = any(
                        isinstance(leaf, ast.Name) and leaf.id in derived
                        for leaf in ast.walk(arg)
                    ) or any(
                        isinstance(leaf, ast.Call)
                        and _dotted_root(leaf.func) in _DEVICE_MODULES
                        for leaf in ast.walk(arg)
                    )
                    if uses_device:
                        yield self.diag(
                            source,
                            node.lineno,
                            f'{func.id}() of a device value in hot path '
                            f'{qual!r} forces a blocking transfer',
                        )


# ------------------------------------------------------------ lock-discipline
@register
class LockDisciplineRule(Rule):
    """Attributes annotated ``# guarded by self.<lock>`` on their
    assignment line may only be read or written inside a ``with
    self.<lock>:`` block in the same class — a static race detector for
    state shared between the engine thread, the aiohttp event loop, and
    watchdog threads. Constructors (``__init__``/``__new__``) are exempt
    (the object is not yet shared); a method *called with the lock held*
    documents that with ``# guarded by self.<lock>`` on its ``def``
    line."""

    id = 'lock-discipline'
    description = 'guarded attribute touched outside its lock'

    def applies(self, source: SourceFile) -> bool:
        return self.in_package(source)

    def check(self, source: SourceFile, project: Project):
        annotations = source.guarded_annotations()
        if not annotations:
            return
        assert source.tree is not None
        for node in source.nodes():
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(source, node, annotations)

    @staticmethod
    def _with_holds_lock(node: ast.With, lock: str) -> bool:
        for item in node.items:
            expr = item.context_expr
            if (
                isinstance(expr, ast.Attribute)
                and expr.attr == lock
                and isinstance(expr.value, ast.Name)
                and expr.value.id == 'self'
            ):
                return True
        return False

    _CONSTRUCTORS = frozenset({'__init__', '__new__'})

    def _check_class(self, source, cls: ast.ClassDef, annotations):
        # attr -> lock name, discovered from annotated self.X assignments
        # anywhere in the class. Exempt: constructors (the object is not
        # yet shared) and methods whose DEF line carries the annotation
        # (documented as called with the lock held). The annotation
        # itself exempts NOTHING outside a constructor — an unlocked
        # write that carries `# guarded by self._lock` both declares the
        # guard and violates it, and letting the declaration silence the
        # finding would be an unaudited suppression channel.
        guarded: dict[str, str] = {}
        exempt_methods: set[ast.AST] = set()
        methods = [
            node
            for node in cls.body
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for method in methods:
            if annotations.get(method.lineno):
                exempt_methods.add(method)  # def-line: callers hold it
            if method.name in self._CONSTRUCTORS:
                exempt_methods.add(method)
            for node in ast.walk(method):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                    targets = [node.target]
                for tgt in targets:
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == 'self'
                    ):
                        continue
                    end = getattr(node, 'end_lineno', tgt.lineno)
                    lock = annotations.get(tgt.lineno) or annotations.get(end)
                    if lock and lock != tgt.attr:
                        guarded[tgt.attr] = lock
        if not guarded:
            return
        for method in methods:
            if method in exempt_methods:
                continue
            yield from self._check_method(source, cls, method, guarded)

    def _check_method(self, source, cls, method, guarded):
        locked_lines: dict[str, set[int]] = {}
        for node in ast.walk(method):
            if not isinstance(node, ast.With):
                continue
            for lock in set(guarded.values()):
                if not self._with_holds_lock(node, lock):
                    continue
                lines = set(
                    range(node.lineno, (node.end_lineno or node.lineno) + 1)
                )
                # A closure DEFINED under the lock executes LATER,
                # without it — the watchdog-timer-callback race class.
                # Its body lines are not lock-covered.
                for inner in ast.walk(node):
                    if isinstance(
                        inner,
                        (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef),
                    ):
                        lines -= set(
                            range(
                                inner.lineno,
                                (inner.end_lineno or inner.lineno) + 1,
                            )
                        )
                locked_lines.setdefault(lock, set()).update(lines)
        for node in ast.walk(method):
            if not (
                isinstance(node, ast.Attribute)
                and node.attr in guarded
                and isinstance(node.value, ast.Name)
                and node.value.id == 'self'
            ):
                continue
            lock = guarded[node.attr]
            if node.lineno in locked_lines.get(lock, ()):
                continue
            yield self.diag(
                source,
                node.lineno,
                f'{cls.name}.{method.name} touches self.{node.attr} '
                f'(guarded by self.{lock}) outside `with self.{lock}:`',
            )
