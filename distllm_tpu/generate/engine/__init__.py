"""Paged-KV continuous-batching generation engine (vLLM replacement)."""

from distllm_tpu.generate.engine.engine import (
    EngineConfig,
    LLMEngine,
    Request,
    RequestState,
    SamplingParams,
)
from distllm_tpu.generate.engine.kv_cache import PagedKVCache

__all__ = [
    'EngineConfig',
    'LLMEngine',
    'PagedKVCache',
    'Request',
    'RequestState',
    'SamplingParams',
]
