"""Paged-KV continuous-batching generation engine (vLLM replacement)."""

from distllm_tpu.generate.engine.engine import (
    EngineConfig,
    LLMEngine,
    Request,
    RequestState,
    SamplingParams,
)
from distllm_tpu.generate.engine.kv_cache import PagedKVCache
from distllm_tpu.generate.engine.spec import PromptLookupDrafter

__all__ = [
    'EngineConfig',
    'LLMEngine',
    'PagedKVCache',
    'PromptLookupDrafter',
    'Request',
    'RequestState',
    'SamplingParams',
]
