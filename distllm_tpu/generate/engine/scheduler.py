"""Continuous-batching scheduler: native C++ core with a Python twin.

The policy layer of the generation engine (the analogue of vLLM's scheduler,
SURVEY.md §2.4 N1) extracted behind one interface:

- :class:`NativeScheduler` — ctypes binding over
  ``distllm_tpu/native/scheduler.cpp``; owns the block free-list, slot
  table, waiting queue, and preemption policy in C++.
- :class:`PyScheduler` — pure-Python implementation of the identical
  policy (fallback when no compiler is available; also the differential-
  test oracle).

Policy contract (both implementations, tested in lockstep):

- ``admit_next`` pops the waiting-queue head into the lowest free slot when
  blocks for ``num_tokens + 1`` are available (all-or-nothing). Blocks a
  request already carries (a borrowed prefix-cache prefix) count toward
  that budget: only the shortfall is allocated.
- ``prepare_decode(k, rids=None, ks=None)`` guarantees every running
  sequence can take ``k`` more tokens (k > 1 backs multi-step fused
  decode windows), preempting the youngest (highest rid) on OOM —
  recompute preemption: blocks freed, request to the FRONT of the
  waiting queue. ``rids`` restricts the guarantee to the listed rows
  (mixed serving windows: rows whose prefill chunks ride the window get
  no speculative decode headroom — their blocks were fully allocated at
  admission). ``ks`` (parallel to ``rids``) grants PER-ROW headroom
  instead of the uniform ``k`` — speculative verify windows reserve each
  row's own ``1 + draft`` span rather than the batch max
  (docs/speculative.md).
- ``trim(rid)`` returns owned tail blocks beyond what ``num_tokens + 1``
  needs to the free list (newest first, so a later extension re-pops the
  identical blocks) — how a speculative window's rejected-suffix
  reservation is rolled back to the never-drafted state.
- Block 0 is the reserved trash block and is never allocated.

Borrowed prefixes (automatic prefix caching, docs/prefix_caching.md): a
request's block row may start with blocks OWNED BY THE PREFIX CACHE —
attached at ``add`` (cache hit) or marked afterwards with ``lend_prefix``
(this request's freshly prefilled prompt blocks entering the cache, OR
blocks mid-promotion from the host/disk KV tier — the engine lends them
the moment the promotion scatter is dispatched, so promotion-pending rows
behave exactly like borrowed prefixes in BOTH front-ends: counted toward
budgets, never freed to the free list mid-promotion, surviving
preemption). The scheduler never returns borrowed blocks to its free
list: ``finish`` and preemption free only the owned tail, and the cache
hands evicted blocks back through ``release_blocks``. Refcounts/eviction
policy live in ``kv_cache.PrefixCache``; the tier pools live in
``kv_cache.HostKVTier``/``DiskKVTier``; the scheduler only knows "the
first N blocks of this row are not mine to free".
"""

from __future__ import annotations

import ctypes
from collections import deque
from dataclasses import dataclass, field
from typing import Protocol


class SchedulerExhausted(RuntimeError):
    """The block pool cannot serve even a lone request; raise to the caller.

    ``preempted`` carries rids already moved to the waiting queue by the
    same (failed) ``prepare_decode`` call — the engine must mark those
    requests WAITING before propagating, or its state diverges from the
    scheduler's.
    """

    def __init__(self, message: str, preempted: list[int] | None = None):
        super().__init__(message)
        self.preempted = list(preempted or [])


class Scheduler(Protocol):
    def add(
        self, rid: int, num_tokens: int, cached_blocks: 'list[int] | tuple' = ()
    ) -> None: ...

    def admit_next(self) -> int | None: ...

    def prepare_decode(
        self,
        k: int = 1,
        rids: 'list[int] | None' = None,
        ks: 'list[int] | None' = None,
    ) -> list[int]: ...

    def append_token(self, rid: int) -> None: ...

    def trim(self, rid: int) -> int: ...

    def finish(self, rid: int) -> None: ...

    def lend_prefix(self, rid: int, num_blocks: int) -> None: ...

    def release_blocks(self, blocks: list[int]) -> None: ...

    def num_borrowed(self, rid: int) -> int: ...

    def slot(self, rid: int) -> int: ...

    def running(self) -> list[tuple[int, int]]: ...

    def block_row(self, rid: int) -> list[int]: ...

    @property
    def num_free_blocks(self) -> int: ...

    @property
    def num_running(self) -> int: ...

    @property
    def num_waiting(self) -> int: ...

    @property
    def has_unfinished(self) -> bool: ...


@dataclass
class _PyRequest:
    rid: int
    num_tokens: int
    blocks: list[int] = field(default_factory=list)
    slot: int = -1
    # First `num_borrowed` blocks are prefix-cache property: never freed
    # to the scheduler free list, and they survive recompute preemption.
    num_borrowed: int = 0


class PyScheduler:
    """Pure-Python scheduler (same observable policy as the C++ core)."""

    def __init__(self, num_blocks: int, block_size: int, max_num_seqs: int) -> None:
        if num_blocks < 2:
            raise ValueError('need >= 2 blocks (block 0 is reserved)')
        self._block_size = block_size
        self._free = list(range(num_blocks - 1, 0, -1))
        self._waiting: deque[int] = deque()
        self._slots: list[int] = [-1] * max_num_seqs
        self._requests: dict[int, _PyRequest] = {}

    def _blocks_needed(self, tokens: int) -> int:
        return (tokens + self._block_size - 1) // self._block_size

    def add(
        self, rid: int, num_tokens: int, cached_blocks: 'list[int] | tuple' = ()
    ) -> None:
        if rid in self._requests:
            raise ValueError(f'duplicate request id {rid}')
        self._requests[rid] = _PyRequest(
            rid,
            num_tokens,
            blocks=list(cached_blocks),
            num_borrowed=len(cached_blocks),
        )
        self._waiting.append(rid)

    def admit_next(self) -> int | None:
        if not self._waiting:
            return None
        try:
            slot = self._slots.index(-1)
        # distlint: disable=swallowed-exception -- no-free-slot is a normal admission outcome (None = defer), not a degradation; the wrapper counts deferrals
        except ValueError:
            return None
        rid = self._waiting[0]
        req = self._requests[rid]
        # Borrowed (and preemption-surviving) blocks already cover part of
        # the budget; only the shortfall comes out of the free list.
        short = self._blocks_needed(req.num_tokens + 1) - len(req.blocks)
        if short > len(self._free):
            if self.num_running == 0:
                raise SchedulerExhausted(
                    f'request {rid} needs {short} KV blocks but only '
                    f'{len(self._free)} are free with nothing running; '
                    'increase num_blocks'
                )
            return None
        self._waiting.popleft()
        req.blocks.extend(self._free.pop() for _ in range(short))
        req.slot = slot
        self._slots[slot] = rid
        return rid

    def _free_owned(self, req: _PyRequest) -> None:
        self._free.extend(req.blocks[req.num_borrowed :])
        del req.blocks[req.num_borrowed :]

    def _preempt_youngest(self) -> int | None:
        running = [r for r in self._slots if r >= 0]
        if len(running) <= 1:
            return None
        victim = self._requests[max(running)]
        self._free_owned(victim)
        self._slots[victim.slot] = -1
        victim.slot = -1
        self._waiting.appendleft(victim.rid)
        return victim.rid

    def _extend(self, req: _PyRequest, tokens: int) -> bool:
        while len(req.blocks) < self._blocks_needed(tokens):
            if not self._free:
                return False
            req.blocks.append(self._free.pop())
        return True

    def prepare_decode(
        self,
        k: int = 1,
        rids: 'list[int] | None' = None,
        ks: 'list[int] | None' = None,
    ) -> list[int]:
        """``rids`` (mixed serving windows) restricts the k-token capacity
        guarantee to the listed running requests: rows mid-prefill inside
        a mixed window already own blocks for their full prompt from
        admission, so extending them too would waste pool and provoke
        spurious preemptions. Victims are still chosen youngest-first over
        ALL running rows. ``None`` = every running row (classic policy).
        ``ks`` (parallel to ``rids``) overrides ``k`` per row — the
        speculative verify window's per-row ``1 + draft`` headroom."""
        if k < 1:
            raise ValueError('k must be >= 1')
        if ks is not None:
            if rids is None or len(ks) != len(rids):
                raise ValueError('ks must parallel rids')
            if any(kk < 1 for kk in ks):
                raise ValueError('per-row k must be >= 1')
            if len(set(rids)) != len(rids):
                # A duplicate rid would make the per-row k ambiguous (and
                # the Python/native twins would resolve it differently):
                # reject instead of silently picking one.
                raise ValueError('duplicate rids with per-row ks')
        per_row = dict(zip(rids, ks)) if ks is not None else None
        selected = None if rids is None else set(rids)
        preempted: list[int] = []
        for rid in list(self._slots):
            if rid < 0:
                continue
            if selected is not None and rid not in selected:
                continue  # not selected for decode this window
            req = self._requests[rid]
            if req.slot < 0:
                continue  # preempted earlier in this loop
            k_row = per_row[rid] if per_row is not None else k
            while not self._extend(req, req.num_tokens + k_row):
                victim = self._preempt_youngest()
                if victim is None:
                    raise SchedulerExhausted(
                        'KV cache exhausted with a single running sequence; '
                        'increase num_blocks or reduce max_model_len',
                        preempted=preempted,
                    )
                preempted.append(victim)
                if victim == rid:
                    break
        return preempted

    def append_token(self, rid: int) -> None:
        self._requests[rid].num_tokens += 1

    def trim(self, rid: int) -> int:
        """Free owned tail blocks beyond ``blocks_needed(num_tokens + 1)``.

        The rejected-suffix rollback of speculative windows: headroom
        reserved for drafts that did not survive verification returns to
        the free list, newest block first, so the free list (a LIFO) is
        restored to exactly its pre-reservation state and a later
        extension re-pops the identical blocks. Borrowed prefix blocks
        are never touched. Returns the number of blocks freed.
        """
        req = self._requests[rid]
        keep = max(self._blocks_needed(req.num_tokens + 1), req.num_borrowed)
        freed = len(req.blocks) - keep
        if freed <= 0:
            return 0
        self._free.extend(reversed(req.blocks[keep:]))
        del req.blocks[keep:]
        return freed

    def finish(self, rid: int) -> None:
        req = self._requests.pop(rid)
        # Borrowed prefix blocks belong to the prefix cache; only the
        # owned tail returns to the free list.
        self._free.extend(req.blocks[req.num_borrowed :])
        if req.slot >= 0:
            self._slots[req.slot] = -1
        try:
            self._waiting.remove(rid)
        # distlint: disable=swallowed-exception -- membership-probe control flow: finishing a RUNNING request is the common case and it is simply not in the waiting deque
        except ValueError:
            pass

    def lend_prefix(self, rid: int, num_blocks: int) -> None:
        """Extend ``rid``'s borrowed prefix to ``num_blocks`` blocks total
        (the prefix cache adopted this request's freshly prefilled prompt
        blocks). Idempotent for smaller values; never exceeds the row."""
        req = self._requests[rid]
        if num_blocks > len(req.blocks):
            raise ValueError(
                f'cannot lend {num_blocks} blocks of a {len(req.blocks)}-row'
            )
        req.num_borrowed = max(req.num_borrowed, num_blocks)

    def release_blocks(self, blocks: list[int]) -> None:
        """Return cache-evicted blocks to the free list."""
        self._free.extend(blocks)

    def num_borrowed(self, rid: int) -> int:
        return self._requests[rid].num_borrowed

    def slot(self, rid: int) -> int:
        return self._requests[rid].slot

    def running(self) -> list[tuple[int, int]]:
        """Occupied ``(slot, rid)`` pairs in slot order — O(max_num_seqs)."""
        return [(i, rid) for i, rid in enumerate(self._slots) if rid >= 0]

    def block_row(self, rid: int) -> list[int]:
        return list(self._requests[rid].blocks)

    @property
    def num_free_blocks(self) -> int:
        return len(self._free)

    @property
    def num_running(self) -> int:
        return sum(1 for r in self._slots if r >= 0)

    @property
    def num_waiting(self) -> int:
        return len(self._waiting)

    @property
    def has_unfinished(self) -> bool:
        return bool(self._waiting) or self.num_running > 0


class NativeScheduler:
    """ctypes binding over the C++ scheduler core."""

    def __init__(self, num_blocks: int, block_size: int, max_num_seqs: int) -> None:
        from distllm_tpu.native import build_library

        so_path = build_library('scheduler.cpp')
        if so_path is None:
            raise RuntimeError('native scheduler unavailable')
        lib = ctypes.CDLL(str(so_path))
        lib.sched_create.restype = ctypes.c_void_p
        lib.sched_create.argtypes = [ctypes.c_int32] * 3
        lib.sched_destroy.argtypes = [ctypes.c_void_p]
        lib.sched_add.restype = ctypes.c_int32
        lib.sched_add.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32]
        lib.sched_add_cached.restype = ctypes.c_int32
        lib.sched_add_cached.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.sched_lend_prefix.restype = ctypes.c_int32
        lib.sched_lend_prefix.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int32,
        ]
        lib.sched_release_blocks.restype = ctypes.c_int32
        lib.sched_release_blocks.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.sched_num_borrowed.restype = ctypes.c_int32
        lib.sched_num_borrowed.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sched_admit_next.restype = ctypes.c_int64
        lib.sched_admit_next.argtypes = [ctypes.c_void_p]
        lib.sched_prepare_decode_k.restype = ctypes.c_int32
        lib.sched_prepare_decode_k.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.sched_prepare_decode_rows.restype = ctypes.c_int32
        lib.sched_prepare_decode_rows.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.sched_prepare_decode_rows_k.restype = ctypes.c_int32
        lib.sched_prepare_decode_rows_k.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.sched_trim.restype = ctypes.c_int32
        lib.sched_trim.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        for name in ('sched_append_token', 'sched_finish'):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int32
            fn.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sched_slot.restype = ctypes.c_int32
        lib.sched_slot.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sched_running.restype = ctypes.c_int32
        lib.sched_running.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.sched_block_row.restype = ctypes.c_int32
        lib.sched_block_row.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        for name in (
            'sched_num_free',
            'sched_num_running',
            'sched_num_waiting',
            'sched_has_unfinished',
        ):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int32
            fn.argtypes = [ctypes.c_void_p]

        handle = lib.sched_create(num_blocks, block_size, max_num_seqs)
        if not handle:
            raise RuntimeError(
                f'sched_create({num_blocks}, {block_size}, {max_num_seqs}) failed'
            )
        self._lib = lib
        self._handle = handle
        self._max_num_seqs = max_num_seqs
        self._num_blocks = num_blocks

    def add(
        self, rid: int, num_tokens: int, cached_blocks: 'list[int] | tuple' = ()
    ) -> None:
        if cached_blocks:
            arr = (ctypes.c_int32 * len(cached_blocks))(*cached_blocks)
            rc = self._lib.sched_add_cached(
                self._handle, rid, num_tokens, arr, len(cached_blocks)
            )
        else:
            rc = self._lib.sched_add(self._handle, rid, num_tokens)
        if rc == -2:
            raise ValueError(f'duplicate request id {rid}')
        if rc != 0:
            raise RuntimeError(f'sched_add failed: {rc}')

    def admit_next(self) -> int | None:
        rid = int(self._lib.sched_admit_next(self._handle))
        if rid == -2:
            raise SchedulerExhausted(
                'request needs more KV blocks than are free with nothing '
                'running; increase num_blocks'
            )
        return None if rid < 0 else rid

    def prepare_decode(
        self,
        k: int = 1,
        rids: 'list[int] | None' = None,
        ks: 'list[int] | None' = None,
    ) -> list[int]:
        if k < 1:
            raise ValueError('k must be >= 1')
        if ks is not None:
            if rids is None or len(ks) != len(rids):
                raise ValueError('ks must parallel rids')
            if any(kk < 1 for kk in ks):
                raise ValueError('per-row k must be >= 1')
            if len(set(rids)) != len(rids):
                raise ValueError('duplicate rids with per-row ks')
        out = (ctypes.c_int64 * self._max_num_seqs)()
        if rids is None:
            n = int(self._lib.sched_prepare_decode_k(self._handle, k, out))
        else:
            arr = (ctypes.c_int64 * max(1, len(rids)))(*rids)
            ks_arr = (
                (ctypes.c_int32 * max(1, len(ks)))(*ks)
                if ks is not None
                else None
            )
            n = int(
                self._lib.sched_prepare_decode_rows_k(
                    self._handle, k, arr, ks_arr, len(rids), out
                )
            )
        if n < 0:
            # Fatal encoding is -(1 + n_preempted): preemptions already
            # performed are not rolled back and must reach the engine.
            raise SchedulerExhausted(
                'KV cache exhausted with a single running sequence; '
                'increase num_blocks or reduce max_model_len',
                preempted=[int(out[i]) for i in range(-n - 1)],
            )
        return [int(out[i]) for i in range(n)]

    def append_token(self, rid: int) -> None:
        if self._lib.sched_append_token(self._handle, rid) != 0:
            raise KeyError(rid)

    def trim(self, rid: int) -> int:
        n = int(self._lib.sched_trim(self._handle, rid))
        if n < 0:
            raise KeyError(rid)
        return n

    def finish(self, rid: int) -> None:
        if self._lib.sched_finish(self._handle, rid) != 0:
            raise KeyError(rid)

    def lend_prefix(self, rid: int, num_blocks: int) -> None:
        rc = self._lib.sched_lend_prefix(self._handle, rid, num_blocks)
        if rc == -1:
            raise KeyError(rid)
        if rc != 0:
            raise ValueError(
                f'cannot lend {num_blocks} blocks of request {rid}\'s row'
            )

    def release_blocks(self, blocks: list[int]) -> None:
        if not blocks:
            return
        arr = (ctypes.c_int32 * len(blocks))(*blocks)
        if self._lib.sched_release_blocks(self._handle, arr, len(blocks)) != 0:
            raise RuntimeError('sched_release_blocks failed')

    def num_borrowed(self, rid: int) -> int:
        n = int(self._lib.sched_num_borrowed(self._handle, rid))
        if n < 0:
            raise KeyError(rid)
        return n

    def slot(self, rid: int) -> int:
        return int(self._lib.sched_slot(self._handle, rid))

    def running(self) -> list[tuple[int, int]]:
        slots = (ctypes.c_int32 * self._max_num_seqs)()
        rids = (ctypes.c_int64 * self._max_num_seqs)()
        n = int(self._lib.sched_running(self._handle, slots, rids))
        return [(int(slots[i]), int(rids[i])) for i in range(n)]

    def block_row(self, rid: int) -> list[int]:
        out = (ctypes.c_int32 * self._num_blocks)()
        n = int(self._lib.sched_block_row(self._handle, rid, out, self._num_blocks))
        if n < 0:
            raise KeyError(rid)
        return [int(out[i]) for i in range(n)]

    @property
    def num_free_blocks(self) -> int:
        return int(self._lib.sched_num_free(self._handle))

    @property
    def num_running(self) -> int:
        return int(self._lib.sched_num_running(self._handle))

    @property
    def num_waiting(self) -> int:
        return int(self._lib.sched_num_waiting(self._handle))

    @property
    def has_unfinished(self) -> bool:
        return bool(self._lib.sched_has_unfinished(self._handle))

    def __del__(self) -> None:  # pragma: no cover - interpreter teardown
        lib = getattr(self, '_lib', None)
        handle = getattr(self, '_handle', None)
        if lib is not None and handle:
            lib.sched_destroy(handle)
            self._handle = None


class InstrumentedScheduler:
    """Delegating wrapper that publishes scheduler state as metrics.

    Wraps either implementation (policy untouched — the differential tests
    drive the raw classes) and keeps the process-wide gauges/counters in
    ``observability.instruments`` current on every mutating call: queue
    depth, running slots, KV-block occupancy, admit/defer decisions, and
    preemptions. Gauges are process-wide; with several engines in one
    process the last mutator wins (serving runs one engine per process).
    """

    def __init__(self, inner: Scheduler, num_blocks: int) -> None:
        from distllm_tpu.observability import instruments
        from distllm_tpu.observability.flight import get_flight_recorder

        self._inner = inner
        self._m = instruments
        # Preemptions and pool exhaustion are the scheduler events worth a
        # flight-ring entry: rare, and exactly what a post-mortem needs.
        # Admission defers are counters only — they fire every loop under
        # load and would evict useful ring history.
        self._flight = get_flight_recorder()
        self._usable_blocks = num_blocks - 1  # block 0 is reserved
        self._m.KV_BLOCKS_TOTAL.set(self._usable_blocks)
        self._sync()

    def _sync(self) -> None:
        in_use = self._usable_blocks - self._inner.num_free_blocks
        self._m.KV_BLOCKS_IN_USE.set(in_use)
        self._m.KV_OCCUPANCY.set(
            in_use / self._usable_blocks if self._usable_blocks else 0.0
        )
        self._m.SCHED_QUEUE_DEPTH.set(self._inner.num_waiting)
        self._m.SCHED_RUNNING.set(self._inner.num_running)

    def add(
        self, rid: int, num_tokens: int, cached_blocks: 'list[int] | tuple' = ()
    ) -> None:
        self._inner.add(rid, num_tokens, cached_blocks)
        self._sync()

    def admit_next(self) -> int | None:
        rid = self._inner.admit_next()
        if rid is not None:
            self._m.SCHED_ADMITTED.inc()
            self._sync()
        elif self._inner.num_waiting:
            self._m.SCHED_DEFERRED.inc()
        return rid

    def prepare_decode(
        self,
        k: int = 1,
        rids: 'list[int] | None' = None,
        ks: 'list[int] | None' = None,
    ) -> list[int]:
        try:
            preempted = self._inner.prepare_decode(k, rids, ks)
        except SchedulerExhausted as exc:
            # Preemptions performed before the fatal exhaustion still
            # happened; count them before propagating.
            if exc.preempted:
                self._m.SCHED_PREEMPTIONS.inc(len(exc.preempted))
            self._sync()
            self._flight.record(
                'event',
                event='scheduler_exhausted',
                error=str(exc)[:300],
                preempted=list(exc.preempted),
                free_blocks=self._inner.num_free_blocks,
                queue_depth=self._inner.num_waiting,
            )
            raise
        if preempted:
            self._m.SCHED_PREEMPTIONS.inc(len(preempted))
            self._flight.record(
                'preempt',
                rids=list(preempted),
                k=k,
                free_blocks=self._inner.num_free_blocks,
                running=self._inner.num_running,
                queue_depth=self._inner.num_waiting,
            )
        self._sync()
        return preempted

    def append_token(self, rid: int) -> None:
        # No _sync: appending only bumps the token count — block
        # allocation happens in prepare_decode, which does sync.
        self._inner.append_token(rid)

    def trim(self, rid: int) -> int:
        freed = self._inner.trim(rid)
        if freed:
            self._sync()
        return freed

    def finish(self, rid: int) -> None:
        self._inner.finish(rid)
        self._sync()

    def lend_prefix(self, rid: int, num_blocks: int) -> None:
        # No _sync: lending only re-labels ownership — occupancy unchanged.
        self._inner.lend_prefix(rid, num_blocks)

    def release_blocks(self, blocks: list[int]) -> None:
        self._inner.release_blocks(blocks)
        self._sync()

    def num_borrowed(self, rid: int) -> int:
        return self._inner.num_borrowed(rid)

    def slot(self, rid: int) -> int:
        return self._inner.slot(rid)

    def running(self) -> list[tuple[int, int]]:
        return self._inner.running()

    def block_row(self, rid: int) -> list[int]:
        return self._inner.block_row(rid)

    @property
    def num_free_blocks(self) -> int:
        return self._inner.num_free_blocks

    @property
    def num_running(self) -> int:
        return self._inner.num_running

    @property
    def num_waiting(self) -> int:
        return self._inner.num_waiting

    @property
    def has_unfinished(self) -> bool:
        return self._inner.has_unfinished


def make_scheduler(
    num_blocks: int,
    block_size: int,
    max_num_seqs: int,
    prefer_native: bool = True,
) -> Scheduler:
    if prefer_native:
        try:
            return NativeScheduler(num_blocks, block_size, max_num_seqs)
        except (RuntimeError, OSError) as exc:
            # Same contract as kv_cache.make_allocator: the Python twin
            # is a tested drop-in, but the substitution is never silent.
            from distllm_tpu.observability.instruments import log_event

            log_event(
                f'[engine] native scheduler unavailable ({exc!r:.120}); '
                'using the Python twin',
                component='engine',
            )
    return PyScheduler(num_blocks, block_size, max_num_seqs)
