"""Prompt-lookup speculative drafting (docs/speculative.md).

The dependency-free drafter behind ``EngineConfig.draft_k``: instead of a
second model, each request's OWN prompt+output history is the draft
source. An n-gram ending at some earlier position predicts that the
tokens which followed it will follow again — the classic prompt-lookup
decoding heuristic, and a strong one for the serving shapes this engine
targets (RAG contexts quoted back in answers, MCQA stems, code, chat
turns that restate the question).

The lookup table reuses the prefix cache's token hasher
(:func:`~distllm_tpu.generate.engine.kv_cache.hash_block_tokens`): one
sha256 digest per ``ngram``-token window, mapped to the position just
past that window's most recent occurrence. The same collision-safety
argument applies — a digest collision would splice another suffix's
continuation into the draft, which the verify pass would merely reject
(correctness is never at stake; only the acceptance rate), but the
hasher is already battle-tested and fast enough for the host loop.

Drafts are PROPOSALS only: the engine verifies all of them in one ragged
dispatch and keeps the longest matching prefix, so a bad draft costs one
span slot, never a wrong token (``LLMEngine._process_spec_window``).

Draft-distribution convention (docs/speculative.md "Sampled
verification"): this drafter proposes tokens without probabilities, so
the rejection-sampling verifier treats q as a POINT MASS on the proposed
token — the acceptance probability for draft d degenerates to
min(1, p(d)/1) = p̃(d), the filtered target probability of d itself, and
the rejection residual (p − q)+ normalizes to p with d masked out. A
future model-based drafter supplying real q distributions plugs into the
same ``spec_draft_source`` seam; the verifier math in
``distllm_tpu.ops.sampling.verify_spans`` already phrases acceptance in
p/q terms, so only the q inputs change.

Cost note: the first ``draft`` call after admission indexes the whole
prompt — one sha256 of a tiny n-gram string per position, sub-µs each,
~30 ms one-time at 32k context — and stays incremental afterwards (vLLM's
prompt-lookup re-scans the whole prompt EVERY step). If prompt-index
time ever shows in profiles, plain ``tuple`` keys are the drop-in
micro-optimization; the digest form is kept for parity with the prefix
cache's hash-chain machinery.
"""

from __future__ import annotations

from typing import Sequence

from distllm_tpu.generate.engine.kv_cache import hash_block_tokens


class PromptLookupDrafter:
    """Per-request n-gram → continuation index over the token history.

    Incremental: ``draft`` indexes only history positions it has not seen
    yet (the table survives across windows and recompute preemption —
    preemption keeps prompt and outputs, so every indexed position stays
    valid). The terminal n-gram (the one ending at the last token) is
    never indexed while it is terminal: it is the lookup KEY, and mapping
    it to itself would always propose the empty continuation.
    """

    def __init__(self, ngram: int = 2) -> None:
        if ngram < 1:
            raise ValueError('ngram must be >= 1')
        self.ngram = ngram
        # digest of the ngram ending at position p -> p + 1 (continuation
        # start); later occurrences overwrite earlier ones, so lookups
        # resolve to the MOST RECENT match (recency beats frequency for
        # the repetitive serving shapes prompt lookup exploits).
        self._table: dict[bytes, int] = {}
        # History positions whose ending-ngram has been indexed: every
        # p < _indexed_end is in the table.
        self._indexed_end = 0

    def _digest(self, tokens: Sequence[int]) -> bytes:
        return hash_block_tokens(None, tokens)

    def draft(self, history: Sequence[int], k: int) -> list[int]:
        """Up to ``k`` proposed continuation tokens for ``history``.

        Empty when ``k <= 0``, the history is shorter than the n-gram, or
        the final n-gram has no earlier occurrence.
        """
        n = self.ngram
        end = len(history)
        # Index every ngram ending strictly before the terminal position.
        start = max(self._indexed_end, n - 1)
        for p in range(start, end - 1):
            self._table[self._digest(history[p - n + 1 : p + 1])] = p + 1
        self._indexed_end = max(self._indexed_end, end - 1)
        if k <= 0 or end < n:
            return []
        pos = self._table.get(self._digest(history[end - n : end]))
        if pos is None:
            return []
        return [int(t) for t in history[pos : pos + k]]
