"""Continuous-batching generation engine over the paged KV cache.

The TPU-native replacement for the vLLM offline engine the reference wraps
(``distllm/generate/generators/vllm_backend.py``; SURVEY.md section 2.4 N1):

- **prefill**: one sequence per call, bucketed prompt lengths (jit cache
  stays small), K/V scattered into that sequence's blocks;
- **decode**: ONE jitted step for the whole running batch at fixed shapes
  (``max_num_seqs`` slots), paged attention over block tables, per-slot
  sampling params (temperature / top-p / min-p / greedy);
- **scheduler**: waiting → running admission under block budget, vLLM-style
  recompute preemption when the pool runs dry mid-decode — implemented as a
  NATIVE C++ core (``distllm_tpu/native/scheduler.cpp`` via
  ``engine/scheduler.py``, Python twin as fallback/oracle);
- requests join and leave the batch between steps — continuous batching.

The KV caches are donated through the jitted step so XLA updates them in
place in HBM (no per-step cache copies).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from enum import Enum

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.generate.engine.kv_cache import PagedKVCache
from distllm_tpu.generate.engine.scheduler import (
    SchedulerExhausted,
    make_scheduler,
)
from distllm_tpu.models import mistral
from distllm_tpu.models.tokenizer import bucket_ladder, pick_bucket
from distllm_tpu.ops.paged_attention import write_prefill_kv
from distllm_tpu.ops.sampling import sample_tokens
from distllm_tpu.utils import BaseConfig


@dataclass
class SamplingParams:
    """vLLM-parity sampling knobs (``vllm_backend.py:48-60``)."""

    temperature: float = 0.5
    top_p: float = 1.0
    min_p: float = 0.0
    max_tokens: int = 2000
    stop_token_ids: tuple[int, ...] = ()


class RequestState(Enum):
    WAITING = 'waiting'
    RUNNING = 'running'
    FINISHED = 'finished'


@dataclass
class Request:
    request_id: int
    prompt_ids: list[int]
    params: SamplingParams
    state: RequestState = RequestState.WAITING
    output_ids: list[int] = field(default_factory=list)

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_ids) + len(self.output_ids)


class EngineConfig(BaseConfig):
    """Capacity knobs (vLLM analogues: ``max_num_seqs``, ``max_model_len``,
    ``block_size``, ``gpu_memory_utilization`` → ``num_blocks``)."""

    block_size: int = 16
    num_blocks: int = 256
    max_num_seqs: int = 8
    max_model_len: int = 1024
    prefill_min_bucket: int = 16
    # Admitted requests with the same length bucket prefill together in one
    # padded dispatch (vLLM batches prefills via max_num_batched_tokens);
    # batch dim is bucketed to powers of two up to this cap to bound the
    # jit cache.
    max_prefill_batch: int = 8
    # Upper bound on batch x bucket tokens per prefill dispatch (the vLLM
    # max_num_batched_tokens analogue); also bounds the number of compiled
    # prefill shapes per bucket.
    max_prefill_tokens: int = 2048
    # Governs the scheduler implementation (C++ core vs Python twin).
    prefer_native_allocator: bool = True
    attn_backend: str = 'xla'  # 'xla' | 'pallas' (TPU decode kernel)
    quantization: str | None = None  # None | 'int8' | 'nf4' (weight-only)
    seed: int = 0


class LLMEngine:
    """Drives a Mistral-family decoder with paged KV + continuous batching."""

    def __init__(
        self,
        model_cfg: mistral.MistralConfig,
        params: dict,
        tokenizer,
        config: EngineConfig | None = None,
        mesh=None,
    ) -> None:
        self.model_cfg = model_cfg
        self.params = params
        self.tokenizer = tokenizer
        self.config = config or EngineConfig()
        cfg = self.config

        # Tensor parallelism: K/V pages shard over the kv-head dim on the
        # mesh's model axis (same split as the attention heads in
        # param_specs), so paged gather/scatter stays local per shard;
        # host-built step inputs (ids / positions / block tables) are
        # replicated explicitly — committed single-device arrays would
        # conflict with mesh-sharded params inside the jitted step.
        kv_sharding = None
        self._replicated = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            if model_cfg.num_kv_heads % mesh.shape.get('model', 1):
                raise ValueError(
                    f'num_kv_heads={model_cfg.num_kv_heads} not divisible '
                    f"by tensor parallel degree {mesh.shape.get('model', 1)}"
                )
            kv_sharding = NamedSharding(mesh, P(None, None, None, 'model'))
            self._replicated = NamedSharding(mesh, P())

        self.kv = PagedKVCache(
            num_layers=model_cfg.num_layers,
            num_blocks=cfg.num_blocks,
            block_size=cfg.block_size,
            num_kv_heads=model_cfg.num_kv_heads,
            head_dim=model_cfg.head_size,
            dtype=model_cfg.dtype,
            sharding=kv_sharding,
        )
        self.max_blocks_per_seq = self.kv.blocks_needed(cfg.max_model_len)
        self.prefill_buckets = bucket_ladder(
            cfg.max_model_len, cfg.prefill_min_bucket, scheme='pow2'
        )

        # All admission / preemption / block-budget decisions live in the
        # scheduler (native C++ core, Python twin fallback).
        self.sched = make_scheduler(
            cfg.num_blocks,
            cfg.block_size,
            cfg.max_num_seqs,
            prefer_native=cfg.prefer_native_allocator,
        )
        self._requests: dict[int, Request] = {}
        self._next_id = itertools.count()
        self._finished: dict[int, Request] = {}
        self._key = jax.random.PRNGKey(cfg.seed)

        model = self.model_cfg

        if cfg.quantization:
            # Weight-only quantized serving (reference: bnb NF4 in the HF
            # generator, huggingface_backend.py:66-77): codes live in HBM,
            # dequant happens inside the compiled step.
            from distllm_tpu.ops.quantization import (
                dequantize_pytree as _deq,
                quantize_pytree,
            )

            self.params = quantize_pytree(
                self.params, mode=cfg.quantization, out_dtype=model.dtype
            )
        else:
            def _deq(p):
                return p

        def prefill_fn(params, ids, mask, last_pos):
            params = _deq(params)
            hidden, k, v = mistral.prefill(params, model, ids, mask)
            # Only the last valid position's logits are sampled; computing
            # the lm_head for [B, S, V] would waste MXU time and HBM.
            last_hidden = jnp.take_along_axis(
                hidden, last_pos[:, None, None], axis=1
            )
            return mistral.logits(params, model, last_hidden)[:, 0], k, v

        self._prefill = jax.jit(prefill_fn)

        attn_backend = cfg.attn_backend
        self._decode = jax.jit(
            lambda params, ids, pos, k, v, bt, ctx: mistral.decode_step(
                _deq(params), model, ids, pos, k, v, bt, ctx,
                attn_backend=attn_backend,
            ),
            donate_argnums=(3, 4),
        )
        self._write_prefill = jax.jit(
            _write_prefill_all_layers, donate_argnums=(0, 1)
        )
        self._sample = jax.jit(sample_tokens)

    def _put(self, x):
        """Host value → device array, replicated over the mesh under TP."""
        if self._replicated is not None:
            return jax.device_put(x, self._replicated)
        return jnp.asarray(x)

    def warmup(self) -> None:
        """Compile every serving shape outside the request path.

        Runs each (batch, bucket) prefill the admission policy can emit,
        the KV scatter, the full-batch decode step, and the per-shape
        samplers on dummy inputs. Block tables are all zero, so every K/V
        write lands in the reserved trash block — scheduler state and real
        cache contents are untouched. Combine with jax's persistent
        compilation cache to make later processes start hot.
        """
        saved_key = self._key  # sampling stream must not observe warmup
        for bucket in self.prefill_buckets:
            cap = self._prefill_batch_cap(bucket)
            b = 1
            while True:
                ids = np.zeros((b, bucket), np.int32)
                mask = np.ones((b, bucket), np.int32)
                last_pos = np.zeros((b,), np.int32)
                lengths = np.zeros((b,), np.int32)  # all writes -> trash
                block_rows = np.zeros((b, self.max_blocks_per_seq), np.int32)
                logits, k_all, v_all = self._prefill(
                    self.params,
                    self._put(ids),
                    self._put(mask),
                    self._put(last_pos),
                )
                self.kv.k, self.kv.v = self._write_prefill(
                    self.kv.k,
                    self.kv.v,
                    k_all,
                    v_all,
                    self._put(block_rows),
                    self._put(lengths),
                )
                self._sample_batch(logits, [None] * b)
                if b >= cap:
                    break
                b *= 2
        bsz = self.config.max_num_seqs
        logits, self.kv.k, self.kv.v = self._decode(
            self.params,
            self._put(np.zeros((bsz,), np.int32)),
            self._put(np.zeros((bsz,), np.int32)),
            self.kv.k,
            self.kv.v,
            self._put(np.zeros((bsz, self.max_blocks_per_seq), np.int32)),
            self._put(np.ones((bsz,), np.int32)),
        )
        self._sample_batch(logits, [None] * bsz)
        jax.block_until_ready(self.kv.k)
        self._key = saved_key

    # ------------------------------------------------------------- requests
    def add_request(
        self, prompt_ids: list[int], params: SamplingParams | None = None
    ) -> int:
        if not prompt_ids:
            raise ValueError('empty prompt')
        # Reserve room for at least one generated token.
        prompt_ids = prompt_ids[-(self.config.max_model_len - 1) :]
        needed = self.kv.blocks_needed(len(prompt_ids) + 1)
        if needed > self.kv.num_blocks - 1:  # block 0 is reserved
            raise ValueError(
                f'prompt needs {needed} KV blocks but the pool only has '
                f'{self.kv.num_blocks - 1}; increase num_blocks'
            )
        request = Request(
            request_id=next(self._next_id),
            prompt_ids=list(prompt_ids),
            params=params or SamplingParams(),
        )
        self._requests[request.request_id] = request
        self.sched.add(request.request_id, request.num_tokens)
        return request.request_id

    @property
    def has_unfinished(self) -> bool:
        return self.sched.has_unfinished

    # ------------------------------------------------------------ scheduling
    def _admit(self) -> list[tuple[int, int]]:
        """Admit waiting requests while the scheduler allows.

        Returns the first tokens emitted by prefill as (request_id, token).
        Admissible requests are batch-planned: grouped by prompt-length
        bucket and prefilled together in one padded dispatch (under many
        short requests — the MCQA pattern — per-sequence prefill serializes
        admission behind dispatch latency). A prefill may immediately
        finish its request (stop token / max_tokens=1), freeing slots, so
        the admit→prefill cycle repeats until the scheduler yields nothing.
        """
        emitted: list[tuple[int, int]] = []
        while True:
            admitted: list[Request] = []
            while (rid := self.sched.admit_next()) is not None:
                request = self._requests[rid]
                request.state = RequestState.RUNNING
                admitted.append(request)
            if not admitted:
                return emitted
            groups: dict[int, list[Request]] = {}
            for request in admitted:
                # Re-prefill covers generated tokens too (recompute
                # preemption path).
                length = request.num_tokens
                bucket = pick_bucket(length, self.prefill_buckets)
                groups.setdefault(bucket, []).append(request)
            for bucket, requests in sorted(groups.items()):
                cap = self._prefill_batch_cap(bucket)
                for i in range(0, len(requests), cap):
                    emitted.extend(
                        self._run_prefill_batch(requests[i : i + cap], bucket)
                    )

    def _prefill_batch_cap(self, bucket: int) -> int:
        """Largest pow2 batch for this bucket under the prefill caps.

        Also bounded by pow2ceil(max_num_seqs): no admission group can
        exceed the slot count, so larger shapes would be compiled (by
        ``warmup``) but never dispatched.
        """
        cap = min(
            self.config.max_prefill_batch,
            max(1, self.config.max_prefill_tokens // bucket),
        )
        b = 1
        while b * 2 <= cap:
            b *= 2
        seqs_ceil = 1
        while seqs_ceil < self.config.max_num_seqs:
            seqs_ceil *= 2
        return min(b, seqs_ceil)

    # -------------------------------------------------------------- prefill
    def _run_prefill_batch(
        self, requests: list[Request], bucket: int
    ) -> list[tuple[int, int]]:
        """Prefill same-bucket requests in one padded dispatch.

        The batch dim pads up the pow2 ladder (capped at
        ``max_prefill_batch``) so the jit cache holds at most
        O(log batch x log length) prefill shapes. Padding rows carry
        length 0: their K/V scatter lands in trash block 0 and their
        sampled token is discarded.
        """
        b = 1
        while b < len(requests):
            b *= 2
        ids = np.zeros((b, bucket), np.int32)
        mask = np.zeros((b, bucket), np.int32)
        last_pos = np.zeros((b,), np.int32)
        lengths = np.zeros((b,), np.int32)
        block_rows = np.zeros((b, self.max_blocks_per_seq), np.int32)
        for i, request in enumerate(requests):
            prompt = request.prompt_ids + request.output_ids
            ids[i, : len(prompt)] = prompt
            mask[i, : len(prompt)] = 1
            last_pos[i] = len(prompt) - 1
            lengths[i] = len(prompt)
            block_rows[i] = self._block_row(request.request_id)

        last_logits, k_all, v_all = self._prefill(
            self.params, self._put(ids), self._put(mask), self._put(last_pos)
        )
        self.kv.k, self.kv.v = self._write_prefill(
            self.kv.k,
            self.kv.v,
            k_all,
            v_all,
            self._put(block_rows),
            self._put(lengths),
        )
        # First token of each sequence, sampled from its last prompt
        # position; padding rows sample too but are dropped here.
        slots: list[Request | None] = list(requests) + [None] * (
            b - len(requests)
        )
        tokens = self._sample_batch(last_logits, slots)
        emitted = []
        for i, request in enumerate(requests):
            token = int(tokens[i])
            self._emit_token(request, token)
            emitted.append((request.request_id, token))
        return emitted

    def _block_row(self, rid: int) -> np.ndarray:
        row = np.zeros((self.max_blocks_per_seq,), np.int32)
        blocks = self.sched.block_row(rid)
        row[: len(blocks)] = blocks
        return row

    # --------------------------------------------------------------- decode
    def step(self) -> list[tuple[int, int]]:
        """One engine iteration. Returns [(request_id, new_token)] emitted."""
        emitted = self._admit()
        if self.sched.num_running == 0:
            return emitted

        # The scheduler guarantees every running sequence a block for its
        # next token, preempting the youngest on OOM (recompute preemption:
        # output_ids stay intact, so results and token budgets are
        # unaffected; the request re-prefills on re-admission).
        try:
            preempted = self.sched.prepare_decode()
        except SchedulerExhausted as exc:
            # Preemptions performed before the fatal exhaustion are not
            # rolled back; sync their states so a caller that catches and
            # continues sees engine state consistent with the scheduler.
            for rid in exc.preempted:
                self._requests[rid].state = RequestState.WAITING
            raise
        for rid in preempted:
            self._requests[rid].state = RequestState.WAITING
        # O(max_num_seqs) slot-table read, not a scan of every queued request.
        running = [
            (slot, self._requests[rid]) for slot, rid in self.sched.running()
        ]
        if not running:
            return emitted

        b = self.config.max_num_seqs
        ids = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        block_tables = np.zeros((b, self.max_blocks_per_seq), np.int32)
        context_lens = np.ones((b,), np.int32)
        slot_requests: list[Request | None] = [None] * b
        for slot, request in running:
            last = (
                request.output_ids[-1]
                if request.output_ids
                else request.prompt_ids[-1]
            )
            ids[slot] = last
            positions[slot] = request.num_tokens - 1
            block_tables[slot] = self._block_row(request.request_id)
            context_lens[slot] = request.num_tokens
            slot_requests[slot] = request

        logits, self.kv.k, self.kv.v = self._decode(
            self.params,
            self._put(ids),
            self._put(positions),
            self.kv.k,
            self.kv.v,
            self._put(block_tables),
            self._put(context_lens),
        )
        tokens = self._sample_batch(logits, slot_requests)
        for slot, request in running:
            token = int(tokens[slot])
            self._emit_token(request, token)
            emitted.append((request.request_id, token))
        return emitted

    def _sample_batch(self, logits: jnp.ndarray, slots) -> np.ndarray:
        b = logits.shape[0]
        temperature = np.zeros((b,), np.float32)
        top_p = np.ones((b,), np.float32)
        min_p = np.zeros((b,), np.float32)
        for i, request in enumerate(slots):
            if request is None:
                continue
            temperature[i] = request.params.temperature
            top_p[i] = request.params.top_p
            min_p[i] = request.params.min_p
        self._key, key = jax.random.split(self._key)
        return np.asarray(
            self._sample(
                logits,
                key,
                self._put(temperature),
                self._put(top_p),
                self._put(min_p),
            )
        )

    def _emit_token(self, request: Request, token: int) -> None:
        # Note: the emitted token is NOT yet written to the KV cache; it is
        # fed as input on the next decode step, which writes it then.
        request.output_ids.append(token)
        self.sched.append_token(request.request_id)
        eos = getattr(self.tokenizer, 'eos_id', None)
        stops = set(request.params.stop_token_ids)
        if eos is not None:
            stops.add(eos)
        if (
            token in stops
            or len(request.output_ids) >= request.params.max_tokens
            or request.num_tokens >= self.config.max_model_len
        ):
            self._finish(request)

    def _finish(self, request: Request) -> None:
        request.state = RequestState.FINISHED
        self.sched.finish(request.request_id)
        del self._requests[request.request_id]
        self._finished[request.request_id] = request

    # -------------------------------------------------------------- offline
    def generate_ids(
        self,
        prompts: list[list[int]],
        params: SamplingParams | None = None,
    ) -> list[list[int]]:
        """Offline batch API: token ids in, generated token ids out."""
        ids = [self.add_request(p, params) for p in prompts]
        while self.has_unfinished:
            self.step()
        outs = []
        for rid in ids:
            request = self._finished.pop(rid)
            out = request.output_ids
            # Strip the stop token if present.
            eos = getattr(self.tokenizer, 'eos_id', None)
            stops = set(request.params.stop_token_ids)
            if eos is not None:
                stops.add(eos)
            if out and out[-1] in stops:
                out = out[:-1]
            outs.append(out)
        return outs

    def generate(
        self, prompts: list[str], params: SamplingParams | None = None
    ) -> list[str]:
        """Offline text API (vLLM ``llm.generate`` parity)."""
        batches = self.tokenizer(prompts)
        prompt_ids = [
            [int(t) for t, m in zip(row_ids, row_mask) if m]
            for row_ids, row_mask in zip(
                batches.input_ids, batches.attention_mask
            )
        ]
        outputs = self.generate_ids(prompt_ids, params)
        return [self.tokenizer.decode(out) for out in outputs]

    def shutdown(self) -> None:
        self.params = None
        self.kv = None


def _write_prefill_all_layers(
    k_cache, v_cache, k_seq, v_seq, block_rows, lengths
):
    """Scatter ``[L, B, S, N_kv, Hd]`` prefill K/V into the paged cache.

    ``block_rows`` is ``[B, R]`` and ``lengths`` ``[B]``; positions at or
    beyond a row's length (padding rows have length 0) write to the
    reserved trash block 0.
    """
    num_layers, batch, seq_len = k_seq.shape[:3]
    block_size = k_cache.shape[2]
    positions = jnp.arange(seq_len)[None, :]  # [1, S]
    valid = positions < lengths[:, None]  # [B, S]
    block_ids = jnp.where(
        valid,
        jnp.take_along_axis(block_rows, positions // block_size, axis=1),
        0,
    )
    offsets = jnp.where(valid, positions % block_size, 0)
    flat_blocks = block_ids.reshape(-1)
    flat_offsets = offsets.reshape(-1)
    k_flat = k_seq.reshape(num_layers, batch * seq_len, *k_seq.shape[3:])
    v_flat = v_seq.reshape(num_layers, batch * seq_len, *v_seq.shape[3:])
    k_cache = k_cache.at[:, flat_blocks, flat_offsets].set(
        k_flat.astype(k_cache.dtype)
    )
    v_cache = v_cache.at[:, flat_blocks, flat_offsets].set(
        v_flat.astype(v_cache.dtype)
    )
    return k_cache, v_cache
