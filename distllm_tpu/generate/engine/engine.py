"""Continuous-batching generation engine over the paged KV cache.

The TPU-native replacement for the vLLM offline engine the reference wraps
(``distllm/generate/generators/vllm_backend.py``; SURVEY.md section 2.4 N1):

- **prefill**: one sequence per call, bucketed prompt lengths (jit cache
  stays small), K/V scattered into that sequence's blocks;
- **decode**: ONE jitted dispatch generates a *window* of
  ``decode_steps`` tokens for the whole running batch at fixed shapes
  (``max_num_seqs`` slots) — a ``lax.scan`` of fused decode+sample steps
  in which each sampled token feeds the next step entirely on device
  (``models/mistral.py decode_loop``). On this environment a host↔device
  round trip costs ~68 ms (measured, ``scripts/probe_bw.py``), so
  per-token host syncs — what vLLM's GPU loop tolerates at ~10 µs — are
  the difference between 184 tok/s and >1000 tok/s here. ``generate_ids``
  additionally pipelines ``pipeline_depth`` windows: the next window is
  dispatched before the previous window's tokens are fetched, hiding the
  round trip entirely; EOS is discovered one window late (bounded token
  waste, vLLM-style multi-step scheduling makes the same trade);
- **scheduler**: waiting → running admission under block budget, vLLM-style
  recompute preemption when the pool runs dry mid-decode — implemented as a
  NATIVE C++ core (``distllm_tpu/native/scheduler.cpp`` via
  ``engine/scheduler.py``, Python twin as fallback/oracle);
- requests join and leave the batch between steps — continuous batching.

The KV caches are donated through the jitted step so XLA updates them in
place in HBM (no per-step cache copies).
"""

from __future__ import annotations

import contextlib
import itertools
import time
from dataclasses import dataclass, field
from enum import Enum

import jax
import jax.numpy as jnp
import numpy as np
from pydantic import field_validator, model_validator

from distllm_tpu.generate.engine.kv_cache import (
    DiskKVTier,
    HostKVTier,
    PagedKVCache,
    PeerKVTier,
    PrefixCache,
    block_digests,
)
from distllm_tpu.generate.engine.scheduler import (
    InstrumentedScheduler,
    SchedulerExhausted,
    make_scheduler,
)
from distllm_tpu.models import mistral
from distllm_tpu.models.tokenizer import bucket_ladder, pick_bucket
from distllm_tpu.observability import instruments as _metrics
from distllm_tpu.observability import xla_cost as _xla_cost
from distllm_tpu.observability.flight import get_flight_recorder
from distllm_tpu.observability.startup import (
    get_compile_watcher,
    record_backend_init,
)
from distllm_tpu.ops.paged_attention import (
    KV_QUANT_MAX,
    QuantizedKV,
    quantize_kv_rows,
)
from distllm_tpu.ops.sampling import fold_row_keys, sample_tokens
from distllm_tpu.resilience.admission import (
    EngineLoadView,
    EngineOverloaded,
    shed_decision,
)
from distllm_tpu.resilience.faults import get_fault_injector
from distllm_tpu.utils import BaseConfig


@dataclass
class SamplingParams:
    """vLLM-parity sampling knobs (``vllm_backend.py:48-60``)."""

    temperature: float = 0.5
    top_p: float = 1.0
    min_p: float = 0.0
    # Per-request top-k over the served distribution (0 disables). Applied
    # as a rank mask intersected with top-p/min-p (ops/sampling.py).
    top_k: int = 0
    # Per-request sampling seed; None derives a stable per-request seed
    # from (EngineConfig.seed, request_id). Sampled output streams are
    # deterministic per (seed, schedule) — docs/speculative.md.
    seed: int | None = None
    max_tokens: int = 2000
    stop_token_ids: tuple[int, ...] = ()


# Sentinel returned by _dispatch_window when nothing can be dispatched
# (every running slot's budget is covered by in-flight windows).
_DRAIN = object()


def _request_seed(
    engine_seed: int, request_id: int, explicit: int | None
) -> int:
    """Resolve a request's uint32 sampling seed.

    An explicit ``SamplingParams.seed`` wins (masked to uint32); otherwise
    hash (engine seed, request id) so every request owns an independent
    stream while the whole run stays reproducible from ``EngineConfig.seed``
    and the admission order — the (seed, schedule) determinism contract
    (docs/speculative.md "Sampled verification").
    """
    import hashlib

    if explicit is not None:
        return explicit & 0xFFFFFFFF
    digest = hashlib.blake2s(
        f'{engine_seed}:{request_id}'.encode(), digest_size=4
    ).digest()
    return int.from_bytes(digest, 'little')


class RequestState(Enum):
    WAITING = 'waiting'
    RUNNING = 'running'
    FINISHED = 'finished'
    # Terminal quarantine (docs/resilience.md): the request's dispatches
    # kept failing past the retry budget, or it outlived
    # ``request_deadline_s``. Its blocks are freed, the error is recorded
    # on the request, and it never re-enters the scheduler.
    FAILED = 'failed'


@dataclass
class Request:
    request_id: int
    prompt_ids: list[int]
    params: SamplingParams
    state: RequestState = RequestState.WAITING
    output_ids: list[int] = field(default_factory=list)
    # --- automatic prefix caching (docs/prefix_caching.md) ---
    # Chained block digests of the prompt's full blocks (cache keys).
    digests: list[bytes] = field(default_factory=list)
    # Prompt tokens whose KV is already valid in cache blocks at prefill
    # time — prefill runs only on the tail past this point.
    num_cached_tokens: int = 0
    # Leading blocks of this request's row owned by the prefix cache
    # (mirrors the scheduler's borrowed-prefix count).
    num_borrowed_blocks: int = 0
    # Aligned full-cover hit: the final matched block is SHARED and the
    # last prompt token must be recomputed into a private copy of it
    # (copy-on-write, resolved at prefill dispatch).
    cow_src_block: int | None = None
    # --- host/disk KV tier (docs/prefix_caching.md "Tier hierarchy") ---
    # Digests found in the host (or disk) tier past the HBM match at
    # add_request: promoted back into the paged pool at admission via
    # async device_put; cleared once the promotion begins.
    promo_digests: list[bytes] = field(default_factory=list)
    # --- mixed serving windows (docs/serving.md) ---
    # Absolute token counts tracking a prefill tail riding decode windows:
    # target = tokens that must be prefilled (prompt + any recompute
    # outputs, set at enrollment), sent = dispatched in some window
    # (possibly still in flight), done = confirmed by a processed window.
    # The request joins decode plans only once done >= target (and its
    # first token was emitted by the final chunk's sample). All three stay
    # 0 outside mixed mode, which makes every request decode-ready.
    prefill_target: int = 0
    prefill_sent: int = 0
    prefill_done: int = 0
    # --- prompt-lookup speculative decoding (docs/speculative.md) ---
    # Per-request n-gram drafter (None = this row never drafts: draft_k
    # is 0 or spec_draft_source is 'none'). Sampled rows draft too —
    # device-side rejection sampling verifies their spans ("Sampled
    # verification"). The drafter's index covers prompt+output history,
    # which recompute preemption preserves, so it survives preemption
    # untouched.
    drafter: 'object | None' = None
    # Resolved per-request sampling seed (uint32 domain): the request's
    # explicit SamplingParams.seed, else a stable hash of
    # (EngineConfig.seed, request_id). Feeds the counter-based PRNG key
    # derivation in ops/sampling.py.
    sample_seed: int = 0
    # --- lifecycle timestamps (flight recorder, docs/observability.md) ---
    # monotonic seconds; 0.0 = not reached. t_admit/t_first_token keep
    # their FIRST value across recompute preemption: the client-visible
    # latencies are measured from enqueue, not from the retry.
    t_enqueue: float = 0.0
    t_admit: float = 0.0
    t_first_token: float = 0.0
    t_finish: float = 0.0
    # Propagated request id (the server's X-Request-Id), captured from
    # tracing.request_scope at add_request; carried on the 'request'
    # flight record so one id correlates server spans, engine lifecycle,
    # and the Perfetto request track (docs/observability.md).
    trace_id: str | None = None
    # --- crash-domain recovery (docs/resilience.md) ---
    # Why the request reached a terminal state: '' while live, 'stop' /
    # 'length' for normal finishes, 'timeout' for a request that
    # outlived request_deadline_s, 'dispatch_failed' for quarantine
    # after repeated dispatch failures. A FAILED request also records
    # the error text.
    finish_reason: str = ''
    error: str | None = None

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_ids) + len(self.output_ids)


class EngineConfig(BaseConfig):
    """Capacity knobs (vLLM analogues: ``max_num_seqs``, ``max_model_len``,
    ``block_size``, ``gpu_memory_utilization`` → ``num_blocks``)."""

    block_size: int = 16
    num_blocks: int = 256
    max_num_seqs: int = 8
    max_model_len: int = 1024
    prefill_min_bucket: int = 16
    # Admitted requests with the same length bucket prefill together in one
    # padded dispatch (vLLM batches prefills via max_num_batched_tokens);
    # batch dim is bucketed to powers of two up to this cap to bound the
    # jit cache.
    max_prefill_batch: int = 8
    # Upper bound on batch x bucket tokens per prefill dispatch (the vLLM
    # max_num_batched_tokens analogue); also bounds the number of compiled
    # prefill shapes per bucket.
    max_prefill_tokens: int = 2048
    # Governs the scheduler implementation (C++ core vs Python twin).
    prefer_native_allocator: bool = True
    # Paged-attention kernel selector for EVERY serving dispatch — decode
    # windows, paged/chunked prefill tails, mixed windows, and
    # speculative verify spans all route through the one
    # ops.paged_attention.ragged_paged_attention callsite
    # (docs/serving.md "Attention kernel backends"). 'xla' is the
    # always-available bit-exact baseline; 'pallas' is the fused TPU
    # kernel; 'interpret' runs the same kernel on the Pallas interpreter
    # (CPU parity tier); 'auto' resolves ONCE at engine construction
    # (pallas on TPU for CI-covered head dims, else xla) and is pinned
    # into the jitted serving functions like qmm_backend — a later
    # config/global change can never re-route live dispatches. The
    # RESOLVED value is surfaced in engine telemetry and the
    # distllm_engine_attn_backend_info metric.
    attn_backend: str = 'xla'  # 'auto' | 'xla' | 'pallas' | 'interpret'
    # Storage dtype of the paged KV pool (docs/serving.md "Quantized KV
    # cache"). 'auto' (default) keeps today's behavior bit-exactly: the
    # pool stores the model compute dtype — the structural baseline, the
    # spec_draft_source='none' discipline applied to KV storage. 'bf16' /
    # 'fp32' pin an explicit float pool (useful for A/Bs against 'auto');
    # 'int8' stores K/V as int8 with per-block-per-KV-head symmetric fp32
    # scales, quantized at write time and dequantized fused into the
    # attention kernels' per-band KV loads — half the bytes per paged-
    # attention dispatch and per tier spill/promotion. int8 raises the
    # Pallas sublane tile to 32, so the default block_size=16 serves int8
    # through the XLA backend ('auto' falls back quietly; an explicit
    # 'pallas' pin raises with the block_size=32 fix).
    kv_cache_dtype: str = 'auto'  # 'auto' | 'bf16' | 'fp32' | 'int8'
    quantization: str | None = None  # None | 'int8' | 'nf4' (weight-only)
    # Tokens generated per decode dispatch (the fused lax.scan window).
    # 1 restores per-token dispatch; >1 amortizes dispatch+sync latency.
    decode_steps: int = 8
    # Sampling considers only the top-K logits per step (vLLM's top_k
    # semantic, applied before top-p). Avoids a full-vocab sort inside the
    # decode scan — XLA's TPU sort over 32k is a multi-pass bitonic
    # network paid every step. Probabilities keep the full-vocab
    # normalizer, so top-p/min-p are exact whenever the cutoff falls
    # inside the window. Default 0 = exact full-vocab semantics (reference
    # parity: vLLM's top_k is off by default); serving deployments that
    # want the fast path set 64 explicitly (bench.py does).
    sampling_top_window: int = 0
    # Unroll the layer scan inside decode dispatches. Decode is weight-
    # bandwidth bound and the rolled scan's dynamic-slice of stacked MLP
    # kernels is materialized by XLA (~3x HBM traffic on most of the
    # weights — AOT HLO census, scripts/probe_decode_hlo.py); unrolling
    # folds the slices into the matmuls. Cold-start cost is REAL: the
    # unrolled 7B window compiles in ~2-6.5 min per decode shape (AOT,
    # BENCH_NOTES_r04.md) vs seconds rolled — deployments must seed the
    # persistent compilation cache (scripts/aot_preflight.py) or accept
    # minutes of dead chip at first serve. Prefill keeps the rolled scan
    # either way.
    decode_layer_unroll: bool = True

    @field_validator(
        'sampling_top_window', 'prefill_chunk_tokens',
        'max_window_prefill_tokens', 'draft_k', 'host_kv_tier_bytes',
        'disk_kv_tier_bytes', 'max_dispatch_retries', 'peer_kv_timeout_ms',
    )
    @classmethod
    def _non_negative_window(cls, v: int, info) -> int:
        if v < 0:
            raise ValueError(f'{info.field_name} must be >= 0')
        return v

    @field_validator(
        'request_deadline_s', 'retry_backoff_s', 'history_interval_s',
        'peer_kv_backoff_s',
    )
    @classmethod
    def _non_negative_seconds(cls, v: float, info) -> float:
        if v < 0:
            raise ValueError(f'{info.field_name} must be >= 0')
        return v

    @field_validator('spec_ngram')
    @classmethod
    def _ngram_at_least_one(cls, v: int, info) -> int:
        if v < 1:
            raise ValueError(f'{info.field_name} must be >= 1')
        return v

    @field_validator('max_window_prefill_seqs')
    @classmethod
    def _at_least_one_row(cls, v: int, info) -> int:
        if v < 1:
            raise ValueError(f'{info.field_name} must be >= 1')
        return v

    @model_validator(mode='after')
    def _mixed_batching_consistent(self):
        if self.enable_mixed_batching and self.defer_prefill:
            # Both features re-route prefill emission through the window
            # pipeline and their bookkeeping (carried-ids scatter vs chunk
            # plans) conflicts; defer_prefill also measured SLOWER on the
            # serving tunnel (822 -> 636 tok/s, BENCH_NOTES_r05.md) while
            # mixed batching attacks the same gap without tiny extra
            # dispatches — there is no configuration where both win.
            raise ValueError(
                'enable_mixed_batching and defer_prefill are mutually '
                'exclusive: both re-route prefill emission through the '
                'window pipeline (and defer_prefill measured 822 -> 636 '
                'tok/s on the r5 serving workload — see defer_prefill '
                'docs); disable one'
            )
        if self.enable_mixed_batching and self.max_window_prefill_tokens < 1:
            raise ValueError(
                'enable_mixed_batching needs max_window_prefill_tokens >= 1'
            )
        if self.enable_mixed_batching and not (
            self.enable_prefix_cache or self.prefill_chunk_tokens
        ):
            # Only paged-route tails (cache-hit tails / chunk-split spans)
            # ride windows; without either feature NOTHING can ever
            # enroll, yet warmup would still compile the whole mixed shape
            # ladder — multi-minute dead TPU time for a structurally inert
            # feature. Fail at config time instead of silently.
            raise ValueError(
                'enable_mixed_batching needs enable_prefix_cache and/or '
                'prefill_chunk_tokens: only cache-hit tails and chunked '
                'spans ride mixed windows (docs/serving.md)'
            )
        if self.draft_k and self.defer_prefill:
            # Speculative windows process synchronously (the prompt-lookup
            # drafter needs the host-fetched history before it can propose
            # the next span), so there is never an in-flight deque for
            # deferred first tokens to ride — the combination would leave
            # carried-ids scatters that are fetched nowhere.
            raise ValueError(
                'draft_k and defer_prefill are mutually exclusive: '
                'speculative windows fetch every window synchronously '
                '(the drafter needs host-side history), which removes '
                "defer_prefill's in-flight deque (docs/speculative.md)"
            )
        if self.host_kv_tier_bytes and not self.enable_prefix_cache:
            raise ValueError(
                'host_kv_tier_bytes needs enable_prefix_cache: the tier '
                'spills and promotes PREFIX-CACHE blocks — without the '
                'cache nothing ever reaches it (docs/prefix_caching.md)'
            )
        if self.disk_kv_tier_dir and not self.host_kv_tier_bytes:
            raise ValueError(
                'disk_kv_tier_dir needs host_kv_tier_bytes > 0: spills '
                'reach disk by writing through the host tier, and '
                'promotions route disk → host → device '
                '(docs/prefix_caching.md "Tier hierarchy")'
            )
        if self.peer_kv_endpoints is not None and not self.host_kv_tier_bytes:
            raise ValueError(
                'peer_kv_endpoints needs host_kv_tier_bytes > 0: peer '
                'fetches land in the host pool and promote host → device '
                'exactly like a disk hit (docs/routing.md "Peer KV tier")'
            )
        if self.peer_kv_serve_endpoint and not self.host_kv_tier_bytes:
            raise ValueError(
                'peer_kv_serve_endpoint needs host_kv_tier_bytes > 0: the '
                'KVBlockServer answers HAS/GET from the host/disk pools — '
                'without a host tier there is nothing to serve '
                '(docs/routing.md "Peer KV tier")'
            )
        if self.admission_control and self.ttft_slo_s <= 0:
            raise ValueError(
                'admission_control needs ttft_slo_s > 0: shedding is '
                'defined as refusing load whose predicted TTFT busts the '
                'SLO — without an SLO there is no shed threshold '
                '(docs/resilience.md "Shedding policy")'
            )
        return self
    # Automatic prefix caching (docs/prefix_caching.md): full prompt
    # blocks enter a hash-chain cache as they prefill; later requests
    # sharing a block-aligned prefix reuse those KV blocks (refcounted,
    # LRU-evicted under pool pressure) and prefill ONLY the uncached tail
    # — TTFT and prefill compute drop from O(prompt) to O(tail) for
    # prefix-heavy workloads (RAG system prompts, MCQA stems).
    enable_prefix_cache: bool = False
    # Host-RAM KV tier behind the prefix cache (docs/prefix_caching.md
    # "Tier hierarchy"): evicted ref==0 cache blocks spill device→host
    # into a bounded digest-keyed pool instead of dropping their KV, and
    # later same-prefix arrivals promote them back into the paged pool
    # via async jax.device_put overlapped with in-flight decode windows
    # — warm TTFT at prefix working sets far beyond HBM. Byte budget of
    # the host pool (LRU); 0 disables the tier (HBM-only cache, the
    # pre-tier behavior). Requires enable_prefix_cache.
    host_kv_tier_bytes: int = 0
    # Optional disk tier under the host pool: spills write THROUGH to
    # one digest-named file per block in this directory, so a fresh
    # engine serving the same corpus promotes straight from a previous
    # process's spills (cold-start warm TTFT). None disables.
    disk_kv_tier_dir: str | None = None
    # Disk-tier byte budget (LRU; evictions there are final drops).
    disk_kv_tier_bytes: int = 1 << 30
    # Peer KV tier (docs/routing.md "Peer KV tier"): sibling replicas'
    # KVBlockServer endpoints ('tcp://host:port') to consult AFTER host
    # and disk miss — a replica adopts a peer's spilled .kvblock payloads
    # through the same async promotion path as a disk hit, the
    # content-addressed KV-handoff seed of prefill/decode disaggregation.
    # None disables the tier entirely; an empty tuple enables it with no
    # peers yet (endpoints can be added at runtime via
    # engine.kv_tier.peer.add_endpoint). Requires host_kv_tier_bytes > 0.
    peer_kv_endpoints: tuple[str, ...] | None = None
    # Serve THIS replica's spilled blocks to peers: a zmq bind spec for
    # the KVBlockServer ('tcp://127.0.0.1:0' picks a free port; the
    # resolved endpoint is exposed as engine.peer_kv_endpoint). None
    # disables serving. Requires host_kv_tier_bytes > 0.
    peer_kv_serve_endpoint: str | None = None
    # Per-request timeout for one peer HAS/GET round trip, and the
    # cool-off a failing endpoint sits out before being consulted again
    # (fetch failure degrades to cold prefill, never blocks serving).
    peer_kv_timeout_ms: int = 500
    peer_kv_backoff_s: float = 5.0
    # Split uncached prefill tails longer than this many tokens into
    # bucketed chunks dispatched sequentially (each chunk attends to the
    # KV already in the paged cache), so one long prompt cannot
    # monopolize the chip in a single monolithic dispatch. 0 disables
    # chunking.
    prefill_chunk_tokens: int = 0
    # TTFT service-level objective in seconds (0 = no SLO accounting).
    # When set, every finished request counts into
    # distllm_request_slo_total{outcome=met|missed} and met requests'
    # output tokens into distllm_engine_goodput_tokens_total — goodput,
    # the throughput a latency-bound deployment actually delivered.
    ttft_slo_s: float = 0.0
    # --- resilience (docs/resilience.md) ---
    # Per-request wall-clock deadline (enqueue → terminal state), in
    # seconds; 0 disables. A request that outlives it — stuck behind a
    # stalled window, a livelocked retry ladder, or simply abandoned —
    # finishes with finish_reason='timeout' and FREES its KV blocks
    # instead of holding pool capacity forever. The chat server defaults
    # this on (ChatAppConfig.build_generator).
    request_deadline_s: float = 0.0
    # Crash-domain recovery: how many times a request's dispatches may
    # fail before it is quarantined to the terminal FAILED status with a
    # recorded error. 0 (default) preserves the legacy contract — the
    # first dispatch exception propagates to the caller; > 0 makes the
    # serving loop roll per-row state back, back off
    # (retry_backoff_s * 2^attempt, capped), and retry the window, so
    # one poison request or transient backend fault cannot take the
    # whole batch down with it.
    max_dispatch_retries: int = 0
    # Base of the bounded exponential backoff between window retries.
    retry_backoff_s: float = 0.05
    # SLO-aware admission control (requires ttft_slo_s > 0): predict
    # TTFT at enqueue from EWMA-measured prefill/window rates (roofline
    # floors before traffic) and the current backlog, and REFUSE —
    # raise resilience.EngineOverloaded with an honest Retry-After —
    # requests whose prediction busts the SLO, instead of queueing them
    # into guaranteed misses. Runtime-flippable via
    # ``engine.admission_control`` (the attribution pattern).
    admission_control: bool = False
    # Decode windows in flight during generate_ids (2 hides the
    # host<->device round trip behind the next window's compute).
    pipeline_depth: int = 2
    # TUNNEL-ONLY OPT-IN — do not re-enable by default. Keeps prefill's
    # first-token fetch on device and processes it with the in-flight
    # window records (sampled tokens scatter into the carried last-ids
    # vector). Token-exact either way, but MEASURED SLOWER on the serving
    # tunnel: 822 -> 636 tok/s on the r5 serving workload (probe_gen,
    # chipback_r05, BENCH_NOTES_r05.md) — the extra tiny dispatches it
    # adds (scatter/merge/slices) cost more than the 18 blocking sample
    # fetches they remove. Only a directly-attached deployment (per-
    # dispatch latency in microseconds, not milliseconds) should even
    # experiment with it, and enable_mixed_batching is the measured-
    # faster answer to the same prefill-serialization gap; the validator
    # rejects enabling both.
    defer_prefill: bool = False
    # Mixed prefill+decode serving windows (docs/serving.md): each fused
    # decode dispatch may also carry up to max_window_prefill_tokens of
    # uncached prefill-tail chunk tokens, so prefill work rides the
    # weight stream (and the dispatch) the decode window already pays for
    # instead of serializing between windows — the whole measured gap
    # between the r5 serving loop (830 tok/s) and the isolated window
    # rate (1101 tok/s). Token-identical to the separate-prefill path
    # under greedy sampling (tested); stochastic sampling draws from a
    # different key-split order.
    enable_mixed_batching: bool = False
    # Budget of prefill-chunk tokens one mixed window may carry (the
    # max_num_batched_tokens analogue for the ridden prefill share).
    # Chunk spans additionally respect prefill_chunk_tokens when set, so
    # chunk planning composes with the PR-2 chunked-prefill buckets.
    max_window_prefill_tokens: int = 256
    # Prefill-chunk ROWS (distinct requests) per mixed window. Each
    # (rows, bucket) pair is a compiled window shape; keep this small —
    # on TPU every extra mixed shape is another multi-minute unrolled-
    # window compile at warmup (see docs/serving.md).
    max_window_prefill_seqs: int = 2
    # Prompt-lookup speculative decoding (docs/speculative.md): up to
    # draft_k tokens per row are proposed from the row's OWN prompt+output
    # history and verified in ONE ragged dispatch (per-row spans of
    # 1 + draft_k through the same write-then-attend kernel as paged
    # prefill), so every accepted draft token is a decode token that
    # skipped its weight pass. Greedy output with speculation on is
    # token-identical to speculation off (tested across the full engine
    # identity matrix); rows with temperature > 0 draft too and are
    # verified device-side by exact rejection sampling against the
    # filtered target distribution (docs/speculative.md "Sampled
    # verification") — their sampled streams stay deterministic per
    # (seed, schedule) via counter-based per-row PRNG keys.
    # 0 disables speculation entirely (the classic decode-scan windows).
    # Speculative windows process synchronously (the drafter needs the
    # host-fetched history), so pipeline_depth is effectively 1 while
    # draft_k > 0: the trade is dispatch-latency hiding for weight-pass
    # skipping, which wins at the low-batch/low-latency end where decode
    # is weight-stream-bound.
    draft_k: int = 0
    # n-gram length the prompt-lookup drafter matches on. Longer n-grams
    # propose less often but more precisely.
    spec_ngram: int = 2
    # Where drafts come from. 'prompt_lookup' is the real drafter;
    # 'none' proposes nothing — every window is a span-1 verify dispatch
    # through the SAME compiled executable, which makes it the
    # bit-identity baseline for speculation A/Bs in bf16: two compiled
    # programs (the decode scan vs the ragged verify) may round a
    # near-tied logit differently, so cross-KERNEL token identity is
    # only guaranteed in fp32, while drafting-on vs drafting-off inside
    # the verify kernel is bit-identical in any dtype
    # (docs/speculative.md; the gen_spec bench stage asserts it).
    spec_draft_source: str = 'prompt_lookup'
    # Serving-path attribution (docs/observability.md): per-window
    # host/put/dispatch/fetch timing split on flight records,
    # jax.profiler.TraceAnnotation labels on every dispatch kind, and the
    # analytic roofline gauges (distllm_engine_mfu /
    # distllm_engine_bandwidth_utilization). Pure host-side bookkeeping —
    # token output is bit-identical on vs off (the gen_load bench stage
    # asserts it). Off sheds the record fields, profiler annotations, and
    # roofline math; the raw time.monotonic() reads at the dispatch sites
    # stay (nanoseconds — gating them would complicate every window path
    # for nothing measurable).
    attribution: bool = True
    # Metric-history sampler (docs/observability.md "Metric history &
    # sampling"): > 0 makes THIS engine own a background
    # ``HistorySampler`` ticking the process-wide ``MetricsHistory`` at
    # the given interval, started in ``__init__`` and stopped in
    # ``shutdown()`` (no leaked thread — tested). 0 (default) starts
    # nothing: the chat server owns the process sampler in serving
    # deployments, and two samplers over one history would double the
    # sample density for no information. Set it only for headless /
    # scripted engines that want history without a server.
    history_interval_s: float = 0.0
    seed: int = 0

    @field_validator('spec_draft_source')
    @classmethod
    def _known_draft_source(cls, v: str) -> str:
        if v not in ('prompt_lookup', 'none'):
            raise ValueError(
                "spec_draft_source must be 'prompt_lookup' or 'none'"
            )
        return v

    @field_validator('attn_backend')
    @classmethod
    def _known_attn_backend(cls, v: str) -> str:
        from distllm_tpu.ops.paged_attention import ATTN_BACKENDS

        if v not in ATTN_BACKENDS:
            raise ValueError(
                f'attn_backend must be one of {ATTN_BACKENDS}, got {v!r}'
            )
        return v

    @field_validator('kv_cache_dtype')
    @classmethod
    def _known_kv_cache_dtype(cls, v: str) -> str:
        if v not in ('auto', 'bf16', 'fp32', 'int8'):
            raise ValueError(
                "kv_cache_dtype must be 'auto', 'bf16', 'fp32', or "
                f"'int8', got {v!r}"
            )
        return v


class LLMEngine:
    """Drives a Mistral-family decoder with paged KV + continuous batching.

    ``model_cfg`` may be a :class:`~distllm_tpu.models.mixtral.
    MixtralConfig` too: the shared serving machinery dispatches the MLP
    block on pytree structure (``models/mistral.py _mlp_block``), so
    dense SwiGLU and MoE families serve through one engine — mirroring
    the reference, whose vLLM backend serves both.
    """

    def __init__(
        self,
        model_cfg: 'mistral.MistralConfig | object',
        params: dict,
        tokenizer,
        config: EngineConfig | None = None,
        mesh=None,
        own_params: bool = False,
    ) -> None:
        """``own_params=True`` hands the engine ownership of ``params``:
        destructive HBM optimizations (weight relayout, quantized-source
        deletion) may delete the caller's buffers. Required to serve 7B
        bf16 on a 16 GB chip — without it the engine keeps the caller's
        copies alive and falls back to layout-copying dispatches."""
        self.model_cfg = model_cfg
        self.params = params
        self.tokenizer = tokenizer
        self.config = config or EngineConfig()
        self._own_params = own_params
        cfg = self.config
        # Startup attribution (docs/observability.md): every expensive
        # init/warmup phase below lands as a 'compile' flight record, so
        # a wedged startup — the r03/r04 bench failure mode — names the
        # phase it died in. The first engine in a process also pays (and
        # attributes) the real backend init here; later calls are
        # near-instant cache-hit records.
        self._compile_watcher = get_compile_watcher()
        # Per-engine dedup scope: a rebuilt engine's jit wrappers really
        # recompile, so its phases must start cold in the watcher.
        self._compile_scope = self._compile_watcher.new_scope()
        record_backend_init(self._compile_watcher)

        # Tensor parallelism: K/V pages shard over the kv-head dim on the
        # mesh's model axis (same split as the attention heads in
        # param_specs), so paged gather/scatter stays local per shard;
        # host-built step inputs (ids / positions / block tables) are
        # replicated explicitly — committed single-device arrays would
        # conflict with mesh-sharded params inside the jitted step.
        kv_sharding = None
        self._replicated = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            if model_cfg.num_kv_heads % mesh.shape.get('model', 1):
                raise ValueError(
                    f'num_kv_heads={model_cfg.num_kv_heads} not divisible '
                    f"by tensor parallel degree {mesh.shape.get('model', 1)}"
                )
            kv_sharding = NamedSharding(mesh, P(None, None, None, 'model'))
            self._replicated = NamedSharding(mesh, P())

        # Resolve the KV storage dtype ONCE (the attn/qmm pinning
        # pattern): 'auto' stores the model compute dtype — bit-exact
        # with the pre-kv_cache_dtype engine; 'int8' switches the pool to
        # QuantizedKV storage (docs/serving.md "Quantized KV cache").
        kv_pool_dtype = {
            'bf16': 'bfloat16', 'fp32': 'float32', 'int8': 'int8',
        }.get(cfg.kv_cache_dtype, model_cfg.dtype)

        # Lazy: the pool is materialized only after the (transient-heavy)
        # weight-layout migration below, so migration headroom isn't
        # squeezed by an idle 1-6 GiB of zeros.
        self.kv = PagedKVCache(
            num_layers=model_cfg.num_layers,
            num_blocks=cfg.num_blocks,
            block_size=cfg.block_size,
            num_kv_heads=model_cfg.num_kv_heads,
            head_dim=model_cfg.head_size,
            dtype=kv_pool_dtype,
            sharding=kv_sharding,
            lazy=True,
        )
        self.max_blocks_per_seq = self.kv.blocks_needed(cfg.max_model_len)
        self.prefill_buckets = bucket_ladder(
            cfg.max_model_len, cfg.prefill_min_bucket, scheme='pow2'
        )

        # All admission / preemption / block-budget decisions live in the
        # scheduler (native C++ core, Python twin fallback); the wrapper
        # publishes queue depth / occupancy / admit-defer-preempt metrics.
        self.sched = InstrumentedScheduler(
            make_scheduler(
                cfg.num_blocks,
                cfg.block_size,
                cfg.max_num_seqs,
                prefer_native=cfg.prefer_native_allocator,
            ),
            num_blocks=cfg.num_blocks,
        )
        self._requests: dict[int, Request] = {}
        self._next_id = itertools.count()
        self._finished: dict[int, Request] = {}
        # Serving-loop counters (windows, prefill dispatches, EOS-overshoot
        # waste); generate_ids folds them into ``telemetry`` per run so the
        # bench JSON carries the steady-state split (VERDICT r2 weak #6/#10).
        from collections import Counter

        self._stats: 'Counter[str]' = Counter()
        # Flight recorder: one bounded ring record per prefill dispatch /
        # decode window / finished request. The process-wide ring also
        # feeds the StallWatchdog's default progress signal, so a wedged
        # engine is detectable without any extra wiring.
        self.flight = get_flight_recorder()
        # Resilience layer (docs/resilience.md): the process fault
        # injector (inert unless a chaos schedule armed it), per-request
        # consecutive dispatch-failure counts feeding the quarantine
        # threshold, prefill dispatches that must re-run after a failed
        # attempt, and the recovery backoff state.
        self._faults = get_fault_injector()
        self._dispatch_failures: dict[int, int] = {}
        self._pending_prefill: list[int] = []
        self._consecutive_failures = 0
        # SLO-aware admission control (runtime-flippable, the
        # attribution pattern) + the EWMA-measured predictor inputs
        # (_record_step feeds them; roofline floors cover cold start).
        self.admission_control = cfg.admission_control
        self._ewma: dict[str, float] = {}
        # Metric-history sampler, engine-owned ONLY when configured
        # (history_interval_s > 0); serving deployments leave this 0 and
        # let the chat server own the process sampler. Stopped (and the
        # thread joined) in shutdown() — never leaks past the engine.
        self._history_sampler = None
        if cfg.history_interval_s > 0:
            from distllm_tpu.observability.history import (
                HistorySampler,
                get_metrics_history,
            )

            self._history_sampler = HistorySampler(
                get_metrics_history(), interval_s=cfg.history_interval_s
            )
            self._history_sampler.start()

        model = self.model_cfg

        if cfg.quantization:
            # Weight-only quantized serving (reference: bnb NF4 in the HF
            # generator, huggingface_backend.py:66-77): codes live in HBM;
            # dequant happens INSIDE the compiled step, per layer, at the
            # point of use (common.dense unpacks QTensor leaves riding the
            # layer scan) — never as a whole-tree pass, which would
            # materialize the full float model as HLO temps.
            from distllm_tpu.ops.quantization import quantize_pytree

            # ``delete_source`` streams the conversion when we own the
            # buffers: each replaced bf16 leaf is freed BEFORE its codes are
            # materialized, so HBM peaks at the unquantized weights instead
            # of weights+codes (which OOMed a 16 GiB v5e at 7B dims).
            with self._compile_watcher.phase(
                'quantize', cfg.quantization, compiles=False,
                scope=self._compile_scope,
            ):
                self.params = quantize_pytree(
                    self.params,
                    mode=cfg.quantization,
                    out_dtype=model.dtype,
                    delete_source=self._own_params,
                )
            # Resolve the quantized-matmul tier ONCE, here, and pin it
            # into the model config the jitted forwards close over.
            # dense() otherwise re-reads the process-global
            # default_backend() at trace time, so a set_default_backend
            # call between engine construction and the first dispatch
            # could route a 'pallas' kernel under a TP mesh — past the
            # mesh check below (the TP-mesh/pallas bypass, ADVICE r5).
            from distllm_tpu.ops import quantized_matmul as _qmm

            resolved_qmm = (
                getattr(model, 'qmm_backend', None) or _qmm.default_backend()
            )
            if mesh is not None and resolved_qmm in ('pallas', 'interpret'):
                # GSPMD cannot partition a pallas_call over model-sharded
                # int8 kernels; the XLA scale-after-dot tier partitions
                # like any dot. 'auto' already means 'xla', so only an
                # explicit 'pallas' pin needs rejecting.
                raise ValueError(
                    'quantized-matmul backend '
                    f'{resolved_qmm!r} cannot serve under a '
                    "tensor-parallel mesh; use 'auto'/'xla'"
                )
            if hasattr(model, 'model_copy'):
                model = model.model_copy(update={'qmm_backend': resolved_qmm})
                self.model_cfg = model

        def prefill_fn(params, ids, mask, last_pos):
            hidden, k, v = mistral.prefill(params, model, ids, mask)
            # Only the last valid position's logits are sampled; computing
            # the lm_head for [B, S, V] would waste MXU time and HBM.
            last_hidden = jnp.take_along_axis(
                hidden, last_pos[:, None, None], axis=1
            )
            return mistral.logits(params, model, last_hidden)[:, 0], k, v

        self._prefill = jax.jit(prefill_fn)

        # Resolve the paged-attention backend ONCE, here, and close every
        # jitted serving function below over the result — the qmm_backend
        # pinning pattern (ops.paged_attention.resolve_attn_backend):
        # 'auto' picks the fused ragged Pallas kernel on TPU for
        # CI-covered head dims and the always-available XLA baseline
        # everywhere else, and a config change after construction can
        # never re-route live dispatches.
        from distllm_tpu.ops.paged_attention import (
            kv_sublane_tile,
            resolve_attn_backend,
        )

        attn_backend = resolve_attn_backend(
            cfg.attn_backend, model,
            # 'auto' eligibility includes the kernel's DMA contract on the
            # KV block geometry — a config the kernel would reject must
            # resolve to XLA, never trace into a ValueError. The STORAGE
            # dtype decides the sublane tile: an int8 pool needs
            # block_size % 32 == 0, so int8 + the default block_size=16
            # quietly keeps the XLA tier under 'auto'.
            block_size=cfg.block_size, kv_dtype=kv_pool_dtype,
        )
        _sublane = kv_sublane_tile(kv_pool_dtype)
        if (
            jnp.dtype(kv_pool_dtype) == jnp.dtype(jnp.int8)
            and attn_backend in ('pallas', 'interpret')
            and cfg.block_size % _sublane
        ):
            # Explicit kernel pin on an ineligible int8 KV geometry: fail
            # at construction with the fix, not mid-warmup from the
            # kernel's trace-time guard (the head-dim guard's discipline).
            # Full-precision pools keep their seed behavior — interpret
            # mode runs any block size, and 'auto' already routes
            # compiled-TPU ineligibility to XLA via resolve_attn_backend.
            raise ValueError(
                f'attn_backend={attn_backend!r} needs block_size % '
                f'{_sublane} == 0 for {jnp.dtype(kv_pool_dtype).name} KV '
                f'caches, got block_size={cfg.block_size}; use '
                f'block_size={_sublane} (EngineConfig.block_size) or '
                "attn_backend='xla'"
            )
        if mesh is not None and attn_backend != 'xla':
            # GSPMD cannot partition the ragged pallas_call over the
            # kv-head-sharded cache planes (the qmm 'pallas' TP rule,
            # applied to attention). 'auto' quietly keeps the XLA tier —
            # it partitions like any gather/dot — while an explicit pin
            # must fail loudly rather than serve a broken partitioning.
            if cfg.attn_backend == 'auto':
                attn_backend = 'xla'
            else:
                raise ValueError(
                    f'attn_backend {attn_backend!r} cannot serve under a '
                    "tensor-parallel mesh; use 'auto'/'xla'"
                )
        if (
            cfg.attn_backend == 'auto'
            and attn_backend == 'xla'
            and jax.default_backend() == 'tpu'
        ):
            # The fallback is correct but silently costs ~3x decode —
            # this is the ONE site that sees every reason 'auto' can
            # land on XLA (head dim, KV block geometry, TP mesh), so the
            # warning lives here; telemetry carries the resolved value.
            import logging

            logging.getLogger(__name__).warning(
                "attn_backend='auto' resolved to the XLA paged-attention "
                'path on a TPU (head_dim %d, block_size %d, kv dtype %s, '
                'tensor parallel: %s) — the fused Pallas kernel is not '
                'eligible for this config',
                model.head_size, cfg.block_size,
                jnp.dtype(kv_pool_dtype).name, mesh is not None,
            )

        # Automatic prefix caching: hash-chain over full prompt blocks,
        # refcounted sharing, LRU eviction (docs/prefix_caching.md).
        # Cache-hit tails and chunked prefills dispatch through
        # prefill_paged (write tail K/V, attend over the paged cache).
        self.prefix_cache = (
            PrefixCache(cfg.block_size) if cfg.enable_prefix_cache else None
        )
        # Host-RAM (and disk, and peer) KV tier behind the prefix cache
        # (docs/prefix_caching.md "Tier hierarchy"): eviction pressure
        # cascades HBM → host → disk → peer → drop, and host/disk/peer
        # hits promote back into the paged pool via async device_put at
        # admission. The peer hop (docs/routing.md) consults sibling
        # replicas' KVBlockServers after a local miss; this replica's own
        # spills are served back when peer_kv_serve_endpoint is set.
        self.kv_tier = None
        self.peer_kv_endpoint: str | None = None
        self._peer_kv_server = None
        if cfg.host_kv_tier_bytes:
            disk = (
                DiskKVTier(cfg.disk_kv_tier_dir, cfg.disk_kv_tier_bytes)
                if cfg.disk_kv_tier_dir
                else None
            )
            peer = (
                PeerKVTier(
                    cfg.peer_kv_endpoints,
                    timeout_ms=cfg.peer_kv_timeout_ms,
                    failure_backoff_s=cfg.peer_kv_backoff_s,
                )
                if cfg.peer_kv_endpoints is not None
                else None
            )
            self.kv_tier = HostKVTier(
                cfg.host_kv_tier_bytes, disk=disk, peer=peer
            )
            if cfg.peer_kv_serve_endpoint:
                from distllm_tpu.parallel.fabric import KVBlockServer

                self._peer_kv_server = KVBlockServer(
                    self.kv_tier.contains_local,
                    self.kv_tier.encoded_local,
                    bind=cfg.peer_kv_serve_endpoint,
                ).start()
                self.peer_kv_endpoint = self._peer_kv_server.endpoint
        # In-flight promotions: rid -> completion record ({'token': a tiny
        # post-scatter device slice whose readiness proves the promoted
        # KV landed, timing fields}). The request stays non-decode-ready
        # (prefill_target gate) until _finish_promotions retires it.
        self._promoting: dict[int, dict] = {}
        # Promotion overlap accounting (tier_summary): span = begin →
        # retire wall time, wait = the blocking part of that span (the
        # one audited completion sync). overlap = 1 - wait/span.
        self._tier_times = {'promote_wait_s': 0.0, 'promote_span_s': 0.0}
        # Spill fetch (device→host gather of evicted blocks' KV) and
        # promotion write-back (scatter of device_put'ed host KV).
        # Block-count dims pad up a pow2 ladder so the jit cache stays
        # O(log max_blocks_per_seq); pad slots index the trash block.
        # tree.map keeps these pool-container-generic: for a bare-array
        # pool the maps ARE the direct ops (bit-identical HLO); for a
        # QuantizedKV pool the int8 data and the fp32 scales both carry
        # their block axis at axis 1, so one lambda moves both planes —
        # spills and promotions transport quantized blocks natively,
        # never through a dequantized copy.
        self._gather_blocks = jax.jit(
            lambda k, v, idx: jax.tree.map(lambda c: c[:, idx], (k, v))
        )
        self._write_promoted = jax.jit(
            lambda k, v, kp, vp, idx: jax.tree.map(
                lambda c, p: c.at[:, idx].set(p.astype(c.dtype)),
                (k, v), (kp, vp),
            ),
            donate_argnums=(0, 1),
        )
        # Tiny post-scatter slice: fetching ONE element is the only
        # reliable completion barrier on this backend (see _migrate
        # _sync) — the promotion-landed probe.
        self._probe = jax.jit(
            lambda a: jnp.ravel(jax.tree.leaves(a)[0])[:1]
        )
        _max_tables = cfg.max_model_len

        def prefill_paged_fn(params, ids, pos, k, v, bt, ctx, tails):
            return mistral.prefill_paged(
                params, model, ids, pos, k, v, bt, ctx, tails,
                max_table_positions=_max_tables, attn_backend=attn_backend,
            )

        self._prefill_paged = jax.jit(prefill_paged_fn, donate_argnums=(3, 4))
        # Batched COW: copy shared blocks' K/V (all layers) into the
        # requests' private copies in one dispatch. tree.map for the
        # same reason as the tier jits above: a quantized source block's
        # int8 data AND its scale row copy together, so the private copy
        # stays bit-exact (no requantization on COW).
        self._cow_copy = jax.jit(
            lambda k, v, src, dst: jax.tree.map(
                lambda c: c.at[:, dst].set(c[:, src]), (k, v)
            ),
            donate_argnums=(0, 1),
        )

        num_steps = cfg.decode_steps
        max_tables = cfg.max_model_len

        def window_fn(
            params, ids, pos, ctx, k, v, bt, steps_left, temp, top_p, min_p,
            top_k, seeds,
        ):
            return mistral.decode_loop(
                params, model, ids, pos, k, v, bt, ctx, steps_left,
                temp, top_p, min_p, top_k, seeds, num_steps=num_steps,
                attn_backend=attn_backend, max_table_positions=max_tables,
                sampling_top_window=cfg.sampling_top_window,
                layer_unroll=cfg.decode_layer_unroll,
            )

        self._decode_window = jax.jit(window_fn, donate_argnums=(4, 5))

        # Mixed serving windows: chunk rows + the decode scan in ONE
        # dispatch (mistral.mixed_window; docs/serving.md). Built only
        # when enabled — the shapes are extra compiles a pure-decode
        # deployment never wants.
        def mixed_fn(
            params, ids, pos, ctx, k, v, bt, steps_left, temp, top_p,
            min_p, top_k, seeds, c_ids, c_pos, c_bt, c_ctx, c_tails,
            c_temp, c_top_p, c_min_p, c_top_k, c_seeds,
        ):
            return mistral.mixed_window(
                params, model, ids, pos, k, v, bt, ctx, steps_left,
                temp, top_p, min_p, top_k, seeds, c_ids, c_pos, c_bt,
                c_ctx, c_tails, c_temp, c_top_p, c_min_p, c_top_k,
                c_seeds, num_steps=num_steps,
                attn_backend=attn_backend, max_table_positions=max_tables,
                sampling_top_window=cfg.sampling_top_window,
                layer_unroll=cfg.decode_layer_unroll,
            )

        self._mixed_fn = mixed_fn
        self._mixed_window = (
            jax.jit(mixed_fn, donate_argnums=(4, 5))
            if cfg.enable_mixed_batching
            else None
        )

        # Speculative verify windows (docs/speculative.md): one ragged
        # dispatch scores every row's [last_token, drafts...] span. Two
        # variants — plain, and chunk-carrying (mixed batching): the
        # chunk tuple is pytree-static, so each compiles its own graph
        # and a pure-spec deployment never compiles the chunk shapes.
        def spec_fn(
            params, ids, pos, ctx, k, v, bt, tails, temp, top_p, min_p,
            top_k, seeds,
        ):
            return mistral.spec_window(
                params, model, ids, pos, k, v, bt, ctx, tails,
                temp, top_p, min_p, top_k, seeds,
                max_table_positions=max_tables,
                sampling_top_window=cfg.sampling_top_window,
                attn_backend=attn_backend,
            )

        def spec_mixed_fn(
            params, ids, pos, ctx, k, v, bt, tails, temp, top_p, min_p,
            top_k, seeds, c_ids, c_pos, c_bt, c_ctx, c_tails, c_temp,
            c_top_p, c_min_p, c_top_k, c_seeds,
        ):
            return mistral.spec_window(
                params, model, ids, pos, k, v, bt, ctx, tails,
                temp, top_p, min_p, top_k, seeds,
                chunk=(
                    c_ids, c_pos, c_bt, c_ctx, c_tails, c_temp, c_top_p,
                    c_min_p, c_top_k, c_seeds,
                ),
                max_table_positions=max_tables,
                sampling_top_window=cfg.sampling_top_window,
                attn_backend=attn_backend,
            )

        self._spec_fn = spec_fn
        self._spec_mixed_fn = spec_mixed_fn
        self._spec_window = (
            jax.jit(spec_fn, donate_argnums=(4, 5)) if cfg.draft_k else None
        )
        self._spec_mixed_window = (
            jax.jit(spec_mixed_fn, donate_argnums=(4, 5))
            if cfg.draft_k and cfg.enable_mixed_batching
            else None
        )
        # Resolved-at-serve-time values: a config that believes it enabled
        # the Pallas kernel can otherwise ship 3x slower with no signal.
        # (attn_backend here is the RESOLVED selector, never 'auto'.)
        self.telemetry: dict[str, str] = {'attn_backend': attn_backend}
        # Scrape-visible twin of the telemetry field: exactly one backend
        # label reads 1.
        for _be in _metrics.ATTN_BACKEND_LABELS:
            _metrics.ATTN_BACKEND_INFO.labels(backend=_be).set(
                1.0 if _be == attn_backend else 0.0
            )
        # Same pattern for the RESOLVED KV storage dtype ('auto' is never
        # surfaced — what the pool actually stores is): exactly one dtype
        # label reads 1, so a scrape proves which encoding served.
        _kv_name = jnp.dtype(kv_pool_dtype).name
        self.telemetry['kv_cache_dtype'] = _kv_name
        for _dt in _metrics.KV_CACHE_DTYPE_LABELS:
            _metrics.KV_CACHE_DTYPE_INFO.labels(dtype=_dt).set(
                1.0 if _dt == _kv_name else 0.0
            )
        if _kv_name not in _metrics.KV_CACHE_DTYPE_LABELS:
            _metrics.KV_CACHE_DTYPE_INFO.labels(dtype='other').set(1.0)
        if cfg.quantization and hasattr(model, 'qmm_backend'):
            self.telemetry['qmm_backend'] = model.qmm_backend
        if (
            self._own_params
            and mesh is None
            and jax.devices()[0].platform == 'tpu'
        ):
            # Let XLA pick the weight layouts the decode loop wants and
            # store the params that way at rest. Without this, XLA inserts
            # layout-conversion copies of the stacked q/k/v kernels (1.5 GB
            # at 7B dims) inside every window dispatch — enough to overflow
            # a v5e's HBM next to the weights, and pure wasted bandwidth.
            # Prefill is layout-agnostic (measured:
            # scripts/probe_prefill_layout.py — 0.13 GiB temp either way),
            # so the migrated layout serves every executable.
            compiled = formats = None
            try:
                with self._compile_watcher.phase(
                    'auto_layout', f'b{cfg.max_num_seqs}',
                    scope=self._compile_scope,
                ):
                    compiled, formats = self._compile_auto_layout(window_fn)
            except Exception as exc:  # pragma: no cover - TPU-only path
                self.telemetry['auto_layout_fallback'] = repr(exc)[:300]
            if compiled is not None:
                # Destructive from here on (source leaves are deleted as
                # they migrate); failures are fatal, not a fallback —
                # callers rebuild with fresh params (see bench.py ladder).
                with self._compile_watcher.phase(
                    'migrate_params', 'params', compiles=False,
                    scope=self._compile_scope,
                ):
                    self.params = self._migrate_params(formats)
                self._decode_window = compiled
                self._pin_mixed_layout(formats)
                self._pin_spec_layout(formats)
        with self._compile_watcher.phase(
            'kv_allocate', f'blocks{cfg.num_blocks}', compiles=False,
            scope=self._compile_scope,
        ):
            self.kv.allocate()
        # Merge host-known overrides (fresh admissions) into the device-
        # carried last-token vector between pipelined windows.
        self._merge_ids = jax.jit(
            lambda carried, mask, vals: jnp.where(mask, vals, carried)
        )
        self._write_prefill = jax.jit(
            _write_prefill_all_layers, donate_argnums=(0, 1)
        )
        self._sample = jax.jit(
            lambda lg, t, tp, mp, tk, seeds, counters: sample_tokens(
                lg, None, t, tp, mp,
                top_window=cfg.sampling_top_window, top_k=tk,
                row_keys=fold_row_keys(seeds, counters),
            )
        )
        # Tokens dispatched on device but not yet fetched, per request —
        # the pipelined path's lag bookkeeping.
        self._unacked: dict[int, int] = {}
        # Requests whose uncached prefill tail rides mixed windows, in
        # FIFO dispatch order (rids; entries are dropped at final-chunk
        # processing, preemption, or lazily when a request vanishes).
        self._prefilling: list[int] = []
        # Set by _run_to_completion: lets chunked prefill retire one
        # in-flight decode window between chunks.
        self._drain_hook = None
        # Device-side last-token vector carried across the pipelined loop;
        # deferred prefill scatters freshly sampled first tokens into it.
        self._carried = None
        self._scatter_tokens = jax.jit(
            lambda carried, slot_idx, toks: carried.at[slot_idx].set(toks)
        )
        # Serving-path attribution (docs/observability.md): a runtime-
        # flippable flag (no compiled shapes depend on it), the analytic
        # roofline cost model priced from the FINAL params (post-quant,
        # post-relayout — the bytes that really stream), and per-kind
        # accumulators behind roofline_summary(). Cost-model failures
        # (exotic leaf types) disable the gauges, never the engine.
        self.attribution = cfg.attribution
        self._cost_model = None
        self._roofline: dict[str, dict[str, float]] = {}
        # Measured executable costs from compiled.cost_analysis(), filled
        # by warmup() (observability/xla_cost.py): the XLA-measured twin
        # of the analytic cost model above, behind the
        # distllm_engine_mfu_measured gauges and the calibration ratios.
        self._measured_costs: dict[str, _xla_cost.XlaCost] = {}
        # Built unconditionally (a cheap metadata walk) so flipping
        # self.attribution ON at runtime works even when the engine was
        # constructed with attribution off.
        try:
            from distllm_tpu.observability.roofline import CostModel

            self._cost_model = CostModel.from_params(
                self.params, cfg.decode_steps,
                # Param leaves report GLOBAL size/bytes under TP; the
                # roofline scales the peaks by the mesh size to match.
                num_devices=mesh.size if mesh is not None else 1,
            )
        except Exception as exc:
            self.telemetry['roofline_fallback'] = repr(exc)[:300]

    def _put(self, x):
        """Host value → device array, replicated over the mesh under TP."""
        if self._replicated is not None:
            return jax.device_put(x, self._replicated)
        return jnp.asarray(x)

    def _put_many(self, *xs):
        """One batched host→device transfer for a dispatch's plan arrays.

        Every individual ``device_put`` is a separate host↔device round
        trip; through the serving tunnel the decode loop paid ~160 ms of
        its 624 ms host cycle on 8 per-window puts while the chip sat
        idle (probe_gen, chipback_r05). A single batched put ships them
        in one transfer.
        """
        if self._replicated is not None:
            return jax.device_put(tuple(xs), self._replicated)
        return jax.device_put(tuple(xs))

    def _compile_auto_layout(self, window_fn):
        """AOT-compile the decode window with ``Layout.AUTO`` for params.

        Non-destructive: returns ``(compiled_window, chosen_formats)``;
        the caller decides whether to run the destructive migration.
        """
        from jax.experimental.layout import Format, Layout

        b = self.config.max_num_seqs
        sds = jax.ShapeDtypeStruct
        i32 = jnp.int32
        f32 = jnp.float32

        def spec(tree):
            return jax.tree.map(lambda x: sds(x.shape, x.dtype), tree)

        shapes = (
            spec(self.params),
            sds((b,), i32),  # ids
            sds((b,), i32),  # positions
            sds((b,), i32),  # context_lens
            self.kv.spec(),
            self.kv.spec(),
            sds((b, self.max_blocks_per_seq), i32),
            sds((b,), i32),  # steps_left
            sds((b,), f32),
            sds((b,), f32),
            sds((b,), f32),
            sds((b,), i32),  # top_k
            sds((b,), jnp.uint32),  # seeds
        )
        jitted = jax.jit(
            window_fn,
            donate_argnums=(4, 5),
            in_shardings=(Format(Layout.AUTO),) + (Format(),) * 12,
        )
        compiled = jitted.lower(*shapes).compile()
        return compiled, compiled.input_formats[0][0]

    def _migrate_params(self, formats):
        """Move weights into ``formats`` leaf-by-leaf, deleting each source
        buffer as it lands so peak HBM stays ~one-largest-leaf above the
        weights (a whole-tree device_put would transiently need 2x).

        Destructive: a mid-migration failure (e.g. HBM fragmentation)
        leaves already-migrated leaves deleted, so it raises — the engine
        is not usable with half-deleted params and callers must rebuild.
        """
        from jax.experimental.layout import Format
        from jax.sharding import SingleDeviceSharding

        sharding = SingleDeviceSharding(jax.devices()[0])

        def _sync(array) -> None:
            # block_until_ready is a no-op on this backend; fetching one
            # element is the only reliable completion barrier.
            np.asarray(jax.jit(lambda a: jnp.ravel(a)[:1])(array))

        flat_params, treedef = jax.tree.flatten(self.params)
        flat_formats = treedef.flatten_up_to(formats)
        migrated = []
        moved_bytes = 0
        # Device-side relayout needs source + target live at once; for the
        # stacked MLP kernels (3.8 GiB each at 7B dims) that overflows HBM
        # beside the rest of the weights, so big leaves bounce through host
        # RAM instead (~1 s each over the link — one-time at startup).
        bounce_limit = 1 << 30
        try:
            for leaf, fmt in zip(flat_params, flat_formats):
                # input_formats carry layouts without concrete shardings;
                # device_put requires both.
                fmt = Format(fmt.layout, sharding)
                nbytes = getattr(leaf, 'nbytes', 0)
                on_device = isinstance(leaf, jax.Array)
                if on_device and nbytes > bounce_limit:
                    # Fetch in slices along dim 0 (a single multi-GiB d2h
                    # exhausts the backend's staging memory), free the
                    # source, then rebuild ON DEVICE: the target buffer is
                    # created directly in the final layout and filled
                    # slice-by-slice with donated updates — device_put of
                    # a whole non-default-layout tensor stages BOTH a
                    # default-layout upload and a relayout copy (2x the
                    # tensor), which overflows HBM beside 7B weights.
                    host = np.empty(leaf.shape, leaf.dtype)
                    for i in range(leaf.shape[0]):
                        host[i] = np.asarray(leaf[i])
                    leaf.delete()
                    moved = jax.jit(
                        lambda shape=leaf.shape, dtype=leaf.dtype: jnp.zeros(
                            shape, dtype
                        ),
                        out_shardings=fmt,
                    )()
                    fill = jax.jit(
                        lambda buf, part, idx: jax.lax.dynamic_update_index_in_dim(
                            buf, part, idx, 0
                        ),
                        donate_argnums=0,
                        out_shardings=fmt,
                    )
                    for i in range(host.shape[0]):
                        moved = fill(moved, host[i], np.int32(i))
                    del host
                    _sync(moved)
                elif on_device:
                    # Compiled identity relayout, NOT device_put: on the
                    # serving backend a device_put with an explicit
                    # non-default Format can silently keep the source
                    # layout (observed on the stacked f32 scale leaves,
                    # bench run 5 — the cached auto-layout window then
                    # rejects the params at dispatch). XLA always honors
                    # out_shardings; donation bounds the transient to the
                    # target buffer.
                    moved = jax.jit(
                        lambda a: a, donate_argnums=0, out_shardings=fmt
                    )(leaf)
                    moved_bytes += nbytes
                    if moved_bytes > (1 << 30):
                        _sync(moved)
                        moved_bytes = 0
                else:
                    moved = jax.device_put(leaf, fmt)
                    moved_bytes += nbytes
                    if moved_bytes > (1 << 30):
                        _sync(moved)
                        moved_bytes = 0
                migrated.append(moved)
        except Exception as exc:
            raise RuntimeError(
                f'weight layout migration failed after {len(migrated)}/'
                f'{len(flat_params)} leaves; params are partially deleted — '
                'rebuild the engine with fresh params'
            ) from exc
        return jax.tree.unflatten(treedef, migrated)

    def _pin_mixed_layout(self, formats) -> None:
        """Re-jit the mixed window with params pinned to the migrated
        layouts (TPU auto-layout path). Without this, the lazily compiled
        mixed executable would ask for default layouts and XLA would
        insert multi-GiB relayout copies of the stacked kernels inside
        every chunk-carrying window — silently repaying the bandwidth the
        migration bought."""
        if self._mixed_window is None:
            return
        try:  # pragma: no cover - TPU-only path
            from jax.experimental.layout import Format
            from jax.sharding import SingleDeviceSharding

            sharding = SingleDeviceSharding(jax.devices()[0])
            pinned = jax.tree.map(
                lambda fmt: Format(fmt.layout, sharding), formats
            )
            self._mixed_window = jax.jit(
                self._mixed_fn,
                donate_argnums=(4, 5),
                in_shardings=(pinned,) + (Format(),) * 22,
            )
        except Exception as exc:  # pragma: no cover - TPU-only path
            self.telemetry['mixed_layout_fallback'] = repr(exc)[:300]

    def _pin_spec_layout(self, formats) -> None:
        """Re-jit the speculative windows with params pinned to the
        migrated layouts (the mixed-window rationale applies unchanged:
        a default-layout lazy compile would bury multi-GiB relayout
        copies inside every verify dispatch)."""
        if self._spec_window is None:
            return
        try:  # pragma: no cover - TPU-only path
            from jax.experimental.layout import Format
            from jax.sharding import SingleDeviceSharding

            sharding = SingleDeviceSharding(jax.devices()[0])
            pinned = jax.tree.map(
                lambda fmt: Format(fmt.layout, sharding), formats
            )
            self._spec_window = jax.jit(
                self._spec_fn,
                donate_argnums=(4, 5),
                in_shardings=(pinned,) + (Format(),) * 12,
            )
            if self._spec_mixed_window is not None:
                self._spec_mixed_window = jax.jit(
                    self._spec_mixed_fn,
                    donate_argnums=(4, 5),
                    in_shardings=(pinned,) + (Format(),) * 22,
                )
        except Exception as exc:  # pragma: no cover - TPU-only path
            self.telemetry['spec_layout_fallback'] = repr(exc)[:300]

    def warmup(self) -> None:
        """Compile every serving shape outside the request path.

        Runs each (batch, bucket) prefill the admission policy can emit,
        the KV scatter, the full-batch decode step, and the per-shape
        samplers on dummy inputs. Block tables are all zero, so every K/V
        write lands in the reserved trash block — scheduler state and real
        cache contents are untouched. Combine with jax's persistent
        compilation cache to make later processes start hot.

        Every shape in the ladder runs under a compile-watcher phase
        (docs/observability.md "Startup & compile attribution"): one
        ``compile`` flight record + ``distllm_compile_seconds{kind,shape}``
        observation per (kind, batch, bucket), cache-hit marked on the
        re-warmup / persistent-cache fast paths — so a 22–45 min cold
        warmup (or a wedge inside it) is attributable shape by shape.
        Afterwards the warmed serving executables are priced via
        ``cost_analysis()`` (observability/xla_cost.py) for the measured
        MFU gauges.
        """
        watch = self._compile_watcher
        # Quantized pools compile their own executables for every phase
        # that touches KV (the int8 scatter/dequant graphs are different
        # programs): tag the shape labels so the compile ledger
        # attributes an int8 warmup to the int8 config, not to a
        # mysteriously-recompiling float one.
        qtag = 'q8' if self.kv.quantized else ''
        for bucket in self.prefill_buckets:
            cap = self._prefill_batch_cap(bucket)
            b = 1
            while True:
                ids = np.zeros((b, bucket), np.int32)
                mask = np.ones((b, bucket), np.int32)
                last_pos = np.zeros((b,), np.int32)
                lengths = np.zeros((b,), np.int32)  # all writes -> trash
                block_rows = np.zeros((b, self.max_blocks_per_seq), np.int32)
                with watch.phase(
                    'prefill', f'b{b}x{bucket}{qtag}', scope=self._compile_scope
                ):
                    logits, k_all, v_all = self._prefill(
                        self.params,
                        self._put(ids),
                        self._put(mask),
                        self._put(last_pos),
                    )
                    self.kv.k, self.kv.v = self._write_prefill(
                        self.kv.k,
                        self.kv.v,
                        k_all,
                        v_all,
                        self._put(block_rows),
                        self._put(lengths),
                    )
                    np.asarray(self._sample_device(logits, [None] * b))
                if (
                    self.prefix_cache is not None
                    or self.config.prefill_chunk_tokens
                ):
                    # Paged-context prefill shapes (cache-hit tails and
                    # chunks dispatch through prefill_paged): tail_lens 0
                    # routes every write to the trash block.
                    with watch.phase(
                        'prefill_paged', f'b{b}x{bucket}{qtag}',
                        scope=self._compile_scope,
                    ):
                        (
                            ids_dev,
                            pos_dev,
                            rows_dev,
                            ctx_dev,
                            tails_dev,
                        ) = self._put_many(
                            ids,
                            np.zeros((b, bucket), np.int32),
                            block_rows,
                            np.ones((b,), np.int32),
                            np.zeros((b,), np.int32),
                        )
                        pg_logits, self.kv.k, self.kv.v = self._prefill_paged(
                            self.params,
                            ids_dev,
                            pos_dev,
                            self.kv.k,
                            self.kv.v,
                            rows_dev,
                            ctx_dev,
                            tails_dev,
                        )
                        np.asarray(
                            self._sample_device(pg_logits, [None] * b)
                        )
                if b >= cap:
                    break
                b *= 2
        if self.prefix_cache is not None:
            # Warm the COW block copy at its common shape (one hit per
            # dispatch): src = dst = trash block 0 is a state-safe
            # self-copy. Without this, the first aligned full-cover cache
            # hit pays the compile inside the very TTFT the cache exists
            # to shrink.
            with watch.phase('cow_copy', f'b1{qtag}', scope=self._compile_scope):
                src_dev, dst_dev = self._put_many(
                    np.zeros((1,), np.int32), np.zeros((1,), np.int32)
                )
                self.kv.k, self.kv.v = self._cow_copy(
                    self.kv.k, self.kv.v, src_dev, dst_dev
                )
        if self.kv_tier is not None:
            # Warm the tier's gather (spill fetch) / scatter (promotion
            # write-back) pow2 block-count ladder. All indices are the
            # trash block 0, so writes and reads touch no real state;
            # without this the first pool-pressure spill would pay the
            # compile inside the serving loop it interrupts.
            num_layers, _, bs_, n_kv, head_dim = self.kv.shape
            npad = 1
            cap = self._pow2(self.max_blocks_per_seq)
            while npad <= cap:
                with watch.phase(
                    'tier_promote', f'n{npad}{qtag}',
                    scope=self._compile_scope,
                ):
                    idx = np.zeros((npad,), np.int32)
                    zeros = np.zeros(
                        (num_layers, npad, bs_, n_kv, head_dim),
                        dtype=self.kv.dtype,
                    )
                    if self.kv.quantized:
                        # Promotion operands for an int8 pool are
                        # QuantizedKV trees: stage zero scale planes
                        # beside the zero data so the warmed executable
                        # matches the serving _begin_promotion shapes.
                        s_zeros = np.zeros(
                            (num_layers, npad, n_kv), np.float32
                        )
                        k_d, v_d, ks_d, vs_d, idx_dev = self._put_many(
                            zeros, zeros, s_zeros, s_zeros, idx
                        )
                        k_dev = QuantizedKV(k_d, ks_d)
                        v_dev = QuantizedKV(v_d, vs_d)
                    else:
                        k_dev, v_dev, idx_dev = self._put_many(
                            zeros, zeros, idx
                        )
                    self.kv.k, self.kv.v = self._write_promoted(
                        self.kv.k, self.kv.v, k_dev, v_dev, idx_dev
                    )
                    gk, gv = self._gather_blocks(
                        self.kv.k, self.kv.v, self._put(idx)
                    )
                    np.asarray(self._probe(self.kv.k))
                    np.asarray(self._probe(gk))
                    np.asarray(self._probe(gv))
                npad *= 2
        bsz = self.config.max_num_seqs
        # Warm the fused decode window: steps_left = 0 freezes every slot,
        # so all KV writes land in the trash block and no state advances.
        with watch.phase(
            'decode_window', f'b{bsz}x{self.config.decode_steps}{qtag}',
            scope=self._compile_scope,
        ):
            tokens, self.kv.k, self.kv.v, _ = self._decode_window(
                self.params,
                self._put(np.zeros((bsz,), np.int32)),
                self._put(np.zeros((bsz,), np.int32)),
                self._put(np.ones((bsz,), np.int32)),
                self.kv.k,
                self.kv.v,
                self._put(np.zeros((bsz, self.max_blocks_per_seq), np.int32)),
                self._put(np.zeros((bsz,), np.int32)),
                self._put(np.zeros((bsz,), np.float32)),
                self._put(np.ones((bsz,), np.float32)),
                self._put(np.zeros((bsz,), np.float32)),
                self._put(np.zeros((bsz,), np.int32)),
                self._put(np.zeros((bsz,), np.uint32)),
            )
            self._merge_ids(
                self._put(np.zeros((bsz,), np.int32)),
                self._put(np.zeros((bsz,), bool)),
                self._put(np.zeros((bsz,), np.int32)),
            )
            # In-phase completion barrier (every other ladder phase ends
            # with a host fetch): without it the window's async execution
            # tail would be attributed to whatever phase runs next.
            np.asarray(tokens)
        if self._mixed_window is not None and not self.config.draft_k:
            # Warm every mixed-window shape the chunk planner can emit
            # — but NOT in speculative mode: _dispatch_window then always
            # routes to spec windows, so the classic mixed executable is
            # structurally unreachable and each of its bucket shapes
            # would be a multi-minute unrolled-window compile for
            # nothing (chunk traffic rides _spec_mixed_window, warmed
            # below). The jit object still exists — _plan_window_chunks
            # uses it as the mixed-enabled signal — it is just never
            # compiled.
            # rows always pad to the pow2 of max_window_prefill_seqs, so
            # only the chunk-token bucket varies (ladder capped at the
            # window budget). tail_lens 0 + all-zero tables route every
            # write to the trash block; steps_left 0 freezes decode.
            cb = self._mixed_rows()
            span_bucket = pick_bucket(
                self._mixed_span_cap(), self.prefill_buckets
            )
            for bucket in self.prefill_buckets:
                if bucket > span_bucket:
                    break
                with watch.phase(
                    'mixed_window', f'b{bsz}x{bucket}c{cb}{qtag}',
                    scope=self._compile_scope,
                ):
                    mixed_tokens, self.kv.k, self.kv.v, _, _ = (
                        self._mixed_window(
                            self.params,
                            self._put(np.zeros((bsz,), np.int32)),
                            self._put(np.zeros((bsz,), np.int32)),
                            self._put(np.ones((bsz,), np.int32)),
                            self.kv.k,
                            self.kv.v,
                            self._put(
                                np.zeros(
                                    (bsz, self.max_blocks_per_seq), np.int32
                                )
                            ),
                            self._put(np.zeros((bsz,), np.int32)),
                            self._put(np.zeros((bsz,), np.float32)),
                            self._put(np.ones((bsz,), np.float32)),
                            self._put(np.zeros((bsz,), np.float32)),
                            self._put(np.zeros((bsz,), np.int32)),
                            self._put(np.zeros((bsz,), np.uint32)),
                            self._put(np.zeros((cb, bucket), np.int32)),
                            self._put(np.zeros((cb, bucket), np.int32)),
                            self._put(
                                np.zeros(
                                    (cb, self.max_blocks_per_seq), np.int32
                                )
                            ),
                            self._put(np.ones((cb,), np.int32)),
                            self._put(np.zeros((cb,), np.int32)),
                            self._put(np.zeros((cb,), np.float32)),
                            self._put(np.ones((cb,), np.float32)),
                            self._put(np.zeros((cb,), np.float32)),
                            self._put(np.zeros((cb,), np.int32)),
                            self._put(np.zeros((cb,), np.uint32)),
                        )
                    )
                    np.asarray(mixed_tokens)
        if self._spec_window is not None:
            # Warm the speculative verify window: ONE fixed span shape
            # [B, 1 + draft_k] (rows with shorter drafts pad via
            # span_lens, so the span dim never adds compiled shapes).
            # span_lens 0 + all-zero tables route every write to the
            # trash block; logits/tokens are garbage the host discards.
            span = 1 + self.config.draft_k
            with watch.phase(
                'spec_window', f'b{bsz}s{span}{qtag}', scope=self._compile_scope
            ):
                spec_tokens, self.kv.k, self.kv.v, _ = self._spec_window(
                    self.params,
                    self._put(np.zeros((bsz, span), np.int32)),
                    self._put(np.zeros((bsz, span), np.int32)),
                    self._put(np.ones((bsz,), np.int32)),
                    self.kv.k,
                    self.kv.v,
                    self._put(
                        np.zeros((bsz, self.max_blocks_per_seq), np.int32)
                    ),
                    self._put(np.zeros((bsz,), np.int32)),
                    self._put(np.zeros((bsz,), np.float32)),
                    self._put(np.ones((bsz,), np.float32)),
                    self._put(np.zeros((bsz,), np.float32)),
                    self._put(np.zeros((bsz,), np.int32)),
                    self._put(np.zeros((bsz,), np.uint32)),
                )
                np.asarray(spec_tokens)
        if self._spec_mixed_window is not None:
            # Chunk-carrying spec windows: the same chunk-bucket ladder
            # the mixed warmup walks, beside the fixed spec span.
            span = 1 + self.config.draft_k
            cb = self._mixed_rows()
            span_bucket = pick_bucket(
                self._mixed_span_cap(), self.prefill_buckets
            )
            for bucket in self.prefill_buckets:
                if bucket > span_bucket:
                    break
                with watch.phase(
                    'spec_mixed_window', f'b{bsz}s{span}x{bucket}c{cb}{qtag}',
                    scope=self._compile_scope,
                ):
                    spec_tokens, self.kv.k, self.kv.v, _ = (
                        self._spec_mixed_window(
                            self.params,
                            self._put(np.zeros((bsz, span), np.int32)),
                            self._put(np.zeros((bsz, span), np.int32)),
                            self._put(np.ones((bsz,), np.int32)),
                            self.kv.k,
                            self.kv.v,
                            self._put(
                                np.zeros(
                                    (bsz, self.max_blocks_per_seq), np.int32
                                )
                            ),
                            self._put(np.zeros((bsz,), np.int32)),
                            self._put(np.zeros((bsz,), np.float32)),
                            self._put(np.ones((bsz,), np.float32)),
                            self._put(np.zeros((bsz,), np.float32)),
                            self._put(np.zeros((bsz,), np.int32)),
                            self._put(np.zeros((bsz,), np.uint32)),
                            self._put(np.zeros((cb, bucket), np.int32)),
                            self._put(np.zeros((cb, bucket), np.int32)),
                            self._put(
                                np.zeros(
                                    (cb, self.max_blocks_per_seq), np.int32
                                )
                            ),
                            self._put(np.ones((cb,), np.int32)),
                            self._put(np.zeros((cb,), np.int32)),
                            self._put(np.zeros((cb,), np.float32)),
                            self._put(np.ones((cb,), np.float32)),
                            self._put(np.zeros((cb,), np.float32)),
                            self._put(np.zeros((cb,), np.int32)),
                            self._put(np.zeros((cb,), np.uint32)),
                        )
                    )
                    np.asarray(spec_tokens)
        # On this backend block_until_ready does not synchronize; a tiny
        # host fetch is the only reliable completion barrier.
        np.asarray(tokens)
        # Price what XLA actually compiled, now that every serving
        # executable is warm (measured MFU gauges + calibration ratios,
        # docs/observability.md "Measured vs analytic MFU").
        self._price_serving_executables()

    def _pricing_allowed(self, fn) -> bool:
        """Whether pricing ``fn`` via ``lower().compile()`` is safe.

        Already-compiled executables (the TPU auto-layout decode window)
        are free. Re-lowering a ``jax.jit`` wrapper compiles a second
        executable with identical HLO — fine on non-TPU backends (tiny
        compiles) or when the persistent compilation cache will serve it
        from disk, but never worth a second multi-minute unrolled-window
        compile on a cold TPU.
        """
        if hasattr(fn, 'cost_analysis'):
            return True
        if jax.devices()[0].platform != 'tpu':
            return True
        try:
            return bool(jax.config.jax_compilation_cache_dir)
        # distlint: disable=swallowed-exception -- jax builds without the cache-dir config attribute simply cannot be priced; the skip lands in the caller's xla_cost_skipped telemetry note
        except Exception:
            return False

    def _price_serving_executables(self) -> None:
        """Store per-kind :class:`~distllm_tpu.observability.xla_cost.
        XlaCost` for the warmed serving executables — what XLA *measured*
        for one dispatch of each window kind, as opposed to the analytic
        ``CostModel``. ``_record_step`` divides these by each window's
        wall time into the ``distllm_engine_mfu_measured`` gauges and the
        analytic-vs-measured calibration ratios. Pricing is telemetry:
        every failure degrades to a telemetry note, never an error.

        The priced shapes are the serving steady state: full-batch
        decode/spec/mixed windows and the largest prefill shape. Per-kind
        executable cost is fixed per dispatch (frozen slots still pay),
        which is exactly the property that makes it *measured truth* —
        occupancy-dependence lives in the analytic side of the ratio.
        Only decode and (chunk-less) spec have ONE serving shape, so only
        they feed the per-dispatch measured gauges (_record_step);
        prefill/mixed costs are warmup-shape snapshots surfaced via
        :meth:`measured_costs` alone.
        """
        if self._cost_model is None:
            return
        cfg = self.config
        bsz = cfg.max_num_seqs

        def zi(*shape):
            return self._put(np.zeros(shape, np.int32))

        def oi(*shape):
            return self._put(np.ones(shape, np.int32))

        def zf(*shape):
            return self._put(np.zeros(shape, np.float32))

        def of(*shape):
            return self._put(np.ones(shape, np.float32))

        def zu(*shape):
            return self._put(np.zeros(shape, np.uint32))

        bt = zi(bsz, self.max_blocks_per_seq)
        targets: list[tuple[str, object, tuple]] = []
        bucket = self.prefill_buckets[-1]
        pb = self._prefill_batch_cap(bucket)
        targets.append((
            'prefill',
            self._prefill,
            (self.params, zi(pb, bucket), oi(pb, bucket), zi(pb)),
        ))
        targets.append((
            'decode',
            self._decode_window,
            (self.params, zi(bsz), zi(bsz), oi(bsz), self.kv.k, self.kv.v,
             bt, zi(bsz), zf(bsz), of(bsz), zf(bsz), zi(bsz), zu(bsz)),
        ))
        if self._spec_window is not None:
            span = 1 + cfg.draft_k
            targets.append((
                'spec',
                self._spec_window,
                (self.params, zi(bsz, span), zi(bsz, span), oi(bsz),
                 self.kv.k, self.kv.v, bt, zi(bsz), zf(bsz), of(bsz),
                 zf(bsz), zi(bsz), zu(bsz)),
            ))
        if self._mixed_window is not None and not cfg.draft_k:
            span_bucket = pick_bucket(
                self._mixed_span_cap(), self.prefill_buckets
            )
            buckets = [bk for bk in self.prefill_buckets if bk <= span_bucket]
            if buckets:
                cb, mb = self._mixed_rows(), buckets[-1]
                targets.append((
                    'mixed',
                    self._mixed_window,
                    (self.params, zi(bsz), zi(bsz), oi(bsz), self.kv.k,
                     self.kv.v, bt, zi(bsz), zf(bsz), of(bsz), zf(bsz),
                     zi(bsz), zu(bsz),
                     zi(cb, mb), zi(cb, mb), zi(cb, self.max_blocks_per_seq),
                     oi(cb), zi(cb), zf(cb), of(cb), zf(cb), zi(cb),
                     zu(cb)),
                ))
        for kind, fn, args in targets:
            try:
                if not self._pricing_allowed(fn):
                    self.telemetry.setdefault(
                        'xla_cost_skipped',
                        'cold-TPU jit executables not re-lowered; seed the '
                        'persistent compilation cache to price them',
                    )
                    continue
                cost = _xla_cost.price_callable(fn, *args)
            except Exception as exc:
                self.telemetry.setdefault(
                    'xla_cost_fallback', repr(exc)[:200]
                )
                continue
            if cost is not None:
                self._measured_costs[kind] = cost
                bytes_accessed = cost.to_dict().get('bytes_accessed')
                if bytes_accessed:
                    # Scrape-visible per-dispatch byte traffic: the KV-
                    # sensitive roofline numerator (an int8 pool shows as
                    # the decode/mixed kinds dropping by the KV share).
                    _metrics.ENGINE_KV_DISPATCH_BYTES.labels(
                        kind=kind
                    ).set(float(bytes_accessed))

    def measured_costs(self) -> dict[str, dict]:
        """XLA-measured per-dispatch executable cost by window kind
        (``{'flops', 'bytes_accessed', 'source'}``; filled by
        :meth:`warmup`, empty before it or when pricing was skipped) —
        the measured side of the roofline calibration ratios."""
        return {k: c.to_dict() for k, c in self._measured_costs.items()}

    # ------------------------------------------------------------- requests
    def add_request(
        self, prompt_ids: list[int], params: SamplingParams | None = None
    ) -> int:
        if not prompt_ids:
            raise ValueError('empty prompt')
        # Reserve room for at least one generated token.
        prompt_ids = prompt_ids[-(self.config.max_model_len - 1) :]
        needed = self.kv.blocks_needed(len(prompt_ids) + 1)
        if needed > self.kv.num_blocks - 1:  # block 0 is reserved
            raise ValueError(
                f'prompt needs {needed} KV blocks but the pool only has '
                f'{self.kv.num_blocks - 1}; increase num_blocks'
            )
        if self.admission_control:
            # May raise EngineOverloaded (honest backpressure) BEFORE any
            # engine state is touched — a shed request owns nothing.
            self._maybe_shed(len(prompt_ids))
        from distllm_tpu.observability.tracing import current_request_id

        request = Request(
            request_id=next(self._next_id),
            prompt_ids=list(prompt_ids),
            params=params or SamplingParams(),
            t_enqueue=time.monotonic(),
            # Bound by the server's request_scope (X-Request-Id) when the
            # add happens inside one; None for offline/batch callers.
            trace_id=current_request_id(),
        )
        request.sample_seed = _request_seed(
            self.config.seed, request.request_id,
            request.params.seed,
        )
        if (
            self.config.draft_k
            and self.config.spec_draft_source == 'prompt_lookup'
        ):
            # Greedy rows verify by argmax comparison; temperature > 0
            # rows verify by device-side rejection sampling against the
            # filtered target (docs/speculative.md "Sampled
            # verification") — both draft from the same n-gram lookup.
            from distllm_tpu.generate.engine.spec import PromptLookupDrafter

            request.drafter = PromptLookupDrafter(self.config.spec_ngram)
        cached_blocks: list[int] = []
        if self.prefix_cache is not None:
            bs = self.config.block_size
            request.digests = block_digests(request.prompt_ids, bs)
            matched = self.prefix_cache.acquire(
                request.request_id, request.digests
            )
            if matched and len(matched) * bs == len(prompt_ids):
                # Aligned full-cover hit: every prompt block is cached,
                # but prefill must still produce last-token logits and
                # the last token's K write would land INSIDE the shared
                # final block. Keep the match, re-prefill only the last
                # token, and copy-on-write that block at dispatch.
                request.cow_src_block = matched[-1]
                cached_blocks = matched[:-1]
                request.num_cached_tokens = len(prompt_ids) - 1
            else:
                cached_blocks = matched
                request.num_cached_tokens = len(matched) * bs
            request.num_borrowed_blocks = len(cached_blocks)
            if matched:
                _metrics.PREFIX_TIER_HITS.labels(tier='hbm').inc(
                    len(matched)
                )
            if self.kv_tier is not None and request.cow_src_block is None:
                # Tier walk past the HBM hit: later digests still in the
                # host/disk tier extend the cached prefix via promotion
                # (begun at admission). Capped so at least one prompt
                # token stays uncached — prefill needs a tail to produce
                # last-token logits from (the HBM full-cover case routes
                # through COW instead; a chain split by partial eviction
                # stops the walk at the first block neither tier holds).
                promo: list[bytes] = []
                for digest in request.digests[len(cached_blocks):]:
                    if self.kv_tier.lookup(digest) is None:
                        break
                    promo.append(digest)
                while promo and (
                    (len(cached_blocks) + len(promo)) * bs
                    >= len(prompt_ids)
                ):
                    promo.pop()
                request.promo_digests = promo
            elif self.kv_tier is None and len(matched) < len(request.digests):
                _metrics.PREFIX_TIER_MISSES.labels(tier='hbm').inc()
            _metrics.PREFIX_LOOKUP_TOKENS.inc(len(prompt_ids))
            if request.num_cached_tokens:
                _metrics.PREFIX_HIT_TOKENS.inc(request.num_cached_tokens)
                self._stats['prefix_hit_tokens'] += request.num_cached_tokens
            self._stats['prefix_lookup_tokens'] += len(prompt_ids)
        self._requests[request.request_id] = request
        self.sched.add(request.request_id, request.num_tokens, cached_blocks)
        _metrics.ENGINE_REQUESTS_ADDED.inc()
        _metrics.ENGINE_PROMPT_TOKENS.inc(len(prompt_ids))
        return request.request_id

    # ------------------------------------- SLO-aware admission (shedding)
    def _ewma_update(
        self, key: str, value: float, alpha: float = 0.25
    ) -> None:
        prev = self._ewma.get(key)
        self._ewma[key] = (
            value if prev is None else prev + alpha * (value - prev)
        )

    def _load_view(self) -> EngineLoadView:
        """Snapshot of engine load for the TTFT predictor
        (resilience/admission.py): scheduler backlog plus EWMA-measured
        per-token prefill time and window cadence, falling back to the
        analytic roofline floors before the first windows land.

        The request scan is O(live requests) per arrival, and that is
        self-limiting BY the policy it feeds: shedding caps the waiting
        backlog near the SLO-equivalent token budget
        (``slo_s / prefill_s_per_token``), so the scan cost is bounded
        by the configured SLO, not by offered load — incremental
        counters would trade that bound for drift risk across
        admit/preempt/quarantine paths."""
        cfg = self.config
        waiting_tokens = 0
        pending_decode = 0
        for r in self._requests.values():
            if r.state is RequestState.WAITING:
                waiting_tokens += r.num_tokens
                pending_decode += r.params.max_tokens
            elif r.state is RequestState.RUNNING:
                pending_decode += max(
                    0, r.params.max_tokens - len(r.output_ids)
                )
        per_tok = self._ewma.get('prefill_s_per_token')
        window_s = self._ewma.get('window_s')
        if (
            per_tok is None or window_s is None
        ) and self._cost_model is not None:
            cm = self._cost_model
            if per_tok is None:
                per_tok = 2.0 * cm.n_params / cm.peak_flops
            if window_s is None:
                window_s = (
                    cm.weight_bytes * cm.decode_steps / cm.peak_hbm_bytes
                )
        return EngineLoadView(
            waiting_tokens=waiting_tokens,
            pending_decode_tokens=pending_decode,
            num_waiting=self.sched.num_waiting,
            num_running=self.sched.num_running,
            max_num_seqs=cfg.max_num_seqs,
            decode_steps=cfg.decode_steps,
            prefill_s_per_token=per_tok or 0.0,
            window_s=window_s or 0.0,
            slo_s=cfg.ttft_slo_s,
        )

    def _maybe_shed(self, prompt_tokens: int) -> None:
        """Shed at enqueue when the predicted TTFT busts the SLO —
        429-style honest backpressure instead of queueing a request into
        a guaranteed miss (docs/resilience.md "Shedding policy")."""
        admit, predicted, retry_after = shed_decision(
            self._load_view(), prompt_tokens
        )
        _metrics.RESILIENCE_PREDICTED_TTFT.observe(predicted)
        if admit:
            return
        _metrics.RESILIENCE_SHED.labels(reason='overload').inc()
        self._stats['shed_requests'] += 1
        self.flight.record(
            'shed',
            reason='overload',
            predicted_ttft_s=round(predicted, 6),
            retry_after_s=round(retry_after, 3),
            prompt_tokens=prompt_tokens,
            queue_depth=self.sched.num_waiting,
        )
        raise EngineOverloaded(
            predicted, retry_after, self.config.ttft_slo_s
        )

    @property
    def has_unfinished(self) -> bool:
        return self.sched.has_unfinished

    # ------------------------------------------------------------ scheduling
    def _admit(self, defer_to=None) -> list[tuple[int, int]]:
        """Admit waiting requests while the scheduler allows.

        Returns the first tokens emitted by prefill as (request_id, token)
        (empty in deferred mode — they surface when the caller processes
        the in-flight records in ``defer_to``). Admissible requests are
        batch-planned: grouped by prompt-length bucket and prefilled
        together in one padded dispatch (under many short requests — the
        MCQA pattern — per-sequence prefill serializes admission behind
        dispatch latency). A synchronous prefill may immediately finish
        its request (stop token / max_tokens=1), freeing slots, so the
        admit→prefill cycle repeats until the scheduler yields nothing.
        """
        # Retire landed tier promotions FIRST (non-blocking poll): their
        # prefill tails are the oldest admitted work, and a promotion
        # begun last cycle has had at least one decode window of
        # transfer overlap by now.
        emitted: list[tuple[int, int]] = list(
            self._finish_promotions(defer_to, may_block=False)
        )
        # Recovery: prefills whose earlier dispatch failed re-run before
        # anything else — their requests hold admitted slots and blocks,
        # and stay decode-gated until this succeeds.
        emitted.extend(self._retry_pending_prefills(defer_to))
        admitted_any = False
        while True:
            admitted: list[Request] = []
            while (rid := self._admit_next_evicting()) is not None:
                request = self._requests[rid]
                request.state = RequestState.RUNNING
                if request.t_admit == 0.0:  # first admission only, not
                    request.t_admit = time.monotonic()  # preemption retries
                    _metrics.REQUEST_QUEUE_WAIT.observe(
                        request.t_admit - request.t_enqueue
                    )
                admitted.append(request)
            if not admitted:
                # Exit poll: promotions begun THIS call whose transfer
                # already landed (is_ready — synchronous backends, or a
                # transfer that raced ahead) prefill now instead of
                # waiting a full loop cycle; in-flight ones keep
                # overlapping with the windows the caller dispatches.
                # Blocking is allowed only when this call admitted
                # nothing — if it did, that freshly dispatched prefill
                # work deserves its chance to overlap the transfer, and
                # the next cycle's exit poll is the backstop.
                emitted.extend(
                    self._finish_promotions(
                        defer_to, may_block=not admitted_any
                    )
                )
                return emitted
            admitted_any = True
            groups: dict[int, list[Request]] = {}
            paged: list[Request] = []
            chunk = self.config.prefill_chunk_tokens
            # Mixed batching: once windows are flowing, admitted tails ride
            # them as chunk rows instead of standalone dispatches. Decided
            # once per admitted batch — at cold start nothing is decoding,
            # so the first batch prefills standalone and bootstraps the
            # stream the rest ride.
            ride = (
                self.config.enable_mixed_batching and self._mixed_can_ride()
            )
            for request in admitted:
                if request.promo_digests and self._begin_promotion(request):
                    # Host-tier hit: the block transfer is in flight and
                    # the request waits (non-decode-ready, no prefill)
                    # until _finish_promotions retires it next cycle.
                    continue
                # Re-prefill covers generated tokens too (recompute
                # preemption path) but never the cached prefix — tail-only
                # prefill is the prefix cache's whole win.
                tail = request.num_tokens - request.num_cached_tokens
                paged_route = bool(
                    request.num_cached_tokens or (chunk and tail > chunk)
                )
                if ride and paged_route:
                    # Only paged-route tails ride windows: their spans go
                    # through the SAME ragged write-then-attend kernel as
                    # the standalone paged dispatch (key extent is always
                    # the full padded table), so mixed on/off stay bit-
                    # identical even in bf16. Fresh short prompts keep the
                    # batched dense prefill — a different kernel (bf16
                    # bits differ at scale) AND the better dispatch: one
                    # padded batch beats trickling them through budget-
                    # limited windows.
                    self._enroll_mixed(request)
                    continue
                if paged_route:
                    paged.append(request)
                    continue
                bucket = pick_bucket(tail, self.prefill_buckets)
                groups.setdefault(bucket, []).append(request)
            for bucket, requests in sorted(groups.items()):
                cap = self._prefill_batch_cap(bucket)
                for i in range(0, len(requests), cap):
                    self._stats['prefill_dispatches'] += 1
                    _metrics.ENGINE_PREFILL_DISPATCHES.inc()
                    emitted.extend(
                        self._run_prefill_batch(
                            requests[i : i + cap], bucket, defer_to
                        )
                    )
            emitted.extend(self._run_prefill_paged(paged, defer_to))

    def _admit_next_evicting(self) -> int | None:
        """``admit_next`` with prefix-cache eviction pressure: when
        admission stalls on blocks while unreferenced cached blocks exist,
        evict just enough (LRU) and retry."""
        while True:
            try:
                rid = self.sched.admit_next()
            except SchedulerExhausted:
                if not self._evict_for_admission():
                    raise
                continue
            if rid is not None:
                return rid
            if not self._evict_for_admission():
                return None

    def _evict_for_admission(self) -> bool:
        if (
            self.prefix_cache is None
            or not self.prefix_cache.num_evictable
            or not self.sched.num_waiting
            # admit_next() returning None conflates "no free slot" with
            # "block shortfall"; when every slot is busy, eviction cannot
            # admit anything and would only flush warm prefixes the next
            # turn needs.
            or self.sched.num_running >= self.config.max_num_seqs
        ):
            return False
        # Worst-case shortfall over waiting requests: evicting a few
        # blocks too many only costs cache entries, never correctness.
        need = 0
        for request in self._requests.values():
            if request.state is not RequestState.WAITING:
                continue
            short = self.kv.blocks_needed(request.num_tokens + 1) - len(
                self.sched.block_row(request.request_id)
            )
            need = max(need, short)
        return self._evict_cached_blocks(need - self.sched.num_free_blocks) > 0

    def _evict_cached_blocks(self, shortfall: int) -> int:
        """Evict up to ``shortfall`` LRU cache blocks into the scheduler's
        free list; returns how many were actually freed. With the host KV
        tier enabled the evicted blocks' KV is spilled device→host first
        (eviction cascades HBM → host → disk → drop); without it the KV
        is dropped outright — counted, never silent."""
        if self.prefix_cache is None or shortfall <= 0:
            return 0
        entries = self.prefix_cache.evict_entries(shortfall)
        if not entries:
            return 0
        if self.kv_tier is not None:
            self._spill_blocks(entries)
        else:
            # HBM is the only tier: this eviction loses the KV for good.
            _metrics.PREFIX_TIER_DROPPED_BLOCKS.inc(len(entries))
        freed = [bid for _, bid in entries]
        self.sched.release_blocks(freed)
        self._stats['prefix_evicted_blocks'] += len(freed)
        return len(freed)

    # ------------------------------------------------- host/disk KV tier
    @staticmethod
    def _pow2(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def _spill_blocks(self, entries: list[tuple[bytes, int]]) -> None:
        """Fetch the evicted blocks' KV device→host in padded gathers and
        adopt them into the host tier, clamped per gather to the pow2
        ladder :meth:`warmup` compiled — a multi-row reservation
        shortfall can evict more blocks than max_blocks_per_seq, and an
        unwarmed gather shape would stall the serving loop on a compile."""
        cap = self._pow2(self.max_blocks_per_seq)
        for start in range(0, len(entries), cap):
            self._spill_chunk(entries[start : start + cap])

    def _spill_chunk(self, entries: list[tuple[bytes, int]]) -> None:
        """One padded device→host gather of ``entries``' KV — the spill
        side's designed host sync: it runs only under pool pressure,
        serializes against at most the in-flight windows, and its cost is
        on the flight ring as the 'spill' record's fetch_s."""
        t_start = time.monotonic()
        n = len(entries)
        npad = self._pow2(n)
        idx = np.zeros((npad,), np.int32)
        for i, (_, bid) in enumerate(entries):
            idx[i] = bid
        k_dev, v_dev = self._gather_blocks(
            self.kv.k, self.kv.v, self._put(idx)
        )
        quantized = isinstance(k_dev, QuantizedKV)
        t_fetch = time.monotonic()
        ks_host = vs_host = None
        with self._annotate('fetch'):
            # distlint: disable=host-sync-in-hot-path -- the spill tier's ONE designed fetch point: evicted ref==0 blocks must cross to host RAM before their pool blocks are reused, and eviction only fires on pool-pressure shortfalls
            k_host = np.asarray(k_dev.data if quantized else k_dev)
            # distlint: disable=host-sync-in-hot-path -- second half of the same designed spill fetch (V plane of the one padded gather above)
            v_host = np.asarray(v_dev.data if quantized else v_dev)
            if quantized:
                # distlint: disable=host-sync-in-hot-path -- scale rows of the same designed spill fetch (4 bytes per block per KV head, riding the gather already paid for)
                ks_host = np.asarray(k_dev.scale)
                # distlint: disable=host-sync-in-hot-path -- V-side scale rows of the same designed spill fetch
                vs_host = np.asarray(v_dev.scale)
        fetch_s = time.monotonic() - t_fetch
        for i, (digest, _) in enumerate(entries):
            # Per-block copies: LRU eviction must free blocks one at a
            # time, which views over the gathered base array cannot. A
            # quantized pool spills the int8 blocks AS int8 plus their
            # scale rows (half the bytes over the host link; bit-exact
            # on promotion — no dequant/requant round trip).
            if quantized:
                self.kv_tier.put(
                    digest, k_host[:, i].copy(), v_host[:, i].copy(),
                    ks_host[:, i].copy(), vs_host[:, i].copy(),
                )
            else:
                self.kv_tier.put(
                    digest, k_host[:, i].copy(), v_host[:, i].copy()
                )
        self._stats['tier_spills'] += 1
        self._stats['tier_spilled_blocks'] += n
        spilled_bytes = int(k_host[:, :n].nbytes + v_host[:, :n].nbytes)
        if quantized:
            spilled_bytes += int(
                ks_host[:, :n].nbytes + vs_host[:, :n].nbytes
            )
        self.flight.record(
            'spill',
            blocks=n,
            bytes=spilled_bytes,
            fetch_s=round(fetch_s, 6),
            duration_s=round(time.monotonic() - t_start, 6),
            host_tier_blocks=self.kv_tier.num_blocks,
        )

    def _begin_promotion(self, request: Request) -> bool:
        """Start the async promotion of ``request``'s host-tier blocks
        back into the paged pool: device_put the pooled KV, dispatch the
        scatter into the request's own blocks, and ADOPT those blocks
        into the prefix cache immediately (insert + lend_prefix), so they
        are borrowed — counted toward budgets, never freed to the free
        list mid-promotion, surviving preemption like any cached prefix.
        No host sync here: the transfer overlaps in-flight decode windows
        and ``_finish_promotions`` retires it next cycle. Returns False
        when the tier entries vanished (evicted since add_request) — the
        caller falls through to the normal prefill routing."""
        digests = request.promo_digests
        request.promo_digests = []
        rid = request.request_id
        bs = self.config.block_size
        num_layers, _, block_size, n_kv, head_dim = self.kv.shape
        slice_shape = (num_layers, block_size, n_kv, head_dim)
        pool_quantized = self.kv.quantized
        arity = 4 if pool_quantized else 2
        pulled: list[tuple[np.ndarray, ...]] = []
        for digest in digests:
            kv = self.kv_tier.get(digest)
            if kv is None:
                break  # tier-evicted since the add_request walk
            if (
                len(kv) != arity
                or kv[0].dtype != self.kv.dtype
                or kv[0].shape != slice_shape
            ):
                # A spill from a different kv_cache_dtype/geometry config
                # (e.g. bf16 disk files meeting a fresh int8 pool, or the
                # reverse): payload-shape truth beats index membership —
                # treat as a miss and cold-prefill rather than scatter
                # another encoding's bytes into the pool.
                self._stats['tier_payload_mismatches'] += 1
                break
            pulled.append(kv)
        if not pulled:
            return False
        t_start = time.monotonic()
        n = len(pulled)
        digests = digests[:n]
        nb = request.num_borrowed_blocks
        blocks = self.sched.block_row(rid)[nb : nb + n]
        npad = self._pow2(n)
        k_host = np.zeros(
            (num_layers, npad, block_size, n_kv, head_dim),
            dtype=pulled[0][0].dtype,
        )
        v_host = np.zeros_like(k_host)
        ks_host = vs_host = None
        if pool_quantized:
            ks_host = np.zeros((num_layers, npad, n_kv), np.float32)
            vs_host = np.zeros_like(ks_host)
        idx = np.zeros((npad,), np.int32)
        for i, entry in enumerate(pulled):
            k_host[:, i] = entry[0]
            v_host[:, i] = entry[1]
            if pool_quantized:
                ks_host[:, i] = entry[2]
                vs_host[:, i] = entry[3]
            idx[i] = blocks[i]
        t_host = time.monotonic()
        try:
            # Injection site 'device_put': the promotion transfer is the
            # one host→device path that runs against tier state rather
            # than request state, so its failure degrades — the request
            # falls through to cold prefill (return False), counted into
            # distllm_prefix_tier_errors_total{tier="host"}, never raised
            # into admission.
            self._faults.fail('device_put')
            if pool_quantized:
                # Scales stage beside the data planes in the SAME put
                # batch, then ride _write_promoted's tree.map scatter as
                # QuantizedKV leaves — promotion is int8-to-int8
                # bit-exact, scales intact.
                k_dev, v_dev, ks_dev, vs_dev, idx_dev = self._put_many(
                    k_host, v_host, ks_host, vs_host, idx
                )
                k_dev = QuantizedKV(k_dev, ks_dev)
                v_dev = QuantizedKV(v_dev, vs_dev)
            else:
                k_dev, v_dev, idx_dev = self._put_many(k_host, v_host, idx)
            with self._annotate('promote'):
                self.kv.k, self.kv.v = self._write_promoted(
                    self.kv.k, self.kv.v, k_dev, v_dev, idx_dev
                )
            token = self._probe(self.kv.k)
        except Exception as exc:
            _metrics.PREFIX_TIER_ERRORS.labels(tier='host').inc()
            self._stats['tier_promotion_failures'] += 1
            self.flight.record(
                'event',
                event='promotion_failed',
                rids=[rid],
                blocks=n,
                error=repr(exc)[:200],
            )
            return False
        t_dispatch = time.monotonic()
        # Adopt NOW (not at completion): once inserted + lent the blocks
        # are cache property in both scheduler front-ends — preemption
        # keeps them and dispatch ordering guarantees every later reader
        # sees the scattered KV. First-writer-wins may reject a digest a
        # concurrent request prefilled meanwhile; blocks past the first
        # rejection stay owned (their KV is still valid for THIS row).
        lent = nb
        for digest, bid in zip(digests, blocks):
            if not self.prefix_cache.insert(rid, digest, bid):
                break
            lent += 1
        if lent > nb:
            self.sched.lend_prefix(rid, lent)
            request.num_borrowed_blocks = lent
        request.num_cached_tokens = (nb + n) * bs
        # Decode-readiness gate (the mixed-window mechanism, reused): the
        # request takes no decode steps and no prefill until the
        # promotion retires and its tail prefills.
        request.prefill_target = request.num_tokens
        request.prefill_sent = request.num_cached_tokens
        request.prefill_done = request.num_cached_tokens
        self._promoting[rid] = {
            'token': token,
            'blocks': n,
            'tokens': n * bs,
            't_start': t_start,
            'put_s': round(t_dispatch - t_host, 6),
            'host_s': round(t_host - t_start, 6),
        }
        self._stats['tier_promotions'] += 1
        self._stats['tier_promoted_blocks'] += n
        _metrics.PREFIX_TIER_PROMOTIONS.labels(tier='host').inc(n)
        _metrics.PREFIX_HIT_TOKENS.inc(n * bs)
        self._stats['prefix_hit_tokens'] += n * bs
        return True

    def _finish_promotions(
        self, defer_to=None, may_block: bool = True
    ) -> list[tuple[int, int]]:
        """Retire landed promotions: one audited completion sync per
        promotion (visible as the 'promote' record's wait_s, the put_s
        twin of the window fetch), then prefill the still-uncached tail
        exactly as a plain cache hit would. Non-blocking while other rows
        can make progress — the poll keeps the device_put overlapped with
        decode windows; it hard-waits only when ``may_block`` (the
        caller's admission round produced nothing to overlap with) AND
        every running row is itself waiting on a promotion — the state
        nothing else can advance out of."""
        if not self._promoting:
            return []
        block = may_block and all(
            rid in self._promoting for _, rid in self.sched.running()
        )
        ready: list[Request] = []
        for rid in list(self._promoting):
            record = self._promoting[rid]
            request = self._requests.get(rid)
            if request is None or request.state is not RequestState.RUNNING:
                self._promoting.pop(rid)  # finished/preempted meanwhile
                continue
            token = record['token']
            if not block:
                is_ready = getattr(token, 'is_ready', None)
                if is_ready is not None and not is_ready():
                    continue  # still in flight; keep overlapping
            t_wait = time.monotonic()
            with self._annotate('fetch'):
                # distlint: disable=host-sync-in-hot-path -- the promotion path's ONE designed completion sync: a one-element probe of the post-scatter pool proves the promoted KV landed before the tail prefill (and any decode window) reads it
                np.asarray(token)
            wait_s = time.monotonic() - t_wait
            span_s = time.monotonic() - record['t_start']
            self._tier_times['promote_wait_s'] += wait_s
            self._tier_times['promote_span_s'] += span_s
            self._promoting.pop(rid)
            request.prefill_target = 0
            request.prefill_sent = request.num_cached_tokens
            request.prefill_done = request.num_cached_tokens
            ready.append(request)
            self.flight.record(
                'promote',
                rids=[rid],
                blocks=record['blocks'],
                tokens=record['tokens'],
                host_s=record['host_s'],
                put_s=record['put_s'],
                wait_s=round(wait_s, 6),
                span_s=round(span_s, 6),
                overlap=round(max(0.0, 1.0 - wait_s / span_s), 4)
                if span_s > 0 else None,
            )
        if not ready:
            return []
        return self._run_prefill_paged(ready, defer_to)

    def tier_summary(self) -> dict:
        """Host/disk KV-tier counters and promotion-overlap efficiency
        (empty when the tier is disabled) — what the ``gen_tier`` bench
        stage checkpoints next to warm/cold TTFT."""
        if self.kv_tier is None:
            return {}
        wait = self._tier_times['promote_wait_s']
        span = self._tier_times['promote_span_s']
        out = {
            'spills': int(self._stats.get('tier_spills', 0)),
            'spilled_blocks': int(self._stats.get('tier_spilled_blocks', 0)),
            'promotions': int(self._stats.get('tier_promotions', 0)),
            'promoted_blocks': int(
                self._stats.get('tier_promoted_blocks', 0)
            ),
            'promote_wait_s': round(wait, 6),
            'promote_span_s': round(span, 6),
            'promotion_overlap': (
                round(max(0.0, 1.0 - wait / span), 4) if span > 0 else None
            ),
            'host_blocks': self.kv_tier.num_blocks,
            'host_bytes': self.kv_tier.bytes_used,
        }
        if self.kv_tier.disk is not None:
            out['disk_blocks'] = self.kv_tier.disk.num_blocks
            out['disk_bytes'] = self.kv_tier.disk.bytes_used
        if self.kv_tier.peer is not None:
            out['peer_fetched_blocks'] = self.kv_tier.peer.fetched_blocks
            out['peer_fetched_bytes'] = self.kv_tier.peer.fetched_bytes
        if self._peer_kv_server is not None:
            out['peer_served_blocks'] = self._peer_kv_server.served_blocks
            out['peer_served_bytes'] = self._peer_kv_server.served_bytes
        return out

    def _prefill_batch_cap(self, bucket: int) -> int:
        """Largest pow2 batch for this bucket under the prefill caps.

        Also bounded by pow2ceil(max_num_seqs): no admission group can
        exceed the slot count, so larger shapes would be compiled (by
        ``warmup``) but never dispatched.
        """
        cap = min(
            self.config.max_prefill_batch,
            max(1, self.config.max_prefill_tokens // bucket),
        )
        b = 1
        while b * 2 <= cap:
            b *= 2
        seqs_ceil = 1
        while seqs_ceil < self.config.max_num_seqs:
            seqs_ceil *= 2
        return min(b, seqs_ceil)

    # ------------------------------------------------ mixed serving windows
    def _mixed_rows(self) -> int:
        """Chunk-row count of every mixed dispatch: the pow2 ceiling of
        ``max_window_prefill_seqs``. FIXED (planner under-fills with trash
        rows) so the row dim never adds compiled shapes — only the chunk
        token bucket varies."""
        b = 1
        while b < self.config.max_window_prefill_seqs:
            b *= 2
        return b

    def _mixed_span_cap(self) -> int:
        """Largest chunk span one request may ride per window: the window
        budget, further capped by ``prefill_chunk_tokens`` when set so
        mixed chunk planning composes with the chunked-prefill buckets."""
        cap = min(
            self.config.max_window_prefill_tokens,
            self.config.max_model_len,
        )
        if self.config.prefill_chunk_tokens:
            cap = min(cap, self.config.prefill_chunk_tokens)
        return max(1, cap)

    @staticmethod
    def _decode_ready(request: Request) -> bool:
        """May this running request take decode steps? False only while
        its prefill tail is still riding mixed windows (the final chunk's
        processed sample is what turns it decode-ready)."""
        return request.prefill_done >= request.prefill_target

    def _mixed_can_ride(self) -> bool:
        """True when windows are flowing for chunks to ride: some running
        request is actively decoding (emitted or in-flight tokens), or
        chunk work is already pending (chunk-only windows keep dispatching
        until it drains). At cold start neither holds and admission uses
        the standalone prefill path — a chunk-only window would pay the
        full ``decode_steps`` weight stream for a handful of prefill
        tokens, so the engine bootstraps the stream before anything rides
        it. Freshly admitted same-batch requests don't count: they have
        neither output nor unacked tokens yet."""
        if self._prefilling:
            return True
        for _, rid in self.sched.running():
            request = self._requests[rid]
            if request.output_ids or self._unacked.get(rid):
                return True
        return False

    def _enroll_mixed(self, request: Request) -> None:
        """Route this admitted request's uncached tail through mixed
        windows. COW resolves here (admission) rather than at prefill
        dispatch — the source block's contents are already final, so the
        copy is value-identical at either point."""
        if request.cow_src_block is not None:
            self._resolve_cow([request])
        request.prefill_target = request.num_tokens
        request.prefill_sent = request.num_cached_tokens
        request.prefill_done = request.num_cached_tokens
        self._prefilling.append(request.request_id)

    def _plan_window_chunks(self) -> list[tuple[Request, int, int]]:
        """Chunk spans riding the next window: FIFO over mid-prefill
        requests, one span each, bounded by the window token budget, the
        row cap, and the span cap. Returns ``[(request, start, ntok)]``
        in absolute tokens; prunes stale (finished/preempted) entries."""
        if self._mixed_window is None or not self._prefilling:
            return []
        budget = self.config.max_window_prefill_tokens
        span_cap = self._mixed_span_cap()
        plan: list[tuple[Request, int, int]] = []
        for rid in list(self._prefilling):
            if budget <= 0 or len(plan) >= self.config.max_window_prefill_seqs:
                break
            request = self._requests.get(rid)
            if request is None or request.state is not RequestState.RUNNING:
                self._prefilling.remove(rid)
                continue
            remaining = request.prefill_target - request.prefill_sent
            if remaining <= 0:
                continue  # final chunk already in flight
            ntok = min(remaining, span_cap, budget)
            plan.append((request, request.prefill_sent, ntok))
            budget -= ntok
        return plan

    def _span_host_arrays(self, spans, bucket: int, rows: int,
                          token_rows=None):
        """The padded paged-span host arrays — (ids, positions,
        block_rows, context_lens, tail_lens) — for ``spans`` =
        ``[(request, start, ntok)]``. ONE builder shared by standalone
        paged prefill, mixed chunk rows, and speculative verify spans:
        the span/padding contract (trash-routed pads, clamped RoPE
        positions) is exactly what the mixed-vs-pure and spec-on/off
        bit-identity guarantees rest on, so it must not be able to
        diverge between the dispatch paths. Pad rows — and spans whose
        ``request`` is None or ``ntok`` 0 (inactive slots in a spec
        window's slot-indexed layout) — carry tail 0 + all-zero tables:
        writes land in the trash block and their logits are garbage the
        caller discards. ``token_rows`` (parallel to ``spans``) supplies
        each span's tokens explicitly instead of slicing the request's
        history — verify spans carry drafts that are not history yet."""
        ids = np.zeros((rows, bucket), np.int32)
        positions = np.zeros((rows, bucket), np.int32)
        block_rows = np.zeros((rows, self.max_blocks_per_seq), np.int32)
        context_lens = np.ones((rows,), np.int32)
        tail_lens = np.zeros((rows,), np.int32)
        max_pos = self.config.max_model_len - 1
        for i, (request, start, ntok) in enumerate(spans):
            if request is None or ntok <= 0:
                continue  # inactive slot: the pad-row contract applies
            toks = (
                token_rows[i][:ntok]
                if token_rows is not None
                else (request.prompt_ids + request.output_ids)[
                    start : start + ntok
                ]
            )
            ids[i, :ntok] = toks
            # Padding columns clamp to max_model_len-1 so the RoPE table
            # gather stays in range; their writes are masked to trash.
            positions[i] = np.minimum(start + np.arange(bucket), max_pos)
            block_rows[i] = self._block_row(request.request_id)
            context_lens[i] = start + ntok
            tail_lens[i] = ntok
        return ids, positions, block_rows, context_lens, tail_lens

    def _build_chunk_arrays(self, chunk_plan) -> list[np.ndarray]:
        """Host arrays for a mixed window's chunk rows, in the mixed
        executable's operand order: the shared span arrays plus per-row
        sampling params. Rows pad to the FIXED ``_mixed_rows()`` count."""
        cb = self._mixed_rows()
        bucket = pick_bucket(
            max(ntok for _, _, ntok in chunk_plan), self.prefill_buckets
        )
        ids, positions, block_rows, context_lens, tail_lens = (
            self._span_host_arrays(chunk_plan, bucket, cb)
        )
        c_temp = np.zeros((cb,), np.float32)
        c_top_p = np.ones((cb,), np.float32)
        c_min_p = np.zeros((cb,), np.float32)
        c_top_k = np.zeros((cb,), np.int32)
        c_seeds = np.zeros((cb,), np.uint32)
        for i, (request, _, _) in enumerate(chunk_plan):
            c_temp[i] = request.params.temperature
            c_top_p[i] = request.params.top_p
            c_min_p[i] = request.params.min_p
            c_top_k[i] = request.params.top_k
            c_seeds[i] = request.sample_seed
        return [ids, positions, block_rows, context_lens, tail_lens,
                c_temp, c_top_p, c_min_p, c_top_k, c_seeds]

    # -------------------------------------------------------------- prefill
    def _mark_prefill_retry(self, requests: list[Request]) -> None:
        """A prefill dispatch for ``requests`` failed: gate each request
        out of decode plans (the mixed-window prefill_target mechanism —
        decode must never read KV the prefill never wrote) and queue it
        for a recovery re-dispatch (``_retry_pending_prefills``). Chunk
        progress resets to the cached prefix: re-writing already-written
        positions is idempotent, so the retry is exact."""
        for request in requests:
            request.prefill_target = request.num_tokens
            request.prefill_sent = request.num_cached_tokens
            request.prefill_done = request.num_cached_tokens
            if request.request_id not in self._pending_prefill:
                self._pending_prefill.append(request.request_id)

    def _retry_pending_prefills(self, defer_to=None) -> list[tuple[int, int]]:
        """Re-dispatch prefills whose earlier attempt failed (recovery
        path), through the paged route — tail-only over whatever KV is
        already valid, which covers dense-path victims too (their tail is
        the whole prompt)."""
        if not self._pending_prefill:
            return []
        rids, self._pending_prefill = self._pending_prefill, []
        requests: list[Request] = []
        for rid in rids:
            request = self._requests.get(rid)
            if request is None or request.state is not RequestState.RUNNING:
                continue  # quarantined / preempted / finished meanwhile
            request.prefill_target = 0
            request.prefill_sent = request.num_cached_tokens
            request.prefill_done = request.num_cached_tokens
            requests.append(request)
        return self._run_prefill_paged(requests, defer_to)

    def _run_prefill_batch(
        self, requests: list[Request], bucket: int, defer_to=None
    ) -> list[tuple[int, int]]:
        """Dense-path prefill with the recovery contract: a failure marks
        every batched request for re-prefill before propagating, so a
        retrying serving loop cannot decode over unwritten KV (the retry
        routes through the paged path — bit-identical in fp32, while a
        bf16 retry may differ bitwise from the dense kernel; chaos
        identity guarantees are fp32, docs/resilience.md)."""
        try:
            self._faults.fail('dispatch')
            return self._run_prefill_batch_inner(requests, bucket, defer_to)
        except Exception:
            self._mark_prefill_retry(requests)
            raise

    def _run_prefill_batch_inner(
        self, requests: list[Request], bucket: int, defer_to=None
    ) -> list[tuple[int, int]]:
        """Prefill same-bucket requests in one padded dispatch.

        ``defer_to`` (a deque of in-flight window records) switches to the
        pipelined emission path: first tokens stay on device and their
        host fetch is processed later with the decode windows.

        The batch dim pads up the pow2 ladder (capped at
        ``max_prefill_batch``) so the jit cache holds at most
        O(log batch x log length) prefill shapes. Padding rows carry
        length 0: their K/V scatter lands in trash block 0 and their
        sampled token is discarded.
        """
        _metrics.ENGINE_PREFILL_BATCH.observe(len(requests))
        t_start = time.monotonic()
        b = 1
        while b < len(requests):
            b *= 2
        ids = np.zeros((b, bucket), np.int32)
        mask = np.zeros((b, bucket), np.int32)
        last_pos = np.zeros((b,), np.int32)
        lengths = np.zeros((b,), np.int32)
        block_rows = np.zeros((b, self.max_blocks_per_seq), np.int32)
        for i, request in enumerate(requests):
            prompt = request.prompt_ids + request.output_ids
            ids[i, : len(prompt)] = prompt
            mask[i, : len(prompt)] = 1
            last_pos[i] = len(prompt) - 1
            lengths[i] = len(prompt)
            block_rows[i] = self._block_row(request.request_id)

        t_host = time.monotonic()
        (
            ids_dev,
            mask_dev,
            last_pos_dev,
            block_rows_dev,
            lengths_dev,
        ) = self._put_many(ids, mask, last_pos, block_rows, lengths)
        t_put = time.monotonic()
        with self._annotate('prefill'):
            last_logits, k_all, v_all = self._prefill(
                self.params, ids_dev, mask_dev, last_pos_dev
            )
            self.kv.k, self.kv.v = self._write_prefill(
                self.kv.k,
                self.kv.v,
                k_all,
                v_all,
                block_rows_dev,
                lengths_dev,
            )
        t_dispatch = time.monotonic()
        # Full prompt blocks just entered the paged cache — adopt them
        # into the prefix cache BEFORE emission (a max_tokens=1 request
        # finishes inside _emit_prefill, after which its row is gone).
        for request in requests:
            self._insert_prompt_blocks(request)
        emitted = self._emit_prefill(requests, last_logits, b, defer_to)
        self._record_step(
            'prefill', t_start, batch=len(requests),
            tokens=int(lengths.sum()),
            **self._attribution_fields(
                t_start, t_host, t_put, t_dispatch,
                rids=[r.request_id for r in requests],
            ),
        )
        return emitted

    def _emit_prefill(
        self,
        requests: list[Request],
        last_logits,
        b: int,
        defer_to,
    ) -> list[tuple[int, int]]:
        """Sample + emit each prefilled request's first token.

        First token of each sequence, sampled from its last prompt
        position; padding rows sample too but are dropped here.
        """
        slots: list[Request | None] = list(requests) + [None] * (
            b - len(requests)
        )
        if defer_to is None:
            tokens = np.asarray(self._sample_device(last_logits, slots))
            emitted = []
            for i, request in enumerate(requests):
                token = int(tokens[i])
                self._emit_token(request, token)
                emitted.append((request.request_id, token))
            return emitted

        # Pipelined path: the sampled first tokens STAY on device. They are
        # scattered into the carried last-ids vector (so the next decode
        # window reads them without a host round trip) and the host fetch
        # rides the in-flight deque as a 1-step window record — the same
        # unacked/one-window-late bookkeeping decode EOS already uses.
        # probe_gen (chipback_r05) showed decode windows already run at
        # device speed; the serving-loop gap was 18 blocking prefill
        # fetches serializing against the decode pipeline.
        tok_dev = self._sample_device(last_logits, slots)
        slot_of = {rid: slot for slot, rid in self.sched.running()}
        slot_idx = np.asarray(
            [slot_of[r.request_id] for r in requests], np.int32
        )
        if self._carried is None:
            self._carried = self._put(
                np.zeros((self.config.max_num_seqs,), np.int32)
            )
        self._carried = self._scatter_tokens(
            self._carried, self._put(slot_idx), tok_dev[: len(requests)]
        )
        plan = []
        for i, request in enumerate(requests):
            rid = request.request_id
            self._unacked[rid] = self._unacked.get(rid, 0) + 1
            plan.append((i, rid, 1))
        defer_to.append({'tokens': tok_dev[None, :], 'plan': plan})
        return []

    # ---------------------------------------------- prefix-cached prefill
    def _run_prefill_paged(
        self, requests: list[Request], defer_to=None
    ) -> list[tuple[int, int]]:
        """Prefill requests through the paged-context path: cache hits
        prefill only their uncached tail, and tails longer than
        ``prefill_chunk_tokens`` split into sequential bucketed chunks."""
        if not requests:
            return []
        self._resolve_cow(
            [r for r in requests if r.cow_src_block is not None]
        )
        emitted: list[tuple[int, int]] = []
        chunk = self.config.prefill_chunk_tokens
        whole: dict[int, list[Request]] = {}
        chunked: list[Request] = []
        for request in requests:
            tail = request.num_tokens - request.num_cached_tokens
            if chunk and tail > chunk:
                chunked.append(request)
            else:
                bucket = pick_bucket(tail, self.prefill_buckets)
                whole.setdefault(bucket, []).append(request)
        for bucket, rs in sorted(whole.items()):
            cap = self._prefill_batch_cap(bucket)
            for i in range(0, len(rs), cap):
                batch = rs[i : i + cap]
                spans = [
                    (
                        r,
                        r.num_cached_tokens,
                        r.num_tokens - r.num_cached_tokens,
                    )
                    for r in batch
                ]
                emitted.extend(
                    self._dispatch_prefill_paged(spans, bucket, defer_to)
                )
        for request in chunked:
            emitted.extend(self._run_prefill_chunked(request, defer_to))
        return emitted

    def _run_prefill_chunked(
        self, request: Request, defer_to=None
    ) -> list[tuple[int, int]]:
        """Prefill one long uncached tail as sequential bucketed chunks.

        Each chunk attends over the KV already in the paged cache (the
        cached prefix plus earlier chunks), so splitting is exact. Only
        the final chunk samples; between chunks the pipelined loop may
        retire an in-flight decode window (``_drain_hook``) so a long
        prompt cannot stall decode for its whole prefill.
        """
        chunk = self.config.prefill_chunk_tokens
        start = request.num_cached_tokens
        total = request.num_tokens
        emitted: list[tuple[int, int]] = []
        while start < total:
            ntok = min(chunk, total - start)
            final = start + ntok >= total
            bucket = pick_bucket(ntok, self.prefill_buckets)
            self._stats['prefill_chunks'] += 1
            _metrics.ENGINE_PREFILL_CHUNKS.inc()
            _metrics.ENGINE_PREFILL_CHUNK_TOKENS.observe(ntok)
            emitted.extend(
                self._dispatch_prefill_paged(
                    [(request, start, ntok)], bucket, defer_to, sample=final
                )
            )
            start += ntok
            if not final and self._drain_hook is not None:
                self._drain_hook()
        return emitted

    def _dispatch_prefill_paged(
        self,
        spans: list[tuple[Request, int, int]],
        bucket: int,
        defer_to=None,
        sample: bool = True,
    ) -> list[tuple[int, int]]:
        """Paged-path prefill with the recovery contract (see
        ``_run_prefill_batch``): mark-for-retry on failure, then raise."""
        try:
            self._faults.fail('dispatch')
            return self._dispatch_prefill_paged_inner(
                spans, bucket, defer_to, sample
            )
        except Exception:
            self._mark_prefill_retry([r for r, _, _ in spans])
            raise

    def _dispatch_prefill_paged_inner(
        self,
        spans: list[tuple[Request, int, int]],
        bucket: int,
        defer_to=None,
        sample: bool = True,
    ) -> list[tuple[int, int]]:
        """One padded paged-context prefill dispatch.

        ``spans`` is ``[(request, start_token, num_tokens)]``; every span's
        K/V lands in the request's own blocks at absolute positions, and
        its queries attend to everything before them through the paged
        cache. ``sample=False`` (intermediate chunks) skips emission.
        """
        requests = [r for r, _, _ in spans]
        _metrics.ENGINE_PREFILL_BATCH.observe(len(requests))
        self._stats['prefill_dispatches'] += 1
        _metrics.ENGINE_PREFILL_DISPATCHES.inc()
        t_start = time.monotonic()
        b = 1
        while b < len(spans):
            b *= 2
        ids, positions, block_rows, context_lens, tail_lens = (
            self._span_host_arrays(spans, bucket, b)
        )
        t_host = time.monotonic()
        (
            ids_dev,
            positions_dev,
            block_rows_dev,
            context_lens_dev,
            tail_lens_dev,
        ) = self._put_many(
            ids, positions, block_rows, context_lens, tail_lens
        )
        t_put = time.monotonic()
        with self._annotate('prefill'):
            last_logits, self.kv.k, self.kv.v = self._prefill_paged(
                self.params,
                ids_dev,
                positions_dev,
                self.kv.k,
                self.kv.v,
                block_rows_dev,
                context_lens_dev,
                tail_lens_dev,
            )
        t_dispatch = time.monotonic()
        attrib = self._attribution_fields(
            t_start, t_host, t_put, t_dispatch,
            rids=[r.request_id for r in requests],
        )
        chunk_tokens = int(tail_lens.sum())
        if not sample:
            self._record_step(
                'prefill', t_start, batch=len(requests),
                tokens=chunk_tokens, **attrib,
            )
            return []
        for request in requests:
            self._insert_prompt_blocks(request)
        emitted = self._emit_prefill(requests, last_logits, b, defer_to)
        self._record_step(
            'prefill', t_start, batch=len(requests), tokens=chunk_tokens,
            **attrib,
        )
        return emitted

    def _resolve_cow(self, requests: list[Request]) -> None:
        """Copy-on-write for aligned full-cover hits: duplicate each
        shared final block into the request's first OWNED block (one
        batched device copy across all layers), so the last prompt
        token's K/V write cannot touch a block other requests read."""
        if not requests:
            return
        srcs: list[int] = []
        dsts: list[int] = []
        for request in requests:
            row = self.sched.block_row(request.request_id)
            dsts.append(row[request.num_borrowed_blocks])
            srcs.append(request.cow_src_block)
            request.cow_src_block = None
        self._stats['prefix_cow_copies'] += len(srcs)
        _metrics.PREFIX_COW_COPIES.inc(len(srcs))
        src_dev, dst_dev = self._put_many(
            np.asarray(srcs, np.int32), np.asarray(dsts, np.int32)
        )
        self.kv.k, self.kv.v = self._cow_copy(
            self.kv.k, self.kv.v, src_dev, dst_dev
        )

    def _insert_prompt_blocks(self, request: Request) -> None:
        """Adopt this request's freshly prefilled FULL prompt blocks into
        the prefix cache (first writer wins) and mark them borrowed in the
        scheduler so finish/preemption cannot free them."""
        if self.prefix_cache is None or not request.digests:
            return
        rid = request.request_id
        row = self.sched.block_row(rid)
        nb = request.num_borrowed_blocks
        lent = nb
        for i in range(nb, len(request.digests)):
            if not self.prefix_cache.insert(rid, request.digests[i], row[i]):
                break
            lent = i + 1
        if lent > nb:
            self.sched.lend_prefix(rid, lent)
            request.num_borrowed_blocks = lent

    def _annotate(self, kind: str):
        """``jax.profiler.TraceAnnotation`` around a dispatch when
        attribution is on: profiler captures (``DISTLLM_BENCH_PROFILE``)
        then carry a ``distllm:<kind>`` host slice over every device
        launch, tying XPlane device time back to engine step kinds."""
        if not self.attribution:
            return contextlib.nullcontext()
        try:
            return jax.profiler.TraceAnnotation(f'distllm:{kind}')
        # distlint: disable=swallowed-exception -- annotations are optional decoration on profiler-less backends; the nullcontext fallback changes no behavior and profiler availability is reported by the capture layer
        except Exception:  # pragma: no cover - profiler-less backends
            return contextlib.nullcontext()

    def _attribution_fields(
        self, t_start, t_host, t_put, t_dispatch, *, fetch_s=None, rids=None,
    ) -> dict:
        """The device/host step split for one flight record (empty when
        attribution is off): ``host_s`` (plan build), ``put_s``
        (host→device transfer), ``dispatch_s`` (jit call; async backends
        return before the device finishes), plus ``fetch_s`` (device→host
        token fetch, where pipelined in-flight time surfaces) and the
        participating ``rids`` when the caller knows them."""
        if not self.attribution:
            return {}
        fields = {
            'host_s': round(t_host - t_start, 6),
            'put_s': round(t_put - t_host, 6),
            'dispatch_s': round(t_dispatch - t_put, 6),
        }
        if fetch_s is not None:
            fields['fetch_s'] = round(fetch_s, 6)
        if rids is not None:
            fields['rids'] = list(rids)
        return fields

    def _record_step(self, kind: str, t_start: float, *, batch: int,
                     tokens: int, **extra) -> None:
        """One flight-ring record + metrics pair per engine step.

        ``duration_s`` for prefill is the host-side dispatch (+ sync
        emission on the synchronous path); for decode/mixed it spans
        dispatch → host fetch, so pipelined in-flight time is included —
        the wall clock a stalled window would actually burn. ``extra``
        carries kind-specific fields (the ``mixed`` kind adds
        prefill_tokens/prefill_rows; with attribution on, every kind adds
        the host/put/dispatch/fetch timing split).

        With attribution on, the analytic roofline prices the step
        (observability/roofline.py) and the record carries ``mfu`` /
        ``bw_util`` next to the raw fields, mirrored into the
        ``distllm_engine_mfu`` / ``distllm_engine_bandwidth_utilization``
        gauges and the per-kind ``roofline_summary()`` accumulators.
        """
        duration_s = time.monotonic() - t_start
        _metrics.ENGINE_STEPS.labels(kind=kind).inc()
        _metrics.ENGINE_STEP_SECONDS.labels(kind=kind).observe(duration_s)
        # EWMA-measured TTFT-predictor inputs (resilience/admission.py),
        # fed regardless of the attribution flag — admission control must
        # keep predicting while attribution is flipped off.
        if kind == 'prefill' and tokens > 0:
            self._ewma_update('prefill_s_per_token', duration_s / tokens)
        else:
            self._ewma_update('window_s', duration_s)
        if self._cost_model is not None and self.attribution:
            cost = self._cost_model.step_cost(
                kind,
                tokens=tokens,
                batch=batch,
                draft_tokens=extra.get('draft_tokens', 0),
                prefill_tokens=extra.get('prefill_tokens', 0),
            )
            if cost is not None:
                mfu, bw_util = self._cost_model.utilization(cost, duration_s)
                _metrics.ENGINE_MFU.labels(kind=kind).set(mfu)
                _metrics.ENGINE_BW_UTIL.labels(kind=kind).set(bw_util)
                acc = self._roofline.setdefault(
                    kind,
                    {'windows': 0.0, 'seconds': 0.0, 'flops': 0.0,
                     'hbm_bytes': 0.0},
                )
                acc['windows'] += 1
                acc['seconds'] += duration_s
                acc['flops'] += cost.flops
                acc['hbm_bytes'] += cost.hbm_bytes
                extra = {
                    **extra,
                    'mfu': round(mfu, 5),
                    'bw_util': round(bw_util, 5),
                }
                # Measured twin (observability/xla_cost.py): the same
                # window priced from what XLA actually compiled, plus the
                # analytic-vs-measured calibration ratio gauges. Published
                # ONLY for dispatches whose compiled shape is the priced
                # one: decode always (fixed b x steps), spec when no
                # chunk rows rode (the chunk-carrying dispatch is a
                # different executable per bucket). Prefill/mixed dispatch
                # at varying (batch, bucket) shapes, so publishing the
                # priced largest-shape cost over a smaller dispatch's
                # wall time would inflate the gauges by the shape ratio —
                # their executable costs stay visible via
                # measured_costs(), never as per-dispatch gauges.
                fixed_shape = kind == 'decode' or (
                    kind == 'spec' and not extra.get('prefill_tokens')
                )
                measured = (
                    self._measured_costs.get(kind) if fixed_shape else None
                )
                if measured is not None:
                    m_mfu, m_bw = _xla_cost.publish_measured(
                        kind, measured, duration_s,
                        self._cost_model.peak_flops,
                        self._cost_model.peak_hbm_bytes,
                    )
                    _xla_cost.record_calibration(
                        kind, cost.flops, cost.hbm_bytes, measured
                    )
                    extra = {
                        **extra,
                        'mfu_measured': round(m_mfu, 5),
                        'bw_util_measured': round(m_bw, 5),
                    }
        usable = self.config.num_blocks - 1  # block 0 is reserved
        self.flight.record(
            kind,
            duration_s=round(duration_s, 6),
            batch=batch,
            occupancy=round(batch / self.config.max_num_seqs, 4),
            tokens=tokens,
            queue_depth=self.sched.num_waiting,
            running=self.sched.num_running,
            kv_occupancy=round(
                (usable - self.sched.num_free_blocks) / usable, 4
            ) if usable > 0 else 0.0,
            **extra,
        )

    def roofline_snapshot(self) -> dict[str, dict[str, float]]:
        """Copy of the raw per-kind roofline accumulators — pass a prior
        snapshot to ``roofline_summary(baseline=...)`` to scope the
        summary to just the windows recorded in between (how the loadgen
        isolates its run from warmup traffic)."""
        return {kind: dict(acc) for kind, acc in self._roofline.items()}

    def roofline_summary(
        self, baseline: dict[str, dict[str, float]] | None = None
    ) -> dict[str, dict[str, float]]:
        """Aggregate roofline view per window kind:
        ``{kind: {windows, seconds, mfu, bw_util}}`` with mfu/bw_util the
        time-weighted means (total flops/bytes over total seconds over
        the device peaks) — what the ``gen_load`` bench stage checkpoints.
        ``baseline`` (a prior :meth:`roofline_snapshot`) subtracts
        earlier windows so the summary covers one measured interval.
        Empty when the cost model was unavailable (and nothing
        accumulates while attribution is off)."""
        if self._cost_model is None:
            return {}
        out: dict[str, dict[str, float]] = {}
        for kind, acc in self._roofline.items():
            base = (baseline or {}).get(kind, {})
            acc = {
                key: value - base.get(key, 0.0)
                for key, value in acc.items()
            }
            seconds = acc['seconds']
            if seconds <= 0:
                continue
            out[kind] = {
                'windows': int(acc['windows']),
                'seconds': round(seconds, 4),
                'mfu': round(
                    acc['flops'] / seconds / self._cost_model.peak_flops, 5
                ),
                'bw_util': round(
                    acc['hbm_bytes']
                    / seconds
                    / self._cost_model.peak_hbm_bytes,
                    5,
                ),
            }
        return out

    def _block_row(self, rid: int) -> np.ndarray:
        row = np.zeros((self.max_blocks_per_seq,), np.int32)
        blocks = self.sched.block_row(rid)
        # Window reservation (batch-max kmax, up to pipeline_depth x
        # decode_steps tokens) may overshoot max_model_len by a few blocks;
        # those blocks are never addressed (positions stay < max_model_len)
        # so the row safely truncates.
        n = min(len(blocks), self.max_blocks_per_seq)
        row[:n] = blocks[:n]
        return row

    # --------------------------------------------------------------- decode
    def step(self) -> list[tuple[int, int]]:
        """One synchronous engine iteration: admit, then generate a window
        of up to ``decode_steps`` tokens per running sequence.

        Returns [(request_id, new_token)] in emission order. ``generate_ids``
        does NOT call this — it runs the pipelined loop that keeps
        ``pipeline_depth`` windows in flight; ``step`` is the simple API for
        interactive callers (chat server streaming, tests).

        Crash-domain recovery (``max_dispatch_retries > 0``,
        docs/resilience.md) applies here like in the pipelined loop: a
        failed dispatch is charged, backed off, and retried on the NEXT
        step() call instead of propagating; a step that failed mid-admit
        may under-report tokens already folded into request state, so
        resilient callers (run_loadgen) reconcile from the finished
        requests' ``output_ids``.
        """
        emitted: list[tuple[int, int]] = []
        try:
            self._expire_deadlines()
            emitted = self._admit()
            if self.sched.num_running == 0:
                return emitted
            window = self._dispatch_window(None)
            if window is not _DRAIN:
                emitted.extend(self._process_window(window))
            return emitted
        except Exception as exc:
            # A sync step has no in-flight deque: whatever window the
            # failed step dispatched is lost with its device-side tokens.
            # Clear the unacked lag and roll chunk progress back (the
            # pipelined loop's abnormal-drain rule) so a recovery retry
            # replans from host-visible state instead of waiting forever
            # on tokens nothing will ever fetch.
            self._unacked.clear()
            for pending_rid in self._prefilling:
                pending = self._requests.get(pending_rid)
                if pending is not None:
                    pending.prefill_sent = pending.prefill_done
            if not self._recover(exc):
                raise
            return emitted

    def _window_budget(self, request: Request, unacked: int, k: int) -> int:
        """Tokens this request may still generate in a new window, after
        accounting for unfetched device-side tokens. Zero while the
        request's prefill tail is still riding mixed windows."""
        if not self._decode_ready(request):
            return 0
        budget = min(
            request.params.max_tokens - len(request.output_ids) - unacked,
            self.config.max_model_len - request.num_tokens - unacked,
        )
        return max(0, min(k, budget))

    def _window_kmax(self) -> int:
        """Per-sequence reservation target for the next window: inflight
        (unacked) tokens plus this window's steps, maxed over the batch."""
        k = self.config.decode_steps
        kmax = 1
        for _, rid in self.sched.running():
            request = self._requests[rid]
            unacked = self._unacked.get(rid, 0)
            kmax = max(kmax, unacked + self._window_budget(request, unacked, k))
        return kmax

    def _reserve_shortfall(self, kmax: int, row_ks=None) -> int:
        """Blocks ``prepare_decode(kmax)`` would need beyond what running
        sequences already own — used by the pipelined loop to guarantee no
        preemption happens while windows are in flight (preempting a
        sequence whose blocks an in-flight window still writes to would
        let a re-allocation corrupt another sequence's KV). ``row_ks``
        (speculative windows) replaces the uniform ``kmax`` with each
        row's own headroom; rows absent from it take no decode extension
        this window."""
        bs = self.config.block_size
        short = 0
        for _, rid in self.sched.running():
            request = self._requests[rid]
            if not self._decode_ready(request):
                # Mixed prefill rows take no decode steps this window and
                # their chunk writes land in blocks granted at admission
                # (the full prompt is budgeted up front) — mirrors
                # prepare_decode(kmax, rids=decode-ready) below, so the
                # pipelined drain-before-preempt guard and the scheduler
                # agree on the shortfall.
                continue
            k_row = kmax if row_ks is None else row_ks.get(rid)
            if k_row is None:
                continue  # not participating in this spec window
            target = -(-(request.num_tokens + k_row) // bs)
            short += max(0, target - len(self.sched.block_row(rid)))
        return short

    def _dispatch_window(self, carried_ids) -> dict | object:
        """Plan and dispatch one fused decode window (no host sync).

        ``carried_ids`` is the previous window's device-side last-token
        vector (None = build fully from host knowledge). Slots with no
        unacked tokens are overridden from host state — fresh admissions,
        reused slots, or a drained pipeline. Under mixed batching the
        window may additionally carry prefill-chunk rows (planned below)
        and dispatch through the fused mixed executable. Returns the
        in-flight window record, or ``_DRAIN`` when every running slot's
        budget is already covered by in-flight windows AND no chunk work
        is pending (caller should process one).

        ``draft_k > 0`` routes to the speculative verify window instead
        (docs/speculative.md): one ragged dispatch scoring every row's
        prompt-lookup draft span. Spec windows ignore ``carried_ids`` —
        they process synchronously, so host state is always current.
        """
        if self.config.draft_k:
            return self._dispatch_spec_window()
        # Injection site 'dispatch' (docs/resilience.md): fires BEFORE
        # any state mutation (key split, unacked counts, chunk progress),
        # so a recovery retry replans from unchanged state — the
        # simulation boundary for an XLA dispatch raise.
        self._faults.fail('dispatch')
        t_start = time.monotonic()
        k = self.config.decode_steps
        kmax = self._window_kmax()
        decode_rids = None
        if (
            self.config.enable_mixed_batching
            or self._promoting
            or self._pending_prefill
        ):
            # Promotion-pending rows mirror mixed prefill rows: they take
            # no decode steps this window and their blocks were budgeted
            # at admission, so they must be excluded from the k-token
            # guarantee — otherwise prepare_decode would allocate (and
            # possibly preempt) for rows _reserve_shortfall skipped,
            # breaking the pipelined drain-before-preempt invariant.
            # Pending-prefill rows (a failed prefill dispatch awaiting
            # its recovery retry) are gated the same way: decode must
            # not read KV their prefill never wrote.
            decode_rids = [
                rid for _, rid in self.sched.running()
                if self._decode_ready(self._requests[rid])
            ]
        if decode_rids is None or decode_rids:
            # Eviction pressure beats preemption: unreferenced cached
            # blocks are free capacity, so spend those before recompute-
            # preempting a running sequence.
            self._evict_cached_blocks(
                self._reserve_shortfall(kmax) - self.sched.num_free_blocks
            )
            if self._faults.fire('sched_exhausted') is not None:
                # Injection site 'sched_exhausted': the pool-pressure
                # hazard, without needing a pool actually sized to hit it.
                raise SchedulerExhausted(
                    'injected scheduler exhaustion', preempted=[]
                )
            try:
                preempted = self.sched.prepare_decode(kmax, decode_rids)
            except SchedulerExhausted as exc:
                # Preemptions performed before the fatal exhaustion are not
                # rolled back; sync their states so a caller that catches
                # and continues sees engine state consistent with the
                # scheduler.
                for rid in exc.preempted:
                    self._on_preempt(self._requests[rid])
                raise
            for rid in preempted:
                # The pipelined loop drains in-flight windows before any
                # dispatch that could preempt, so victims never have
                # unacked device-side tokens OR in-flight chunk writes;
                # recompute preemption re-prefills them.
                self._on_preempt(self._requests[rid])
        # A chunk-only window (no decode-ready rows) skips prepare_decode
        # entirely: chunk writes land in admission-granted blocks, so it
        # must neither allocate nor preempt. Planned AFTER preemption so
        # a preempted victim's span never rides this window.
        chunk_plan = self._plan_window_chunks()
        running = [
            (slot, self._requests[rid]) for slot, rid in self.sched.running()
        ]
        if not running:
            return _DRAIN

        b = self.config.max_num_seqs
        ids = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        context_lens = np.ones((b,), np.int32)
        block_tables = np.zeros((b, self.max_blocks_per_seq), np.int32)
        steps_left = np.zeros((b,), np.int32)
        temperature = np.zeros((b,), np.float32)
        top_p = np.ones((b,), np.float32)
        min_p = np.zeros((b,), np.float32)
        top_k = np.zeros((b,), np.int32)
        seeds = np.zeros((b,), np.uint32)
        override_mask = np.zeros((b,), bool)
        plan: list[tuple[int, int, int]] = []
        any_steps = False
        for slot, request in running:
            rid = request.request_id
            unacked = self._unacked.get(rid, 0)
            steps = self._window_budget(request, unacked, k)
            total = request.num_tokens + unacked
            positions[slot] = total - 1
            context_lens[slot] = total
            block_tables[slot] = self._block_row(rid)
            steps_left[slot] = steps
            temperature[slot] = request.params.temperature
            top_p[slot] = request.params.top_p
            min_p[slot] = request.params.min_p
            top_k[slot] = request.params.top_k
            seeds[slot] = request.sample_seed
            if unacked == 0:
                ids[slot] = (
                    request.output_ids[-1]
                    if request.output_ids
                    else request.prompt_ids[-1]
                )
                override_mask[slot] = True
            plan.append((slot, rid, steps))
            any_steps = any_steps or steps > 0
        if not any_steps and not chunk_plan:
            return _DRAIN

        host_arrays = [
            ids, override_mask, positions, context_lens, block_tables,
            steps_left, temperature, top_p, min_p, top_k, seeds,
        ]
        if chunk_plan:
            host_arrays.extend(self._build_chunk_arrays(chunk_plan))
        t_host = time.monotonic()
        devs = self._put_many(*host_arrays)
        t_put = time.monotonic()
        (
            ids_dev,
            override_dev,
            positions_dev,
            context_lens_dev,
            block_tables_dev,
            steps_left_dev,
            temperature_dev,
            top_p_dev,
            min_p_dev,
            top_k_dev,
            seeds_dev,
        ) = devs[:11]
        if carried_ids is not None:
            ids_dev = self._merge_ids(carried_ids, override_dev, ids_dev)
        chunk_tokens = None
        chunk_entries: list[tuple[int, int, int, int, bool]] = []
        if chunk_plan:
            with self._annotate('mixed'):
                (
                    tokens,
                    self.kv.k,
                    self.kv.v,
                    last_ids,
                    chunk_tokens,
                ) = self._mixed_window(
                    self.params,
                    ids_dev,
                    positions_dev,
                    context_lens_dev,
                    self.kv.k,
                    self.kv.v,
                    block_tables_dev,
                    steps_left_dev,
                    temperature_dev,
                    top_p_dev,
                    min_p_dev,
                    top_k_dev,
                    seeds_dev,
                    *devs[11:],
                )
            ridden = 0
            for i, (request, start, ntok) in enumerate(chunk_plan):
                request.prefill_sent = start + ntok
                final = start + ntok >= request.prefill_target
                chunk_entries.append(
                    (i, request.request_id, start, ntok, final)
                )
                ridden += ntok
            self._stats['mixed_windows'] += 1
            self._stats['mixed_prefill_tokens'] += ridden
            _metrics.MIXED_WINDOWS.inc()
            _metrics.MIXED_PREFILL_TOKENS.inc(ridden)
            _metrics.MIXED_PREFILL_TOKENS_PER_WINDOW.observe(ridden)
            _metrics.MIXED_PREFILL_ROWS.observe(len(chunk_plan))
        else:
            with self._annotate('decode'):
                tokens, self.kv.k, self.kv.v, last_ids = self._decode_window(
                    self.params,
                    ids_dev,
                    positions_dev,
                    context_lens_dev,
                    self.kv.k,
                    self.kv.v,
                    block_tables_dev,
                    steps_left_dev,
                    temperature_dev,
                    top_p_dev,
                    min_p_dev,
                    top_k_dev,
                    seeds_dev,
                )
        for _, rid, steps in plan:
            if steps:
                self._unacked[rid] = self._unacked.get(rid, 0) + steps
        self._stats['decode_windows'] += 1
        _metrics.ENGINE_DECODE_WINDOWS.inc()
        _metrics.ENGINE_DECODE_UTILIZATION.observe(
            sum(1 for _, _, steps in plan if steps > 0) / b
        )
        return {
            'tokens': tokens,
            'plan': plan,
            'last_ids': last_ids,
            't_dispatch': time.monotonic(),
            'chunk_tokens': chunk_tokens,
            'chunk_plan': chunk_entries,
            # Attribution: the plan/put/dispatch split, completed with the
            # fetch time when _process_window syncs the tokens.
            'timing': (t_start, t_host, t_put, time.monotonic()),
        }

    # ------------------------------------------- speculative verify windows
    def _dispatch_spec_window(self) -> dict | object:
        """Plan and dispatch one speculative verify window
        (docs/speculative.md).

        For every decode-ready row the prompt-lookup drafter proposes up
        to ``draft_k`` tokens from the row's own history; the row's span
        ``[last_emitted_token, drafts...]`` rides ONE ragged dispatch
        (``mistral.spec_window`` — the same write-then-attend kernel as
        paged prefill) that scores all positions in a single weight pass.
        Block headroom is reserved PER ROW (``prepare_decode(..., ks)``):
        each row gets exactly its own span, not the batch max. Composes
        with mixed batching — pending prefill-chunk rows ride the same
        dispatch through the chunk-carrying variant. Returns the window
        record for ``_process_spec_window``, or ``_DRAIN`` when nothing
        can ride.
        """
        self._faults.fail('dispatch')  # same site as the classic window
        t_start = time.monotonic()
        cfg = self.config
        draft_k = cfg.draft_k
        drafts_by_rid: dict[int, list[int]] = {}
        decode_rids: list[int] = []
        row_ks: list[int] = []
        for _, rid in self.sched.running():
            request = self._requests[rid]
            if not self._decode_ready(request):
                continue
            # The drafter may propose at most budget-1 tokens: a window
            # emits accepted+1 tokens, and emission must never overshoot
            # max_tokens / max_model_len (spec discards nothing emitted).
            budget = self._window_budget(request, 0, draft_k + 1)
            if budget <= 0:
                continue
            drafts: list[int] = []
            if budget > 1 and request.drafter is not None:
                drafts = request.drafter.draft(
                    request.prompt_ids + request.output_ids,
                    min(draft_k, budget - 1),
                )
            drafts_by_rid[rid] = drafts
            decode_rids.append(rid)
            # Per-row headroom: the span writes K/V up to position
            # num_tokens - 1 + len(drafts), i.e. num_tokens + len(drafts)
            # tokens of coverage; 1 keeps the classic single-step floor.
            row_ks.append(max(1, len(drafts)))
        if decode_rids:
            self._evict_cached_blocks(
                self._reserve_shortfall(
                    1, row_ks=dict(zip(decode_rids, row_ks))
                )
                - self.sched.num_free_blocks
            )
            try:
                preempted = self.sched.prepare_decode(
                    1, decode_rids, row_ks
                )
            except SchedulerExhausted as exc:
                for rid in exc.preempted:
                    self._on_preempt(self._requests[rid])
                raise
            for rid in preempted:
                # Spec windows process synchronously, so victims never
                # have in-flight tokens; recompute preemption re-prefills
                # them (preemption mid-draft: the un-dispatched draft is
                # simply dropped with the rest of the row's state).
                self._on_preempt(self._requests[rid])
                drafts_by_rid.pop(rid, None)
        chunk_plan = self._plan_window_chunks()

        b = cfg.max_num_seqs
        span = 1 + draft_k
        spans: list = [(None, 0, 0)] * b
        token_rows: list = [[]] * b
        temperature = np.zeros((b,), np.float32)
        top_p = np.ones((b,), np.float32)
        min_p = np.zeros((b,), np.float32)
        top_k = np.zeros((b,), np.int32)
        seeds = np.zeros((b,), np.uint32)
        plan: list[tuple[int, int, list[int]]] = []
        for slot, rid in self.sched.running():
            drafts = drafts_by_rid.get(rid)
            if drafts is None:
                continue
            request = self._requests[rid]
            if request.state is not RequestState.RUNNING:
                continue
            last = (
                request.output_ids[-1]
                if request.output_ids
                else request.prompt_ids[-1]
            )
            # The span starts at the last emitted token's position (its
            # K/V is not yet written — decode's write-then-attend
            # contract) and extends through the drafts.
            spans[slot] = (request, request.num_tokens - 1, 1 + len(drafts))
            token_rows[slot] = [last] + drafts
            temperature[slot] = request.params.temperature
            top_p[slot] = request.params.top_p
            min_p[slot] = request.params.min_p
            top_k[slot] = request.params.top_k
            seeds[slot] = request.sample_seed
            plan.append((slot, rid, drafts))
        if not plan and not chunk_plan:
            return _DRAIN

        ids, positions, block_rows, context_lens, tail_lens = (
            self._span_host_arrays(spans, span, b, token_rows=token_rows)
        )
        host_arrays = [
            ids, positions, block_rows, context_lens, tail_lens,
            temperature, top_p, min_p, top_k, seeds,
        ]
        if chunk_plan:
            host_arrays.extend(self._build_chunk_arrays(chunk_plan))
        t_host = time.monotonic()
        devs = self._put_many(*host_arrays)
        t_put = time.monotonic()
        chunk_tokens = None
        chunk_entries: list[tuple[int, int, int, int, bool]] = []
        if chunk_plan:
            with self._annotate('spec'):
                tokens, self.kv.k, self.kv.v, chunk_tokens = (
                    self._spec_mixed_window(
                        self.params,
                        devs[0],  # span ids
                        devs[1],  # span positions
                        devs[3],  # context_lens
                        self.kv.k,
                        self.kv.v,
                        devs[2],  # block tables
                        devs[4],  # span_lens
                        devs[5],
                        devs[6],
                        devs[7],
                        devs[8],  # top_k
                        devs[9],  # seeds
                        *devs[10:],
                    )
                )
            ridden = 0
            for i, (request, start, ntok) in enumerate(chunk_plan):
                request.prefill_sent = start + ntok
                final = start + ntok >= request.prefill_target
                chunk_entries.append(
                    (i, request.request_id, start, ntok, final)
                )
                ridden += ntok
            # The ridden-prefill series stay truthful regardless of which
            # window kind carried the chunks; the WINDOW itself counts as
            # spec (one dispatch is one window).
            self._stats['spec_chunk_windows'] += 1
            self._stats['mixed_prefill_tokens'] += ridden
            _metrics.MIXED_PREFILL_TOKENS.inc(ridden)
            _metrics.MIXED_PREFILL_TOKENS_PER_WINDOW.observe(ridden)
            _metrics.MIXED_PREFILL_ROWS.observe(len(chunk_plan))
        else:
            with self._annotate('spec'):
                tokens, self.kv.k, self.kv.v, _ = self._spec_window(
                    self.params,
                    devs[0],
                    devs[1],
                    devs[3],
                    self.kv.k,
                    self.kv.v,
                    devs[2],
                    devs[4],
                    devs[5],
                    devs[6],
                    devs[7],
                    devs[8],
                    devs[9],
                )
        ndrafted = sum(len(d) for _, _, d in plan)
        self._stats['spec_windows'] += 1
        self._stats['spec_draft_tokens'] += ndrafted
        _metrics.SPEC_WINDOWS.inc()
        if ndrafted:
            _metrics.SPEC_DRAFT_TOKENS.inc(ndrafted)
        return {
            'spec': True,
            'tokens': tokens,
            'plan': plan,
            'chunk_tokens': chunk_tokens,
            'chunk_plan': chunk_entries,
            't_dispatch': time.monotonic(),
            'last_ids': None,
            'timing': (t_start, t_host, t_put, time.monotonic()),
        }

    def _process_spec_window(self, window: dict) -> list[tuple[int, int]]:
        """Fetch one verify window's tokens and run the greedy acceptance
        decisions already made device-side (the only host sync of the
        speculative path).

        The packed fetch is ``[B, S+1]``: per-position output tokens plus
        the accepted-draft count computed by ``verify_spans`` inside the
        dispatch — greedy argmax comparison for temperature-0 rows,
        exact rejection sampling for sampled rows (docs/speculative.md
        "Sampled verification"). Token 0 is always emitted (it follows
        the last REAL token); tokens 1..accept_len are the accepted
        drafts' successors, and token accept_len is the correction /
        bonus, so the output stream is exactly the sequential stream
        (each accepted draft skipped one weight pass). EOS / max_tokens
        inside the accepted prefix finish the request mid-span and the
        remaining verified tokens are discarded. Rejected suffixes roll
        back: ``sched.trim`` returns the unused per-row headroom so
        scheduler state matches a never-drafted run (the rejected K/V
        needs no rollback — it sits at positions every later dispatch
        overwrites before attending or masks out).
        """
        t_fetch = time.monotonic()
        with self._annotate('fetch'):
            # distlint: disable=host-sync-in-hot-path -- the spec window's ONE designed fetch point: emission needs the verified tokens + accept length on host, and spec windows process synchronously (depth 1)
            tokens = np.asarray(window['tokens'])  # [B, S+1] packed
        fetch_s = time.monotonic() - t_fetch
        emitted: list[tuple[int, int]] = []
        drafted = accepted = rows = 0
        sampled_rows = resampled = 0
        for slot, rid, drafts in window['plan']:
            request = self._requests.get(rid)
            if request is None or request.state is not RequestState.RUNNING:
                continue  # finished/preempted during an abnormal drain
            rows += 1
            drafted += len(drafts)
            sampled = request.params.temperature > 0
            if sampled and drafts:
                sampled_rows += 1
            n_acc = min(int(tokens[slot, -1]), len(drafts))
            if sampled and drafts and n_acc < len(drafts):
                # A sampled row that stopped short burned one residual
                # resample (the correction token).
                resampled += 1
            token = int(tokens[slot, 0])
            self._emit_token(request, token)
            emitted.append((rid, token))
            for i in range(n_acc):
                if rid not in self._requests:
                    break  # finished (EOS / max_tokens): discard the rest
                accepted += 1
                token = int(tokens[slot, i + 1])
                self._emit_token(request, token)
                emitted.append((rid, token))
            if rid in self._requests and request.state is RequestState.RUNNING:
                self.sched.trim(rid)
        self._stats['spec_accepted_tokens'] += accepted
        self._stats['spec_sampled_rows'] += sampled_rows
        self._stats['spec_resampled_tokens'] += resampled
        if accepted:
            _metrics.SPEC_ACCEPTED_TOKENS.inc(accepted)
        if drafted:
            _metrics.SPEC_ACCEPT_RATE.observe(accepted / drafted)
        if sampled_rows:
            _metrics.SPEC_SAMPLED_ROWS.inc(sampled_rows)
        if resampled:
            _metrics.SPEC_RESAMPLED_TOKENS.inc(resampled)
        chunk_entries = window.get('chunk_plan') or []
        extra = {
            'draft_tokens': drafted,
            'accepted_tokens': accepted,
            'sampled_rows': sampled_rows,
            'resampled_tokens': resampled,
        }
        if chunk_entries:
            extra['prefill_tokens'] = sum(
                n for *_, n, _ in chunk_entries
            )
            extra['prefill_rows'] = len(chunk_entries)
        if window.get('timing'):
            ts, th, tp, td = window['timing']
            extra.update(self._attribution_fields(
                ts, th, tp, td, fetch_s=fetch_s,
            ))
        self._record_step(
            'spec', window['t_dispatch'], batch=rows, tokens=len(emitted),
            **extra,
        )
        emitted.extend(self._process_chunk_entries(window))
        return emitted

    def _on_preempt(self, request: Request) -> None:
        request.state = RequestState.WAITING
        # A promotion in flight for the victim is simply dropped: its
        # scatter is already dispatched (ordering protects later readers)
        # and the blocks it adopted are borrowed — preemption keeps them,
        # so re-admission resumes from the promoted coverage for free.
        self._promoting.pop(request.request_id, None)
        if self.prefix_cache is not None:
            # Recompute preemption kept only the borrowed (cache-owned)
            # prefix; everything past it was freed and must re-prefill.
            request.num_cached_tokens = (
                request.num_borrowed_blocks * self.config.block_size
            )
        # Mixed chunk progress is recompute state too: chunks past the
        # borrowed prefix lived in the freed owned blocks. target 0 =
        # decode-ready-by-default; re-admission re-enrolls (or prefills
        # standalone) with a fresh target.
        request.prefill_target = 0
        request.prefill_sent = request.num_cached_tokens
        request.prefill_done = request.num_cached_tokens
        try:
            self._prefilling.remove(request.request_id)
        # distlint: disable=swallowed-exception -- membership-probe control flow: the victim simply was not mid-prefill, nothing degraded
        except ValueError:
            pass

    def _process_window(self, window: dict) -> list[tuple[int, int]]:
        """Fetch one window's tokens (the only host sync in the decode
        path) and fold them into request state; post-EOS overshoot tokens
        are discarded (counted in ``_stats['overshoot_tokens']`` — the
        bounded waste the pipelined EOS-one-window-late design trades for
        hidden dispatch latency). Speculative windows carry a different
        token layout and acceptance rule and route to
        ``_process_spec_window``."""
        if window.get('spec'):
            return self._process_spec_window(window)
        # Injection site 'slow_window': the stall hazard — the sleep sits
        # where a wedged device fetch would, so watchdogs and per-request
        # deadlines see exactly what they would see in production.
        self._faults.maybe_sleep('slow_window')
        t_fetch = time.monotonic()
        with self._annotate('fetch'):
            # distlint: disable=host-sync-in-hot-path -- the window loop's ONE designed fetch point: processing happens a window late, after the next dispatch is already in flight (pipeline_depth hides this sync)
            tokens = np.asarray(window['tokens'])  # [K, B]
        fetch_s = time.monotonic() - t_fetch
        emitted: list[tuple[int, int]] = []
        chunk_entries = window.get('chunk_plan') or []
        if 't_dispatch' in window:  # prefill fetch records carry no clock
            extra = {}
            if chunk_entries:
                extra = {
                    'prefill_tokens': sum(n for *_, n, _ in chunk_entries),
                    'prefill_rows': len(chunk_entries),
                }
            if window.get('timing'):
                ts, th, tp, td = window['timing']
                extra.update(self._attribution_fields(
                    ts, th, tp, td, fetch_s=fetch_s,
                ))
            self._record_step(
                'mixed' if chunk_entries else 'decode',
                window['t_dispatch'],
                batch=sum(1 for _, _, s in window['plan'] if s > 0),
                tokens=sum(s for _, _, s in window['plan']),
                **extra,
            )
        for slot, rid, steps in window['plan']:
            if rid in self._unacked:
                self._unacked[rid] = max(0, self._unacked[rid] - steps)
            if rid not in self._requests:
                self._stats['overshoot_tokens'] += steps
                _metrics.ENGINE_OVERSHOOT_TOKENS.inc(steps)
                continue  # finished in an earlier window; overshoot tokens
            request = self._requests[rid]
            if request.state is not RequestState.RUNNING:
                continue  # preempted while idle; will re-prefill
            for i in range(steps):
                token = int(tokens[i, slot])
                self._emit_token(request, token)
                emitted.append((rid, token))
                if rid not in self._requests:
                    self._stats['overshoot_tokens'] += steps - i - 1
                    _metrics.ENGINE_OVERSHOOT_TOKENS.inc(steps - i - 1)
                    break  # finished mid-window
        emitted.extend(self._process_chunk_entries(window))
        return emitted

    def _process_chunk_entries(self, window: dict) -> list[tuple[int, int]]:
        """Fold a fetched window's ridden prefill-chunk spans into request
        state (shared by the mixed decode and speculative processors).
        The caller's token fetch is the completion barrier: once the
        window's tokens are on host, its chunk K/V writes are in the
        cache."""
        chunk_entries = window.get('chunk_plan') or []
        emitted: list[tuple[int, int]] = []
        if not chunk_entries:
            return emitted
        # distlint: disable=host-sync-in-hot-path -- the mixed window's designed chunk-token fetch: runs after the caller's token fetch already synced this window, so no extra device round-trip is added
        chunk_tokens = np.asarray(window['chunk_tokens'])
        for row_i, rid, start, ntok, final in chunk_entries:
            request = self._requests.get(rid)
            if request is None or request.state is not RequestState.RUNNING:
                continue  # preempted during an abnormal drain
            request.prefill_done = max(
                request.prefill_done, start + ntok
            )
            if final:
                # Freshly prefilled full prompt blocks enter the
                # prefix cache BEFORE emission — a max_tokens=1
                # request finishes inside _emit_token, after which
                # its row is gone (same ordering as the standalone
                # paths).
                self._insert_prompt_blocks(request)
                try:
                    self._prefilling.remove(rid)
                # distlint: disable=swallowed-exception -- membership-probe control flow: a re-enrolled span may already be off the list, nothing degraded
                except ValueError:
                    pass
                token = int(chunk_tokens[row_i])
                self._emit_token(request, token)
                emitted.append((rid, token))
        return emitted

    def _run_to_completion(self) -> None:
        """Drive every request to a terminal state.

        With ``max_dispatch_retries == 0`` (default) this is exactly the
        legacy contract: the first dispatch exception propagates. With
        recovery on, a failed serving pass — its in-flight windows
        already folded back by ``_serve_pipelined``'s cleanup — charges
        the involved requests, quarantines the ones past the retry
        budget, backs off, and re-enters the loop: the engine either
        recovers or fails *only* the affected requests, never wedges
        (docs/resilience.md "Crash-domain recovery")."""
        while True:
            try:
                self._serve_pipelined()
                return
            except Exception as exc:
                if not self._recover(exc):
                    raise

    def _serve_pipelined(self) -> None:
        """Drive all requests to completion with ``pipeline_depth`` decode
        windows in flight, so the ~68 ms host↔device round trip is hidden
        behind the next window's compute. EOS and admission react one
        window late — bounded overshoot, unchanged results.

        Speculative mode (``draft_k > 0``) forces depth 1: the prompt-
        lookup drafter needs each window's host-fetched tokens before it
        can propose the next span, so windows process synchronously and
        the latency trade shifts from dispatch-hiding to weight-pass-
        skipping (docs/speculative.md)."""
        from collections import deque

        depth = (
            1 if self.config.draft_k else max(1, self.config.pipeline_depth)
        )
        inflight: deque[dict] = deque()
        self._carried = None

        def process_one() -> None:
            self._process_window(inflight.popleft())

        def drain_one() -> None:
            if inflight:
                process_one()

        self._drain_hook = drain_one
        try:
            while self.has_unfinished or inflight:
                if self._expired_requests():
                    # Deadline expiry frees the victims' blocks, which is
                    # only safe with nothing in flight (an in-flight
                    # window still writes to them) — drain first. A
                    # deadline event is rare; the drain is cheap next to
                    # the seconds the request already burned.
                    while inflight:
                        process_one()
                    self._expire_deadlines()
                # Deferred prefill (opt-in): first tokens stay on device
                # (scattered into self._carried) and their fetch records
                # join the in-flight deque instead of blocking the decode
                # pipeline. See EngineConfig.defer_prefill for why the
                # default is the synchronous path.
                self._admit(
                    defer_to=inflight if self.config.defer_prefill else None
                )
                if self.sched.num_running == 0:
                    if inflight:
                        process_one()
                    continue
                # Never let a dispatch preempt while windows are in flight.
                # Evictable cached blocks count as free capacity first.
                while inflight and (
                    short := self._reserve_shortfall(self._window_kmax())
                    - self.sched.num_free_blocks
                ) > 0:
                    if self._evict_cached_blocks(short):
                        continue
                    process_one()
                window = self._dispatch_window(self._carried)
                if window is _DRAIN:
                    if inflight:
                        process_one()
                    continue
                self._carried = window['last_ids']
                inflight.append(window)
                if len(inflight) >= depth:
                    process_one()
        except BaseException:
            # Keep catch-and-continue recovery sound (the SchedulerExhausted
            # contract): fold every dispatched window back into request
            # state so no _unacked counts, device-side tokens, or in-flight
            # chunk spans are orphaned.
            while inflight:
                try:
                    process_one()
                except Exception as drain_exc:
                    # Abnormal drain: the in-flight windows cannot be
                    # folded back — their device-side tokens are lost
                    # (KV writes at positions >= num_tokens are
                    # overwritten before they are ever attended).
                    # Recorded, never silent: a recovery retry that
                    # starts from a drained pipeline should say so.
                    self.flight.record(
                        'event',
                        event='abnormal_drain',
                        dropped_windows=len(inflight) + 1,
                        error=repr(drain_exc)[:200],
                    )
                    inflight.clear()
                    self._unacked.clear()
            # The mixed analogue of clearing _unacked: a chunk span whose
            # window was dropped above advanced prefill_sent but never
            # prefill_done — rolling sent back lets the span re-ride after
            # a catch-and-continue resume (otherwise the planner skips the
            # request as 'in flight' forever and the loop livelocks).
            for rid in self._prefilling:
                request = self._requests.get(rid)
                if request is not None:
                    request.prefill_sent = request.prefill_done
            raise
        finally:
            self._drain_hook = None

    # ------------------------------------- crash-domain recovery (faults)
    def _recover(self, exc: Exception) -> bool:
        """Decide whether a failed serving pass retries
        (docs/resilience.md "Crash-domain recovery").

        True = retry: the failure is charged to every involved request
        (the running batch — or the waiting head when admission itself
        failed with nothing running), requests past the
        ``max_dispatch_retries`` budget are quarantined to FAILED with
        the error recorded, and a bounded exponential backoff sleeps off
        transient faults. False = recovery disabled or unattributable —
        the caller re-raises. Termination is structural: every True
        return charges at least one live request and each request is
        quarantined after at most ``max_dispatch_retries + 1`` charges,
        so a permanent fault drains the request population into FAILED
        instead of livelocking the loop.

        Callers guarantee no windows are in flight (the pipelined loop's
        exception cleanup already folded them back), so quarantine may
        free blocks safely.
        """
        cfg = self.config
        if cfg.max_dispatch_retries <= 0:
            return False
        involved = [rid for _, rid in self.sched.running()]
        if not involved:
            waiting = [
                r.request_id
                for r in self._requests.values()
                if r.state is RequestState.WAITING
            ]
            if waiting:
                involved = [min(waiting)]
        if not involved:
            return False  # nothing live to charge: unattributable
        self._consecutive_failures += 1
        self._stats['window_retries'] += 1
        _metrics.RESILIENCE_RETRIES.inc()
        for rid in involved:
            self._dispatch_failures[rid] = (
                self._dispatch_failures.get(rid, 0) + 1
            )
        self.flight.record(
            'recovery',
            status='retry',
            error=repr(exc)[:200],
            attempt=self._consecutive_failures,
            rids=involved[:16],
        )
        for rid in involved:
            if (
                self._dispatch_failures.get(rid, 0)
                > cfg.max_dispatch_retries
            ):
                request = self._requests.get(rid)
                if request is not None:
                    self._fail_request(
                        request,
                        reason='dispatch_failed',
                        error=repr(exc)[:300],
                    )
        delay = cfg.retry_backoff_s * (
            2 ** min(self._consecutive_failures - 1, 6)
        )
        if delay > 0:
            time.sleep(min(delay, 2.0))
        return True

    def _expired_requests(self) -> list[Request]:
        """Live requests past ``request_deadline_s`` (empty when the
        deadline is off) — the cheap guard the serving loops poll."""
        deadline = self.config.request_deadline_s
        if deadline <= 0 or not self._requests:
            return []
        now = time.monotonic()
        return [
            r
            for r in self._requests.values()
            if r.state
            in (RequestState.WAITING, RequestState.RUNNING)
            and now - r.t_enqueue > deadline
        ]

    def _expire_deadlines(self) -> None:
        """Quarantine every request past its wall-clock deadline with
        ``finish_reason='timeout'``, freeing its KV blocks instead of
        holding them forever. Callers must have no windows in flight."""
        for request in self._expired_requests():
            self._fail_request(
                request,
                reason='timeout',
                error=(
                    'request exceeded request_deadline_s='
                    f'{self.config.request_deadline_s}'
                ),
            )

    def _fail_request(
        self, request: Request, *, reason: str, error: str
    ) -> None:
        """Terminal quarantine: record the error, free every resource the
        request holds, and park it in the finished map as FAILED — never
        a silent drop (one ``'quarantine'`` flight record + the
        ``distllm_resilience_quarantined_requests_total{reason}``
        counter). Callers must have no windows in flight: quarantine
        frees blocks, and an in-flight window could still write to them.
        """
        rid = request.request_id
        request.state = RequestState.FAILED
        request.finish_reason = reason
        request.error = error
        request.t_finish = time.monotonic()
        _metrics.RESILIENCE_QUARANTINED.labels(reason=reason).inc()
        self._stats['quarantined_requests'] += 1
        self.flight.record(
            'quarantine',
            request_id=rid,
            trace_id=request.trace_id,
            reason=reason,
            error=error[:300],
            prompt_tokens=len(request.prompt_ids),
            output_tokens=len(request.output_ids),
        )
        self.sched.finish(rid)
        if self.prefix_cache is not None:
            self.prefix_cache.release(rid)
        self._promoting.pop(rid, None)
        self._unacked.pop(rid, None)
        self._dispatch_failures.pop(rid, None)
        for pending in (self._prefilling, self._pending_prefill):
            try:
                pending.remove(rid)
            # distlint: disable=swallowed-exception -- membership-probe control flow: the rid simply was not mid-prefill, nothing degraded
            except ValueError:
                pass
        del self._requests[rid]
        self._finished[rid] = request

    def _sample_device(self, logits: jnp.ndarray, slots) -> jnp.ndarray:
        """Sample one token per row on DEVICE (no host sync)."""
        b = logits.shape[0]
        temperature = np.zeros((b,), np.float32)
        top_p = np.ones((b,), np.float32)
        min_p = np.zeros((b,), np.float32)
        top_k = np.zeros((b,), np.int32)
        seeds = np.zeros((b,), np.uint32)
        counters = np.zeros((b,), np.int32)
        for i, request in enumerate(slots):
            if request is None:
                continue
            temperature[i] = request.params.temperature
            top_p[i] = request.params.top_p
            min_p[i] = request.params.min_p
            top_k[i] = request.params.top_k
            seeds[i] = request.sample_seed
            # The prompt occupies absolute indices 0..num_tokens-1, so
            # the first generated token's index — its PRNG counter — is
            # num_tokens (matches the decode scan's pos + 1 convention).
            counters[i] = request.num_tokens
        t_dev, tp_dev, mp_dev, tk_dev, sd_dev, ct_dev = self._put_many(
            temperature, top_p, min_p, top_k, seeds, counters
        )
        return self._sample(
            logits, t_dev, tp_dev, mp_dev, tk_dev, sd_dev, ct_dev
        )

    def _emit_token(self, request: Request, token: int) -> None:
        # Note: the emitted token is NOT yet written to the KV cache; it is
        # fed as input on the next decode step, which writes it then.
        if self._consecutive_failures:
            # First token after one or more failed dispatches: the retry
            # ladder worked — record the recovery, reset the backoff.
            self._consecutive_failures = 0
            self._stats['recoveries'] += 1
            _metrics.RESILIENCE_RECOVERIES.inc()
            self.flight.record(
                'recovery', status='recovered',
                request_id=request.request_id,
            )
        if self._dispatch_failures:
            # Progress clears a request's failure charge: only
            # CONSECUTIVE failures quarantine (poison containment), not
            # failures spread across an otherwise healthy run.
            self._dispatch_failures.pop(request.request_id, None)
        if not request.output_ids and request.t_first_token == 0.0:
            # TTFT is measured to the HOST fetch of the first token — the
            # latency a streaming client sees, including any pipelined lag.
            request.t_first_token = time.monotonic()
            _metrics.REQUEST_TTFT.observe(
                request.t_first_token - request.t_enqueue
            )
        request.output_ids.append(token)
        self.sched.append_token(request.request_id)
        _metrics.ENGINE_GENERATED_TOKENS.inc()
        eos = getattr(self.tokenizer, 'eos_id', None)
        stops = set(request.params.stop_token_ids)
        if eos is not None:
            stops.add(eos)
        if (
            token in stops
            or len(request.output_ids) >= request.params.max_tokens
            or request.num_tokens >= self.config.max_model_len
        ):
            request.finish_reason = 'stop' if token in stops else 'length'
            self._finish(request)

    def _finish(self, request: Request) -> None:
        request.state = RequestState.FINISHED
        request.t_finish = time.monotonic()
        self._observe_lifecycle(request)
        _metrics.ENGINE_REQUESTS_FINISHED.inc()
        self.sched.finish(request.request_id)
        if self.prefix_cache is not None:
            # Drop this request's references; ref==0 blocks become LRU-
            # evictable but KEEP their KV — that persistence is what makes
            # the next same-prefix request free.
            self.prefix_cache.release(request.request_id)
        self._unacked.pop(request.request_id, None)
        del self._requests[request.request_id]
        self._finished[request.request_id] = request

    def _observe_lifecycle(self, request: Request) -> None:
        """Fold one finished request into the lifecycle series and the
        flight ring: TTFT / TPOT histograms, SLO + goodput counters when an
        SLO is configured, and one ``'request'`` flight record carrying the
        whole enqueue→admit→first-token→finish timeline."""
        n_out = len(request.output_ids)
        ttft_s = (
            request.t_first_token - request.t_enqueue
            if request.t_first_token else None
        )
        tpot_s = None
        if request.t_first_token and n_out > 1:
            tpot_s = (request.t_finish - request.t_first_token) / (n_out - 1)
            _metrics.REQUEST_TPOT.observe(tpot_s)
        slo = self.config.ttft_slo_s
        if slo > 0 and ttft_s is not None:
            met = ttft_s <= slo
            _metrics.REQUEST_SLO.labels(
                outcome='met' if met else 'missed'
            ).inc()
            self._stats['slo_met' if met else 'slo_missed'] += 1
            if met:
                _metrics.GOODPUT_TOKENS.inc(n_out)
                self._stats['goodput_tokens'] += n_out
        self.flight.record(
            'request',
            request_id=request.request_id,
            trace_id=request.trace_id,
            prompt_tokens=len(request.prompt_ids),
            output_tokens=n_out,
            queue_wait_s=round(request.t_admit - request.t_enqueue, 6)
            if request.t_admit else None,
            ttft_s=round(ttft_s, 6) if ttft_s is not None else None,
            tpot_s=round(tpot_s, 6) if tpot_s is not None else None,
            # Full enqueue -> finish extent: what lets the Perfetto
            # exporter reconstruct the request's wall-clock slice from
            # this one record (t_wall is the finish instant).
            e2e_s=round(request.t_finish - request.t_enqueue, 6),
            cached_tokens=request.num_cached_tokens,
        )

    # -------------------------------------------------------------- offline
    def generate_ids(
        self,
        prompts: list[list[int]],
        params: SamplingParams | None = None,
    ) -> list[list[int]]:
        """Offline batch API: token ids in, generated token ids out."""
        import time as _time

        self._stats.clear()
        ids = [self.add_request(p, params) for p in prompts]
        loop_start = _time.perf_counter()
        self._run_to_completion()
        loop_s = _time.perf_counter() - loop_start
        n_out = sum(len(r.output_ids) for r in self._finished.values())
        self.telemetry.update(
            {k: int(v) for k, v in self._stats.items()}
        )
        self.telemetry['decode_loop_s'] = round(loop_s, 3)
        windows = self._stats.get('decode_windows', 0)
        if windows and loop_s > 0:
            self.telemetry['windows_per_s'] = round(windows / loop_s, 2)
        lookups = self._stats.get('prefix_lookup_tokens', 0)
        if lookups:
            self.telemetry['prefix_hit_rate'] = round(
                self._stats.get('prefix_hit_tokens', 0) / lookups, 4
            )
        drafted = self._stats.get('spec_draft_tokens', 0)
        if drafted:
            # Accepted tokens / drafted tokens — the speculative win in
            # one number: every accepted token skipped a weight pass.
            self.telemetry['spec_accept_rate'] = round(
                self._stats.get('spec_accepted_tokens', 0) / drafted, 4
            )
        spec_windows = self._stats.get('spec_windows', 0)
        if spec_windows and loop_s > 0:
            self.telemetry['spec_windows_per_s'] = round(
                spec_windows / loop_s, 2
            )
        if self.kv_tier is not None:
            overlap = self.tier_summary().get('promotion_overlap')
            if overlap is not None:
                self.telemetry['tier_promotion_overlap'] = overlap
        if n_out:
            self.telemetry['overshoot_frac'] = round(
                self._stats.get('overshoot_tokens', 0) / n_out, 4
            )
        outs = []
        for rid in ids:
            request = self._finished.pop(rid)
            out = request.output_ids
            # Strip the stop token if present.
            eos = getattr(self.tokenizer, 'eos_id', None)
            stops = set(request.params.stop_token_ids)
            if eos is not None:
                stops.add(eos)
            if out and out[-1] in stops:
                out = out[:-1]
            outs.append(out)
        return outs

    def generate(
        self, prompts: list[str], params: SamplingParams | None = None
    ) -> list[str]:
        """Offline text API (vLLM ``llm.generate`` parity)."""
        batches = self.tokenizer(prompts)
        prompt_ids = [
            [int(t) for t, m in zip(row_ids, row_mask) if m]
            for row_ids, row_mask in zip(
                batches.input_ids, batches.attention_mask
            )
        ]
        outputs = self.generate_ids(prompt_ids, params)
        return [self.tokenizer.decode(out) for out in outputs]

    def shutdown(self) -> None:
        if self._history_sampler is not None:
            self._history_sampler.stop()
            self._history_sampler = None
        if self._peer_kv_server is not None:
            self._peer_kv_server.close()
            self._peer_kv_server = None
        if self.kv_tier is not None and self.kv_tier.peer is not None:
            self.kv_tier.peer.close()
        self.params = None
        self.kv = None


def _write_prefill_all_layers(
    k_cache, v_cache, k_seq, v_seq, block_rows, lengths
):
    """Scatter ``[L, B, S, N_kv, Hd]`` prefill K/V into the paged cache.

    ``block_rows`` is ``[B, R]`` and ``lengths`` ``[B]``; positions at or
    beyond a row's length (padding rows have length 0) write to the
    reserved trash block 0. A :class:`QuantizedKV` pool quantizes at this
    write (per-block-per-KV-head absmax over the live rows — full prefill
    always starts its blocks fresh, so this is single-shot quantization,
    no rescale chain).
    """
    num_layers, batch, seq_len = k_seq.shape[:3]
    quantized = isinstance(k_cache, QuantizedKV)
    block_size = (k_cache.data if quantized else k_cache).shape[2]
    positions = jnp.arange(seq_len)[None, :]  # [1, S]
    valid = positions < lengths[:, None]  # [B, S]
    block_ids = jnp.where(
        valid,
        jnp.take_along_axis(block_rows, positions // block_size, axis=1),
        0,
    )
    offsets = jnp.where(valid, positions % block_size, 0)
    flat_blocks = block_ids.reshape(-1)
    flat_offsets = offsets.reshape(-1)
    if quantized:
        return _write_prefill_all_layers_quantized(
            k_cache, v_cache, k_seq, v_seq, block_rows, lengths,
            valid, flat_blocks, flat_offsets,
        )
    k_flat = k_seq.reshape(num_layers, batch * seq_len, *k_seq.shape[3:])
    v_flat = v_seq.reshape(num_layers, batch * seq_len, *v_seq.shape[3:])
    k_cache = k_cache.at[:, flat_blocks, flat_offsets].set(
        k_flat.astype(k_cache.dtype)
    )
    v_cache = v_cache.at[:, flat_blocks, flat_offsets].set(
        v_flat.astype(v_cache.dtype)
    )
    return k_cache, v_cache


def _write_prefill_all_layers_quantized(
    k_cache, v_cache, k_seq, v_seq, block_rows, lengths,
    valid, flat_blocks, flat_offsets,
):
    """Quantized twin of :func:`_write_prefill_all_layers`.

    Every block this scatter touches is freshly owned by its row (full
    prefill from position 0), so each block's scale is its live rows'
    absmax / 127 computed in one masked pass — never a running-absmax
    rescale. Dead rows and dead blocks route to the trash block 0 with a
    zero scale, and ``quantize_kv_rows``'s guarded denominator keeps the
    dead branch finite (no NaN may reach a scatter, even into trash).
    """
    num_layers, batch, seq_len = k_seq.shape[:3]
    block_size = k_cache.data.shape[2]
    nt = -(-seq_len // block_size)  # blocks per row this shape can touch
    pad = nt * block_size - seq_len
    live_blk = jnp.arange(nt)[None, :] * block_size < lengths[:, None]
    phys = jnp.where(live_blk, block_rows[:, :nt], 0)  # [B, nt]
    flat_phys = phys.reshape(-1)

    def write_one(cache, seq):
        amax = jnp.max(jnp.abs(seq.astype(jnp.float32)), axis=-1)
        amax = jnp.where(valid[None, :, :, None], amax, 0.0)
        blk_amax = jnp.pad(
            amax, ((0, 0), (0, 0), (0, pad), (0, 0))
        ).reshape(num_layers, batch, nt, block_size, -1).max(axis=3)
        new_scale = blk_amax / KV_QUANT_MAX  # [L, B, nt, Nkv]
        scale = cache.scale.at[:, flat_phys].set(
            new_scale.reshape(num_layers, batch * nt, -1)
        )
        # Each token row quantizes against ITS block's scale.
        scale_tok = jnp.repeat(new_scale, block_size, axis=2)[:, :, :seq_len]
        q = quantize_kv_rows(seq, scale_tok)
        q_flat = q.reshape(num_layers, batch * seq_len, *q.shape[3:])
        data = cache.data.at[:, flat_blocks, flat_offsets].set(q_flat)
        return QuantizedKV(data, scale)

    return write_one(k_cache, k_seq), write_one(v_cache, v_seq)
