"""Paged KV cache: HBM block pool, block allocator, automatic prefix cache.

The TPU replacement for vLLM's paged KV memory management (SURVEY.md
section 2.4 N1): K/V live as ``[L, num_blocks, block_size, N_kv, Hd]`` device
arrays; sequences own lists of block ids. Block 0 is the reserved TRASH
block — padded scatter writes land there (see ``ops/paged_attention``).

The allocator is the C++ free-list/refcount implementation in
``distllm_tpu/native/block_allocator.cpp`` (ctypes), with a drop-in Python
fallback when no compiler is available.

:class:`PrefixCache` is the automatic prefix cache (SGLang-style radix
reuse over full paged blocks; docs/prefix_caching.md): a token-block
hash-chain → block-id map with per-block request refcounts and LRU
eviction of unreferenced blocks. It owns the REUSE policy only — physical
block accounting stays with the scheduler, which marks cache-held blocks
as a request's "borrowed prefix" (``scheduler.py``).

Mixed serving windows (docs/serving.md) write prefill-chunk K/V inside
decode dispatches; those writes always land in blocks the owning request
was granted at admission (the full prompt is budgeted up front), so no
block here ever changes owner while a window is in flight — the engine's
drain-before-preempt guard plus ``prepare_decode(..., rids=...)`` keep
that invariant.
"""

from __future__ import annotations

import ctypes
import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Protocol, Sequence

import jax
import jax.numpy as jnp


class BlockAllocator(Protocol):
    def alloc(self) -> int: ...

    def free(self, block_id: int) -> None: ...

    def incref(self, block_id: int) -> None: ...

    @property
    def num_free(self) -> int: ...


class PyBlockAllocator:
    """Pure-Python free-list allocator (fallback; same semantics as C++)."""

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 2:
            raise ValueError('need >= 2 blocks (block 0 is reserved)')
        self._free = list(range(num_blocks - 1, 0, -1))
        self._refcount = [0] * num_blocks
        self._refcount[0] = 1  # trash block, never free

    def alloc(self) -> int:
        if not self._free:
            return -1
        block_id = self._free.pop()
        self._refcount[block_id] = 1
        return block_id

    def incref(self, block_id: int) -> None:
        assert self._refcount[block_id] > 0
        self._refcount[block_id] += 1

    def free(self, block_id: int) -> None:
        assert self._refcount[block_id] > 0, f'double free of block {block_id}'
        self._refcount[block_id] -= 1
        if self._refcount[block_id] == 0:
            self._free.append(block_id)

    @property
    def num_free(self) -> int:
        return len(self._free)


class NativeBlockAllocator:
    """ctypes wrapper over the C++ allocator."""

    def __init__(self, num_blocks: int) -> None:
        from distllm_tpu.native import build_library

        so_path = build_library('block_allocator.cpp')
        if so_path is None:
            raise RuntimeError('native allocator unavailable')
        lib = ctypes.CDLL(str(so_path))
        lib.ba_create.restype = ctypes.c_void_p
        lib.ba_create.argtypes = [ctypes.c_int32]
        for fn in ('ba_alloc', 'ba_incref', 'ba_free', 'ba_num_free'):
            getattr(lib, fn).restype = ctypes.c_int32
        lib.ba_alloc.argtypes = [ctypes.c_void_p]
        lib.ba_num_free.argtypes = [ctypes.c_void_p]
        lib.ba_incref.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ba_free.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ba_destroy.argtypes = [ctypes.c_void_p]
        handle = lib.ba_create(num_blocks)
        if not handle:
            raise RuntimeError(f'ba_create({num_blocks}) failed')
        self._lib = lib
        self._handle = handle

    def alloc(self) -> int:
        return int(self._lib.ba_alloc(self._handle))

    def incref(self, block_id: int) -> None:
        if self._lib.ba_incref(self._handle, block_id) < 0:
            raise ValueError(f'incref of unallocated block {block_id}')

    def free(self, block_id: int) -> None:
        if self._lib.ba_free(self._handle, block_id) < 0:
            raise ValueError(f'double free of block {block_id}')

    @property
    def num_free(self) -> int:
        return int(self._lib.ba_num_free(self._handle))

    def __del__(self) -> None:  # pragma: no cover - interpreter teardown
        lib = getattr(self, '_lib', None)
        handle = getattr(self, '_handle', None)
        if lib is not None and handle:
            lib.ba_destroy(handle)
            self._handle = None


def hash_block_tokens(
    parent: bytes | None, tokens: Sequence[int]
) -> bytes:
    """Digest of one full token block, chained through its prefix.

    The chain (``h_i = H(h_{i-1} || tokens_i)``) makes a block's digest
    identify the ENTIRE prefix up to and including it, so a flat
    digest → block map behaves like a radix trie: matching the longest
    cached prefix is "walk digests until one misses". sha256 rather than
    Python ``hash``: digests index physical KV blocks, and a collision
    would silently serve another prompt's KV.
    """
    h = hashlib.sha256(parent or b'')
    h.update(b','.join(str(int(t)).encode() for t in tokens))
    return h.digest()


def block_digests(
    prompt_ids: Sequence[int], block_size: int
) -> list[bytes]:
    """Chained digests for every FULL block of ``prompt_ids``.

    Partial trailing blocks are not hashable (their content is not yet
    final — later tokens land in them), so reuse granularity is whole
    blocks; the COW path in the engine covers the aligned full-cover case.
    """
    digests: list[bytes] = []
    parent: bytes | None = None
    for start in range(0, len(prompt_ids) - block_size + 1, block_size):
        parent = hash_block_tokens(
            parent, prompt_ids[start : start + block_size]
        )
        digests.append(parent)
    return digests


@dataclass
class _CacheEntry:
    block_id: int
    refcount: int = 0  # live requests referencing this block
    holders: set = field(default_factory=set)  # rids, for shared-block gauge


class PrefixCache:
    """Digest-chain → KV-block map with refcounts and LRU eviction.

    Ownership protocol (engine-driven; see docs/prefix_caching.md):

    - ``acquire(rid, digests)`` — longest-prefix match; increfs every
      matched block for ``rid`` and returns the block ids. Matched blocks
      leave the evictable LRU.
    - ``insert(rid, digest, block_id)`` — adopt a freshly prefilled prompt
      block (the engine then marks it borrowed in the scheduler via
      ``lend_prefix``). Returns False when the digest is already cached
      (first writer wins; the caller keeps its duplicate block private).
    - ``release(rid)`` — drop every reference ``rid`` holds; blocks whose
      refcount reaches zero become LRU-evictable but KEEP their KV
      contents (that persistence is the whole point).
    - ``evict(max_blocks)`` — pop least-recently-used evictable blocks and
      return their ids for the scheduler's free list.

    Purely host-side bookkeeping: never touches device arrays and never
    frees blocks itself.
    """

    def __init__(self, block_size: int) -> None:
        self.block_size = block_size
        self._entries: dict[bytes, _CacheEntry] = {}
        # digest -> block_id for refcount==0 entries, LRU order (oldest
        # first). Entries stay in _entries while evictable.
        self._evictable: 'OrderedDict[bytes, int]' = OrderedDict()
        self._held: dict[int, list[bytes]] = {}  # rid -> digests referenced
        self.stats = {
            'hit_blocks': 0, 'evictions': 0, 'inserts': 0,
            # First-writer-wins losses: a second request prefilled the same
            # block before this insert landed. Mixed serving windows stretch
            # a prompt's prefill over several windows (blocks adopted only at
            # the final chunk), so same-prefix requests admitted meanwhile
            # prefill private duplicates — this counts that lost sharing.
            'insert_dupes': 0,
        }

    # ------------------------------------------------------------- lookup
    def match(self, digests: Sequence[bytes]) -> list[int]:
        """Block ids of the longest cached prefix of ``digests`` (no ref)."""
        blocks: list[int] = []
        for digest in digests:
            entry = self._entries.get(digest)
            if entry is None:
                break
            blocks.append(entry.block_id)
        return blocks

    def acquire(self, rid: int, digests: Sequence[bytes]) -> list[int]:
        """Longest-prefix match + incref each matched block for ``rid``."""
        blocks: list[int] = []
        matched: list[bytes] = []
        for digest in digests:
            entry = self._entries.get(digest)
            if entry is None:
                break
            entry.refcount += 1
            entry.holders.add(rid)
            self._evictable.pop(digest, None)
            matched.append(digest)
            blocks.append(entry.block_id)
        if matched:
            self._held.setdefault(rid, []).extend(matched)
        self.stats['hit_blocks'] += len(blocks)
        self._publish()
        return blocks

    # ------------------------------------------------------------- insert
    def insert(self, rid: int, digest: bytes, block_id: int) -> bool:
        """Adopt ``block_id`` for ``digest``; ``rid`` holds the first ref.

        False when the digest is already cached — the caller's physical
        block stays private to it (freed by the scheduler at finish).
        """
        if digest in self._entries:
            self.stats['insert_dupes'] += 1
            return False
        self._entries[digest] = _CacheEntry(
            block_id, refcount=1, holders={rid}
        )
        self._held.setdefault(rid, []).append(digest)
        self.stats['inserts'] += 1
        self._publish()
        return True

    # ------------------------------------------------------------ release
    def release(self, rid: int) -> None:
        """Drop every reference ``rid`` holds (finish/abort path)."""
        for digest in self._held.pop(rid, []):
            entry = self._entries.get(digest)
            if entry is None:
                continue  # evicted while... cannot happen (ref pinned)
            entry.refcount -= 1
            entry.holders.discard(rid)
            if entry.refcount <= 0:
                # Most-recently released = most likely to be reused next:
                # append to the MRU end.
                self._evictable[digest] = entry.block_id
        self._publish()

    # ------------------------------------------------------------- evict
    def evict(self, max_blocks: int) -> list[int]:
        """Pop up to ``max_blocks`` LRU evictable blocks; caller returns
        them to the scheduler free list."""
        freed: list[int] = []
        while self._evictable and len(freed) < max_blocks:
            digest, block_id = self._evictable.popitem(last=False)
            del self._entries[digest]
            freed.append(block_id)
        if freed:
            from distllm_tpu.observability import instruments as _m

            _m.PREFIX_EVICTIONS.inc(len(freed))
        self.stats['evictions'] += len(freed)
        self._publish()
        return freed

    # -------------------------------------------------------------- state
    @property
    def num_cached(self) -> int:
        return len(self._entries)

    @property
    def num_evictable(self) -> int:
        return len(self._evictable)

    @property
    def num_shared(self) -> int:
        return sum(1 for e in self._entries.values() if len(e.holders) >= 2)

    def _publish(self) -> None:
        from distllm_tpu.observability import instruments as _m

        _m.PREFIX_CACHED_BLOCKS.set(self.num_cached)
        _m.PREFIX_EVICTABLE_BLOCKS.set(self.num_evictable)
        _m.PREFIX_SHARED_BLOCKS.set(self.num_shared)


def make_allocator(num_blocks: int, prefer_native: bool = True) -> BlockAllocator:
    if prefer_native:
        try:
            return NativeBlockAllocator(num_blocks)
        except (RuntimeError, OSError):
            pass
    return PyBlockAllocator(num_blocks)


class PagedKVCache:
    """Device-resident paged K/V arrays (pure container).

    Block *accounting* — who owns which block, admission, preemption — is
    the scheduler's job (``engine/scheduler.py`` over the native C++ core);
    keeping a second free-list here would silently desync from it.
    """

    def __init__(
        self,
        num_layers: int,
        num_blocks: int,
        block_size: int,
        num_kv_heads: int,
        head_dim: int,
        dtype: str = 'bfloat16',
        sharding=None,
        lazy: bool = False,
    ) -> None:
        self.shape = (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
        self.dtype = jnp.dtype(dtype)
        self._sharding = sharding
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.k = None
        self.v = None
        if not lazy:
            self.allocate()

    def allocate(self) -> None:
        """Materialize the pool arrays (``lazy=True`` defers this so the
        engine can run transient-heavy weight migrations first)."""
        if self.k is not None:
            return
        from distllm_tpu.observability import instruments

        if self._sharding is None:
            self.k = jnp.zeros(self.shape, dtype=self.dtype)
            self.v = jnp.zeros(self.shape, dtype=self.dtype)
        else:
            # Allocate directly into the sharded layout: under tensor
            # parallelism num_blocks is sized against AGGREGATE HBM, so a
            # transient full-size allocation on one device would OOM.
            zeros = jax.jit(
                lambda: jnp.zeros(self.shape, dtype=self.dtype),
                out_shardings=self._sharding,
            )
            self.k = zeros()
            self.v = zeros()
        instruments.KV_HBM_BYTES.set(self.hbm_bytes)

    def spec(self):
        """ShapeDtypeStruct for one pool array (AOT compilation input)."""
        return jax.ShapeDtypeStruct(self.shape, self.dtype)

    def blocks_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    @property
    def hbm_bytes(self) -> int:
        return int(self.k.nbytes + self.v.nbytes)
