"""Paged KV cache: HBM block pool + block allocator (native C++ or Python).

The TPU replacement for vLLM's paged KV memory management (SURVEY.md
section 2.4 N1): K/V live as ``[L, num_blocks, block_size, N_kv, Hd]`` device
arrays; sequences own lists of block ids. Block 0 is the reserved TRASH
block — padded scatter writes land there (see ``ops/paged_attention``).

The allocator is the C++ free-list/refcount implementation in
``distllm_tpu/native/block_allocator.cpp`` (ctypes), with a drop-in Python
fallback when no compiler is available.
"""

from __future__ import annotations

import ctypes
from typing import Protocol

import jax
import jax.numpy as jnp


class BlockAllocator(Protocol):
    def alloc(self) -> int: ...

    def free(self, block_id: int) -> None: ...

    def incref(self, block_id: int) -> None: ...

    @property
    def num_free(self) -> int: ...


class PyBlockAllocator:
    """Pure-Python free-list allocator (fallback; same semantics as C++)."""

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 2:
            raise ValueError('need >= 2 blocks (block 0 is reserved)')
        self._free = list(range(num_blocks - 1, 0, -1))
        self._refcount = [0] * num_blocks
        self._refcount[0] = 1  # trash block, never free

    def alloc(self) -> int:
        if not self._free:
            return -1
        block_id = self._free.pop()
        self._refcount[block_id] = 1
        return block_id

    def incref(self, block_id: int) -> None:
        assert self._refcount[block_id] > 0
        self._refcount[block_id] += 1

    def free(self, block_id: int) -> None:
        assert self._refcount[block_id] > 0, f'double free of block {block_id}'
        self._refcount[block_id] -= 1
        if self._refcount[block_id] == 0:
            self._free.append(block_id)

    @property
    def num_free(self) -> int:
        return len(self._free)


class NativeBlockAllocator:
    """ctypes wrapper over the C++ allocator."""

    def __init__(self, num_blocks: int) -> None:
        from distllm_tpu.native import build_library

        so_path = build_library('block_allocator.cpp')
        if so_path is None:
            raise RuntimeError('native allocator unavailable')
        lib = ctypes.CDLL(str(so_path))
        lib.ba_create.restype = ctypes.c_void_p
        lib.ba_create.argtypes = [ctypes.c_int32]
        for fn in ('ba_alloc', 'ba_incref', 'ba_free', 'ba_num_free'):
            getattr(lib, fn).restype = ctypes.c_int32
        lib.ba_alloc.argtypes = [ctypes.c_void_p]
        lib.ba_num_free.argtypes = [ctypes.c_void_p]
        lib.ba_incref.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ba_free.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ba_destroy.argtypes = [ctypes.c_void_p]
        handle = lib.ba_create(num_blocks)
        if not handle:
            raise RuntimeError(f'ba_create({num_blocks}) failed')
        self._lib = lib
        self._handle = handle

    def alloc(self) -> int:
        return int(self._lib.ba_alloc(self._handle))

    def incref(self, block_id: int) -> None:
        if self._lib.ba_incref(self._handle, block_id) < 0:
            raise ValueError(f'incref of unallocated block {block_id}')

    def free(self, block_id: int) -> None:
        if self._lib.ba_free(self._handle, block_id) < 0:
            raise ValueError(f'double free of block {block_id}')

    @property
    def num_free(self) -> int:
        return int(self._lib.ba_num_free(self._handle))

    def __del__(self) -> None:  # pragma: no cover - interpreter teardown
        lib = getattr(self, '_lib', None)
        handle = getattr(self, '_handle', None)
        if lib is not None and handle:
            lib.ba_destroy(handle)
            self._handle = None


def make_allocator(num_blocks: int, prefer_native: bool = True) -> BlockAllocator:
    if prefer_native:
        try:
            return NativeBlockAllocator(num_blocks)
        except (RuntimeError, OSError):
            pass
    return PyBlockAllocator(num_blocks)


class PagedKVCache:
    """Device-resident paged K/V arrays (pure container).

    Block *accounting* — who owns which block, admission, preemption — is
    the scheduler's job (``engine/scheduler.py`` over the native C++ core);
    keeping a second free-list here would silently desync from it.
    """

    def __init__(
        self,
        num_layers: int,
        num_blocks: int,
        block_size: int,
        num_kv_heads: int,
        head_dim: int,
        dtype: str = 'bfloat16',
        sharding=None,
        lazy: bool = False,
    ) -> None:
        self.shape = (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
        self.dtype = jnp.dtype(dtype)
        self._sharding = sharding
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.k = None
        self.v = None
        if not lazy:
            self.allocate()

    def allocate(self) -> None:
        """Materialize the pool arrays (``lazy=True`` defers this so the
        engine can run transient-heavy weight migrations first)."""
        if self.k is not None:
            return
        from distllm_tpu.observability import instruments

        if self._sharding is None:
            self.k = jnp.zeros(self.shape, dtype=self.dtype)
            self.v = jnp.zeros(self.shape, dtype=self.dtype)
        else:
            # Allocate directly into the sharded layout: under tensor
            # parallelism num_blocks is sized against AGGREGATE HBM, so a
            # transient full-size allocation on one device would OOM.
            zeros = jax.jit(
                lambda: jnp.zeros(self.shape, dtype=self.dtype),
                out_shardings=self._sharding,
            )
            self.k = zeros()
            self.v = zeros()
        instruments.KV_HBM_BYTES.set(self.hbm_bytes)

    def spec(self):
        """ShapeDtypeStruct for one pool array (AOT compilation input)."""
        return jax.ShapeDtypeStruct(self.shape, self.dtype)

    def blocks_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    @property
    def hbm_bytes(self) -> int:
        return int(self.k.nbytes + self.v.nbytes)
