"""Paged KV cache: HBM block pool, block allocator, automatic prefix cache.

The TPU replacement for vLLM's paged KV memory management (SURVEY.md
section 2.4 N1): K/V live as ``[L, num_blocks, block_size, N_kv, Hd]`` device
arrays; sequences own lists of block ids. Block 0 is the reserved TRASH
block — padded scatter writes land there (see ``ops/paged_attention``).

The allocator is the C++ free-list/refcount implementation in
``distllm_tpu/native/block_allocator.cpp`` (ctypes), with a drop-in Python
fallback when no compiler is available.

:class:`PrefixCache` is the automatic prefix cache (SGLang-style radix
reuse over full paged blocks; docs/prefix_caching.md): a token-block
hash-chain → block-id map with per-block request refcounts and LRU
eviction of unreferenced blocks. It owns the REUSE policy only — physical
block accounting stays with the scheduler, which marks cache-held blocks
as a request's "borrowed prefix" (``scheduler.py``).

:class:`HostKVTier`, :class:`DiskKVTier`, and :class:`PeerKVTier` extend
the cache past HBM (docs/prefix_caching.md "Tier hierarchy",
docs/routing.md "Peer KV tier"): eviction cascades
HBM → host-RAM → disk → drop instead of dropping KV at the first tier,
and the engine promotes tier hits back into the paged pool via async
``device_put`` overlapped with decode windows. Lookup falls through
host → disk → **peer**: a replica that misses locally can adopt a
sibling replica's spilled blocks over the zmq fabric
(``parallel/fabric.py``) exactly like a disk promotion. All tiers are
keyed by the same chained digests and exchange the same ``.kvblock`` v2
payload (:func:`encode_kvblock` / :func:`decode_kvblock`); the disk
tier's digest-named files persist warm prefixes across engine restarts.

Mixed serving windows (docs/serving.md) write prefill-chunk K/V inside
decode dispatches; those writes always land in blocks the owning request
was granted at admission (the full prompt is budgeted up front), so no
block here ever changes owner while a window is in flight — the engine's
drain-before-preempt guard plus ``prepare_decode(..., rids=...)`` keep
that invariant.
"""

from __future__ import annotations

import ctypes
import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from pathlib import Path
from dataclasses import dataclass, field
from typing import Protocol, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class BlockAllocator(Protocol):
    def alloc(self) -> int: ...

    def free(self, block_id: int) -> None: ...

    def incref(self, block_id: int) -> None: ...

    @property
    def num_free(self) -> int: ...


class PyBlockAllocator:
    """Pure-Python free-list allocator (fallback; same semantics as C++)."""

    def __init__(self, num_blocks: int) -> None:
        if num_blocks < 2:
            raise ValueError('need >= 2 blocks (block 0 is reserved)')
        self._free = list(range(num_blocks - 1, 0, -1))
        self._refcount = [0] * num_blocks
        self._refcount[0] = 1  # trash block, never free

    def alloc(self) -> int:
        if not self._free:
            return -1
        block_id = self._free.pop()
        self._refcount[block_id] = 1
        return block_id

    def incref(self, block_id: int) -> None:
        assert self._refcount[block_id] > 0
        self._refcount[block_id] += 1

    def free(self, block_id: int) -> None:
        assert self._refcount[block_id] > 0, f'double free of block {block_id}'
        self._refcount[block_id] -= 1
        if self._refcount[block_id] == 0:
            self._free.append(block_id)

    @property
    def num_free(self) -> int:
        return len(self._free)


class NativeBlockAllocator:
    """ctypes wrapper over the C++ allocator."""

    def __init__(self, num_blocks: int) -> None:
        from distllm_tpu.native import build_library

        so_path = build_library('block_allocator.cpp')
        if so_path is None:
            raise RuntimeError('native allocator unavailable')
        lib = ctypes.CDLL(str(so_path))
        lib.ba_create.restype = ctypes.c_void_p
        lib.ba_create.argtypes = [ctypes.c_int32]
        for fn in ('ba_alloc', 'ba_incref', 'ba_free', 'ba_num_free'):
            getattr(lib, fn).restype = ctypes.c_int32
        lib.ba_alloc.argtypes = [ctypes.c_void_p]
        lib.ba_num_free.argtypes = [ctypes.c_void_p]
        lib.ba_incref.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ba_free.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ba_destroy.argtypes = [ctypes.c_void_p]
        handle = lib.ba_create(num_blocks)
        if not handle:
            raise RuntimeError(f'ba_create({num_blocks}) failed')
        self._lib = lib
        self._handle = handle

    def alloc(self) -> int:
        return int(self._lib.ba_alloc(self._handle))

    def incref(self, block_id: int) -> None:
        if self._lib.ba_incref(self._handle, block_id) < 0:
            raise ValueError(f'incref of unallocated block {block_id}')

    def free(self, block_id: int) -> None:
        if self._lib.ba_free(self._handle, block_id) < 0:
            raise ValueError(f'double free of block {block_id}')

    @property
    def num_free(self) -> int:
        return int(self._lib.ba_num_free(self._handle))

    def __del__(self) -> None:  # pragma: no cover - interpreter teardown
        lib = getattr(self, '_lib', None)
        handle = getattr(self, '_handle', None)
        if lib is not None and handle:
            lib.ba_destroy(handle)
            self._handle = None


def hash_block_tokens(
    parent: bytes | None, tokens: Sequence[int]
) -> bytes:
    """Digest of one full token block, chained through its prefix.

    The chain (``h_i = H(h_{i-1} || tokens_i)``) makes a block's digest
    identify the ENTIRE prefix up to and including it, so a flat
    digest → block map behaves like a radix trie: matching the longest
    cached prefix is "walk digests until one misses". sha256 rather than
    Python ``hash``: digests index physical KV blocks, and a collision
    would silently serve another prompt's KV.
    """
    h = hashlib.sha256(parent or b'')
    h.update(b','.join(str(int(t)).encode() for t in tokens))
    return h.digest()


def block_digests(
    prompt_ids: Sequence[int], block_size: int
) -> list[bytes]:
    """Chained digests for every FULL block of ``prompt_ids``.

    Partial trailing blocks are not hashable (their content is not yet
    final — later tokens land in them), so reuse granularity is whole
    blocks; the COW path in the engine covers the aligned full-cover case.
    """
    digests: list[bytes] = []
    parent: bytes | None = None
    for start in range(0, len(prompt_ids) - block_size + 1, block_size):
        parent = hash_block_tokens(
            parent, prompt_ids[start : start + block_size]
        )
        digests.append(parent)
    return digests


@dataclass
class _CacheEntry:
    block_id: int
    refcount: int = 0  # live requests referencing this block
    holders: set = field(default_factory=set)  # rids, for shared-block gauge


class PrefixCache:
    """Digest-chain → KV-block map with refcounts and LRU eviction.

    Ownership protocol (engine-driven; see docs/prefix_caching.md):

    - ``acquire(rid, digests)`` — longest-prefix match; increfs every
      matched block for ``rid`` and returns the block ids. Matched blocks
      leave the evictable LRU.
    - ``insert(rid, digest, block_id)`` — adopt a freshly prefilled prompt
      block (the engine then marks it borrowed in the scheduler via
      ``lend_prefix``). Returns False when the digest is already cached
      (first writer wins; the caller keeps its duplicate block private).
    - ``release(rid)`` — drop every reference ``rid`` holds; blocks whose
      refcount reaches zero become LRU-evictable but KEEP their KV
      contents (that persistence is the whole point).
    - ``evict(max_blocks)`` — pop least-recently-used evictable blocks and
      return their ids for the scheduler's free list.

    Purely host-side bookkeeping: never touches device arrays and never
    frees blocks itself.
    """

    def __init__(self, block_size: int) -> None:
        self.block_size = block_size
        self._entries: dict[bytes, _CacheEntry] = {}
        # digest -> block_id for refcount==0 entries, LRU order (oldest
        # first). Entries stay in _entries while evictable.
        self._evictable: 'OrderedDict[bytes, int]' = OrderedDict()
        self._held: dict[int, list[bytes]] = {}  # rid -> digests referenced
        self.stats = {
            'hit_blocks': 0, 'evictions': 0, 'inserts': 0,
            # First-writer-wins losses: a second request prefilled the same
            # block before this insert landed. Mixed serving windows stretch
            # a prompt's prefill over several windows (blocks adopted only at
            # the final chunk), so same-prefix requests admitted meanwhile
            # prefill private duplicates — this counts that lost sharing.
            'insert_dupes': 0,
        }

    # ------------------------------------------------------------- lookup
    def match(self, digests: Sequence[bytes]) -> list[int]:
        """Block ids of the longest cached prefix of ``digests`` (no ref)."""
        blocks: list[int] = []
        for digest in digests:
            entry = self._entries.get(digest)
            if entry is None:
                break
            blocks.append(entry.block_id)
        return blocks

    def acquire(self, rid: int, digests: Sequence[bytes]) -> list[int]:
        """Longest-prefix match + incref each matched block for ``rid``."""
        blocks: list[int] = []
        matched: list[bytes] = []
        for digest in digests:
            entry = self._entries.get(digest)
            if entry is None:
                break
            entry.refcount += 1
            entry.holders.add(rid)
            self._evictable.pop(digest, None)
            matched.append(digest)
            blocks.append(entry.block_id)
        if matched:
            self._held.setdefault(rid, []).extend(matched)
        self.stats['hit_blocks'] += len(blocks)
        self._publish()
        return blocks

    # ------------------------------------------------------------- insert
    def insert(self, rid: int, digest: bytes, block_id: int) -> bool:
        """Adopt ``block_id`` for ``digest``; ``rid`` holds the first ref.

        False when the digest is already cached — the caller's physical
        block stays private to it (freed by the scheduler at finish).
        """
        if digest in self._entries:
            self.stats['insert_dupes'] += 1
            return False
        self._entries[digest] = _CacheEntry(
            block_id, refcount=1, holders={rid}
        )
        self._held.setdefault(rid, []).append(digest)
        self.stats['inserts'] += 1
        self._publish()
        return True

    # ------------------------------------------------------------ release
    def release(self, rid: int) -> None:
        """Drop every reference ``rid`` holds (finish/abort path)."""
        for digest in self._held.pop(rid, []):
            entry = self._entries.get(digest)
            if entry is None:
                continue  # evicted while... cannot happen (ref pinned)
            entry.refcount -= 1
            entry.holders.discard(rid)
            if entry.refcount <= 0:
                # Most-recently released = most likely to be reused next:
                # append to the MRU end.
                self._evictable[digest] = entry.block_id
        self._publish()

    # ------------------------------------------------------------- evict
    def evict(self, max_blocks: int) -> list[int]:
        """Pop up to ``max_blocks`` LRU evictable blocks; caller returns
        them to the scheduler free list."""
        return [bid for _, bid in self.evict_entries(max_blocks)]

    def evict_entries(self, max_blocks: int) -> list[tuple[bytes, int]]:
        """``evict`` but returning ``(digest, block_id)`` pairs, so the
        engine can spill the evicted blocks' KV into the host tier
        (``HostKVTier``) before the blocks rejoin the free list. Eviction
        is never silent: every popped block counts into the per-tier
        eviction series (``distllm_prefix_tier_evictions_total{tier=hbm}``)
        whether or not a lower tier catches it — the caller records the
        final-drop counter when no tier exists."""
        evicted: list[tuple[bytes, int]] = []
        while self._evictable and len(evicted) < max_blocks:
            digest, block_id = self._evictable.popitem(last=False)
            del self._entries[digest]
            evicted.append((digest, block_id))
        if evicted:
            from distllm_tpu.observability import instruments as _m

            _m.PREFIX_EVICTIONS.inc(len(evicted))
            _m.PREFIX_TIER_EVICTIONS.labels(tier='hbm').inc(len(evicted))
        self.stats['evictions'] += len(evicted)
        self._publish()
        return evicted

    # -------------------------------------------------------------- state
    @property
    def num_cached(self) -> int:
        return len(self._entries)

    @property
    def num_evictable(self) -> int:
        return len(self._evictable)

    @property
    def num_shared(self) -> int:
        return sum(1 for e in self._entries.values() if len(e.holders) >= 2)

    def _publish(self) -> None:
        from distllm_tpu.observability import instruments as _m

        _m.PREFIX_CACHED_BLOCKS.set(self.num_cached)
        _m.PREFIX_EVICTABLE_BLOCKS.set(self.num_evictable)
        _m.PREFIX_SHARED_BLOCKS.set(self.num_shared)


def encode_kvblock(
    k: np.ndarray,
    v: np.ndarray,
    k_scale: np.ndarray | None = None,
    v_scale: np.ndarray | None = None,
) -> bytes:
    """Serialize one block's KV (plus int8 scale rows) as ``.kvblock`` v2.

    One JSON header line carrying shape/dtype (and the optional scales
    entry), then the raw K bytes followed by the raw V bytes (then
    K-scale, V-scale) at exact byte offsets — byte-exact for bf16 and
    every other KV dtype, no pickle. The SAME payload serves as the disk
    tier's file format and the peer tier's wire format: a sibling
    replica's fetch and a process restart read identical bytes."""
    meta = {'version': 2, 'shape': list(k.shape), 'dtype': str(k.dtype)}
    meta['scales'] = (
        None if k_scale is None
        else {'shape': list(k_scale.shape), 'dtype': str(k_scale.dtype)}
    )
    # Compact separators: the header rides every spilled block.
    header = json.dumps(meta, separators=(',', ':')).encode() + b'\n'
    payload = header + k.tobytes() + v.tobytes()
    if k_scale is not None:
        payload += k_scale.tobytes() + v_scale.tobytes()
    return payload


def decode_kvblock(payload: bytes) -> tuple[np.ndarray, ...]:
    """Parse a ``.kvblock`` payload back into ``(K, V)`` — or ``(K, V,
    K_scale, V_scale)`` for a quantized spill.

    Raises ``ValueError``/``KeyError``/``TypeError`` on corruption (bad
    header, short read, trailing bytes, unknown version): callers — the
    disk tier's file read, the peer tier's fabric fetch — must degrade
    the failure to a counted tier error + miss, never let it reach
    ``add_request``."""
    header, sep, body = payload.partition(b'\n')
    if not sep:
        raise ValueError('missing header line')
    meta = json.loads(header)
    version = int(meta.get('version', 1))
    if version > 2:
        # A newer process wrote a layout this reader does not
        # understand; halving the body blindly would hand the
        # attention kernel another format's bytes as KV.
        raise ValueError(f'unknown .kvblock version {version}')
    # jnp.dtype resolves 'bfloat16' through ml_dtypes into a
    # numpy-compatible dtype, so the round trip is byte-exact for
    # bf16 KV.
    dtype = np.dtype(jnp.dtype(meta['dtype']))
    shape = tuple(int(d) for d in meta['shape'])
    if version < 2:
        # Version-less pre-int8 spill: body is exactly K then V.
        half = len(body) // 2
        k = np.frombuffer(body[:half], dtype=dtype).reshape(shape)
        v = np.frombuffer(body[half:], dtype=dtype).reshape(shape)
        return k, v
    # v2: exact byte offsets from the header (never len//2 — the
    # optional scale tail would skew the split).
    scales_meta = meta.get('scales')
    arrays: list[np.ndarray] = []
    offset = 0
    specs = [(shape, dtype), (shape, dtype)]
    if scales_meta is not None:
        s_dtype = np.dtype(jnp.dtype(scales_meta['dtype']))
        s_shape = tuple(int(d) for d in scales_meta['shape'])
        specs += [(s_shape, s_dtype), (s_shape, s_dtype)]
    for a_shape, a_dtype in specs:
        count = int(np.prod(a_shape)) * a_dtype.itemsize
        chunk = body[offset:offset + count]
        if len(chunk) != count:
            raise ValueError('truncated .kvblock body')
        arrays.append(
            np.frombuffer(chunk, dtype=a_dtype).reshape(a_shape)
        )
        offset += count
    if offset != len(body):
        raise ValueError('trailing bytes in .kvblock body')
    return tuple(arrays)


class DiskKVTier:
    """Digest-keyed KV block files: the persistence tier under the host
    pool (docs/prefix_caching.md "Tier hierarchy").

    One ``<digest-hex>.kvblock`` file per spilled block (a JSON header
    line carrying shape/dtype, then the raw K bytes followed by the raw V
    bytes — byte-exact for bf16 and every other KV dtype, no pickle).
    Format version 2 adds a ``version`` field and a ``scales`` entry to
    the header so quantized (int8) pools spill their per-block scale rows
    alongside the data: the body becomes K, V, K-scale, V-scale at exact
    byte offsets computed from the header shapes. Version-less files
    (pre-int8 spills) still load on the legacy halve-the-body path;
    an UNKNOWN version counts ``distllm_prefix_tier_errors_total{tier=
    "disk"}`` and degrades to a miss (cold prefill) exactly like the
    other corruption paths — a newer process's format must never crash
    an older reader.
    The digest chain makes the file name self-describing: it identifies
    the ENTIRE token prefix up to and including the block, so a fresh
    engine on the same corpus promotes straight from a previous process's
    spills (cold-start warm TTFT). Bounded by ``max_bytes`` with LRU on
    use order; the on-disk index is rebuilt from file mtimes at
    construction. Thread-safe: the engine loop and server threads may
    race lookups against spills.
    """

    _SUFFIX = '.kvblock'

    def __init__(self, root: str | os.PathLike, max_bytes: int) -> None:
        self._lock = threading.Lock()
        self._root = Path(root)
        self._root.mkdir(parents=True, exist_ok=True)
        self.max_bytes = int(max_bytes)
        # hex digest -> file size, LRU order (oldest first), rebuilt from
        # mtimes so restarts keep the eviction order roughly honest.
        self._index: 'OrderedDict[str, int]' = OrderedDict()  # guarded by self._lock
        self._bytes = 0  # guarded by self._lock
        entries = []
        for path in self._root.glob(f'*{self._SUFFIX}'):
            try:
                stat = path.stat()
            # distlint: disable=swallowed-exception -- benign glob/stat race (a concurrent eviction unlinked the file); the index simply never learns it
            except OSError:
                continue
            entries.append((stat.st_mtime, path.stem, stat.st_size))
        for _, hexdigest, size in sorted(entries):
            self._index[hexdigest] = size
            self._bytes += size
        self._evict_over_budget_locked()
        self._publish_locked()

    def _path(self, hexdigest: str) -> Path:
        return self._root / f'{hexdigest}{self._SUFFIX}'

    # Called with self._lock held by every mutating public method.
    def _evict_over_budget_locked(self) -> int:  # guarded by self._lock
        dropped = 0
        while self._bytes > self.max_bytes and self._index:
            hexdigest, size = self._index.popitem(last=False)
            self._bytes -= size
            try:
                self._path(hexdigest).unlink()
            except OSError:
                # An eviction that cannot delete its file leaks disk
                # bytes outside the budget — counted, never silent.
                from distllm_tpu.observability import instruments as _im

                _im.PREFIX_TIER_ERRORS.labels(tier='disk').inc()
            dropped += 1
        if dropped:
            from distllm_tpu.observability import instruments as _m

            # Disk is the lowest tier: its evictions ARE final drops —
            # the prefix must re-prefill on its next arrival.
            _m.PREFIX_TIER_EVICTIONS.labels(tier='disk').inc(dropped)
            _m.PREFIX_TIER_DROPPED_BLOCKS.inc(dropped)
        return dropped

    def _publish_locked(self) -> None:  # guarded by self._lock
        from distllm_tpu.observability import instruments as _m

        _m.PREFIX_TIER_BYTES.labels(tier='disk').set(self._bytes)

    def contains(self, digest: bytes) -> bool:
        with self._lock:
            return digest.hex() in self._index

    def _drop_entry(self, hexdigest: str, *, unlink: bool = False) -> None:
        """Forget one indexed entry (IO error / corruption path) and count
        the tier error — a bad file must degrade to a miss, never raise
        into ``add_request``'s tier walk. The error is counted ONLY when
        the entry was still indexed: a read racing a concurrent eviction
        (file unlinked, index popped between get()'s lock release and its
        read) is the documented-benign miss, and counting it would let a
        perfectly healthy tier under eviction pressure read as sick."""
        from distllm_tpu.observability import instruments as _m

        with self._lock:
            size = self._index.pop(hexdigest, None)
            if size is not None:
                self._bytes -= size
                self._publish_locked()
        if unlink:
            try:
                os.unlink(self._path(hexdigest))
            # distlint: disable=swallowed-exception -- best-effort cleanup of a file already counted as a tier error below; a second unlink failure adds no signal
            except OSError:
                pass
        if size is not None:
            _m.PREFIX_TIER_ERRORS.labels(tier='disk').inc()

    def put(
        self,
        digest: bytes,
        k: np.ndarray,
        v: np.ndarray,
        k_scale: np.ndarray | None = None,
        v_scale: np.ndarray | None = None,
    ) -> bool:
        """Persist one block's KV (plus its quantization scales when the
        pool is int8); False when already present (the file contents are
        digest-determined, so rewriting buys nothing)."""
        from distllm_tpu.resilience.faults import get_fault_injector

        hexdigest = digest.hex()
        payload = encode_kvblock(k, v, k_scale, v_scale)
        with self._lock:
            if hexdigest in self._index:
                self._index.move_to_end(hexdigest)
                return False
            path = self._path(hexdigest)
            tmp = path.with_suffix('.tmp')
            try:
                get_fault_injector().fail_io('tier_io')
                tmp.write_bytes(payload)
                os.replace(tmp, path)
            except OSError:
                # Full/read-only disk degrades to no tier — counted, so
                # a silently-dead persistence tier shows up in scrapes.
                from distllm_tpu.observability import instruments as _m

                _m.PREFIX_TIER_ERRORS.labels(tier='disk').inc()
                return False
            self._index[hexdigest] = len(payload)
            self._bytes += len(payload)
            from distllm_tpu.observability import instruments as _m

            _m.PREFIX_TIER_SPILLS.labels(tier='disk').inc()
            self._evict_over_budget_locked()
            self._publish_locked()
        return True

    def get(self, digest: bytes) -> tuple[np.ndarray, ...] | None:
        """Load one block's host arrays — ``(K, V)``, or ``(K, V,
        K_scale, V_scale)`` for a quantized spill — refreshing its LRU
        slot. The file read happens OUTSIDE the lock — contains() runs on
        the admission path and must not stall behind multi-megabyte
        cold-disk reads. A concurrent eviction racing the read is just a
        miss. A corrupt or truncated file (bad header, short read — a
        torn spill from a killed process, bit rot, or a foreign file
        wearing the suffix) and an unknown ``version`` alike count a
        ``distllm_prefix_tier_errors_total{tier="disk"}``, drop the
        entry, and return None: the caller falls through to cold
        prefill, never an exception in ``add_request``."""
        from distllm_tpu.resilience.faults import get_fault_injector

        hexdigest = digest.hex()
        with self._lock:
            if hexdigest not in self._index:
                return None
            self._index.move_to_end(hexdigest)
        try:
            get_fault_injector().fail_io('tier_io')
            payload = self._path(hexdigest).read_bytes()
        # distlint: disable=swallowed-exception -- degradation is counted: _drop_entry increments distllm_prefix_tier_errors_total{tier="disk"}
        except OSError:
            self._drop_entry(hexdigest)
            return None
        try:
            return decode_kvblock(payload)
        # distlint: disable=swallowed-exception -- degradation is counted: _drop_entry increments distllm_prefix_tier_errors_total{tier="disk"} and unlinks the corrupt file
        except (ValueError, KeyError, TypeError):
            self._drop_entry(hexdigest, unlink=True)
            return None

    @property
    def num_blocks(self) -> int:
        with self._lock:
            return len(self._index)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes


class PeerKVTier:
    """Sibling replicas' spilled KV blocks, fetched over the zmq fabric —
    the tier between disk and drop (docs/routing.md "Peer KV tier").

    Each peer endpoint is a sibling replica's
    :class:`~distllm_tpu.parallel.fabric.KVBlockServer`, answering
    digest-keyed HAS/GET with the same ``.kvblock`` v2 payload the disk
    tier persists (:func:`encode_kvblock`): content-addressed KV handoff,
    no new wire format. Purely a READ tier — spills never write here
    (each replica owns its own spill budget); a fetched block re-enters
    the local host pool like a disk promotion. Every failure degrades:
    an unreachable peer backs off ``failure_backoff_s`` and the lookup
    misses (cold prefill), a corrupt payload counts
    ``distllm_prefix_tier_errors_total{tier="peer"}`` — the serving loop
    never sees a network exception. Endpoints may be added after
    construction (``add_endpoint``): sibling ports are usually unknown
    until every replica has bound its serve socket.
    """

    def __init__(
        self,
        endpoints: Sequence[str] = (),
        *,
        timeout_ms: int = 500,
        failure_backoff_s: float = 5.0,
    ) -> None:
        # Lazy fabric import: kv_cache must stay importable without zmq
        # reaching module scope (mirrors the tiers' lazy instruments).
        from distllm_tpu.parallel.fabric import KVBlockClient

        self._lock = threading.Lock()
        self.endpoints: list[str] = list(endpoints)  # guarded by self._lock
        self.failure_backoff_s = float(failure_backoff_s)
        self._client = KVBlockClient(timeout_ms=timeout_ms)
        # endpoint -> monotonic instant its backoff expires.
        self._backoff_until: dict[str, float] = {}  # guarded by self._lock
        # Tiny digest -> endpoint memo so get() asks the peer contains()
        # just saw first, instead of re-probing every sibling.
        self._hit_memo: 'OrderedDict[bytes, str]' = OrderedDict()  # guarded by self._lock
        self.fetched_blocks = 0
        self.fetched_bytes = 0

    def add_endpoint(self, endpoint: str) -> None:
        with self._lock:
            if endpoint not in self.endpoints:
                self.endpoints.append(endpoint)

    def _live_endpoints(self) -> list[str]:
        now = time.monotonic()
        with self._lock:
            return [
                ep for ep in self.endpoints
                if self._backoff_until.get(ep, 0.0) <= now
            ]

    def _note_failure(self, endpoint: str) -> None:
        from distllm_tpu.observability import instruments as _m

        _m.PREFIX_TIER_ERRORS.labels(tier='peer').inc()
        with self._lock:
            self._backoff_until[endpoint] = (
                time.monotonic() + self.failure_backoff_s
            )

    def _memo(self, digest: bytes, endpoint: str) -> None:
        with self._lock:
            self._hit_memo[digest] = endpoint
            self._hit_memo.move_to_end(digest)
            while len(self._hit_memo) > 1024:
                self._hit_memo.popitem(last=False)

    def contains(self, digest: bytes) -> bool:
        """Membership across live peers (first hit wins, memoized for the
        ``get`` that follows). Network probes on the admission path are
        bounded by the client timeout and the per-peer backoff."""
        from distllm_tpu.parallel.fabric import KV_HIT

        for endpoint in self._live_endpoints():
            reply = self._client.request(endpoint, b'HAS', digest)
            if reply is None:
                self._note_failure(endpoint)
                continue
            if reply[0] == KV_HIT:
                self._memo(digest, endpoint)
                return True
        return False

    def get(self, digest: bytes) -> tuple[np.ndarray, ...] | None:
        """Fetch one block's host arrays from a sibling replica, memoized
        endpoint first. A hit lands a ``peer_fetch`` flight record (the
        fabric twin of the promotion path's ``promote``); every failure
        mode — timeout, MISS, corrupt payload — returns None so the
        caller degrades to cold prefill."""
        from distllm_tpu.observability import instruments as _m
        from distllm_tpu.observability.flight import get_flight_recorder
        from distllm_tpu.parallel.fabric import KV_HIT

        with self._lock:
            memo = self._hit_memo.get(digest)
        ordered = self._live_endpoints()
        if memo in ordered:
            ordered.remove(memo)
            ordered.insert(0, memo)
        for endpoint in ordered:
            t_start = time.monotonic()
            reply = self._client.request(endpoint, b'GET', digest)
            if reply is None:
                self._note_failure(endpoint)
                continue
            status, payload = reply
            if status != KV_HIT:
                continue  # evicted on the sibling since the HAS probe
            try:
                arrays = decode_kvblock(payload)
            except (ValueError, KeyError, TypeError):
                # Counted degradation; the caller falls through to cold
                # prefill (docs/routing.md "Peer KV tier").
                _m.PREFIX_TIER_ERRORS.labels(tier='peer').inc()
                continue
            fetch_s = time.monotonic() - t_start
            self.fetched_blocks += 1
            self.fetched_bytes += len(payload)
            get_flight_recorder().record(
                'peer_fetch',
                endpoint=endpoint,
                blocks=1,
                bytes=len(payload),
                fetch_s=round(fetch_s, 6),
            )
            return arrays
        return None

    def close(self) -> None:
        self._client.close()


class HostKVTier:
    """Bounded digest-keyed host-RAM pool of spilled KV blocks — the tier
    between the HBM prefix cache and the (optional) disk tier.

    The engine spills evicted ref==0 cache blocks here (one device→host
    fetch per eviction batch) instead of dropping their KV; a later
    same-prefix arrival promotes them back into the paged pool via async
    ``jax.device_put`` (engine ``_begin_promotion``). Entries are whole
    per-block KV slices (``[L, block_size, N_kv, Hd]`` each for K and V;
    quantized pools append the two ``[L, N_kv]`` fp32 scale slices) keyed
    by the chained block digest, LRU-ordered, bounded by ``max_bytes``.
    With a :class:`DiskKVTier` attached, spills write THROUGH to disk
    (persistence never depends on host-LRU timing) and host misses fall
    through to disk, pulling hits back into the host pool. With a
    :class:`PeerKVTier` attached, the fallthrough extends one hop
    further — host → disk → peer — and a peer hit re-enters the host
    pool the same way (docs/routing.md). Thread-safe for the same reason
    as the disk tier.
    """

    def __init__(
        self,
        max_bytes: int,
        disk: DiskKVTier | None = None,
        peer: 'PeerKVTier | None' = None,
    ) -> None:
        self._lock = threading.Lock()
        self.max_bytes = int(max_bytes)
        self.disk = disk
        self.peer = peer
        # digest -> (k, v[, k_scale, v_scale]) host arrays, LRU order
        # (oldest first). Arity follows what was spilled: the tier never
        # inspects payloads beyond byte accounting.
        self._entries: 'OrderedDict[bytes, tuple[np.ndarray, ...]]' = (
            OrderedDict()
        )  # guarded by self._lock
        self._bytes = 0  # guarded by self._lock

    def _publish_locked(self) -> None:  # guarded by self._lock
        from distllm_tpu.observability import instruments as _m

        _m.PREFIX_TIER_BYTES.labels(tier='host').set(self._bytes)

    def _evict_over_budget_locked(self) -> None:  # guarded by self._lock
        from distllm_tpu.observability import instruments as _m

        while self._bytes > self.max_bytes and self._entries:
            digest, arrays = self._entries.popitem(last=False)
            self._bytes -= sum(a.nbytes for a in arrays)
            _m.PREFIX_TIER_EVICTIONS.labels(tier='host').inc()
            # Write-through at put() time normally persisted the block,
            # but a full/read-only disk degrades put() to a no-op — so
            # the drop decision checks what the disk actually HOLDS, not
            # what was attempted. Lock order host→disk only (the disk
            # tier never takes the host lock), so this cannot deadlock.
            if self.disk is None or not self.disk.contains(digest):
                _m.PREFIX_TIER_DROPPED_BLOCKS.inc()

    def lookup(self, digest: bytes) -> str | None:
        """Which tier holds ``digest``
        (``'host'``/``'disk'``/``'peer'``/None), with hit/miss
        accounting. Pure membership — no load, no LRU touch — so
        ``add_request``'s promotion-planning walk stays cheap (the peer
        hop is a bounded-timeout fabric probe, consulted last)."""
        from distllm_tpu.observability import instruments as _m

        with self._lock:
            if digest in self._entries:
                _m.PREFIX_TIER_HITS.labels(tier='host').inc()
                return 'host'
        if self.disk is not None and self.disk.contains(digest):
            _m.PREFIX_TIER_HITS.labels(tier='disk').inc()
            return 'disk'
        if self.peer is not None and self.peer.contains(digest):
            _m.PREFIX_TIER_HITS.labels(tier='peer').inc()
            return 'peer'
        lowest = (
            'peer' if self.peer is not None
            else 'disk' if self.disk is not None
            else 'host'
        )
        _m.PREFIX_TIER_MISSES.labels(tier=lowest).inc()
        return None

    def contains_local(self, digest: bytes) -> bool:
        """Metric-free host/disk membership — the KVBlockServer's HAS
        answer. A sibling's probe must not skew THIS replica's tier
        hit/miss accounting, and must never recurse into this replica's
        own peer tier (two replicas would ping-pong a miss forever)."""
        with self._lock:
            if digest in self._entries:
                return True
        return self.disk is not None and self.disk.contains(digest)

    def encoded_local(self, digest: bytes) -> bytes | None:
        """One block as ``.kvblock`` payload from the LOCAL host/disk
        tiers only — the KVBlockServer's GET answer (serve side of the
        peer hop; peer recursion excluded for the same reason as
        ``contains_local``)."""
        arrays = self.get(digest, allow_peer=False)
        if arrays is None:
            return None
        return encode_kvblock(*arrays)

    def put(
        self,
        digest: bytes,
        k: np.ndarray,
        v: np.ndarray,
        k_scale: np.ndarray | None = None,
        v_scale: np.ndarray | None = None,
    ) -> bool:
        """Adopt one spilled block (host copies of its K/V slices, plus
        the per-block scale rows for a quantized pool)."""
        from distllm_tpu.observability import instruments as _m

        arrays = (
            (k, v) if k_scale is None else (k, v, k_scale, v_scale)
        )
        if self.disk is not None:
            self.disk.put(digest, k, v, k_scale, v_scale)
        with self._lock:
            if digest in self._entries:
                self._entries.move_to_end(digest)
                return False
            self._entries[digest] = arrays
            self._bytes += sum(a.nbytes for a in arrays)
            _m.PREFIX_TIER_SPILLS.labels(tier='host').inc()
            self._evict_over_budget_locked()
            self._publish_locked()
        return True

    def get(
        self, digest: bytes, *, allow_peer: bool = True
    ) -> tuple[np.ndarray, ...] | None:
        """``(K, V)`` — or ``(K, V, K_scale, V_scale)`` for a quantized
        spill — for ``digest``, refreshing its LRU slot; host misses fall
        through to the disk tier, then (``allow_peer``) to the peer tier,
        and a lower-tier hit re-enters the host pool (a promoted prefix
        is about to be hot again)."""
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                self._entries.move_to_end(digest)
                return entry
        loaded = source = None
        if self.disk is not None:
            loaded = self.disk.get(digest)
            if loaded is not None:
                source = 'disk'
        if loaded is None and allow_peer and self.peer is not None:
            loaded = self.peer.get(digest)
            if loaded is not None:
                source = 'peer'
        if loaded is None:
            return None
        from distllm_tpu.observability import instruments as _m

        _m.PREFIX_TIER_PROMOTIONS.labels(tier=source).inc()
        with self._lock:
            if digest not in self._entries:
                self._entries[digest] = loaded
                self._bytes += sum(a.nbytes for a in loaded)
                self._evict_over_budget_locked()
                self._publish_locked()
        return loaded

    @property
    def num_blocks(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes


def make_allocator(num_blocks: int, prefer_native: bool = True) -> BlockAllocator:
    if prefer_native:
        try:
            return NativeBlockAllocator(num_blocks)
        except (RuntimeError, OSError) as exc:
            # The Python twin is a designed drop-in (same policy, same
            # tests), but WHICH allocator served must never be a silent
            # guess in a perf investigation.
            from distllm_tpu.observability.instruments import log_event

            log_event(
                f'[engine] native block allocator unavailable '
                f'({exc!r:.120}); using the Python fallback',
                component='engine',
            )
    return PyBlockAllocator(num_blocks)


class PagedKVCache:
    """Device-resident paged K/V arrays (pure container).

    Block *accounting* — who owns which block, admission, preemption — is
    the scheduler's job (``engine/scheduler.py`` over the native C++ core);
    keeping a second free-list here would silently desync from it.

    With ``dtype='int8'`` each pool array is a
    :class:`~distllm_tpu.ops.paged_attention.QuantizedKV` — int8 data of
    the same paged shape plus a per-block-per-KV-head fp32 scale array
    ``[num_layers, num_blocks, num_kv_heads]`` (docs/serving.md
    "Quantized KV cache"). QuantizedKV is a NamedTuple pytree, so every
    jitted engine path that treats the pool as an opaque carry (scan,
    donation, COW gathers) works unchanged; only code that quantizes,
    dequantizes, or inspects ``.shape`` dispatches on the container.
    """

    def __init__(
        self,
        num_layers: int,
        num_blocks: int,
        block_size: int,
        num_kv_heads: int,
        head_dim: int,
        dtype: str = 'bfloat16',
        sharding=None,
        lazy: bool = False,
    ) -> None:
        self.shape = (num_layers, num_blocks, block_size, num_kv_heads, head_dim)
        self.dtype = jnp.dtype(dtype)
        self.quantized = self.dtype == jnp.dtype(jnp.int8)
        # Symmetric per-block-per-KV-head scales: one fp32 per (layer,
        # block, kv head), for K and V independently (the two pool arrays
        # each carry their own scale plane — the ``[L, blocks, 2, nkv]``
        # layout realized as its K/V halves).
        self.scale_shape = (num_layers, num_blocks, num_kv_heads)
        self._sharding = sharding
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.k = None
        self.v = None
        if not lazy:
            self.allocate()

    def _zeros(self):
        from distllm_tpu.ops.paged_attention import QuantizedKV

        if self._sharding is None:
            data = jnp.zeros(self.shape, dtype=self.dtype)
        else:
            # Allocate directly into the sharded layout: under tensor
            # parallelism num_blocks is sized against AGGREGATE HBM, so a
            # transient full-size allocation on one device would OOM.
            data = jax.jit(
                lambda: jnp.zeros(self.shape, dtype=self.dtype),
                out_shardings=self._sharding,
            )()
        if not self.quantized:
            return data
        # Scales are tiny (4 bytes per block per KV head — ~1/1024 of the
        # data plane) and are read by every device each dispatch, so they
        # stay replicated even when the data plane is sharded.
        return QuantizedKV(data, jnp.zeros(self.scale_shape, jnp.float32))

    def allocate(self) -> None:
        """Materialize the pool arrays (``lazy=True`` defers this so the
        engine can run transient-heavy weight migrations first)."""
        if self.k is not None:
            return
        from distllm_tpu.observability import instruments

        self.k = self._zeros()
        self.v = self._zeros()
        instruments.KV_HBM_BYTES.set(self.hbm_bytes)

    def spec(self):
        """Shape/dtype pytree for one pool array (AOT compilation input):
        a bare ShapeDtypeStruct, or a QuantizedKV of them when int8."""
        data = jax.ShapeDtypeStruct(self.shape, self.dtype)
        if not self.quantized:
            return data
        from distllm_tpu.ops.paged_attention import QuantizedKV

        return QuantizedKV(
            data, jax.ShapeDtypeStruct(self.scale_shape, jnp.float32)
        )

    def blocks_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    @property
    def hbm_bytes(self) -> int:
        return int(sum(
            leaf.nbytes for leaf in jax.tree.leaves((self.k, self.v))
        ))
