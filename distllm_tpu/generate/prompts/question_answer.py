"""Multiple-choice answering template with optional scored retrieval context.

Reference parity: ``generate/prompts/question_answer.py:16-118`` — the
"Context (with relevance scores)" block, ``[INST]``-tagged answering
instructions, and a postprocess that strips leading option numbers
(``1.``-``4.``), trailing periods, and lowercases (the MCQA graders depend on
these normalizations).
"""

from __future__ import annotations

from typing import Literal

from distllm_tpu.generate.prompts.base import ensure_list
from distllm_tpu.utils import BaseConfig


class QuestionAnswerPromptTemplateConfig(BaseConfig):
    name: Literal['question_answer'] = 'question_answer'


class QuestionAnswerPromptTemplate:
    template_with_context = (
        'Context (with relevance scores):\n\n{context}\n\n----\n\n'
        'Question: {question}'
        '[INST] Use the context to answer the question by choosing one of '
        'the options. Do not add the option number or any explanation. '
        'Output your chosen option exactly as presented. [/INST]'
        'Answer: '
    )
    template_no_context = (
        'Question: {question}'
        '[INST] Answer the question by choosing one of the options. '
        'Do not add the option number or any explanation. '
        'Output your chosen option exactly as presented. [/INST]'
        'Answer: '
    )

    def __init__(self, config: QuestionAnswerPromptTemplateConfig) -> None:
        self.config = config

    def preprocess(
        self,
        text: str | list[str],
        contexts: list[list[str]] | None = None,
        scores: list[list[float]] | None = None,
    ) -> list[str]:
        questions = ensure_list(text)
        if contexts is None or scores is None:
            return [
                self.template_no_context.format(question=q) for q in questions
            ]
        prompts = []
        for question, context, score in zip(questions, contexts, scores):
            block = '\n'.join(
                f'Context: {c}, score: {s}' for c, s in zip(context, score)
            )
            prompts.append(
                self.template_with_context.format(
                    context=block, question=question
                )
            )
        return prompts

    def postprocess(self, responses: list[str]) -> list[str]:
        out = []
        for response in responses:
            if response[:2] in ('1.', '2.', '3.', '4.'):
                response = response[3:]
            if response and response[-1] == '.':
                response = response[:-1]
            out.append(response.lower())
        return out
