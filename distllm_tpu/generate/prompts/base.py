"""Prompt template protocol (reference: ``generate/prompts/base.py:17-61``).

``preprocess`` turns raw texts (plus optional retrieval contexts/scores) into
model prompts; ``postprocess`` extracts the useful payload from raw model
responses.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable


@runtime_checkable
class PromptTemplate(Protocol):
    config: object

    def preprocess(
        self,
        text: str | list[str],
        contexts: list[list[str]] | None = None,
        scores: list[list[float]] | None = None,
    ) -> list[str]: ...

    def postprocess(self, responses: list[str]) -> list[str]: ...


def ensure_list(text: str | list[str]) -> list[str]:
    return [text] if isinstance(text, str) else list(text)
