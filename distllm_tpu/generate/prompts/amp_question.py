"""AMP protein MCQ-generation template.

Reference parity: ``generate/prompts/amp_question.py:19-165`` — prompt the
model to produce a protein-function multiple-choice question in a
``Question: ... A) .. B) .. C) .. D) .. Answer: X)`` layout, then regex-parse
the response into ``{full_question_text, correct_answer, distractors}`` JSON
(empty-fields JSON when parsing fails).
"""

from __future__ import annotations

import json
import re
from typing import Any, Literal

from distllm_tpu.generate.prompts.base import ensure_list
from distllm_tpu.utils import BaseConfig


class AMPQuestionPromptConfig(BaseConfig):
    name: Literal['amp_question'] = 'amp_question'


class AMPQuestionPromptTemplate:
    template = (
        'Generate a biologically accurate multiple-choice question with '
        "exactly one correct answer that explicitly uses the protein name "
        "'{protein_name}', based on this description of its function: "
        "'{function_description}'. Format the output as the question after "
        "'Question:', four short answer options labeled A), B), C), D), and "
        "the correct answer after 'Answer:'. Keep the options concise and "
        'correct.'
    )

    def __init__(self, config: AMPQuestionPromptConfig) -> None:
        self.config = config

    def preprocess(
        self,
        text: str | list[str],
        contexts: list[list[str]] | None = None,
        scores: list[list[float]] | None = None,
    ) -> list[str]:
        prompts = []
        for entry_json in ensure_list(text):
            entry = json.loads(entry_json)
            prompts.append(
                self.template.format(
                    protein_name=entry['Protein_Name'],
                    function_description=entry['Function'],
                )
            )
        return prompts

    @staticmethod
    def _parse(response: str) -> str:
        output: dict[str, Any] = {
            'full_question_text': None,
            'correct_answer': None,
            'distractors': [],
        }
        parts = re.split(r'\n\s*Question:', response, flags=re.IGNORECASE)
        if len(parts) < 2:
            return json.dumps(output)
        body = parts[1].strip()
        answer_match = re.search(r'Answer:\s*([A-D])\)', body)
        answer_label = answer_match.group(1) if answer_match else None
        options_start = re.search(r'\s*\bA\)', body)
        if not options_start:
            return json.dumps(output)
        question_text = body[: options_start.start()].strip()
        options_text = re.sub(
            r'\s*Answer:\s*[A-D]\).*',
            '',
            body[options_start.start() :].strip(),
            flags=re.IGNORECASE,
        ).strip()
        correct = None
        distractors = []
        for option in re.split(r'\s+(?=[A-D]\))', options_text):
            label, option_text = option[:2], option[3:].strip()
            if answer_label is not None and label == f'{answer_label})':
                correct = option_text
            else:
                distractors.append(option_text)
        output['full_question_text'] = f'{question_text} {options_text}'
        output['correct_answer'] = correct
        output['distractors'] = distractors
        return json.dumps(output)

    def postprocess(self, responses: list[str]) -> list[str]:
        return [self._parse(r) for r in responses]
