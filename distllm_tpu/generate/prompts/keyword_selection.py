"""Keyword-selection template (reference: ``generate/prompts/keyword_selection.py``).

Given a fixed keyword list (inline or newline-separated file) and a document,
ask the model for the 3 most relevant keywords.
"""

from __future__ import annotations

from pathlib import Path
from typing import Literal, Union

from distllm_tpu.generate.prompts.base import ensure_list
from distllm_tpu.utils import BaseConfig


class KeywordSelectionPromptTemplateConfig(BaseConfig):
    name: Literal['keyword_selection'] = 'keyword_selection'
    keywords: Union[Path, list[str]]


class KeywordSelectionPromptTemplate:
    template = (
        'You are an expert scientist in radiation-based medicine and biology '
        'and all adjacent scientific domains.\n'
        'Given a list of domain keywords and a paragraph, select the 3 '
        'keywords most relevant to the paragraph, ordered by relevance '
        'ascending.\n'
        'The document:\n\n{document}\n\n----\n\n'
        'List of keywords: {keywords_list}\n\n'
        'Write an answer based on the context.\n'
        'If every keyword is equally irrelevant, return the str '
        '`None of the above` 3 times.\n'
        'Answer: '
    )

    def __init__(self, config: KeywordSelectionPromptTemplateConfig) -> None:
        self.config = config
        if isinstance(config.keywords, Path):
            self.keywords_list = config.keywords.read_text().splitlines()
        else:
            self.keywords_list = list(config.keywords)

    def preprocess(
        self,
        text: str | list[str],
        contexts: list[list[str]] | None = None,
        scores: list[list[float]] | None = None,
    ) -> list[str]:
        return [
            self.template.format(
                keywords_list=self.keywords_list, document=document
            )
            for document in ensure_list(text)
        ]

    def postprocess(self, responses: list[str]) -> list[str]:
        return responses
