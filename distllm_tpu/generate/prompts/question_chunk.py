"""Question-generation template: one deep question per text chunk.

Reference parity: ``generate/prompts/question_chunk.py:18-92`` — prompt asks
for a concept-level question about the chunk; postprocess sentence-tokenizes
the response (NLTK) and keeps only the FIRST sentence ending in '?', or ''
when the model produced no question.
"""

from __future__ import annotations

from typing import Literal

from distllm_tpu.generate.prompts.base import ensure_list
from distllm_tpu.utils import BaseConfig


class QuestionChunkPromptTemplateConfig(BaseConfig):
    name: Literal['question_chunk'] = 'question_chunk'


class QuestionChunkPromptTemplate:
    template = (
        'You are a scientific researcher. Read the following chunk of text '
        'and write one high-quality question that requires deep understanding '
        'of the concepts it presents. Avoid questions about paper-specific '
        'details such as results, findings, or references.\n\n'
        'Text: {chunk}\nQuestion:'
    )

    def __init__(self, config: QuestionChunkPromptTemplateConfig) -> None:
        self.config = config

    def preprocess(
        self,
        text: str | list[str],
        contexts: list[list[str]] | None = None,
        scores: list[list[float]] | None = None,
    ) -> list[str]:
        return [self.template.format(chunk=chunk) for chunk in ensure_list(text)]

    @staticmethod
    def _first_question(response: str) -> str:
        # Untrained Punkt (default heuristics) — no nltk data download
        # needed, matching the jsonl_chunk dataset splitter.
        import nltk

        tokenizer = nltk.tokenize.PunktSentenceTokenizer()
        for sentence in tokenizer.tokenize(response):
            if sentence.strip().endswith('?'):
                return sentence
        return ''

    def postprocess(self, responses: list[str]) -> list[str]:
        return [self._first_question(r) for r in responses]
