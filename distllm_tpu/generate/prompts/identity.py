"""Identity template: pass texts through unchanged (reference:
``generate/prompts/identity.py:16-63``)."""

from __future__ import annotations

from typing import Literal

from distllm_tpu.generate.prompts.base import ensure_list
from distllm_tpu.utils import BaseConfig


class IdentityPromptTemplateConfig(BaseConfig):
    name: Literal['identity'] = 'identity'


class IdentityPromptTemplate:
    def __init__(self, config: IdentityPromptTemplateConfig) -> None:
        self.config = config

    def preprocess(
        self,
        text: str | list[str],
        contexts: list[list[str]] | None = None,
        scores: list[list[float]] | None = None,
    ) -> list[str]:
        return ensure_list(text)

    def postprocess(self, responses: list[str]) -> list[str]:
        return responses
