"""Prompt template factory (reference: ``generate/prompts/__init__.py:39-54``)."""

from __future__ import annotations

from typing import Any, Union

from distllm_tpu.generate.prompts.amp_question import (
    AMPQuestionPromptConfig,
    AMPQuestionPromptTemplate,
)
from distllm_tpu.generate.prompts.base import PromptTemplate
from distllm_tpu.generate.prompts.identity import (
    IdentityPromptTemplate,
    IdentityPromptTemplateConfig,
)
from distllm_tpu.generate.prompts.keyword_selection import (
    KeywordSelectionPromptTemplate,
    KeywordSelectionPromptTemplateConfig,
)
from distllm_tpu.generate.prompts.question_answer import (
    QuestionAnswerPromptTemplate,
    QuestionAnswerPromptTemplateConfig,
)
from distllm_tpu.generate.prompts.question_chunk import (
    QuestionChunkPromptTemplate,
    QuestionChunkPromptTemplateConfig,
)

PromptTemplateConfigs = Union[
    IdentityPromptTemplateConfig,
    QuestionChunkPromptTemplateConfig,
    QuestionAnswerPromptTemplateConfig,
    KeywordSelectionPromptTemplateConfig,
    AMPQuestionPromptConfig,
]

STRATEGIES: dict[str, tuple[type, type]] = {
    'identity': (IdentityPromptTemplateConfig, IdentityPromptTemplate),
    'question_chunk': (QuestionChunkPromptTemplateConfig, QuestionChunkPromptTemplate),
    'question_answer': (QuestionAnswerPromptTemplateConfig, QuestionAnswerPromptTemplate),
    'keyword_selection': (
        KeywordSelectionPromptTemplateConfig,
        KeywordSelectionPromptTemplate,
    ),
    'amp_question': (AMPQuestionPromptConfig, AMPQuestionPromptTemplate),
}


def get_prompt_template(kwargs: dict[str, Any]) -> PromptTemplate:
    name = kwargs.get('name', '')
    entry = STRATEGIES.get(name)
    if entry is None:
        raise ValueError(
            f'Unknown prompt template: {name!r}. Available: {sorted(STRATEGIES)}'
        )
    config_cls, cls = entry
    return cls(config_cls(**kwargs))


__all__ = [
    'PromptTemplate',
    'PromptTemplateConfigs',
    'get_prompt_template',
    'STRATEGIES',
]
