"""Generate-writer protocol (reference: ``generate/writers/base.py:11-50``)."""

from __future__ import annotations

from pathlib import Path
from typing import Protocol, runtime_checkable


@runtime_checkable
class Writer(Protocol):
    config: object

    def write(
        self,
        output_dir: str | Path,
        paths: list[str],
        text: list[str],
        responses: list[str],
    ) -> None: ...

    def merge(
        self, dataset_dirs: list[str | Path], output_dir: str | Path
    ) -> None: ...
