"""HF-dataset generate writer: ``{path, text, response}`` rows.

Reference parity: ``generate/writers/huggingface.py:32-89`` — merge loads
every shard and SKIPS missing/corrupt ones (partial re-runs rely on this).
"""

from __future__ import annotations

from pathlib import Path
from typing import Literal

from distllm_tpu.observability.instruments import log_event
from distllm_tpu.utils import BaseConfig


class HuggingFaceWriterConfig(BaseConfig):
    name: Literal['huggingface'] = 'huggingface'
    num_proc: int | None = None


class HuggingFaceWriter:
    def __init__(self, config: HuggingFaceWriterConfig) -> None:
        self.config = config

    def write(
        self,
        output_dir: str | Path,
        paths: list[str],
        text: list[str],
        responses: list[str],
    ) -> None:
        from datasets import Dataset

        Dataset.from_dict(
            {'path': paths, 'text': text, 'response': responses}
        ).save_to_disk(str(output_dir))

    def merge(
        self, dataset_dirs: list[str | Path], output_dir: str | Path
    ) -> None:
        from datasets import concatenate_datasets, load_from_disk

        shards = []
        for path in dataset_dirs:
            try:
                shards.append(load_from_disk(str(path)))
            except Exception as exc:  # noqa: BLE001 - skip bad shards
                log_event(
                    f'[writer] skipping shard {path}: {exc}',
                    component='writer',
                )
        if not shards:
            raise ValueError(f'no readable shards among {len(dataset_dirs)} dirs')
        concatenate_datasets(shards).save_to_disk(
            str(output_dir), num_proc=self.config.num_proc
        )
