"""AMP jsonl writer: merge model outputs back into original protein entries.

Reference parity: ``generate/writers/amp_json.py:24-81`` — ``paths`` carry
the original entry JSON; each response (itself JSON from the amp_question
postprocess) is merged into its entry and written one-per-line.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Literal

from distllm_tpu.utils import BaseConfig


class AMPJsonlWriterConfig(BaseConfig):
    name: Literal['amp_jsonl'] = 'amp_jsonl'
    base_name: str = 'amp_questions'


class AMPJsonlWriter:
    def __init__(self, config: AMPJsonlWriterConfig) -> None:
        self.config = config
        self.current_chunk = 0

    def write(
        self,
        output_dir: str | Path,
        paths: list[str],
        text: list[str],
        responses: list[str],
    ) -> None:
        output_dir = Path(output_dir)
        output_dir.mkdir(parents=True, exist_ok=True)
        out_path = (
            output_dir / f'{self.config.base_name}_{self.current_chunk}.jsonl'
        )
        with open(out_path, 'w') as fh:
            for original, response in zip(paths, responses):
                entry = json.loads(original)
                entry.update(json.loads(response))
                fh.write(json.dumps(entry) + '\n')
        self.current_chunk += 1

    def merge(
        self, dataset_dirs: list[str | Path], output_dir: str | Path
    ) -> None:
        output_dir = Path(output_dir)
        output_dir.mkdir(parents=True, exist_ok=True)
        out_path = output_dir / f'{self.config.base_name}_merged.jsonl'
        with open(out_path, 'w') as fh:
            for shard_dir in dataset_dirs:
                for jsonl in sorted(Path(shard_dir).glob('*.jsonl')):
                    fh.write(jsonl.read_text())
