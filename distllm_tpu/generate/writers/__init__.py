"""Generate-writer factory (reference: ``generate/writers/__init__.py``)."""

from __future__ import annotations

from typing import Any, Union

from distllm_tpu.generate.writers.amp_json import (
    AMPJsonlWriter,
    AMPJsonlWriterConfig,
)
from distllm_tpu.generate.writers.base import Writer
from distllm_tpu.generate.writers.huggingface import (
    HuggingFaceWriter,
    HuggingFaceWriterConfig,
)

WriterConfigs = Union[HuggingFaceWriterConfig, AMPJsonlWriterConfig]

STRATEGIES: dict[str, tuple[type, type]] = {
    'huggingface': (HuggingFaceWriterConfig, HuggingFaceWriter),
    'amp_jsonl': (AMPJsonlWriterConfig, AMPJsonlWriter),
}


def get_writer(kwargs: dict[str, Any]) -> Writer:
    name = kwargs.get('name', '')
    entry = STRATEGIES.get(name)
    if entry is None:
        raise ValueError(
            f'Unknown writer name: {name!r}. Available: {sorted(STRATEGIES)}'
        )
    config_cls, cls = entry
    return cls(config_cls(**kwargs))


__all__ = ['Writer', 'WriterConfigs', 'get_writer', 'STRATEGIES']
