"""Generator strategy factory with optional warmstart registration.

Reference parity: ``generate/generators/__init__.py:55-89``.
"""

from __future__ import annotations

from typing import Any, Union

from distllm_tpu.generate.generators.api_backend import (
    ApiGenerator,
    ApiGeneratorConfig,
)
from distllm_tpu.generate.generators.base import LLMGenerator
from distllm_tpu.generate.generators.chat_endpoints import (
    ArgoGenerator,
    ArgoGeneratorConfig,
    OpenAIAPIGenerator,
    OpenAIAPIGeneratorConfig,
)
from distllm_tpu.generate.generators.huggingface_backend import (
    HuggingFaceGenerator,
    HuggingFaceGeneratorConfig,
)
from distllm_tpu.generate.generators.tpu_backend import (
    FakeGenerator,
    FakeGeneratorConfig,
    TpuGenerator,
    TpuGeneratorConfig,
)
from distllm_tpu.registry import registry

GeneratorConfigs = Union[
    TpuGeneratorConfig,
    HuggingFaceGeneratorConfig,
    ApiGeneratorConfig,
    ArgoGeneratorConfig,
    OpenAIAPIGeneratorConfig,
    FakeGeneratorConfig,
]

STRATEGIES: dict[str, tuple[type, type]] = {
    'tpu': (TpuGeneratorConfig, TpuGenerator),
    'vllm': (TpuGeneratorConfig, TpuGenerator),  # reference-config alias
    'huggingface': (HuggingFaceGeneratorConfig, HuggingFaceGenerator),
    'api': (ApiGeneratorConfig, ApiGenerator),
    'langchain': (ApiGeneratorConfig, ApiGenerator),  # reference-config alias
    'argo': (ArgoGeneratorConfig, ArgoGenerator),
    'openai': (OpenAIAPIGeneratorConfig, OpenAIAPIGenerator),
    'fake': (FakeGeneratorConfig, FakeGenerator),
}


def _build_generator(**kwargs: Any) -> LLMGenerator:
    name = kwargs.get('name', '')
    entry = STRATEGIES.get(name)
    if entry is None:
        raise ValueError(
            f'Unknown generator name: {name!r}. Available: {sorted(STRATEGIES)}'
        )
    config_cls, cls = entry
    return cls(config_cls(**kwargs))


def get_generator(kwargs: dict[str, Any], register: bool = False) -> LLMGenerator:
    """Build a generator; ``register=True`` reuses the cached warm instance."""
    if register:
        return registry().get(_build_generator, slot='generator', **kwargs)
    return _build_generator(**kwargs)


__all__ = ['LLMGenerator', 'GeneratorConfigs', 'get_generator', 'STRATEGIES']
