"""API generator: hosted-LLM chat over HTTP (OpenAI, Anthropic, Google).

Reference parity: ``generate/generators/langchain_backend.py:50-103`` — the
reference drives gpt-3.5/gpt-4, gemini-pro, and claude-3-opus through
LangChain's LLMChain, picking the provider class by model name. langchain
is unavailable here, so each provider's wire protocol is spoken natively
(``requests``):

- ``openai``   — ``POST {base}/chat/completions`` (also covers our own
  chat server and any vLLM-style endpoint);
- ``anthropic`` — ``POST {base}/v1/messages`` (Messages API);
- ``google``   — ``POST {base}/v1beta/models/{model}:generateContent``
  (Generative Language API).

``provider='auto'`` infers from the model name exactly as the reference's
chain selection does: ``claude*`` → anthropic, ``gemini*`` → google,
anything else → openai. Registered under both ``api`` and ``langchain``.
API keys come from the environment (reference uses dotenv).
"""

from __future__ import annotations

import os
from typing import Literal

from pydantic import Field

from distllm_tpu.utils import BaseConfig, expo_backoff_retry

_KEY_ENVS = {
    'openai': 'OPENAI_API_KEY',
    'anthropic': 'ANTHROPIC_API_KEY',
    'google': 'GOOGLE_API_KEY',
}


class ApiAuthError(Exception):
    """HTTP 401/403 from the endpoint — retrying cannot help."""


class ApiResponseError(Exception):
    """A 200 response whose body carries no generatable text (e.g. a
    Gemini safety block) — deterministic, so retrying cannot help."""


class ApiGeneratorConfig(BaseConfig):
    name: Literal['api', 'langchain'] = 'api'
    provider: Literal['auto', 'openai', 'anthropic', 'google'] = Field(
        default='auto',
        description="Wire protocol; 'auto' infers from the model name "
        "(claude* -> anthropic, gemini* -> google, else openai).",
    )
    openai_api_base: str = 'https://api.openai.com/v1'
    anthropic_api_base: str = 'https://api.anthropic.com'
    anthropic_version: str = '2023-06-01'
    google_api_base: str = 'https://generativelanguage.googleapis.com'
    model: str = 'gpt-3.5-turbo'
    api_key: str = Field(
        default='', description='Inline API key (takes precedence).'
    )
    api_key_env: str = Field(
        default='',
        description='Env var holding the API key; defaults per provider '
        '(OPENAI_API_KEY / ANTHROPIC_API_KEY / GOOGLE_API_KEY).',
    )
    temperature: float = 0.0
    max_tokens: int = 512
    timeout: float = 120.0
    max_tries: int = 5
    concurrency: int = Field(
        default=8,
        description='Parallel HTTP requests per generate() batch — lets an '
        "OpenAI-compatible server's continuous batching see the whole batch "
        'at once.',
    )
    extra_body: dict = Field(
        default_factory=dict,
        description='Extra JSON merged into each request (e.g. Argo-proxy '
        "style 'user' fields).",
    )

    def resolved_provider(self) -> str:
        if self.provider != 'auto':
            return self.provider
        # A non-default openai_api_base means the user is pointing at an
        # OpenAI-compatible proxy; honoring it beats rerouting a claude*/
        # gemini* model name to the vendor endpoint with the wrong wire
        # format (and ignoring the configured base entirely). Compared
        # against the field default rather than model_fields_set: a
        # write_yaml/from_yaml round trip re-passes every default as an
        # explicit kwarg, which would otherwise flip the route.
        default_base = type(self).model_fields['openai_api_base'].default
        if self.openai_api_base.rstrip('/') != default_base.rstrip('/'):
            return 'openai'
        model = self.model.lower()
        if model.startswith('claude'):
            return 'anthropic'
        if model.startswith('gemini'):
            return 'google'
        return 'openai'


class ApiGenerator:
    def __init__(self, config: ApiGeneratorConfig) -> None:
        self.config = config
        self.provider = config.resolved_provider()

    def _api_key(self) -> str:
        if self.config.api_key:
            return self.config.api_key
        env = self.config.api_key_env or _KEY_ENVS[self.provider]
        return os.environ.get(env, '')

    def _request(self, prompt: str) -> tuple[str, dict, dict]:
        """(url, headers, body) for one prompt on the resolved provider."""
        cfg = self.config
        key = self._api_key()
        if self.provider == 'anthropic':
            headers = {'Content-Type': 'application/json',
                       'anthropic-version': cfg.anthropic_version}
            if key:
                headers['x-api-key'] = key
            return (
                f'{cfg.anthropic_api_base.rstrip("/")}/v1/messages',
                headers,
                {
                    'model': cfg.model,
                    'max_tokens': cfg.max_tokens,
                    'temperature': cfg.temperature,
                    'messages': [{'role': 'user', 'content': prompt}],
                    **cfg.extra_body,
                },
            )
        if self.provider == 'google':
            url = (
                f'{cfg.google_api_base.rstrip("/")}/v1beta/models/'
                f'{cfg.model}:generateContent'
            )
            # Key goes in a header, never the URL: exception messages and
            # request logs format the URL verbatim.
            headers = {'Content-Type': 'application/json'}
            if key:
                headers['x-goog-api-key'] = key
            gen_config = {
                'temperature': cfg.temperature,
                'maxOutputTokens': cfg.max_tokens,
            }
            extra = dict(cfg.extra_body)
            # Google nests sampling knobs under generationConfig; merge an
            # extra_body generationConfig there instead of clobbering it.
            gen_config.update(extra.pop('generationConfig', {}))
            return (
                url,
                headers,
                {
                    'contents': [{'parts': [{'text': prompt}]}],
                    'generationConfig': gen_config,
                    **extra,
                },
            )
        headers = {'Content-Type': 'application/json'}
        if key:
            headers['Authorization'] = f'Bearer {key}'
        return (
            f'{cfg.openai_api_base.rstrip("/")}/chat/completions',
            headers,
            {
                'model': cfg.model,
                'messages': [{'role': 'user', 'content': prompt}],
                'temperature': cfg.temperature,
                'max_tokens': cfg.max_tokens,
                **cfg.extra_body,
            },
        )

    def _parse(self, payload: dict) -> str:
        # A 200 whose body lacks the provider's expected fields (e.g. a
        # proxy error JSON) is deterministic — raise ApiResponseError (in
        # give_up_on) rather than KeyError, which expo_backoff_retry would
        # re-bill.
        try:
            return self._parse_payload(payload)
        except (KeyError, IndexError, TypeError, AttributeError) as e:
            shape = (
                sorted(payload)[:8]
                if isinstance(payload, dict)
                else type(payload).__name__
            )
            raise ApiResponseError(
                f'malformed {self.provider} payload ({shape!r}): {e!r}'
            ) from e

    def _parse_payload(self, payload: dict) -> str:
        if self.provider == 'anthropic':
            return ''.join(
                block.get('text', '')
                for block in payload['content']
                if block.get('type', 'text') == 'text'
            )
        if self.provider == 'google':
            candidates = payload.get('candidates') or []
            if not candidates or 'content' not in candidates[0]:
                # Safety-blocked / empty responses are deterministic:
                # surface the reason instead of retrying the bill.
                reason = (
                    candidates[0].get('finishReason')
                    if candidates
                    else payload.get('promptFeedback')
                )
                raise ApiResponseError(
                    f'no generatable content (reason: {reason!r})'
                )
            parts = candidates[0]['content'].get('parts', [])
            return ''.join(p.get('text', '') for p in parts)
        return payload['choices'][0]['message']['content']

    def _chat(self, prompt: str) -> str:
        import requests

        url, headers, body = self._request(prompt)

        def call() -> str:
            response = requests.post(
                url, json=body, headers=headers, timeout=self.config.timeout
            )
            if response.status_code in (401, 403):
                raise ApiAuthError(f'{response.status_code} from {url}')
            response.raise_for_status()
            return self._parse(response.json())

        return expo_backoff_retry(
            call,
            max_tries=self.config.max_tries,
            give_up_on=(ApiAuthError, ApiResponseError),
        )

    def generate(self, prompts: str | list[str]) -> list[str]:
        if isinstance(prompts, str):
            prompts = [prompts]
        if len(prompts) == 1 or self.config.concurrency <= 1:
            return [self._chat(p) for p in prompts]
        # Concurrent requests: an OpenAI-compatible server with continuous
        # batching schedules them together (one-at-a-time would serialize).
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(self.config.concurrency, len(prompts))
        ) as pool:
            return list(pool.map(self._chat, prompts))
