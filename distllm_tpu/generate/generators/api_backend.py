"""API generator: OpenAI-compatible chat-completions over HTTP.

Reference parity: ``generate/generators/langchain_backend.py`` — the
reference drives gpt/gemini/claude through LangChain's LLMChain; langchain
is unavailable here, so this talks the OpenAI-compatible wire protocol
directly (``requests``), which also covers our own chat server and any
vLLM-style endpoint. Registered under both ``api`` and ``langchain``.
API keys come from the environment (reference uses dotenv).
"""

from __future__ import annotations

import os
from typing import Literal

from pydantic import Field

from distllm_tpu.utils import BaseConfig, expo_backoff_retry


class ApiAuthError(Exception):
    """HTTP 401/403 from the endpoint — retrying cannot help."""


class ApiGeneratorConfig(BaseConfig):
    name: Literal['api', 'langchain'] = 'api'
    openai_api_base: str = 'https://api.openai.com/v1'
    model: str = 'gpt-3.5-turbo'
    api_key: str = Field(
        default='', description='Inline API key (takes precedence).'
    )
    api_key_env: str = Field(
        default='OPENAI_API_KEY', description='Env var holding the API key.'
    )
    temperature: float = 0.0
    max_tokens: int = 512
    timeout: float = 120.0
    max_tries: int = 5
    concurrency: int = Field(
        default=8,
        description='Parallel HTTP requests per generate() batch — lets an '
        "OpenAI-compatible server's continuous batching see the whole batch "
        'at once.',
    )
    extra_body: dict = Field(
        default_factory=dict,
        description='Extra JSON merged into each request (e.g. Argo-proxy '
        "style 'user' fields).",
    )


class ApiGenerator:
    def __init__(self, config: ApiGeneratorConfig) -> None:
        self.config = config

    def _chat(self, prompt: str) -> str:
        import requests

        headers = {'Content-Type': 'application/json'}
        api_key = self.config.api_key or os.environ.get(
            self.config.api_key_env, ''
        )
        if api_key:
            headers['Authorization'] = f'Bearer {api_key}'

        def call() -> str:
            response = requests.post(
                f'{self.config.openai_api_base.rstrip("/")}/chat/completions',
                json={
                    'model': self.config.model,
                    'messages': [{'role': 'user', 'content': prompt}],
                    'temperature': self.config.temperature,
                    'max_tokens': self.config.max_tokens,
                    **self.config.extra_body,
                },
                headers=headers,
                timeout=self.config.timeout,
            )
            if response.status_code in (401, 403):
                raise ApiAuthError(
                    f'{response.status_code} from {self.config.openai_api_base}'
                )
            response.raise_for_status()
            return response.json()['choices'][0]['message']['content']

        return expo_backoff_retry(
            call, max_tries=self.config.max_tries, give_up_on=(ApiAuthError,)
        )

    def generate(self, prompts: str | list[str]) -> list[str]:
        if isinstance(prompts, str):
            prompts = [prompts]
        if len(prompts) == 1 or self.config.concurrency <= 1:
            return [self._chat(p) for p in prompts]
        # Concurrent requests: an OpenAI-compatible server with continuous
        # batching schedules them together (one-at-a-time would serialize).
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(self.config.concurrency, len(prompts))
        ) as pool:
            return list(pool.map(self._chat, prompts))
