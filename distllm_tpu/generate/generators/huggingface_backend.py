"""HuggingFace torch generator (CPU-capable fallback backend).

Reference parity: ``generate/generators/huggingface_backend.py`` —
``AutoModelForCausalLM.generate`` with top-p/beams/do_sample and manual
batching via ``batch_data``. On this framework it serves as the
correctness/compat backend (e.g. architectures the JAX engine doesn't cover
yet); quantization flags are accepted but mapped to torch dtypes (no
bitsandbytes on TPU hosts).
"""

from __future__ import annotations

from typing import Literal

from distllm_tpu.utils import BaseConfig, batch_data


class HuggingFaceGeneratorConfig(BaseConfig):
    name: Literal['huggingface'] = 'huggingface'
    pretrained_model_name_or_path: str
    half_precision: bool = False
    batch_size: int = 4
    top_p: float = 0.95
    num_beams: int = 1
    do_sample: bool = True
    max_new_tokens: int = 256
    trust_remote_code: bool = False


class HuggingFaceGenerator:
    def __init__(self, config: HuggingFaceGeneratorConfig) -> None:
        import torch
        from transformers import AutoModelForCausalLM, AutoTokenizer

        self.config = config
        self._torch = torch
        self.tokenizer = AutoTokenizer.from_pretrained(
            config.pretrained_model_name_or_path,
            trust_remote_code=config.trust_remote_code,
        )
        if self.tokenizer.pad_token is None:
            self.tokenizer.pad_token = self.tokenizer.eos_token
        dtype = torch.float16 if config.half_precision else torch.float32
        self.model = AutoModelForCausalLM.from_pretrained(
            config.pretrained_model_name_or_path,
            torch_dtype=dtype,
            trust_remote_code=config.trust_remote_code,
        ).eval()

    def generate(self, prompts: str | list[str]) -> list[str]:
        if isinstance(prompts, str):
            prompts = [prompts]
        torch = self._torch
        responses: list[str] = []
        for batch in batch_data(prompts, self.config.batch_size):
            inputs = self.tokenizer(
                batch, return_tensors='pt', padding=True, truncation=True
            )
            with torch.no_grad():
                outputs = self.model.generate(
                    **inputs,
                    max_new_tokens=self.config.max_new_tokens,
                    top_p=self.config.top_p,
                    num_beams=self.config.num_beams,
                    do_sample=self.config.do_sample,
                    pad_token_id=self.tokenizer.pad_token_id,
                )
            prompt_len = inputs['input_ids'].shape[1]
            responses.extend(
                self.tokenizer.batch_decode(
                    outputs[:, prompt_len:], skip_special_tokens=True
                )
            )
        return responses

    def shutdown(self) -> None:
        self.model = None
