"""TPU generator: the in-process paged-KV engine behind the LLMGenerator API.

Reference parity: ``distllm/generate/generators/vllm_backend.py`` — same
config surface (model path, temperature, ``top_p`` XOR ``min_p``,
``max_tokens``, ``tensor_parallel_size``) but the backend is our own
JAX/Pallas engine instead of vLLM. Registered under both ``tpu`` and
``vllm`` names so reference YAML configs keep working.
"""

from __future__ import annotations

from typing import Literal

from pydantic import Field, model_validator

from distllm_tpu.generate.engine import EngineConfig, LLMEngine, SamplingParams
from distllm_tpu.ops.quantization import normalize_mode, quantize_pytree
from distllm_tpu.utils import BaseConfig


class TpuGeneratorConfig(BaseConfig):
    name: Literal['tpu', 'vllm'] = 'tpu'
    pretrained_model_name_or_path: str = Field(
        description='Local path to an HF-format decoder checkpoint.'
    )
    tokenizer_name: str | None = None
    trust_remote_code: bool = False
    temperature: float = 0.5
    min_p: float = 0.1
    top_p: float = 0.0
    max_tokens: int = 2000
    tensor_parallel_size: int = Field(
        default=1, description='TP degree over the mesh model axis.'
    )
    # Engine capacity knobs (vLLM analogues).
    block_size: int = 16
    num_blocks: int = 2048
    max_num_seqs: int = 16
    max_model_len: int = 4096
    quantization: bool | Literal['int8', 'nf4'] = Field(
        default=False,
        description='Weight-only quantized serving; True means nf4 (the '
        "reference's bitsandbytes NF4 option).",
    )
    # Serving perf knobs (same surface the bench exercises — production
    # configs must be able to turn on what the measured numbers used).
    # Defaults are None = inherit EngineConfig's documented defaults, so
    # one place owns each default and reference-parity semantics (exact
    # full-vocab sampling) hold unless a config opts in.
    attn_backend: str = Field(
        default='auto',
        description="Paged-attention kernel selector: 'auto' = the fused "
        'ragged Pallas kernel when the chip, head_dim, and KV geometry '
        "support it, XLA otherwise; 'interpret' runs the kernel on the "
        'Pallas interpreter (CPU parity tier). Validated against '
        'ops.paged_attention.ATTN_BACKENDS — the single owner of the '
        'selector set (docs/serving.md "Attention kernel backends").',
    )
    decode_steps: int | None = Field(
        default=None,
        ge=1,
        description='Tokens per fused decode dispatch (amortizes the '
        'host round trip; 1 restores per-token dispatch).',
    )
    sampling_top_window: int | None = Field(
        default=None,
        ge=0,
        description='Sample from the top-K logits per step instead of '
        'sorting the full vocab (0 = exact full-vocab semantics).',
    )
    decode_layer_unroll: bool | None = Field(
        default=None,
        description='Unroll the decode layer scan (folds stacked-weight '
        'slices into the matmuls; longer one-time compile).',
    )
    enable_prefix_cache: bool | None = Field(
        default=None,
        description='Automatic prefix caching: reuse KV blocks across '
        'requests sharing a block-aligned prompt prefix (RAG system '
        'prompts, MCQA stems) — prefill runs only on the uncached tail.',
    )
    prefill_chunk_tokens: int | None = Field(
        default=None,
        ge=0,
        description='Split uncached prefill tails longer than this into '
        'sequential chunks so one long prompt cannot stall decode '
        '(0 disables chunking).',
    )
    enable_mixed_batching: bool | None = Field(
        default=None,
        description='Mixed prefill+decode serving windows: cache-hit '
        'tails and chunked prefill spans ride INSIDE the fused decode '
        'dispatches instead of serializing between them '
        '(docs/serving.md). Token-identical under greedy sampling.',
    )
    max_window_prefill_tokens: int | None = Field(
        default=None,
        ge=0,
        description='Prefill-chunk token budget one mixed window may '
        'carry (each token bucket is one extra compiled window shape).',
    )
    draft_k: int | None = Field(
        default=None,
        ge=0,
        description='Prompt-lookup speculative decoding: draft up to '
        'this many tokens per row from the row\'s own history and '
        'verify them in one ragged dispatch — every accepted token '
        'skipped a weight pass (docs/speculative.md). Greedy rows '
        'verify by argmax comparison; temperature > 0 rows verify by '
        'device-side rejection sampling ("Sampled verification"); '
        '0 disables.',
    )
    spec_ngram: int | None = Field(
        default=None,
        ge=1,
        description='n-gram length the prompt-lookup drafter matches on.',
    )
    # Resilience knobs (docs/resilience.md). None = inherit
    # EngineConfig's defaults; the chat server defaults the deadline and
    # retry budget ON (ChatAppConfig.build_generator) — a serving
    # replica must degrade per-request, not per-process.
    ttft_slo_s: float | None = Field(
        default=None,
        ge=0,
        description='TTFT service-level objective in seconds (SLO/goodput '
        'accounting; the shed threshold when admission_control is on). '
        '0 disables.',
    )
    request_deadline_s: float | None = Field(
        default=None,
        ge=0,
        description='Per-request wall-clock deadline: a stuck request '
        'finishes with finish_reason="timeout" and frees its KV blocks '
        'instead of holding them forever. 0 disables.',
    )
    max_dispatch_retries: int | None = Field(
        default=None,
        ge=0,
        description='Crash-domain recovery: retry a failed window this '
        'many times (bounded backoff) before quarantining the involved '
        'requests to FAILED with a recorded error. 0 = propagate the '
        'first dispatch exception (the offline/batch contract).',
    )
    admission_control: bool | None = Field(
        default=None,
        description='SLO-aware shedding: predict TTFT at enqueue and '
        'refuse (EngineOverloaded -> HTTP 429 + Retry-After) requests '
        'whose prediction busts ttft_slo_s, instead of queueing them '
        'into guaranteed misses. Requires ttft_slo_s > 0.',
    )

    @model_validator(mode='after')
    def _attn_backend_in_catalog(self) -> 'TpuGeneratorConfig':
        # Membership over a Literal copy: the selector set has ONE owner
        # (instruments.ATTN_BACKEND_LABELS -> ops.ATTN_BACKENDS), so a
        # new kernel tier is reachable here without touching this file.
        from distllm_tpu.ops.paged_attention import ATTN_BACKENDS

        if self.attn_backend not in ATTN_BACKENDS:
            raise ValueError(
                f'attn_backend must be one of {ATTN_BACKENDS}, '
                f'got {self.attn_backend!r}'
            )
        return self

    @model_validator(mode='after')
    def _xor_top_p_min_p(self) -> 'TpuGeneratorConfig':
        # Reference behavior (vllm_backend.py:48-60): an explicitly set
        # top_p wins and min_p is ignored; min_p (default 0.1) applies
        # otherwise. A reference config carrying only `top_p: 0.95` must
        # load unchanged — min_p's own default cannot veto it. Only a
        # config that EXPLICITLY sets both truthy values is ambiguous.
        if self.top_p and self.min_p:
            if 'min_p' in self.model_fields_set:
                raise ValueError('Only one of top_p or min_p can be set')
            self.min_p = 0.0
        return self


def _generation_config_eos(model_dir: str) -> tuple[int, ...]:
    """ALL ``eos_token_id`` values from the checkpoint's
    generation_config.json (int or list — vLLM honors every entry, e.g.
    gemma-2-it stops on both <eos> and <end_of_turn>). Empty tuple on a
    missing/malformed file — startup must fall back, never crash."""
    import json
    from pathlib import Path

    path = Path(model_dir) / 'generation_config.json'
    if not path.exists():
        return ()
    try:
        eos = json.loads(path.read_text()).get('eos_token_id')
        ids = eos if isinstance(eos, list) else [eos]
        return tuple(int(i) for i in ids if i is not None)
    except (OSError, ValueError, TypeError, AttributeError):
        return ()


class TpuGenerator:
    def __init__(self, config: TpuGeneratorConfig) -> None:
        import jax

        from distllm_tpu.models import decoder_family
        from distllm_tpu.models.loader import read_checkpoint, read_hf_config
        from distllm_tpu.models.tokenizer import HFTokenizer
        from distllm_tpu.parallel.mesh import MeshSpec, make_mesh
        from distllm_tpu.parallel.sharding import shard_pytree

        self.config = config
        hf_cfg = read_hf_config(config.pretrained_model_name_or_path)
        # Dispatch on the checkpoint's model_type (the vLLM analogue of
        # serving any supported architecture from one backend): the
        # Mistral module covers mistral/llama/qwen2; Mixtral adds the
        # MoE expert banks — both serve through the same engine.
        cfg_cls, family = decoder_family(hf_cfg.get('model_type', 'mistral'))
        model_cfg = cfg_cls.from_hf_config(hf_cfg)
        params = family.params_from_hf(
            read_checkpoint(config.pretrained_model_name_or_path), model_cfg
        )
        quant_mode = normalize_mode(config.quantization)
        if quant_mode:
            # Quantize BEFORE sharding so codes are placed once (QTensor
            # leaves replicate; float leaves take their TP specs).
            params = quantize_pytree(
                params, mode=quant_mode, out_dtype=model_cfg.dtype
            )
        mesh = None
        if config.tensor_parallel_size > 1:
            mesh = make_mesh(
                MeshSpec(data=1, model=config.tensor_parallel_size),
                devices=jax.devices()[: config.tensor_parallel_size],
            )
            params = shard_pytree(
                params, family.param_specs(model_cfg, params), mesh
            )
        tokenizer = HFTokenizer(
            config.tokenizer_name or config.pretrained_model_name_or_path,
            trust_remote_code=config.trust_remote_code,
        )
        # vLLM parity: checkpoints commonly carry EOS (or EXTRA stop ids
        # like gemma-2-it's <end_of_turn>) only in generation_config.json;
        # honoring just the tokenizer's eos would generate to max_tokens.
        gc_eos = _generation_config_eos(config.pretrained_model_name_or_path)
        if getattr(tokenizer._tok, 'eos_token_id', None) is not None:
            tokenizer.eos_id = int(tokenizer._tok.eos_token_id)
        elif gc_eos:
            tokenizer.eos_id = gc_eos[0]
        self._extra_stop_ids = tuple(
            i for i in gc_eos if i != getattr(tokenizer, 'eos_id', None)
        )
        self.engine = LLMEngine(
            model_cfg,
            params,
            tokenizer,
            EngineConfig(
                block_size=config.block_size,
                num_blocks=config.num_blocks,
                max_num_seqs=config.max_num_seqs,
                max_model_len=config.max_model_len,
                quantization=quant_mode,
                # 'auto' is passed THROUGH: the engine resolves it once at
                # construction (where it also knows the mesh and the KV
                # block geometry — a pre-resolved 'pallas' would read as
                # an explicit pin to the engine's TP guard and raise
                # instead of quietly keeping XLA) and logs the fallback.
                attn_backend=config.attn_backend,
                # None = inherit EngineConfig's defaults (single owner).
                **{
                    knob: value
                    for knob, value in (
                        ('decode_steps', config.decode_steps),
                        ('sampling_top_window', config.sampling_top_window),
                        ('decode_layer_unroll', config.decode_layer_unroll),
                        ('enable_prefix_cache', config.enable_prefix_cache),
                        ('prefill_chunk_tokens', config.prefill_chunk_tokens),
                        (
                            'enable_mixed_batching',
                            config.enable_mixed_batching,
                        ),
                        (
                            'max_window_prefill_tokens',
                            config.max_window_prefill_tokens,
                        ),
                        ('draft_k', config.draft_k),
                        ('spec_ngram', config.spec_ngram),
                        ('ttft_slo_s', config.ttft_slo_s),
                        ('request_deadline_s', config.request_deadline_s),
                        (
                            'max_dispatch_retries',
                            config.max_dispatch_retries,
                        ),
                        ('admission_control', config.admission_control),
                    )
                    if value is not None
                },
            ),
            mesh=mesh,
            # The generator created these params itself; let the engine
            # apply destructive HBM optimizations (relayout/quant cleanup).
            own_params=True,
        )

    def _sampling_params(self) -> SamplingParams:
        return SamplingParams(
            temperature=self.config.temperature,
            top_p=self.config.top_p or 1.0,
            min_p=self.config.min_p,
            max_tokens=self.config.max_tokens,
            # generation_config stop ids beyond the primary EOS
            # (gemma-2-it's <end_of_turn>): every entry terminates.
            stop_token_ids=self._extra_stop_ids,
        )

    def generate(self, prompts: str | list[str]) -> list[str]:
        if isinstance(prompts, str):
            prompts = [prompts]
        return self.engine.generate(prompts, self._sampling_params())

    def shutdown(self) -> None:
        self.engine.shutdown()


class FakeGeneratorConfig(BaseConfig):
    """Deterministic local test backend (no reference equivalent — the
    reference relies on downloading small real models; SURVEY.md section 4)."""

    name: Literal['fake'] = 'fake'
    response_template: str = 'response to: {prompt}'
    max_prompt_chars: int = 48
    # Every Nth generate() call raises resilience.EngineOverloaded (the
    # engine's SLO-shed signal) so the chat server's 429/Retry-After
    # surface is testable without a real overloaded engine; 0 disables.
    overload_every: int = 0


class FakeGenerator:
    def __init__(self, config: FakeGeneratorConfig) -> None:
        self.config = config
        self._calls = 0

    def generate(self, prompts: str | list[str]) -> list[str]:
        if isinstance(prompts, str):
            prompts = [prompts]
        self._calls += 1
        every = self.config.overload_every
        if every > 0 and self._calls % every == 0:
            from distllm_tpu.resilience import EngineOverloaded

            raise EngineOverloaded(
                predicted_ttft_s=1.25, retry_after_s=3.0, slo_s=0.5
            )
        return [
            self.config.response_template.format(
                prompt=p[: self.config.max_prompt_chars]
            )
            for p in prompts
        ]
