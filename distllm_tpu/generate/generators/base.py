"""Generator protocol (reference: ``generate/generators/base.py:10-24``)."""

from __future__ import annotations

from typing import Protocol, runtime_checkable


@runtime_checkable
class LLMGenerator(Protocol):
    config: object

    def generate(self, prompts: str | list[str]) -> list[str]: ...
