"""Dedicated Argo-proxy and direct-OpenAI chat generators.

Reference parity: ``distllm/chat_argoproxy.py:216-352`` — beyond the generic
:class:`ApiGenerator`, the reference ships two specialized clients with
distinct conventions:

- :class:`ArgoGenerator` — Argonne's Argo proxy: ``argo:`` model names, an
  api key that "can be any string", env-default ``MODEL``/``BASE_URL``,
  ``/v1`` appended to the base URL, a ``user`` field injected into each
  request (the proxy's attribution convention), and errors returned as
  ``"Error: ..."`` strings rather than raised (``:244-257``).
- :class:`OpenAIAPIGenerator` — the public OpenAI API: api key REQUIRED at
  construction (``:293-298``), ``max_completion_tokens`` instead of the
  legacy ``max_tokens`` (``:320-326``), and explicit handling of
  None/empty-content responses (``:328-343``).

Both expose the framework-wide ``generate(prompts) -> list[str]`` protocol
plus the reference's per-call ``temperature``/``max_tokens`` overrides.
"""

from __future__ import annotations

import os
from typing import Literal

from pydantic import Field

from distllm_tpu.generate.generators.api_backend import ApiAuthError
from distllm_tpu.observability.instruments import log_event
from distllm_tpu.utils import BaseConfig, expo_backoff_retry

_SYSTEM = 'You are a helpful assistant.'


class _ChatEndpointBase:
    """Shared requests plumbing for the two endpoint flavors."""

    def _post(self, url: str, headers: dict, body: dict) -> dict:
        import requests

        def call() -> dict:
            response = requests.post(
                url, json=body, headers=headers, timeout=self.config.timeout
            )
            if response.status_code in (401, 403):
                # Retrying cannot fix bad credentials — fail fast (the
                # reference still surfaces this as an 'Error: ...' string).
                raise ApiAuthError(f'{response.status_code} from {url}')
            response.raise_for_status()
            return response.json()

        return expo_backoff_retry(
            call, max_tries=self.config.max_tries, give_up_on=(ApiAuthError,)
        )

    def _generate_many(self, prompts, temperature, max_tokens) -> list[str]:
        if isinstance(prompts, str):
            prompts = [prompts]
        return [self._one(p, temperature, max_tokens) for p in prompts]


class ArgoGeneratorConfig(BaseConfig):
    name: Literal['argo'] = 'argo'
    model: str = Field(
        default_factory=lambda: os.getenv('MODEL', 'argo:gpt-4o'),
        description='Argo-proxy model name.',
    )
    base_url: str = Field(
        default_factory=lambda: os.getenv('BASE_URL', 'http://localhost:56267'),
        description='Argo proxy base URL (``/v1`` is appended).',
    )
    api_key: str = Field(
        default='whatever+random',
        description='Argo accepts any string as the key.',
    )
    user: str = Field(
        default_factory=lambda: os.getenv('USER', 'distllm'),
        description='Injected into each request body — the Argo proxy '
        'attributes usage per user.',
    )
    temperature: float = 0.0
    max_tokens: int = 16384
    timeout: float = 300.0
    max_tries: int = 3


class ArgoGenerator(_ChatEndpointBase):
    """Chat generator against an Argo proxy (ref ``:216-257``)."""

    def __init__(self, config: ArgoGeneratorConfig) -> None:
        self.config = config

    def _one(self, prompt, temperature=None, max_tokens=None) -> str:
        cfg = self.config
        body = {
            'model': cfg.model,
            'messages': [
                {'role': 'system', 'content': _SYSTEM},
                {'role': 'user', 'content': prompt},
            ],
            'temperature': cfg.temperature if temperature is None else temperature,
            'max_tokens': cfg.max_tokens if max_tokens is None else max_tokens,
            'user': cfg.user,
        }
        headers = {
            'Content-Type': 'application/json',
            'Authorization': f'Bearer {cfg.api_key}',
        }
        url = f'{cfg.base_url.rstrip("/")}/v1/chat/completions'
        try:
            payload = self._post(url, headers, body)
            return payload['choices'][0]['message']['content']
        except Exception as exc:  # reference returns, not raises (:252-257)
            log_event(f'Error calling Argo proxy: {exc}', component='generate')
            return f'Error: {exc!s}'

    def generate(
        self, prompts, temperature=None, max_tokens=None
    ) -> list[str]:
        return self._generate_many(prompts, temperature, max_tokens)


class OpenAIAPIGeneratorConfig(BaseConfig):
    name: Literal['openai'] = 'openai'
    model: str = Field(
        default_factory=lambda: os.getenv('OPENAI_MODEL', 'gpt-4.1')
    )
    api_key: str = Field(
        default_factory=lambda: os.getenv('OPENAI_API_KEY', ''),
    )
    base_url: str | None = Field(
        default_factory=lambda: os.getenv('OPENAI_BASE_URL', None),
        description='Optional override (e.g. Azure).',
    )
    temperature: float = 0.0
    max_tokens: int = 16384
    timeout: float = 300.0
    max_tries: int = 3


class OpenAIAPIGenerator(_ChatEndpointBase):
    """Direct OpenAI API client (ref ``:284-352``)."""

    def __init__(self, config: OpenAIAPIGeneratorConfig) -> None:
        if not config.api_key:
            raise ValueError(
                'OpenAI API key is required. Set OPENAI_API_KEY environment '
                'variable or provide it in the config file.'
            )
        self.config = config

    def _one(self, prompt, temperature=None, max_tokens=None) -> str:
        cfg = self.config
        body = {
            'model': cfg.model,
            'messages': [
                {'role': 'system', 'content': _SYSTEM},
                {'role': 'user', 'content': prompt},
            ],
            'temperature': cfg.temperature if temperature is None else temperature,
            # Current-generation models reject the legacy max_tokens field.
            'max_completion_tokens': (
                cfg.max_tokens if max_tokens is None else max_tokens
            ),
        }
        headers = {
            'Content-Type': 'application/json',
            'Authorization': f'Bearer {cfg.api_key}',
        }
        base = (cfg.base_url or 'https://api.openai.com/v1').rstrip('/')
        try:
            payload = self._post(f'{base}/chat/completions', headers, body)
            choice = payload['choices'][0]
            content = choice['message'].get('content')
            if content is None:  # ref :328-336
                reason = choice.get('finish_reason')
                return f'[No content returned. Finish reason: {reason}]'
            return content
        except Exception as exc:
            log_event(f'Error calling OpenAI API: {exc}', component='generate')
            return f'Error: {exc}'

    def generate(
        self, prompts, temperature=None, max_tokens=None
    ) -> list[str]:
        return self._generate_many(prompts, temperature, max_tokens)
