"""Open-loop load generator for the serving engine (ISSUE 10 tentpole).

An *open-loop* generator submits requests on a fixed arrival schedule,
regardless of how fast the system drains them — the only arrival model
under which tail latency means anything (a closed loop self-throttles and
hides queueing collapse; "From Attention to Disaggregation", PAPERS.md).
This module is the measurement harness the disaggregated-routing and
SLO-scheduling roadmap items build on:

- :func:`build_workload` — a fully deterministic seeded workload: Poisson
  arrivals (exponential inter-arrival gaps at ``rate_rps``), a
  configurable session population where *warm* requests share their
  session's block-aligned prompt prefix (prefix-cache hits after the
  session's first request) and *cold* requests are unique, and per-request
  output budgets. Same seed → same workload, byte for byte — what makes
  the attribution on/off A/B and cross-run comparisons meaningful.
- :func:`run_loadgen` — drives a built engine through the schedule with
  ``engine.step()`` (arrivals injected the moment their time comes, even
  mid-stream at full batch) and reports TTFT / TPOT / queue-wait
  p50/p95/p99 via :func:`~distllm_tpu.observability.metrics.
  quantile_from_cumulative` over the request-lifecycle histogram deltas,
  goodput (SLO accounting + per-window throughput percentiles from the
  flight ring), warm-prefix hit counts, and the per-window-kind
  MFU / bandwidth-utilization summary.

A second driver, :func:`run_http_loadgen`, replays the SAME workload
against an OpenAI-compatible HTTP endpoint (one chat_server, or the
multi-replica router — docs/routing.md) instead of an in-process engine:
prompt token ids render to a deterministic text form
(:func:`arrival_messages`), arrivals fire on the open-loop schedule from
an asyncio loop, and TTFT is measured from the SCHEDULED arrival instant
(never the actual send) — the same coordinated-omission correction the
in-process driver applies to ``t_enqueue``.

Used by the ``gen_load`` / ``gen_router`` bench stages
(``DISTLLM_BENCH_LOAD=0`` / ``DISTLLM_BENCH_ROUTER=0`` skip) and the
``scripts/loadgen.py`` CLI (``--endpoint http://...`` selects the HTTP
mode); knobs documented in ``docs/observability.md``.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field

import numpy as np

from distllm_tpu.observability import instruments as _metrics
from distllm_tpu.observability.metrics import quantile_from_cumulative
from distllm_tpu.resilience.admission import EngineOverloaded

_QUANTILES = (0.50, 0.95, 0.99)
_LIFECYCLE_HISTOGRAMS = {
    'ttft': _metrics.REQUEST_TTFT,
    'tpot': _metrics.REQUEST_TPOT,
    'queue_wait': _metrics.REQUEST_QUEUE_WAIT,
}


@dataclass(frozen=True)
class Arrival:
    """One scheduled request: offset from workload start + its payload."""

    at_s: float
    prompt_ids: tuple[int, ...]
    max_tokens: int
    session: int | None  # warm session id, None = cold (unique prompt)
    temperature: float = 0.0
    top_p: float = 1.0


@dataclass
class LoadgenConfig:
    """Workload shape. Defaults are the CPU-smoke scale; chip runs raise
    ``num_requests``/``rate_rps`` and the token ranges."""

    seed: int = 0
    num_requests: int = 32
    # Poisson arrival rate (requests/second). Offered load, not achieved:
    # the open loop keeps submitting on schedule even when the engine
    # falls behind — queue-wait percentiles are the point.
    rate_rps: float = 8.0
    # Warm/cold prefix mix: each warm request joins one of num_sessions
    # sessions and shares that session's prefix_tokens-token prompt
    # prefix (block-aligned → prefix-cache hits after the session's
    # first request); cold requests are globally unique.
    num_sessions: int = 4
    warm_fraction: float = 0.5
    prefix_tokens: int = 32
    prompt_tokens: tuple[int, int] = (8, 48)   # cold/tail length range
    output_tokens: tuple[int, int] = (4, 24)
    vocab_size: int = 2048
    temperature: float = 0.0  # greedy: deterministic across A/B arms
    # Nucleus filtering for sampled (temperature > 0) workloads; 1.0
    # disables. Sampled streams stay deterministic per (seed, schedule)
    # via the engine's counter-based per-request PRNG keys
    # (docs/speculative.md "Sampled verification").
    top_p: float = 1.0
    # Engine paged-pool override (blocks), consumed by the engine-building
    # callers (scripts/loadgen.py CLI, the bench gen_tier stage) rather
    # than by build_workload: sizing the pool BELOW the workload's warm
    # working set forces HBM-tier eviction, so CPU smokes can exercise
    # the prefix-cache spill/promote tiers with tiny prompts instead of
    # chip-scale ones. None = the caller's default pool.
    cache_blocks: int | None = None


def build_workload(cfg: LoadgenConfig) -> list[Arrival]:
    """Deterministic seeded open-loop workload (see class docs)."""
    if cfg.num_requests < 1:
        raise ValueError('num_requests must be >= 1')
    if cfg.rate_rps <= 0:
        raise ValueError('rate_rps must be > 0')
    rng = np.random.default_rng(cfg.seed)
    arrivals_at = np.cumsum(
        rng.exponential(1.0 / cfg.rate_rps, size=cfg.num_requests)
    )
    prefixes = [
        tuple(
            int(t)
            for t in rng.integers(1, cfg.vocab_size, size=cfg.prefix_tokens)
        )
        for _ in range(max(1, cfg.num_sessions))
    ]
    lo, hi = cfg.prompt_tokens
    out_lo, out_hi = cfg.output_tokens
    workload: list[Arrival] = []
    for at in arrivals_at:
        tail = tuple(
            int(t)
            for t in rng.integers(
                1, cfg.vocab_size, size=int(rng.integers(lo, hi + 1))
            )
        )
        session: int | None = None
        if rng.random() < cfg.warm_fraction:
            session = int(rng.integers(len(prefixes)))
            prompt = prefixes[session] + tail
        else:
            prompt = tail
        workload.append(
            Arrival(
                at_s=float(at),
                prompt_ids=prompt,
                max_tokens=int(rng.integers(out_lo, out_hi + 1)),
                session=session,
                temperature=cfg.temperature,
                top_p=cfg.top_p,
            )
        )
    return workload


@dataclass
class LoadReport:
    """Everything one loadgen run measured. ``percentiles`` maps
    ``'<metric>_p<q>'`` (histogram-estimated); ``tokens_by_request``
    preserves emission order per request for A/B identity checks."""

    requests: int
    tokens: int
    elapsed_s: float
    offered_rps: float | None
    achieved_tok_s: float
    percentiles: dict[str, float | None]
    window_tok_s: dict[str, float | None]
    goodput_tokens: int
    goodput_frac: float | None
    slo_met: int
    slo_missed: int
    warm_prefix_hit_tokens: int
    warm_requests: int
    cold_requests: int
    roofline: dict[str, dict[str, float]]
    # Resilience accounting (docs/resilience.md): arrivals refused by
    # SLO-aware admission control, requests quarantined to FAILED
    # (dispatch failures / deadline timeouts), and the engine's
    # retry/recovery counts over this run — what the gen_chaos stage
    # gates (recoveries, goodput-under-fault) and reports (shed rate).
    shed_requests: int = 0
    shed_rate: float | None = None
    failed_requests: int = 0
    window_retries: int = 0
    recoveries: int = 0
    quarantined: int = 0
    tokens_by_request: list[list[int]] = field(default_factory=list)
    # Schedule-relative TTFT per ARRIVAL, aligned to the workload order
    # (None = shed at admission or never emitted). What lets the
    # gen_tier stage compare warm-session TTFT across tier-on/off arms
    # request by request; tokens_by_request is aligned the same way
    # (shed arrivals contribute an empty list).
    ttft_by_request: list = field(default_factory=list)

    def to_fragment(self, prefix: str) -> dict:
        """Flatten into ``{prefix}key`` fields for a bench stage record."""
        out = {
            f'{prefix}requests': self.requests,
            f'{prefix}tokens': self.tokens,
            f'{prefix}elapsed_s': round(self.elapsed_s, 3),
            f'{prefix}offered_rps': (
                round(self.offered_rps, 3)
                if self.offered_rps is not None else None
            ),
            f'{prefix}tok_s': round(self.achieved_tok_s, 2),
            f'{prefix}goodput_tokens': self.goodput_tokens,
            f'{prefix}goodput_frac': self.goodput_frac,
            f'{prefix}slo_met': self.slo_met,
            f'{prefix}slo_missed': self.slo_missed,
            f'{prefix}warm_prefix_hit_tokens': self.warm_prefix_hit_tokens,
            f'{prefix}warm_requests': self.warm_requests,
            f'{prefix}cold_requests': self.cold_requests,
        }
        for key, value in self.percentiles.items():
            out[f'{prefix}{key}'] = (
                round(value, 6) if value is not None else None
            )
        for key, value in self.window_tok_s.items():
            out[f'{prefix}goodput_{key}'] = (
                round(value, 2) if value is not None else None
            )
        out[f'{prefix}shed_requests'] = self.shed_requests
        out[f'{prefix}shed_rate'] = self.shed_rate
        out[f'{prefix}failed_requests'] = self.failed_requests
        out[f'{prefix}window_retries'] = self.window_retries
        out[f'{prefix}recoveries'] = self.recoveries
        out[f'{prefix}quarantined'] = self.quarantined
        for kind, stats in self.roofline.items():
            out[f'{prefix}mfu_{kind}'] = stats.get('mfu')
            out[f'{prefix}bw_util_{kind}'] = stats.get('bw_util')
        return out


def _exact_percentiles(values: list[float]) -> dict[str, float | None]:
    if not values:
        return {f'p{int(q * 100)}': None for q in _QUANTILES}
    arr = np.asarray(values, dtype=np.float64)
    return {
        f'p{int(q * 100)}': float(np.percentile(arr, q * 100))
        for q in _QUANTILES
    }


def arrival_messages(arrival: Arrival) -> list[dict]:
    """Deterministic OpenAI message rendering of one arrival's prompt.

    Space-joined decimal token ids as a single user message: two arrivals
    sharing a token-id prefix share a byte prefix of the rendered content
    — exactly what the router's byte-level digest chain needs to see the
    same warm/cold structure the in-process driver exercises."""
    return [
        {
            'role': 'user',
            'content': ' '.join(str(t) for t in arrival.prompt_ids),
        }
    ]


@dataclass
class HttpLoadReport:
    """What one HTTP loadgen run measured. Per-arrival lists align with
    the sorted schedule (like ``LoadReport.ttft_by_request``); replica
    attribution comes from the ``X-Distllm-Router-Replica`` header when
    the endpoint is the router (empty dict against a bare chat_server).
    """

    requests: int
    ok: int
    rejected: int       # 429 admission rejections (propagated untouched)
    retried: int        # responses carrying X-Distllm-Router-Retry
    errors: int         # transport failures / 5xx
    elapsed_s: float
    goodput_rps: float  # SLO-met ok requests (all ok if no SLO) / elapsed
    percentiles: dict[str, float | None]
    by_replica: dict[str, int]
    ttft_by_request: list
    statuses: list
    contents: list

    def to_fragment(self, prefix: str) -> dict:
        out = {
            f'{prefix}requests': self.requests,
            f'{prefix}ok': self.ok,
            f'{prefix}rejected': self.rejected,
            f'{prefix}retried': self.retried,
            f'{prefix}errors': self.errors,
            f'{prefix}elapsed_s': round(self.elapsed_s, 3),
            f'{prefix}goodput_rps': round(self.goodput_rps, 3),
            f'{prefix}replicas_used': len(self.by_replica),
        }
        for key, value in self.percentiles.items():
            out[f'{prefix}{key}'] = (
                round(value, 6) if value is not None else None
            )
        return out


async def _run_http_async(
    endpoint: str,
    workload: list[Arrival],
    *,
    slo_s: float,
    timeout_s: float,
    stream: bool,
) -> HttpLoadReport:
    import aiohttp

    schedule = sorted(workload, key=lambda a: a.at_s)
    url = endpoint.rstrip('/') + '/v1/chat/completions'
    n = len(schedule)
    ttfts: list = [None] * n
    statuses: list = [None] * n
    contents: list = [None] * n
    replicas: list = [None] * n
    retried_flags = [False] * n
    t0 = time.monotonic()

    async def fire(i: int, arrival: Arrival, session) -> None:
        delay = (t0 + arrival.at_s) - time.monotonic()
        if delay > 0:
            await asyncio.sleep(delay)
        scheduled_at = t0 + arrival.at_s
        body = {
            'messages': arrival_messages(arrival),
            'max_tokens': arrival.max_tokens,
            'temperature': arrival.temperature,
            'top_p': arrival.top_p,
            'stream': stream,
        }
        try:
            async with session.post(url, json=body) as resp:
                # First payload byte stamps TTFT against the SCHEDULED
                # arrival — a send delayed by a slow event loop must not
                # hide queueing (coordinated-omission correction, the
                # HTTP twin of the in-process t_enqueue re-anchor).
                first = await resp.content.readany()
                ttfts[i] = time.monotonic() - scheduled_at
                payload = first + await resp.content.read()
                statuses[i] = resp.status
                replicas[i] = resp.headers.get('X-Distllm-Router-Replica')
                retried_flags[i] = bool(
                    resp.headers.get('X-Distllm-Router-Retry')
                )
                if resp.status == 200 and not stream:
                    try:
                        doc = json.loads(payload)
                        contents[i] = doc['choices'][0]['message']['content']
                    # distlint: disable=swallowed-exception -- a 200 with an unparseable body is counted below as an error status for the report; the raw status is the signal
                    except (ValueError, KeyError, IndexError):
                        statuses[i] = -1
                elif resp.status == 200:
                    contents[i] = payload.decode('utf-8', 'replace')
        # distlint: disable=swallowed-exception -- a transport failure IS a datapoint in an open-loop run (the errors count + None status); raising would abort the schedule mid-flight
        except (aiohttp.ClientError, asyncio.TimeoutError):
            statuses[i] = None

    timeout = aiohttp.ClientTimeout(total=timeout_s)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        await asyncio.gather(
            *(fire(i, a, session) for i, a in enumerate(schedule))
        )
    elapsed_s = time.monotonic() - t0

    ok_indices = [i for i, s in enumerate(statuses) if s == 200]
    ok_ttfts = [ttfts[i] for i in ok_indices if ttfts[i] is not None]
    met = [
        t for t in ok_ttfts if slo_s <= 0 or t <= slo_s
    ]
    percentiles = {
        f'ttft_{k}': v for k, v in _exact_percentiles(ok_ttfts).items()
    }
    by_replica: dict[str, int] = {}
    for i in ok_indices:
        if replicas[i]:
            by_replica[replicas[i]] = by_replica.get(replicas[i], 0) + 1
    return HttpLoadReport(
        requests=n,
        ok=len(ok_indices),
        rejected=sum(1 for s in statuses if s == 429),
        retried=sum(retried_flags),
        errors=sum(
            1 for s in statuses
            if s is None or s == -1 or (isinstance(s, int) and s >= 500)
        ),
        elapsed_s=elapsed_s,
        goodput_rps=len(met) / elapsed_s if elapsed_s > 0 else 0.0,
        percentiles=percentiles,
        by_replica=by_replica,
        ttft_by_request=[
            round(t, 6) if t is not None else None for t in ttfts
        ],
        statuses=statuses,
        contents=contents,
    )


def run_http_loadgen(
    endpoint: str,
    workload: list[Arrival],
    *,
    slo_s: float = 0.0,
    timeout_s: float = 120.0,
    stream: bool = False,
) -> HttpLoadReport:
    """Replay ``workload`` open-loop against an OpenAI-compatible HTTP
    endpoint (chat_server or the router). Blocking facade over the
    asyncio driver — call from synchronous code (CLI, bench stages)."""
    return asyncio.run(
        _run_http_async(
            endpoint,
            workload,
            slo_s=slo_s,
            timeout_s=timeout_s,
            stream=stream,
        )
    )


def run_loadgen(
    engine, workload: list[Arrival], *, poll_sleep_s: float = 0.005
) -> LoadReport:
    """Drive ``engine`` through ``workload`` open-loop and measure.

    The engine should be warmed (compiles inside the run would poison
    every latency percentile) and, for the warm-prefix mix to mean
    anything, built with ``enable_prefix_cache=True``. Greedy workloads
    (``temperature=0``) produce identical token streams across repeat
    runs on equal engine state — the attribution A/B relies on it.
    """
    from distllm_tpu.generate.engine.engine import SamplingParams

    schedule = sorted(workload, key=lambda a: a.at_s)
    hist_before = {
        name: hist.cumulative_counts()
        for name, hist in _LIFECYCLE_HISTOGRAMS.items()
    }
    stats_before = {
        key: int(engine._stats.get(key, 0))
        for key in (
            'prefix_hit_tokens', 'goodput_tokens', 'slo_met', 'slo_missed',
            'window_retries', 'recoveries', 'quarantined_requests',
        )
    }
    flight_total_before = engine.flight.total_recorded
    roofline_before = engine.roofline_snapshot()

    tokens_by_rid: dict[int, list[int]] = {}
    # One slot per ARRIVAL in schedule order; None = shed at admission.
    arrival_rids: list[int | None] = []
    shed = 0
    next_i = 0
    t0 = time.monotonic()
    while next_i < len(schedule) or engine.has_unfinished:
        now = time.monotonic() - t0
        while next_i < len(schedule) and schedule[next_i].at_s <= now:
            arrival = schedule[next_i]
            next_i += 1
            try:
                rid = engine.add_request(
                    list(arrival.prompt_ids),
                    SamplingParams(
                        temperature=arrival.temperature,
                        top_p=arrival.top_p,
                        max_tokens=arrival.max_tokens,
                    ),
                )
            # distlint: disable=swallowed-exception -- honest backpressure, already counted at the source: the engine recorded the 'shed' flight record + metric before raising
            except EngineOverloaded:
                # SLO-aware admission control refused the arrival —
                # honest backpressure, counted (the engine already
                # recorded the 'shed' flight record + metric).
                shed += 1
                arrival_rids.append(None)
                continue
            # Coordinated-omission correction: if this arrival's
            # scheduled instant passed while a blocking step() held the
            # loop, add_request stamped a LATE t_enqueue — measuring
            # from it would erase exactly the schedule-relative queueing
            # an open loop exists to expose. Re-anchor the lifecycle
            # clock to the scheduled arrival, so every downstream
            # TTFT/queue-wait/e2e observation (histograms included) is
            # schedule-relative.
            engine._requests[rid].t_enqueue = t0 + arrival.at_s
            tokens_by_rid[rid] = []
            arrival_rids.append(rid)
        if engine.has_unfinished:
            for rid, tok in engine.step():
                tokens_by_rid.setdefault(rid, []).append(tok)
        elif next_i < len(schedule):
            time.sleep(
                min(poll_sleep_s, max(0.0, schedule[next_i].at_s - now))
            )
    elapsed_s = time.monotonic() - t0
    # step()-driven runs leave finished requests parked in the engine's
    # finished map (generate_ids is what normally pops them); drop this
    # run's entries so back-to-back loadgen arms don't accumulate them.
    # t_enqueue was re-anchored to the scheduled arrival above, so the
    # harvested TTFTs are schedule-relative like the histograms. The
    # finished objects' output_ids are also the AUTHORITATIVE token
    # streams: a recovered step() may have under-reported emissions it
    # folded into request state while failing (docs/resilience.md).
    ttft_by_request: list = []
    failed = 0
    for rid in arrival_rids:
        if rid is None:
            ttft_by_request.append(None)
            continue
        finished = engine._finished.pop(rid, None)
        if finished is not None:
            tokens_by_rid[rid] = list(finished.output_ids)
            if finished.error is not None:
                failed += 1
        ttft_by_request.append(
            round(finished.t_first_token - finished.t_enqueue, 6)
            if finished is not None and finished.t_first_token
            else None
        )

    percentiles: dict[str, float | None] = {}
    for name, hist in _LIFECYCLE_HISTOGRAMS.items():
        after = hist.cumulative_counts()
        delta = [a - b for a, b in zip(after, hist_before[name])]
        for q in _QUANTILES:
            percentiles[f'{name}_p{int(q * 100)}'] = quantile_from_cumulative(
                hist.buckets, delta, q
            )

    # Per-request goodput rate over THIS run's requests: output tokens
    # over enqueue→finish wall time, counting only requests that met the
    # TTFT SLO when one is configured (all requests otherwise) — the
    # distribution of service rate the system actually *delivered*,
    # flight-ring sourced. The ring may have evicted the oldest records
    # of a very long run; percentiles then cover the retained tail (the
    # ring is 4096 records deep).
    new_records = engine.flight.snapshot()
    grew = engine.flight.total_recorded - flight_total_before
    new_records = new_records[-grew:] if grew else []
    slo_s = float(getattr(engine.config, 'ttft_slo_s', 0.0) or 0.0)
    goodput_rates = [
        record['output_tokens'] / record['e2e_s']
        for record in new_records
        if record.get('kind') == 'request'
        and record.get('e2e_s')
        and record.get('output_tokens')
        and (
            slo_s <= 0
            or (record.get('ttft_s') is not None
                and record['ttft_s'] <= slo_s)
        )
    ]
    window_tok_s = {
        f'tok_s_{k}': v for k, v in _exact_percentiles(goodput_rates).items()
    }

    total_tokens = sum(len(v) for v in tokens_by_rid.values())
    met = int(engine._stats.get('slo_met', 0)) - stats_before['slo_met']
    missed = (
        int(engine._stats.get('slo_missed', 0)) - stats_before['slo_missed']
    )
    goodput_tokens = (
        int(engine._stats.get('goodput_tokens', 0))
        - stats_before['goodput_tokens']
    )
    warm = sum(1 for a in schedule if a.session is not None)
    # N arrivals span N-1 inter-arrival gaps; a single-request workload
    # has no meaningful rate (None, not inf — the report must stay
    # strict-JSON serializable).
    span = schedule[-1].at_s - schedule[0].at_s if len(schedule) > 1 else 0.0

    def _stat_delta(key: str) -> int:
        return int(engine._stats.get(key, 0)) - stats_before[key]

    return LoadReport(
        requests=len(schedule),
        tokens=total_tokens,
        elapsed_s=elapsed_s,
        offered_rps=(len(schedule) - 1) / span if span > 0 else None,
        achieved_tok_s=total_tokens / elapsed_s if elapsed_s > 0 else 0.0,
        percentiles=percentiles,
        window_tok_s=window_tok_s,
        goodput_tokens=goodput_tokens,
        goodput_frac=(
            goodput_tokens / total_tokens if total_tokens and (met + missed)
            else None
        ),
        slo_met=met,
        slo_missed=missed,
        warm_prefix_hit_tokens=(
            int(engine._stats.get('prefix_hit_tokens', 0))
            - stats_before['prefix_hit_tokens']
        ),
        warm_requests=warm,
        cold_requests=len(schedule) - warm,
        roofline=engine.roofline_summary(baseline=roofline_before),
        shed_requests=shed,
        shed_rate=shed / len(schedule) if schedule else None,
        failed_requests=failed,
        window_retries=_stat_delta('window_retries'),
        recoveries=_stat_delta('recoveries'),
        quarantined=_stat_delta('quarantined_requests'),
        tokens_by_request=[
            tokens_by_rid.get(rid, []) if rid is not None else []
            for rid in arrival_rids
        ],
        ttft_by_request=ttft_by_request,
    )
