"""HF-dataset reader (reference: ``distllm/generate/readers/huggingface.py``)."""

from __future__ import annotations

from pathlib import Path
from typing import Literal

from distllm_tpu.utils import BaseConfig


class HuggingFaceReaderConfig(BaseConfig):
    name: Literal['huggingface'] = 'huggingface'
    text_field: str = 'text'
    path_field: str = 'path'


class HuggingFaceReader:
    def __init__(self, config: HuggingFaceReaderConfig) -> None:
        self.config = config

    def read(self, input_path: str | Path) -> tuple[list[str], list[str]]:
        from datasets import load_from_disk

        ds = load_from_disk(str(input_path))
        texts = [str(t) for t in ds[self.config.text_field]]
        if self.config.path_field in ds.column_names:
            paths = [str(p) for p in ds[self.config.path_field]]
        else:
            paths = [str(input_path)] * len(texts)
        return texts, paths
