"""AMP protein JSON reader (reference: ``generate/readers/amp_json.py:19-53``).

The nested JSON maps group-name → list of entries; each entry is serialized
back to JSON and used as BOTH text and path, so the writer can merge model
outputs back into the original entries.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Literal

from distllm_tpu.utils import BaseConfig


class AMPJsonReaderConfig(BaseConfig):
    name: Literal['amp_json'] = 'amp_json'


class AMPJsonReader:
    def __init__(self, config: AMPJsonReaderConfig) -> None:
        self.config = config

    def read(self, input_path: str | Path) -> tuple[list[str], list[str]]:
        with open(input_path) as fh:
            data = json.load(fh)
        texts: list[str] = []
        paths: list[str] = []
        for entries in data.values():
            for entry in entries:
                serialized = json.dumps(entry)
                texts.append(serialized)
                paths.append(serialized)
        return texts, paths
