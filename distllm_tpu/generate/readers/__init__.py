"""Reader strategy factory (reference: ``distllm/generate/readers/__init__.py``)."""

from __future__ import annotations

from typing import Any, Union

from distllm_tpu.generate.readers.amp_json import AMPJsonReader, AMPJsonReaderConfig
from distllm_tpu.generate.readers.base import Reader
from distllm_tpu.generate.readers.huggingface import (
    HuggingFaceReader,
    HuggingFaceReaderConfig,
)
from distllm_tpu.generate.readers.jsonl import JsonlReader, JsonlReaderConfig

ReaderConfigs = Union[JsonlReaderConfig, HuggingFaceReaderConfig, AMPJsonReaderConfig]

STRATEGIES: dict[str, tuple[type, type]] = {
    'jsonl': (JsonlReaderConfig, JsonlReader),
    'huggingface': (HuggingFaceReaderConfig, HuggingFaceReader),
    'amp_json': (AMPJsonReaderConfig, AMPJsonReader),
}


def get_reader(kwargs: dict[str, Any]) -> Reader:
    name = kwargs.get('name', '')
    entry = STRATEGIES.get(name)
    if entry is None:
        raise ValueError(
            f'Unknown reader name: {name!r}. Available: {sorted(STRATEGIES)}'
        )
    config_cls, cls = entry
    return cls(config_cls(**kwargs))


__all__ = ['Reader', 'ReaderConfigs', 'get_reader', 'STRATEGIES']
