"""Reader protocol: ``read(input_path) -> (texts, paths)``.

Reference parity: ``distllm/generate/readers/base.py:10-30`` — ``paths``
carries per-item provenance (or full metadata JSON for AMP) through the
generation pipeline to the writer.
"""

from __future__ import annotations

from pathlib import Path
from typing import Protocol, runtime_checkable


@runtime_checkable
class Reader(Protocol):
    config: object

    def read(self, input_path: str | Path) -> tuple[list[str], list[str]]: ...
