"""Jsonl reader (reference: ``distllm/generate/readers/jsonl.py:22-53``)."""

from __future__ import annotations

import json
from pathlib import Path
from typing import Literal

from distllm_tpu.utils import BaseConfig


class JsonlReaderConfig(BaseConfig):
    name: Literal['jsonl'] = 'jsonl'
    text_field: str = 'text'
    path_field: str = 'path'


class JsonlReader:
    def __init__(self, config: JsonlReaderConfig) -> None:
        self.config = config

    def read(self, input_path: str | Path) -> tuple[list[str], list[str]]:
        texts: list[str] = []
        paths: list[str] = []
        with open(input_path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                entry = json.loads(line)
                texts.append(entry[self.config.text_field])
                paths.append(str(entry.get(self.config.path_field, input_path)))
        return texts, paths
