"""Generation pipeline: readers → prompts → generators → writers + engine.

Mirrors the reference's four strategy families
(``distllm/generate/__init__.py``) plus the TPU-native paged-KV engine that
replaces vLLM (SURVEY.md section 2.4 N1). Submodules import lazily so the
engine can be used without the text-pipeline dependencies.
"""

from __future__ import annotations

import importlib
from typing import Any

_LAZY = {
    'get_generator': 'distllm_tpu.generate.generators',
    'GeneratorConfigs': 'distllm_tpu.generate.generators',
    'get_prompt_template': 'distllm_tpu.generate.prompts',
    'PromptTemplateConfigs': 'distllm_tpu.generate.prompts',
    'get_reader': 'distllm_tpu.generate.readers',
    'ReaderConfigs': 'distllm_tpu.generate.readers',
    'get_writer': 'distllm_tpu.generate.writers',
    'WriterConfigs': 'distllm_tpu.generate.writers',
}


def __getattr__(name: str) -> Any:
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(name)
    return getattr(importlib.import_module(module), name)


__all__ = list(_LAZY)
