"""MCQA evaluation harness.

TPU-native re-design of the reference's self-contained MCQA pipelines
(``distllm/mcqa/rag_argonium_score_parallel_{v2,v3}.py``, ~3.8k LoC): a model
answers multiple-choice questions (RAG-augmented or direct) and a second LLM
grades the answers. Feature parity targets (SURVEY.md section 2.3):

- local engine-server boot with auto port + monitor threads + readiness poll
- client-side request batching (queue + batch thread)
- thread-pool parallelism over questions
- checkpoint/resume with compatibility validation (+ per-question mode)
- grader JSON retry ladder (3 escalating prompts) with expo backoff
- chunk-ID traceability and retrieval metrics
- accuracy stats + incorrect-answer export, signal-handler cleanup
"""

from distllm_tpu.mcqa.config import MCQAConfig, ModelServerEntry
from distllm_tpu.mcqa.harness import main, run_mcqa

__all__ = ['MCQAConfig', 'ModelServerEntry', 'main', 'run_mcqa']
