"""Answer grading by a second LLM with a JSON retry ladder.

Reference parity: ``rag_argonium_score_parallel_v3.py:2017-2243`` — the
grader is asked for a strict JSON verdict; if parsing fails the prompt is
escalated through three increasingly strict phrasings, each attempt wrapped
in exponential backoff. Auth errors give up immediately
(``v3:1957-1963``).
"""

from __future__ import annotations

import json
from typing import Callable

from distllm_tpu.utils import expo_backoff_retry


class GraderAuthError(Exception):
    """Authentication failure — never retried."""


_PROMPT_LADDER = [
    (
        'You are grading a multiple-choice answer.\n'
        'Question:\n{question}\n\nReference answer: {reference}\n'
        'Model answer: {answer}\n\n'
        'Reply with JSON: {{"correct": true|false, "reason": "..."}}'
    ),
    (
        'Grade the answer. Respond with ONLY a JSON object, no prose.\n'
        'Question:\n{question}\nReference answer: {reference}\n'
        'Model answer: {answer}\n'
        'JSON schema: {{"correct": boolean, "reason": string}}'
    ),
    (
        'Output exactly one line of minified JSON and nothing else: '
        '{{"correct":true}} or {{"correct":false}}.\n'
        'Question: {question}\nReference: {reference}\nAnswer: {answer}'
    ),
]

def parse_grader_json(response: str) -> dict | None:
    """Extract the first JSON object with a boolean 'correct' field.

    Decodes at each '{' with ``raw_decode`` so a valid verdict followed by
    stray braces (grader prose) still parses.
    """
    decoder = json.JSONDecoder()
    pos = response.find('{')
    while pos != -1:
        try:
            payload, _ = decoder.raw_decode(response, pos)
        except json.JSONDecodeError:
            pos = response.find('{', pos + 1)
            continue
        if isinstance(payload, dict) and isinstance(
            payload.get('correct'), bool
        ):
            return payload
        pos = response.find('{', pos + 1)
    return None


def grade_answer(
    call_grader: Callable[[str], str],
    question: str,
    reference: str,
    answer: str,
    max_tries_per_level: int = 3,
) -> dict:
    """Run the retry ladder; returns {'correct': bool, 'reason': str, ...}.

    Raises RuntimeError when every ladder level fails to produce valid JSON.
    """
    last_response = ''
    for level, template in enumerate(_PROMPT_LADDER):
        prompt = template.format(
            question=question, reference=reference, answer=answer
        )

        def attempt() -> str:
            from distllm_tpu.generate.generators.api_backend import ApiAuthError

            try:
                return call_grader(prompt)
            except ApiAuthError as exc:
                raise GraderAuthError(str(exc)) from exc

        try:
            response = expo_backoff_retry(
                attempt,
                max_tries=max_tries_per_level,
                give_up_on=(GraderAuthError,),
                base_delay=0.5,
            )
        except GraderAuthError:
            raise
        except Exception as exc:  # noqa: BLE001 - try the next ladder level
            last_response = f'<error: {exc}>'
            continue
        last_response = response
        payload = parse_grader_json(response)
        if payload is not None:
            payload.setdefault('reason', '')
            payload['ladder_level'] = level
            return payload
    raise RuntimeError(
        f'grader produced no parseable JSON verdict; last response: '
        f'{last_response[:200]}'
    )
