"""Local engine-server boot for the MCQA harness.

Reference parity: ``rag_argonium_score_parallel_v3.py:1002-1405`` — the
harness can boot its own OpenAI-compatible model server as a subprocess with
an auto-selected port, stdout/stderr monitor threads writing timestamped log
files, a readiness poll against ``/health``, startup failure reports, and
SIGINT/SIGTERM cleanup (``v3:3319-3337``). The booted server is OUR engine
(``distllm_tpu.chat_server`` over the paged-KV engine), not vLLM.
"""

from __future__ import annotations

import atexit
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path


def find_free_port() -> int:
    with socket.socket() as sock:
        sock.bind(('127.0.0.1', 0))
        return sock.getsockname()[1]


class LocalServerManager:
    """Boot + monitor + tear down a local OpenAI-compatible engine server."""

    def __init__(
        self,
        model_path: str,
        log_dir: str | Path | None = None,
        port: int | None = None,
        startup_timeout: float = 300.0,
        engine_args: dict | None = None,
    ) -> None:
        self.model_path = model_path
        self.port = port or find_free_port()
        self.startup_timeout = startup_timeout
        self.engine_args = engine_args or {}
        self.log_dir = Path(log_dir or tempfile.mkdtemp(prefix='mcqa_server_'))
        self.log_dir.mkdir(parents=True, exist_ok=True)
        self.process: subprocess.Popen | None = None
        self._monitors: list[threading.Thread] = []
        self._log_files = []

    @property
    def base_url(self) -> str:
        return f'http://127.0.0.1:{self.port}/v1'

    def _write_config(self) -> Path:
        import yaml

        config = {
            'generator_config': {
                'name': 'tpu',
                'pretrained_model_name_or_path': self.model_path,
                'temperature': 0.0,
                'min_p': 0.0,
                **self.engine_args,
            }
        }
        path = self.log_dir / 'server_config.yaml'
        path.write_text(yaml.safe_dump(config))
        return path

    def _pump(self, stream, log_path: Path) -> None:
        with open(log_path, 'a') as fh:
            for line in iter(stream.readline, ''):
                stamp = time.strftime('%Y-%m-%d %H:%M:%S')
                fh.write(f'[{stamp}] {line}')
                fh.flush()

    def start(self) -> None:
        config_path = self._write_config()
        self.process = subprocess.Popen(
            [
                sys.executable,
                '-m',
                'distllm_tpu.chat_server',
                '--config',
                str(config_path),
                '--host',
                '127.0.0.1',
                '--port',
                str(self.port),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for stream, name in (
            (self.process.stdout, 'server_stdout.log'),
            (self.process.stderr, 'server_stderr.log'),
        ):
            thread = threading.Thread(
                target=self._pump, args=(stream, self.log_dir / name), daemon=True
            )
            thread.start()
            self._monitors.append(thread)
        self._install_cleanup()
        self._wait_ready()

    def _wait_ready(self) -> None:
        import requests

        deadline = time.monotonic() + self.startup_timeout
        while time.monotonic() < deadline:
            if self.process.poll() is not None:
                raise RuntimeError(self._failure_report())
            try:
                response = requests.get(
                    f'http://127.0.0.1:{self.port}/health', timeout=2
                )
                if response.ok:
                    return
            except Exception:  # noqa: BLE001 - retrying until the deadline
                pass
            time.sleep(1.0)
        self.stop()
        raise TimeoutError(
            f'server not ready after {self.startup_timeout}s; '
            f'logs: {self.log_dir}'
        )

    def _failure_report(self) -> str:
        """Startup failure report with log tails (``v3`` startup reports)."""
        lines = [
            f'local server exited with code {self.process.returncode}',
            f'model: {self.model_path}',
            f'logs: {self.log_dir}',
        ]
        for name in ('server_stderr.log', 'server_stdout.log'):
            path = self.log_dir / name
            if path.exists():
                tail = path.read_text().splitlines()[-15:]
                lines.append(f'--- {name} tail ---')
                lines.extend(tail)
        return '\n'.join(lines)

    def _install_cleanup(self) -> None:
        atexit.register(self.stop)

        def handler(signum, frame):
            self.stop()
            signal.default_int_handler(signum, frame) if signum == signal.SIGINT else sys.exit(1)

        try:
            signal.signal(signal.SIGINT, handler)
            signal.signal(signal.SIGTERM, handler)
        except ValueError:
            pass  # not the main thread (tests)

    def stop(self) -> None:
        if self.process is not None and self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.process.kill()
        self.process = None
